"""Decode-throughput microbenchmark.

Measures the BASELINE.json headline (decode tokens/sec/chip) on a
Llama-3.2-1B-shaped model — the same architecture the reference benchmarks on
A100 (BASELINE.md Table 3: bf16 51.84 tok/s, int8 25.83 tok/s — int8 2×
SLOWER there; the bar this module exists to beat is int8 ≥ bf16 on TPU).

Random weights: throughput is weight-value-independent; quality numbers come
from the eval harness with real checkpoints, never from here.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.families import config_for_family
from edgemesh.models.transformer import init_params
from edgemesh.ops.int8 import quantize_params
from edgemesh.runtime import generate

# Reference numbers (BASELINE.md Table 3, A100 40GB, generated-tokens/sec).
REFERENCE_TOK_S = {"bf16": 51.84, "int8": 25.83}

PRESETS = {
    # Llama-3.2-1B-Instruct architecture (HF config) — the reference's refiner
    # model and its published single-model rows.
    "llama1b": dict(
        vocab_size=128256, hidden_size=2048, num_layers=16, num_heads=32,
        num_kv_heads=8, intermediate_size=8192, max_seq_len=2048,
        tie_embeddings=True,
    ),
    # CI-sized smoke preset.
    "tiny": dict(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=256, max_seq_len=512, dtype="float32",
    ),
}


def decode_benchmark(
    preset: str | None = None,
    precision: str | None = None,
    batch: int = 8,
    prompt_len: int = 32,
    decode_steps: int = 128,
    repeats: int = 3,
) -> dict[str, Any]:
    preset = preset or os.environ.get("EDGEMESH_BENCH_PRESET", "llama1b")
    precision = precision or os.environ.get("EDGEMESH_BENCH_PRECISION", "int8")
    if preset not in PRESETS:
        raise ValueError(f"unknown preset {preset!r}; choose from {sorted(PRESETS)}")
    cfg = config_for_family("llama", **PRESETS[preset])
    if preset != "tiny":
        cfg = cfg.replace(dtype="bfloat16")

    params = init_params(cfg, jax.random.PRNGKey(0))
    if precision == "int8":
        params = quantize_params(params)
        params = jax.tree.map(lambda x: jax.device_put(x), params)

    sampling = SamplingParams(
        max_new_tokens=decode_steps, temperature=0.7, top_k=50, top_p=0.9,
        repetition_penalty=1.2, do_sample=True,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    lengths = jnp.full((batch,), prompt_len, jnp.int32)

    # Warmup compiles prefill + decode loop; then take the best of `repeats`.
    generate(cfg, params, tokens, lengths, sampling)
    best_tps, best_ttft = 0.0, float("inf")
    for _ in range(repeats):
        r = generate(cfg, params, tokens, lengths, sampling)
        best_tps = max(best_tps, r.decode_tok_s)
        best_ttft = min(best_ttft, r.prefill_time_s)

    baseline = REFERENCE_TOK_S.get(precision, REFERENCE_TOK_S["bf16"])
    return {
        "metric": f"decode_tok_s_llama3.2-1b_{precision}_b{batch}",
        "value": round(best_tps, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(best_tps / baseline, 3),
        "ttft_s": round(best_ttft, 4),
        "decode_steps": decode_steps,
    }
