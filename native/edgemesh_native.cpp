// edgemesh native runtime: CSV dataset loader + byte-level BPE tokenizer.
//
// The reference delegates these to native code in third-party wheels —
// pandas' C CSV engine (Code/C-DAC Server/try.py:292) and HuggingFace's Rust
// tokenizers (every loader, e.g. combiner_fp.py:276). This library is the
// framework's own native provider for both, exposed through a plain C ABI
// (ctypes-friendly; no pybind11 in the image).
//
// Build: `make -C native` → libedgemesh_native.so. Python side:
// edgemesh/runtime/native.py (graceful fallback to pure-Python when absent).
//
// CSV: full RFC 4180 — quoted fields, escaped quotes ("") and embedded
// newlines/commas (the Natural Questions dump uses all of them).
//
// BPE: GPT-2 style byte-level BPE (vocab.json + merges.txt, the format the
// Pythia/GPT-NeoX family ships). The pre-tokenizer reproduces the GPT-2
// pattern ('s|'t|'re|'ve|'m|'ll|'d| ?L+| ?N+| ?other+|ws(?!\S)|ws) with a
// hand-rolled UTF-8 state machine; letter/number classes cover ASCII plus
// common BMP ranges (full Unicode property tables are out of scope — parity
// is asserted against HF tokenizers on the English eval corpus in tests).

#include <cstdint>
#include <memory>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------------------
// CSV loader
// ---------------------------------------------------------------------------

namespace {

struct Csv {
  std::string data;                              // parsed cell bytes, concatenated
  std::vector<std::pair<size_t, size_t>> cells;  // (offset, len) per cell
  std::vector<size_t> row_start;                 // index into cells per row
  size_t ncols = 0;
};

}  // namespace

extern "C" void* em_csv_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::string raw;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  raw.resize(sz < 0 ? 0 : static_cast<size_t>(sz));
  if (sz > 0 && std::fread(&raw[0], 1, raw.size(), f) != raw.size()) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);

  Csv* csv = new Csv();
  csv->data.reserve(raw.size());
  std::string cell;
  std::vector<std::pair<size_t, size_t>> row;
  bool in_quotes = false;
  bool line_has_content = false;  // blank lines become ZERO-cell rows,
                                  // matching Python csv.reader's [] rows
  auto push_cell = [&]() {
    row.emplace_back(csv->data.size(), cell.size());
    csv->data += cell;
    cell.clear();
  };
  auto push_row = [&]() {
    csv->row_start.push_back(csv->cells.size());
    for (auto& c : row) csv->cells.push_back(c);
    if (row.size() > csv->ncols) csv->ncols = row.size();
    row.clear();
  };
  size_t i = 0, n = raw.size();
  while (i < n) {
    char c = raw[i];
    if (in_quotes) {
      if (c == '"') {
        if (i + 1 < n && raw[i + 1] == '"') { cell += '"'; i += 2; continue; }
        in_quotes = false;
        i++;
      } else {
        cell += c;
        i++;
      }
    } else if (c == '"') {
      in_quotes = true;
      line_has_content = true;
      i++;
    } else if (c == ',') {
      push_cell();
      line_has_content = true;
      i++;
    } else if (c == '\r') {
      // Row terminator, like csv.reader: lone CR (classic-Mac) ends the
      // record; CRLF consumes the LF too.
      if (line_has_content) push_cell();
      push_row();
      line_has_content = false;
      i++;
      if (i < n && raw[i] == '\n') i++;
    } else if (c == '\n') {
      if (line_has_content) push_cell();
      push_row();
      line_has_content = false;
      i++;
    } else {
      cell += c;
      line_has_content = true;
      i++;
    }
  }
  if (line_has_content) {  // last line without trailing newline
    push_cell();
    push_row();
  }
  csv->row_start.push_back(csv->cells.size());  // sentinel
  return csv;
}

extern "C" long em_csv_rows(void* h) {
  return h ? static_cast<long>(static_cast<Csv*>(h)->row_start.size()) - 1 : 0;
}

extern "C" long em_csv_cols(void* h, long row) {
  if (!h) return 0;
  Csv* csv = static_cast<Csv*>(h);
  if (row < 0 || row + 1 >= static_cast<long>(csv->row_start.size())) return 0;
  return static_cast<long>(csv->row_start[row + 1] - csv->row_start[row]);
}

extern "C" const char* em_csv_cell(void* h, long row, long col, long* len) {
  *len = 0;
  if (!h) return nullptr;
  Csv* csv = static_cast<Csv*>(h);
  if (row < 0 || row + 1 >= static_cast<long>(csv->row_start.size())) return nullptr;
  size_t base = csv->row_start[row];
  if (col < 0 || base + col >= csv->row_start[row + 1]) return nullptr;
  auto& cell = csv->cells[base + col];
  *len = static_cast<long>(cell.second);
  return csv->data.data() + cell.first;
}

extern "C" void em_csv_close(void* h) { delete static_cast<Csv*>(h); }

// ---------------------------------------------------------------------------
// Byte-level BPE (GPT-2 / GPT-NeoX format)
// ---------------------------------------------------------------------------

namespace {

void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out += static_cast<char>(cp);
  } else if (cp < 0x800) {
    out += static_cast<char>(0xC0 | (cp >> 6));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out += static_cast<char>(0xE0 | (cp >> 12));
    out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  } else {
    out += static_cast<char>(0xF0 | (cp >> 18));
    out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
    out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
    out += static_cast<char>(0x80 | (cp & 0x3F));
  }
}

// GPT-2 byte<->unicode bijection: printable latin bytes map to themselves,
// the rest shift into 256+k so every byte is a printable codepoint.
void byte_unicode_tables(std::vector<uint32_t>& b2u,
                         std::unordered_map<uint32_t, uint8_t>& u2b) {
  b2u.assign(256, 0);
  int k = 0;
  for (int b = 0; b < 256; ++b) {
    bool printable =
        (b >= '!' && b <= '~') || (b >= 0xA1 && b <= 0xAC) || (b >= 0xAE && b <= 0xFF);
    b2u[b] = printable ? static_cast<uint32_t>(b) : 256 + k++;
  }
  for (int b = 0; b < 256; ++b) u2b[b2u[b]] = static_cast<uint8_t>(b);
}

uint32_t next_cp(const std::string& s, size_t& i) {
  uint8_t c = s[i];
  uint32_t cp;
  int extra;
  if (c < 0x80) { cp = c; extra = 0; }
  else if ((c >> 5) == 0x6) { cp = c & 0x1F; extra = 1; }
  else if ((c >> 4) == 0xE) { cp = c & 0x0F; extra = 2; }
  else { cp = c & 0x07; extra = 3; }
  i++;
  for (int k = 0; k < extra && i < s.size(); ++k, ++i) cp = (cp << 6) | (s[i] & 0x3F);
  return cp;
}

bool is_letter(uint32_t cp) {
  if ((cp >= 'a' && cp <= 'z') || (cp >= 'A' && cp <= 'Z')) return true;
  if (cp >= 0xC0 && cp <= 0xFF && cp != 0xD7 && cp != 0xF7) return true;  // Latin-1
  if (cp == 0xAA || cp == 0xB5 || cp == 0xBA) return true;
  if (cp >= 0x100 && cp <= 0x2AF) return true;  // Latin extended
  if (cp >= 0x370 && cp <= 0x3FF && cp != 0x374 && cp != 0x375 && cp != 0x384 && cp != 0x385 && cp != 0x387) return true;  // Greek
  if (cp >= 0x400 && cp <= 0x4FF) return true;  // Cyrillic
  if (cp >= 0x4E00 && cp <= 0x9FFF) return true;  // CJK unified
  if (cp >= 0x3040 && cp <= 0x30FF && cp != 0x3097 && cp != 0x3098) return true;  // kana
  return false;
}

bool is_number(uint32_t cp) {
  if (cp >= '0' && cp <= '9') return true;
  if (cp == 0xB2 || cp == 0xB3 || cp == 0xB9 || (cp >= 0xBC && cp <= 0xBE)) return true;
  return false;
}

bool is_space(uint32_t cp) {
  return cp == ' ' || cp == '\t' || cp == '\n' || cp == '\r' || cp == 0x0B ||
         cp == 0x0C || cp == 0x85 || cp == 0xA0 || cp == 0x2028 || cp == 0x2029 ||
         (cp >= 0x2000 && cp <= 0x200A) || cp == 0x202F || cp == 0x205F || cp == 0x3000;
}

bool is_other(uint32_t cp) { return !is_space(cp) && !is_letter(cp) && !is_number(cp); }

struct Bpe {
  std::unordered_map<std::string, int> vocab;       // token string -> id
  std::vector<std::string> id_to_tok;
  std::unordered_map<std::string, int> merge_rank;  // "left right" -> rank
  std::vector<uint32_t> b2u;
  std::unordered_map<uint32_t, uint8_t> u2b;
};

// Minimal JSON {string: int} parser with \uXXXX (incl. surrogate pairs).
bool parse_vocab_json(const std::string& text, std::unordered_map<std::string, int>& out) {
  size_t i = 0, n = text.size();
  auto skip_ws = [&]() {
    while (i < n && (text[i] == ' ' || text[i] == '\n' || text[i] == '\t' || text[i] == '\r')) i++;
  };
  skip_ws();
  if (i >= n || text[i] != '{') return false;
  i++;
  while (true) {
    skip_ws();
    if (i < n && text[i] == '}') return true;
    if (i >= n || text[i] != '"') return false;
    i++;
    std::string key;
    while (i < n && text[i] != '"') {
      char c = text[i];
      if (c == '\\') {
        i++;
        if (i >= n) return false;
        char e = text[i];
        if (e == 'u') {
          if (i + 4 >= n) return false;
          uint32_t cp = static_cast<uint32_t>(std::stoul(text.substr(i + 1, 4), nullptr, 16));
          i += 5;
          if (cp >= 0xD800 && cp <= 0xDBFF && i + 5 < n && text[i] == '\\' && text[i + 1] == 'u') {
            uint32_t lo = static_cast<uint32_t>(std::stoul(text.substr(i + 2, 4), nullptr, 16));
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            i += 6;
          }
          append_utf8(key, cp);
          continue;
        }
        switch (e) {
          case 'n': key += '\n'; break;
          case 't': key += '\t'; break;
          case 'r': key += '\r'; break;
          case 'b': key += '\b'; break;
          case 'f': key += '\f'; break;
          case '/': key += '/'; break;
          case '\\': key += '\\'; break;
          case '"': key += '"'; break;
          default: key += e;
        }
        i++;
      } else {
        key += c;
        i++;
      }
    }
    i++;  // closing quote
    skip_ws();
    if (i >= n || text[i] != ':') return false;
    i++;
    skip_ws();
    size_t start = i;
    while (i < n && (isdigit(static_cast<unsigned char>(text[i])) || text[i] == '-')) i++;
    if (start == i) return false;
    out[key] = std::stoi(text.substr(start, i - start));
    skip_ws();
    if (i < n && text[i] == ',') { i++; continue; }
    if (i < n && text[i] == '}') return true;
    return false;
  }
}

// GPT-2 pre-tokenizer over UTF-8 input; emits byte-span (start, len) pieces.
// Ordered alternation of the GPT-2 pattern:
//   's 't 're 've 'm 'll 'd           (case-sensitive, as in the original)
//   " ?\p{L}+" | " ?\p{N}+" | " ?[^\s L N]+"
//   "\s+(?!\S)" | "\s+"  — a whitespace run followed by more text yields its
//   LAST char to the next piece (it becomes the " ?" prefix if it is a plain
//   space, else it stands alone).
void pretokenize(const std::string& s, std::vector<std::pair<size_t, size_t>>& pieces) {
  size_t n = s.size();
  auto class_run = [&](size_t from, bool (*pred)(uint32_t)) {
    size_t j = from;
    while (j < n) {
      size_t t = j;
      uint32_t c = next_cp(s, t);
      if (!pred(c)) break;
      j = t;
    }
    return j;
  };
  size_t i = 0;
  while (i < n) {
    size_t start = i;
    size_t j = i;
    uint32_t cp = next_cp(s, j);

    if (cp == '\'' && j < n) {  // contractions (lowercase only, like GPT-2)
      size_t k = j;
      uint32_t c1 = next_cp(s, k);
      if (c1 == 's' || c1 == 't' || c1 == 'm' || c1 == 'd') {
        pieces.emplace_back(start, k - start);
        i = k;
        continue;
      }
      if (k < n && (c1 == 'r' || c1 == 'v' || c1 == 'l')) {
        size_t k2 = k;
        uint32_t c2 = next_cp(s, k2);
        if ((c1 == 'r' && c2 == 'e') || (c1 == 'v' && c2 == 'e') ||
            (c1 == 'l' && c2 == 'l')) {
          pieces.emplace_back(start, k2 - start);
          i = k2;
          continue;
        }
      }
    }

    // " ?X+" — optional single literal-space prefix before a class run.
    size_t body = start;
    uint32_t head = cp;
    if (cp == ' ' && j < n) {
      size_t k = j;
      uint32_t c1 = next_cp(s, k);
      if (!is_space(c1)) { body = j; head = c1; }
    }
    if (!is_space(head)) {
      size_t end_;
      if (is_letter(head)) end_ = class_run(body, is_letter);
      else if (is_number(head)) end_ = class_run(body, is_number);
      else end_ = class_run(body, is_other);
      pieces.emplace_back(start, end_ - start);
      i = end_;
      continue;
    }

    // Whitespace run [start, k); `prev` is the offset of its last char.
    size_t k = start;
    size_t prev = start;
    while (k < n) {
      size_t t = k;
      uint32_t c = next_cp(s, t);
      if (!is_space(c)) break;
      prev = k;
      k = t;
    }
    if (k >= n || prev == start) {
      // Trailing run, or a single non-' ' whitespace char before text.
      pieces.emplace_back(start, k - start);
      i = k;
    } else {
      // Run followed by text: keep the last whitespace char for the next
      // piece ("\s+(?!\S)" semantics).
      pieces.emplace_back(start, prev - start);
      i = prev;
    }
  }
}

}  // namespace

// C++ exceptions must never cross the C ABI into ctypes (std::terminate →
// SIGABRT kills the Python process). A corrupt vocab (bad \u escape → stoul
// throws, id beyond int → stoi throws, OOM) returns nullptr like a missing
// file — the Python layer's documented graceful-fallback contract.
extern "C" void* em_bpe_open(const char* vocab_path, const char* merges_path) try {
  FILE* vf = std::fopen(vocab_path, "rb");
  if (!vf) return nullptr;
  std::string vtext;
  std::fseek(vf, 0, SEEK_END);
  long vs = std::ftell(vf);
  std::fseek(vf, 0, SEEK_SET);
  vtext.resize(vs < 0 ? 0 : static_cast<size_t>(vs));
  if (vs > 0 && std::fread(&vtext[0], 1, vtext.size(), vf) != vtext.size()) {
    std::fclose(vf);
    return nullptr;
  }
  std::fclose(vf);

  std::unique_ptr<Bpe> bpe(new Bpe());
  byte_unicode_tables(bpe->b2u, bpe->u2b);
  if (!parse_vocab_json(vtext, bpe->vocab)) return nullptr;
  int max_id = -1;
  for (auto& kv : bpe->vocab) max_id = kv.second > max_id ? kv.second : max_id;
  if (max_id < 0) return nullptr;
  bpe->id_to_tok.assign(max_id + 1, "");
  for (auto& kv : bpe->vocab) bpe->id_to_tok[kv.second] = kv.first;

  FILE* mf = std::fopen(merges_path, "rb");
  if (!mf) return nullptr;
  char line[4096];
  int rank = 0;
  bool first = true;
  while (std::fgets(line, sizeof(line), mf)) {
    std::string l(line);
    while (!l.empty() && (l.back() == '\n' || l.back() == '\r')) l.pop_back();
    if (first && l.rfind("#version", 0) == 0) { first = false; continue; }
    first = false;
    if (l.empty()) continue;
    bpe->merge_rank[l] = rank++;
  }
  std::fclose(mf);
  return bpe.release();
} catch (...) {
  return nullptr;  // corrupt input or OOM — same contract as a missing file
}

extern "C" long em_bpe_vocab_size(void* h) {
  return h ? static_cast<long>(static_cast<Bpe*>(h)->id_to_tok.size()) : 0;
}

extern "C" long em_bpe_token_id(void* h, const char* tok) {
  if (!h) return -1;
  Bpe* bpe = static_cast<Bpe*>(h);
  auto it = bpe->vocab.find(tok);
  return it == bpe->vocab.end() ? -1 : it->second;
}

extern "C" long em_bpe_encode(void* h, const char* text, long text_len, int32_t* out,
                              long max_out) try {
  if (!h) return -1;
  Bpe* bpe = static_cast<Bpe*>(h);
  std::string s(text, text_len);
  std::vector<std::pair<size_t, size_t>> pieces;
  pretokenize(s, pieces);

  long count = 0;
  std::vector<std::string> parts;
  for (auto& piece : pieces) {
    parts.clear();  // bytes -> unicode symbols (one string per byte)
    for (size_t b = 0; b < piece.second; ++b) {
      uint8_t byte = static_cast<uint8_t>(s[piece.first + b]);
      std::string sym;
      append_utf8(sym, bpe->b2u[byte]);
      parts.push_back(sym);
    }
    while (parts.size() > 1) {  // greedy lowest-rank merging
      int best_rank = INT32_MAX;
      size_t best_i = 0;
      for (size_t k = 0; k + 1 < parts.size(); ++k) {
        auto it = bpe->merge_rank.find(parts[k] + " " + parts[k + 1]);
        if (it != bpe->merge_rank.end() && it->second < best_rank) {
          best_rank = it->second;
          best_i = k;
        }
      }
      if (best_rank == INT32_MAX) break;
      parts[best_i] = parts[best_i] + parts[best_i + 1];
      parts.erase(parts.begin() + best_i + 1);
    }
    for (auto& p : parts) {
      auto it = bpe->vocab.find(p);
      if (it == bpe->vocab.end()) continue;  // GPT-2 vocabs are byte-complete
      if (count < max_out) out[count] = it->second;
      count++;
    }
  }
  return count;
} catch (...) {
  return -1;
}

extern "C" long em_bpe_decode(void* h, const int32_t* ids, long n, char* out, long max_out) try {
  if (!h) return -1;
  Bpe* bpe = static_cast<Bpe*>(h);
  std::string text;
  for (long k = 0; k < n; ++k) {
    if (ids[k] < 0 || ids[k] >= static_cast<long>(bpe->id_to_tok.size())) continue;
    const std::string& tok = bpe->id_to_tok[ids[k]];
    size_t i = 0;
    while (i < tok.size()) {
      uint32_t cp = next_cp(tok, i);
      auto it = bpe->u2b.find(cp);
      if (it != bpe->u2b.end()) text += static_cast<char>(it->second);
    }
  }
  long sz = static_cast<long>(text.size());
  if (sz > max_out) sz = max_out;
  std::memcpy(out, text.data(), sz);
  return static_cast<long>(text.size());
} catch (...) {
  return -1;
}

extern "C" void em_bpe_close(void* h) { delete static_cast<Bpe*>(h); }
