"""Flash-attention kernel numerics vs the XLA reference path.

Runs the Pallas kernel in interpret mode on the CPU test mesh (conftest pins
JAX_PLATFORMS=cpu) and checks it against ops.attention.attend, which the rest
of the stack already validates against HF torch outputs (test_hf_parity.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.ops.attention import LayerKV, attend
from edgemesh.ops.flash_attention import flash_attention



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _reference(q, k, v, q_positions, kv_lens):
    max_seq = k.shape[1]
    cache = LayerKV(k, v)
    kv_valid = jnp.arange(max_seq)[None, :] < kv_lens[:, None]
    return attend(q, cache, q_positions, kv_valid)


def _random_case(key, b, s, skv, nh, kh, hd, dtype=jnp.float32):
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (b, s, nh, hd), dtype)
    k = jax.random.normal(ks[1], (b, skv, kh, hd), dtype)
    v = jax.random.normal(ks[2], (b, skv, kh, hd), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "b,s,nh,kh,hd",
    [
        (2, 64, 4, 4, 64),  # MHA, hd below lane width (pad path)
        (2, 64, 8, 2, 64),  # GQA groups=4
        (1, 100, 4, 2, 128),  # s not a block multiple
        (2, 16, 4, 1, 80),  # MQA, odd head_dim (Phi-2 style)
    ],
)
def test_prefill_matches_reference(b, s, nh, kh, hd):
    q, k, v = _random_case(jax.random.PRNGKey(0), b, s, s, nh, kh, hd)
    lengths = jnp.array([s] * b).at[0].set(max(1, s - 7))
    # Prefill: positions clamped to the last real token, kv valid below length.
    positions = jnp.minimum(
        jnp.broadcast_to(jnp.arange(s)[None, :], (b, s)), (lengths - 1)[:, None]
    )
    got = flash_attention(q, k, v, lengths, interpret=True)
    want = _reference(q, k, v, positions, lengths)
    valid = np.arange(s)[None, :] < np.asarray(lengths)[:, None]
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(want)[valid], atol=2e-5, rtol=2e-5
    )


def test_decode_shape_matches_reference():
    """Decode-as-flash: one query row per head group against a long cache."""
    b, nh, kh, hd, m = 2, 8, 2, 64, 96
    q, k, v = _random_case(jax.random.PRNGKey(1), b, 1, m, nh, kh, hd)
    lengths = jnp.array([37, 96], jnp.int32)  # cache fill levels
    positions = (lengths - 1)[:, None]  # new token's position
    got = flash_attention(q, k, v, lengths, causal=False, interpret=True)
    want = _reference(q, k, v, positions, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_bf16_inputs_close_to_fp32_reference():
    b, s, nh, kh, hd = 1, 64, 4, 2, 64
    q, k, v = _random_case(jax.random.PRNGKey(2), b, s, s, nh, kh, hd)
    lengths = jnp.full((b,), s, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    got = flash_attention(
        q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16),
        lengths, interpret=True,
    )
    want = _reference(q, k, v, positions, lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want), atol=0.06, rtol=0.06
    )


def test_small_blocks_exercise_multiblock_accumulation():
    b, s, nh, kh, hd = 1, 64, 2, 2, 64
    q, k, v = _random_case(jax.random.PRNGKey(3), b, s, s, nh, kh, hd)
    lengths = jnp.array([50], jnp.int32)
    positions = jnp.minimum(
        jnp.broadcast_to(jnp.arange(s)[None, :], (b, s)), (lengths - 1)[:, None]
    )
    got = flash_attention(
        q, k, v, lengths, block_q=16, block_k=16, interpret=True
    )
    want = _reference(q, k, v, positions, lengths)
    valid = np.arange(s)[None, :] < np.asarray(lengths)[:, None]
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(want)[valid], atol=2e-5, rtol=2e-5
    )


def test_full_model_prefill_flash_vs_xla():
    """attention_impl='flash' (interpreted on CPU) matches 'xla' end-to-end."""
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params

    cfg = tiny_config("llama", num_heads=4, num_kv_heads=2, hidden_size=64,
                      intermediate_size=128, num_layers=2, vocab_size=128,
                      max_seq_len=64).replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, 128, jnp.int32)
    lengths = jnp.array([24, 17], jnp.int32)
    cache = init_kv_cache(cfg, 2)
    logits_flash, _ = forward_prefill(
        cfg.replace(attention_impl="flash"), params, tokens, lengths, cache)
    logits_xla, _ = forward_prefill(
        cfg.replace(attention_impl="xla"), params, tokens, lengths, cache)
    np.testing.assert_allclose(
        np.asarray(logits_flash), np.asarray(logits_xla), atol=1e-4, rtol=1e-4)


def test_flash_sliding_window_matches_attend():
    """Windowed flash (interpret) == windowed XLA attend, across window sizes
    including ones smaller than / equal to / spanning the block size."""
    from edgemesh.ops.attention import LayerKV, attend

    b, s, nh, kh, hd = 2, 48, 4, 2, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s, nh, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, kh, hd), jnp.float32)
    lens = jnp.asarray([s, s - 7], jnp.int32)
    kv_valid = jnp.arange(s)[None, :] < lens[:, None]
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))

    for w in (4, 16, 48):
        ref = attend(q, LayerKV(k, v), positions, kv_valid, sliding_window=w)
        out = flash_attention(
            q, k, v, lens, causal=True, block_q=16, block_k=16,
            interpret=True, sliding_window=w,
        )
        # Compare only real rows (flash computes padded rows too).
        for bb in range(b):
            n = int(lens[bb])
            np.testing.assert_allclose(
                np.asarray(out[bb, :n]), np.asarray(ref[bb, :n]),
                rtol=2e-5, atol=2e-5, err_msg=f"window={w} row={bb}",
            )


def test_windowed_model_flash_matches_xla():
    """A Mistral-style model forced onto the flash kernel must match its own
    XLA attend path exactly (prefill logits)."""
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params

    cfg_x = tiny_config("mistral", vocab_size=64, sliding_window=6,
                        max_seq_len=64, attention_impl="xla")
    cfg_f = cfg_x.replace(attention_impl="flash")
    params = init_params(cfg_x, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 64, jnp.int32)
    lengths = jnp.asarray([20, 13], jnp.int32)
    cache_x = init_kv_cache(cfg_x, 2, 40)
    cache_f = init_kv_cache(cfg_f, 2, 40)
    lx, _ = forward_prefill(cfg_x, params, tokens, lengths, cache_x)
    lf, _ = forward_prefill(cfg_f, params, tokens, lengths, cache_f)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lf), rtol=2e-4, atol=2e-4)


def test_flash_soft_cap_and_query_scale_match_attend():
    """Gemma-2's score soft cap and fixed query scale inside the kernel:
    interpret-mode flash must match the XLA attend with the same dials."""
    import jax

    from edgemesh.ops.attention import LayerKV, attend
    from edgemesh.ops.flash_attention import flash_attention

    b, s, nh, kh, hd = 2, 24, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, nh, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    kv_lens = jnp.asarray([s, s - 5], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    valid = jnp.arange(s)[None, :] < kv_lens[:, None]

    scale = 25.0**-0.5  # fixed query_pre_attn_scalar, != hd^-0.5
    for window in (0, 7):
        ref = attend(q, LayerKV(k, v), positions, valid, scale=scale,
                     sliding_window=window, soft_cap=50.0)
        got = flash_attention(
            q, k, v, kv_lens, causal=True, scale=scale, interpret=True,
            sliding_window=window, soft_cap=50.0,
        )
        rows = np.asarray(valid)
        np.testing.assert_allclose(
            np.asarray(got)[rows], np.asarray(ref)[rows], rtol=2e-5, atol=2e-5,
        )


def test_gemma2_prefill_flash_matches_xla():
    """End-to-end: gemma-2 prefill with attention_impl='flash' (interpret on
    CPU) equals the XLA path — the kernel honors all three attention dials."""
    import jax

    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params

    cfg = tiny_config("gemma2", vocab_size=128, max_seq_len=64,
                      dtype="float32").replace(sliding_window=6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 128, jnp.int32)
    lengths = jnp.asarray([20, 14], jnp.int32)

    ref, _ = forward_prefill(cfg.replace(attention_impl="xla"), params, tokens,
                             lengths, init_kv_cache(cfg, 2, 32))
    got, _ = forward_prefill(cfg.replace(attention_impl="flash"), params, tokens,
                             lengths, init_kv_cache(cfg, 2, 32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
