"""Sampling transforms: top-k, top-p, repetition penalty, greedy."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import SamplingParams
from edgemesh.ops.sampling import (
    NEG_INF,
    apply_repetition_penalty,
    apply_top_k,
    apply_top_p,
    sample_token,
)


def test_top_k_keeps_exactly_k():
    logits = jnp.array([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = apply_top_k(logits, 2)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [False, True, False, False, True]


def test_top_p_keeps_minimal_nucleus():
    # probs ~ [0.6, 0.3, 0.1] → p=0.8 keeps the first two
    logits = jnp.log(jnp.array([[0.6, 0.3, 0.1]]))
    out = apply_top_p(logits, 0.8)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [True, True, False]


def test_top_p_always_keeps_top_token():
    logits = jnp.log(jnp.array([[0.97, 0.02, 0.01]]))
    out = apply_top_p(logits, 0.5)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [True, False, False]


def test_repetition_penalty_sign_convention():
    # HF/CTRL convention: positive logits divided, negative multiplied.
    logits = jnp.array([[2.0, -2.0, 2.0]])
    mask = jnp.array([[True, True, False]])
    out = apply_repetition_penalty(logits, mask, 2.0)
    np.testing.assert_allclose(np.asarray(out[0]), [1.0, -4.0, 2.0])


def test_greedy_ignores_rng():
    logits = jnp.array([[0.1, 9.0, 0.2]])
    p = SamplingParams(do_sample=False, repetition_penalty=1.0)
    t1 = sample_token(jax.random.PRNGKey(0), logits, p)
    t2 = sample_token(jax.random.PRNGKey(1), logits, p)
    assert int(t1[0]) == int(t2[0]) == 1


def test_sampled_respects_top_k1():
    # top_k=1 == greedy regardless of temperature.
    logits = jnp.array([[0.1, 9.0, 0.2, 3.0]])
    p = SamplingParams(do_sample=True, top_k=1, temperature=5.0, top_p=1.0, repetition_penalty=1.0)
    for seed in range(5):
        t = sample_token(jax.random.PRNGKey(seed), logits, p)
        assert int(t[0]) == 1


def test_candidate_path_stays_inside_filtered_set():
    # The top-k candidate-set fast path must only ever emit tokens the
    # reference filter chain (apply_top_k then apply_top_p) would keep.
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (4, 64))
    p = SamplingParams(do_sample=True, top_k=8, top_p=0.7, temperature=0.9, repetition_penalty=1.0)
    ref = apply_top_p(apply_top_k(logits / p.temperature, p.top_k), p.top_p)
    allowed = np.asarray(ref > NEG_INF / 2)
    for seed in range(20):
        t = np.asarray(sample_token(jax.random.PRNGKey(seed), logits, p))
        assert all(allowed[b, t[b]] for b in range(4))


def test_candidate_path_matches_full_vocab_distribution():
    # Empirical frequencies from the [batch, k] candidate draw match the
    # softmax of the filtered full-vocab logits (same distribution, cheaper).
    logits = jnp.log(jnp.array([[0.45, 0.35, 0.15, 0.04, 0.01]]))
    p = SamplingParams(do_sample=True, top_k=3, top_p=1.0, temperature=1.0, repetition_penalty=1.0)
    draws = 4000
    keys = jax.random.split(jax.random.PRNGKey(1), draws)
    toks = np.asarray(
        jax.vmap(lambda k: sample_token(k, logits, p))(keys)
    ).ravel()
    freq = np.bincount(toks, minlength=5) / draws
    expect = np.array([0.45, 0.35, 0.15, 0.0, 0.0])
    expect = expect / expect.sum()
    np.testing.assert_allclose(freq, expect, atol=0.03)


def test_top_p_zero_degenerates_to_argmax_with_top_k():
    logits = jnp.array([[0.1, 9.0, 0.2, 3.0]])
    p = SamplingParams(do_sample=True, top_k=3, top_p=0.0, temperature=1.0, repetition_penalty=1.0)
    for seed in range(5):
        assert int(sample_token(jax.random.PRNGKey(seed), logits, p)[0]) == 1
