"""Sampling transforms: top-k, top-p, repetition penalty, greedy."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import SamplingParams
from edgemesh.ops.sampling import (
    NEG_INF,
    apply_repetition_penalty,
    apply_top_k,
    apply_top_p,
    sample_token,
)


def test_top_k_keeps_exactly_k():
    logits = jnp.array([[1.0, 5.0, 3.0, 2.0, 4.0]])
    out = apply_top_k(logits, 2)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [False, True, False, False, True]


def test_top_p_keeps_minimal_nucleus():
    # probs ~ [0.6, 0.3, 0.1] → p=0.8 keeps the first two
    logits = jnp.log(jnp.array([[0.6, 0.3, 0.1]]))
    out = apply_top_p(logits, 0.8)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [True, True, False]


def test_top_p_always_keeps_top_token():
    logits = jnp.log(jnp.array([[0.97, 0.02, 0.01]]))
    out = apply_top_p(logits, 0.5)
    kept = np.asarray(out[0] > NEG_INF / 2)
    assert kept.tolist() == [True, False, False]


def test_repetition_penalty_sign_convention():
    # HF/CTRL convention: positive logits divided, negative multiplied.
    logits = jnp.array([[2.0, -2.0, 2.0]])
    mask = jnp.array([[True, True, False]])
    out = apply_repetition_penalty(logits, mask, 2.0)
    np.testing.assert_allclose(np.asarray(out[0]), [1.0, -4.0, 2.0])


def test_greedy_ignores_rng():
    logits = jnp.array([[0.1, 9.0, 0.2]])
    p = SamplingParams(do_sample=False, repetition_penalty=1.0)
    t1 = sample_token(jax.random.PRNGKey(0), logits, p)
    t2 = sample_token(jax.random.PRNGKey(1), logits, p)
    assert int(t1[0]) == int(t2[0]) == 1


def test_sampled_respects_top_k1():
    # top_k=1 == greedy regardless of temperature.
    logits = jnp.array([[0.1, 9.0, 0.2, 3.0]])
    p = SamplingParams(do_sample=True, top_k=1, temperature=5.0, top_p=1.0, repetition_penalty=1.0)
    for seed in range(5):
        t = sample_token(jax.random.PRNGKey(seed), logits, p)
        assert int(t[0]) == 1


def test_candidate_path_stays_inside_filtered_set():
    # The top-k candidate-set fast path must only ever emit tokens the
    # reference filter chain (apply_top_k then apply_top_p) would keep.
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (4, 64))
    p = SamplingParams(do_sample=True, top_k=8, top_p=0.7, temperature=0.9, repetition_penalty=1.0)
    ref = apply_top_p(apply_top_k(logits / p.temperature, p.top_k), p.top_p)
    allowed = np.asarray(ref > NEG_INF / 2)
    for seed in range(20):
        t = np.asarray(sample_token(jax.random.PRNGKey(seed), logits, p))
        assert all(allowed[b, t[b]] for b in range(4))


def test_candidate_path_matches_full_vocab_distribution():
    # Empirical frequencies from the [batch, k] candidate draw match the
    # softmax of the filtered full-vocab logits (same distribution, cheaper).
    logits = jnp.log(jnp.array([[0.45, 0.35, 0.15, 0.04, 0.01]]))
    p = SamplingParams(do_sample=True, top_k=3, top_p=1.0, temperature=1.0, repetition_penalty=1.0)
    draws = 4000
    keys = jax.random.split(jax.random.PRNGKey(1), draws)
    toks = np.asarray(
        jax.vmap(lambda k: sample_token(k, logits, p))(keys)
    ).ravel()
    freq = np.bincount(toks, minlength=5) / draws
    expect = np.array([0.45, 0.35, 0.15, 0.0, 0.0])
    expect = expect / expect.sum()
    np.testing.assert_allclose(freq, expect, atol=0.03)


def test_top_p_zero_degenerates_to_argmax_with_top_k():
    logits = jnp.array([[0.1, 9.0, 0.2, 3.0]])
    p = SamplingParams(do_sample=True, top_k=3, top_p=0.0, temperature=1.0, repetition_penalty=1.0)
    for seed in range(5):
        assert int(sample_token(jax.random.PRNGKey(seed), logits, p)[0]) == 1


def test_min_p_filters_relative_to_top():
    """min-p keeps tokens with prob >= p * max_prob; softmax RATIOS are
    invariant to support restriction (exp of logit differences), so the
    candidate-set path and the vocab-wide path must agree exactly."""
    from edgemesh.ops.sampling import NEG_INF, apply_min_p, filtered_candidates

    logits = jnp.log(jnp.array([[0.5, 0.25, 0.2, 0.04, 0.01]]))
    out = apply_min_p(logits, 0.1)  # threshold 0.05: keeps 0.5/0.25/0.2
    kept = np.asarray(out[0]) > NEG_INF / 2
    np.testing.assert_array_equal(kept, [True, True, True, False, False])
    # p=0 disables
    np.testing.assert_array_equal(np.asarray(apply_min_p(logits, 0.0)), np.asarray(logits))

    # Candidate path: same keep set inside the top-k view.
    sp = SamplingParams(do_sample=True, top_k=4, top_p=1.0, min_p=0.1,
                        temperature=1.0, repetition_penalty=1.0)
    idx, probs = filtered_candidates(logits, sp)
    p = np.asarray(probs[0])
    assert (p[:3] > 0).all() and p[3] == 0.0  # 0.04 filtered within top-4
    np.testing.assert_allclose(p[:3], [0.5/0.95, 0.25/0.95, 0.2/0.95], rtol=1e-5)


def test_min_p_generate_end_to_end():
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime.generate import generate

    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 9, 11]], jnp.int32)
    sp = SamplingParams(max_new_tokens=5, do_sample=True, min_p=0.2,
                        temperature=0.8)
    r = generate(cfg, params, tokens, jnp.array([3]), sp)
    assert int(jnp.sum(r.num_generated)) == 5


def test_min_p_out_of_range_rejected():
    import pytest

    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(min_p=1.5)


def test_top_p_then_min_p_matches_hf_order():
    """Combined top_p+min_p must follow HF's warper order (TopP then MinP).
    probs [0.5, 0.2, 0.2, 0.1], top_p=0.75, min_p=0.3: HF keeps 3 tokens
    (top-p drops only the 0.1 tail; min-p threshold 0.15 keeps the rest).
    The reverse order would renormalize after min-p and drop the third
    token too (cum-exclusive 0.778 >= 0.75) — only 2 survivors."""
    from edgemesh.ops.sampling import NEG_INF, filtered_candidates

    logits = jnp.log(jnp.array([[0.5, 0.2, 0.2, 0.08, 0.02]]))
    sp = SamplingParams(do_sample=True, top_k=4, top_p=0.75, min_p=0.3,
                        temperature=1.0, repetition_penalty=1.0)
    idx, probs = filtered_candidates(logits, sp)
    p = np.asarray(probs[0])
    assert (p > 0).sum() == 3, p
    # Vocab-wide path agrees.
    counts = set()
    for seed in range(40):
        counts.add(int(sample_token(jax.random.PRNGKey(seed), logits,
                                    SamplingParams(do_sample=True, top_k=0,
                                                   top_p=0.75, min_p=0.3,
                                                   temperature=1.0,
                                                   repetition_penalty=1.0))[0]))
    assert counts <= {0, 1, 2} and len(counts) == 3, counts


def test_approx_top_k_candidate_path():
    """approx_top_k=True swaps exact lax.top_k for the TPU-native
    approx_max_k in the candidate fast path. Contract pinned here: rows
    stay descending-sorted (aggregate_to_topk re-ranks exactly, which
    _top_p_on_sorted requires) and the default stays EXACT (HF parity)."""
    from edgemesh.ops.sampling import filtered_candidates

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 1024), jnp.float32)
    sp = SamplingParams(do_sample=True, top_k=50, top_p=0.9, temperature=0.8,
                        approx_top_k=True)
    idx, probs = filtered_candidates(logits, sp)
    assert idx.shape == (4, 50) and probs.shape == (4, 50)
    p = np.asarray(probs)
    assert (p >= 0).all() and np.allclose(p.sum(-1), 1.0, atol=1e-5)
    # kept probs are descending where nonzero
    nz = p[0][p[0] > 0]
    assert (np.diff(nz) <= 1e-7).all()
    assert SamplingParams().approx_top_k is False
