"""Speculative accept-path health (the BENCH_r05 ``spec_accept_rate: 0.0``
regression, fast tier).

The load-bearing fact this file pins: the Leviathan accept wiring in
runtime/speculative.py is CORRECT — a draft identical to the target accepts
(essentially) every proposal, sampled and greedy. BENCH_r05's 0.0 came from
the bench's draft CONSTRUCTION (an unrelated random init whose top-k
candidate support is disjoint from the target's at large vocab), not from a
logit/position mismatch; edgemesh/benchmarks.py now truncates the target
instead and carries a draft==target ``selfcheck`` arm so the artifact
distinguishes machinery-broken from draft-weak. Kept fast-tier so the
accept path can never silently regress to all-reject again.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.runtime.speculative import generate_speculative


def _toy(vocab=64, layers=2):
    cfg = tiny_config("llama", vocab_size=vocab, max_seq_len=128).replace(
        num_layers=layers, dtype="float32"
    )
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _prompt(cfg, batch=1, s=12):
    tokens = jax.random.randint(
        jax.random.PRNGKey(7), (batch, s), 0, cfg.vocab_size, jnp.int32
    )
    return tokens, jnp.full((batch,), s, jnp.int32)


@pytest.mark.parametrize("do_sample", [True, False])
def test_draft_equals_target_accepts_everything(do_sample):
    cfg, params = _toy()
    tokens, lengths = _prompt(cfg)
    sampling = SamplingParams(
        max_new_tokens=16, temperature=0.7, top_k=16, top_p=0.9,
        repetition_penalty=1.2, do_sample=do_sample,
    )
    _, stats = generate_speculative(
        cfg, params, cfg, params, tokens, lengths, sampling, gamma=4
    )
    assert stats.proposed > 0
    # Identical models: q == p on every support, so u*q < p accepts w.p. 1.
    assert stats.accept_rate > 0.95, stats


def test_truncated_target_draft_accepts_some():
    """The bench's draft construction: the target's own first layers share
    its representation space, so acceptance is meaningfully above zero even
    with random weights — unlike the unrelated-init draft r05 measured."""
    cfg, params = _toy(layers=4)
    d_cfg = cfg.replace(num_layers=1)
    d_params = {
        **params, "layers": jax.tree.map(lambda x: x[:1], params["layers"])
    }
    tokens, lengths = _prompt(cfg)
    sampling = SamplingParams(
        max_new_tokens=24, temperature=0.7, top_k=16, top_p=0.9,
        repetition_penalty=1.2, do_sample=True,
    )
    _, stats = generate_speculative(
        cfg, params, d_cfg, d_params, tokens, lengths, sampling, gamma=4
    )
    assert stats.proposed > 0
    assert stats.accepted > 0, stats


def test_independent_draft_rejection_is_draft_not_wiring():
    """The r05 failure reproduced AND explained in one assertion pair: an
    unrelated random draft accepts (near) nothing, while the same wiring
    with draft==target accepts everything — the bench arm was measuring
    draft quality, not a positional bug."""
    cfg, params = _toy(vocab=256, layers=3)
    d_cfg = cfg.replace(num_layers=1)
    d_ind = init_params(d_cfg, jax.random.PRNGKey(9))
    tokens, lengths = _prompt(cfg)
    sampling = SamplingParams(
        max_new_tokens=16, temperature=0.7, top_k=8, top_p=0.9,
        repetition_penalty=1.2, do_sample=True,
    )
    _, ind = generate_speculative(
        cfg, params, d_cfg, d_ind, tokens, lengths, sampling, gamma=4
    )
    _, same = generate_speculative(
        cfg, params, cfg, params, tokens, lengths, sampling, gamma=4
    )
    assert same.accept_rate > 0.95
    assert ind.accept_rate < same.accept_rate
