"""Model-based embedding metrics (eval/embedder.py): the metrics-suite
embedder protocol served by a real model forward instead of the hashing
stand-in (VERDICT r1 missing #2)."""

import numpy as np
import pytest

from edgemesh.eval.embedder import ModelEmbedder, build_embedder
from edgemesh.eval.harness import score_sample
from edgemesh.eval.metrics import HashingEmbedder, bertscore, cosine_similarity



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

@pytest.fixture(scope="module")
def model_embedder():
    emb = build_embedder("synthetic")
    assert isinstance(emb, ModelEmbedder)
    return emb


def test_build_embedder_fallbacks():
    assert isinstance(build_embedder(""), HashingEmbedder)


def test_sentence_vectors_shape_and_norm(model_embedder):
    vecs = model_embedder(["what is the capital of france", "unrelated text"])
    assert vecs.shape == (2, model_embedder.dim)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0, atol=1e-5)


def test_identical_texts_cosine_one(model_embedder):
    assert cosine_similarity("the same text", "the same text", model_embedder) == pytest.approx(1.0, abs=1e-5)


def test_related_beats_unrelated(model_embedder):
    """Contextual embeddings must rank a near-duplicate above an unrelated
    string — the minimum semantic-signal bar."""
    a = "the capital of france is paris"
    near = "the capital city of france is paris"
    far = "zxqv jkwp mmnb ttyy"
    sim_near = cosine_similarity(a, near, model_embedder)
    sim_far = cosine_similarity(a, far, model_embedder)
    assert sim_near > sim_far


def test_token_embeddings_interface(model_embedder):
    toks, vecs = model_embedder.embed_tokens("hello world")
    assert len(toks) == vecs.shape[0] > 0
    assert vecs.shape[1] == model_embedder.dim
    bs = bertscore("hello world", "hello world", model_embedder.embed_tokens)
    assert bs["f1"] == pytest.approx(1.0, abs=1e-5)


def test_empty_text_does_not_crash(model_embedder):
    vecs = model_embedder(["", "x"])
    assert np.all(np.isfinite(vecs))
    bs = bertscore("", "reference", model_embedder.embed_tokens)
    assert bs["f1"] >= 0.0


def test_score_sample_accepts_model_embedder(model_embedder):
    row = score_sample("paris is the capital", "paris", embedder=model_embedder)
    for key in ("rouge1", "bleu", "cosine", "bertscore"):
        assert key in row and np.isfinite(row[key]), key


def test_deterministic_across_instances():
    """'synthetic' pins the init seed: two builds embed identically (resume
    safety — a resumed eval scores with the same embedder)."""
    a = build_embedder("synthetic")(["determinism check"])
    b = build_embedder("synthetic")(["determinism check"])
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_bucket_padding_consistency(model_embedder):
    """The same short text embeds (nearly) identically whether alone or next
    to a long neighbor that forces a bigger bucket — pooling must mask pads."""
    short = "short question"
    alone = model_embedder([short])
    longer = "w " * 100
    together = model_embedder([short, longer])
    np.testing.assert_allclose(alone[0], together[0], atol=1e-4)


def test_build_embedder_hosts_bert_checkpoint(tmp_path):
    """A MiniLM-class (bert model_type) checkpoint routes through the
    bidirectional encoder, and sentence vectors agree with mean-pooled HF
    BertModel states — the reference's actual cosine-metric recipe
    (combiner_fp.py:312-316)."""
    import pytest

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from transformers import BertConfig, BertModel, BertTokenizerFast

    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "the", "eiffel", "tower", "is", "in", "paris", "where", "##s"]
    (tmp_path / "vocab.txt").write_text("\n".join(vocab))
    tok = BertTokenizerFast(vocab_file=str(tmp_path / "vocab.txt"))
    tok.save_pretrained(tmp_path)

    hf_cfg = BertConfig(
        vocab_size=len(vocab), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64, max_position_embeddings=32,
    )
    torch.manual_seed(11)
    model = BertModel(hf_cfg, add_pooling_layer=False).eval()
    model.save_pretrained(tmp_path)

    emb = build_embedder(str(tmp_path), max_len=16)
    texts = ["the eiffel tower is in paris", "where is paris"]
    vecs = emb(texts)
    assert vecs.shape == (2, 32)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0, atol=1e-5)

    # HF reference: mean-pool last_hidden_state over the attention mask.
    enc = tok(texts, return_tensors="pt", padding=True)
    with torch.no_grad():
        hid = model(**enc).last_hidden_state.numpy()
    mask = enc["attention_mask"].numpy().astype(np.float32)
    pooled = (hid * mask[:, :, None]).sum(1) / mask.sum(1, keepdims=True)
    pooled /= np.linalg.norm(pooled, axis=1, keepdims=True)
    np.testing.assert_allclose(vecs, pooled, atol=2e-3)

    # Token-level protocol for BERTScore greedy matching works too.
    toks, tvecs = emb.embed_tokens("eiffel tower")
    assert len(toks) == tvecs.shape[0] > 0
