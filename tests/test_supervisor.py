"""Supervisor failure detection / restart, tracing registry, and the
/stats + supervised-generate REST surface (the Prometheus /metrics surface
is covered in tests/test_obs.py)."""

import json
import time
import urllib.request

import pytest

from edgemesh.serve.supervisor import Supervisor
from edgemesh.utils.tracing import JsonlLogger, phase_report, reset_phases, trace



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

class FlakyBackend:
    """Fails `fail_first` calls after each construction, then succeeds."""

    built = 0

    def __init__(self, fail_first: int):
        type(self).built += 1
        self.remaining_failures = fail_first

    def answer(self, q):
        if self.remaining_failures > 0:
            self.remaining_failures -= 1
            raise RuntimeError("backend exploded")
        return {"answer": f"ok:{q}"}


def _mk_supervisor(fail_first=0, **kw):
    FlakyBackend.built = 0
    # Only the FIRST instance is flaky — a rebuild comes back healthy.
    return Supervisor(
        factory=lambda: FlakyBackend(fail_first if FlakyBackend.built == 0 else 0),
        handler=lambda b, q: b.answer(q),
        **kw,
    )


def test_healthy_path_counts_requests(tmp_path):
    sup = _mk_supervisor(0, event_log=tmp_path / "ev.jsonl")
    assert sup.call("q1") == {"answer": "ok:q1"}
    h = sup.health()
    assert h["healthy"] and h["total_requests"] == 1 and h["total_failures"] == 0
    assert h["p50_latency_s"] is not None


def test_restart_after_consecutive_failures(tmp_path):
    sup = _mk_supervisor(3, max_consecutive_failures=3, event_log=tmp_path / "ev.jsonl")
    for _ in range(3):
        with pytest.raises(RuntimeError):
            sup.call("q")
    # Third failure tripped the restart: a fresh backend was built.
    assert FlakyBackend.built == 2
    assert sup.health()["restarts"] == 1
    assert sup.call("q2")["answer"] == "ok:q2"  # recovered
    events = [json.loads(line)["event"] for line in open(tmp_path / "ev.jsonl")]
    assert "restart" in events and "restart_ok" in events


def test_restart_budget_degrades_not_flaps():
    # Backend that ALWAYS fails: every rebuild starts broken.
    sup = Supervisor(
        factory=lambda: FlakyBackend(10**9),
        handler=lambda b, q: b.answer(q),
        max_consecutive_failures=1,
        max_restarts=2,
    )
    for _ in range(5):
        with pytest.raises(RuntimeError):
            sup.call("q")
    h = sup.health()
    assert h["degraded"] and not h["healthy"]
    assert h["restarts"] == 2  # budget respected, no infinite flapping
    assert "backend exploded" in h["last_error"]


def test_trace_accumulates_phases():
    reset_phases()
    with trace("unit/test-phase"):
        time.sleep(0.01)
    with trace("unit/test-phase"):
        time.sleep(0.01)
    rep = phase_report()["unit/test-phase"]
    assert rep["count"] == 2 and rep["total_s"] >= 0.02
    reset_phases()


def test_jsonl_logger_roundtrip(tmp_path):
    lg = JsonlLogger(tmp_path / "runs" / "log.jsonl")
    lg.log("begin", run=1)
    lg.log("end", run=1, ok=True)
    records = lg.read()
    assert [r["event"] for r in records] == ["begin", "end"]
    assert all("ts" in r for r in records)


def test_rest_stats_and_supervised_generate(tmp_path):
    from edgemesh.serve.rest import serve_rest

    class FakeEnsemble:
        qa_agents = []
        refiner = None

        def answer(self, q):
            raise AssertionError("should route through supervisor")

    sup = _mk_supervisor(0)
    server = serve_rest(FakeEnsemble(), host="127.0.0.1", port=0, block=False,
                        supervisor=sup)
    port = server.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"question": "hi"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.load(resp)["answer"] == "ok:hi"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as resp:
            payload = json.load(resp)
        assert payload["supervisor"]["total_requests"] == 1
        assert "phases" in payload
    finally:
        server.shutdown()
