"""edgemesh.obs.slo fast tier: SLO classification + goodput metrics, the
decayed latency quantile the router's auto-hedge reads, the stream meter,
the SpanTracker load-digest EWMAs, SLO replay, and the `edgemesh obs
summary` SLO report (including logs that predate the fields)."""

import json

import pytest

from edgemesh.obs import (
    DecayingQuantile,
    Registry,
    SloTarget,
    SloTracker,
    SpanTracker,
    StreamMeter,
    replay_spans,
)
from edgemesh.obs.spans import EWMA_ALPHA
from edgemesh.utils.tracing import JsonlLogger

# ---------------------------------------------------------------------------
# SloTracker classification
# ---------------------------------------------------------------------------


def test_slo_classification_table():
    t = SloTracker(Registry(), engine="unit",
                   target=SloTarget(ttft_s=1.0, tpot_s=0.1))
    assert t.classify("ok", 0.5, 0.05) == "good"
    assert t.classify("ok", 2.0, 0.05) == "ttft"
    assert t.classify("ok", 0.5, 0.5) == "tpot"
    assert t.classify("ok", 2.0, 0.5) == "ttft_tpot"
    assert t.classify("error", 0.5, 0.05) == "error"
    # No first token ever = a TTFT miss by definition; a single-token
    # answer (tpot None) cannot miss TPOT.
    assert t.classify("ok", None, None) == "ttft"
    assert t.classify("ok", 0.5, None) == "good"


def test_slo_tracker_feeds_counters_and_goodput_gauge():
    reg = Registry()
    t = SloTracker(reg, engine="unit", target=SloTarget(1.0, 0.1))
    assert t.goodput_ratio() is None  # nothing classified yet
    t.record("ok", 0.5, 0.05)
    t.record("ok", 0.5, 0.05)
    t.record("ok", 5.0, 0.05)
    t.record("error", None, None)
    s = reg.summary()
    assert s['edgemesh_slo_requests_total{engine="unit",result="good"}'] == 2
    assert s['edgemesh_slo_requests_total{engine="unit",result="ttft"}'] == 1
    assert s['edgemesh_slo_requests_total{engine="unit",result="error"}'] == 1
    assert s['edgemesh_slo_goodput_ratio{engine="unit"}'] == 0.5
    assert t.goodput_ratio() == 0.5
    # The active target is scrapeable alongside the verdicts.
    assert s['edgemesh_slo_target_seconds{engine="unit",kind="ttft"}'] == 1.0
    assert s['edgemesh_slo_target_seconds{engine="unit",kind="tpot"}'] == 0.1


def test_slo_target_from_env(monkeypatch):
    monkeypatch.setenv("EDGEMESH_SLO_TTFT_S", "0.75")
    monkeypatch.setenv("EDGEMESH_SLO_TPOT_S", "0.05")
    t = SloTarget.from_env()
    assert t.ttft_s == 0.75 and t.tpot_s == 0.05
    # Garbage / non-positive values fall back to defaults, never raise.
    monkeypatch.setenv("EDGEMESH_SLO_TTFT_S", "soon")
    monkeypatch.setenv("EDGEMESH_SLO_TPOT_S", "-1")
    t = SloTarget.from_env()
    assert t.ttft_s == SloTarget().ttft_s and t.tpot_s == SloTarget().tpot_s


# ---------------------------------------------------------------------------
# DecayingQuantile (the auto-hedge estimator)
# ---------------------------------------------------------------------------


def test_decaying_quantile_gates_on_min_weight_then_answers():
    clock = {"t": 0.0}
    dq = DecayingQuantile(half_life_s=10.0, min_weight=16.0,
                          now=lambda: clock["t"])
    for _ in range(10):
        dq.observe(0.01)
    assert dq.quantile(0.95) is None  # 10 < min_weight: not armed
    for _ in range(30):
        dq.observe(0.01)
    p95 = dq.quantile(0.95)
    assert p95 is not None and 0.005 <= p95 <= 0.02


def test_decaying_quantile_forgets_the_old_regime():
    clock = {"t": 0.0}
    dq = DecayingQuantile(half_life_s=5.0, min_weight=8.0,
                          now=lambda: clock["t"])
    for _ in range(100):
        dq.observe(0.01)  # fast regime
    clock["t"] = 50.0  # 10 half-lives: the fast samples are ~0.1 weight
    for _ in range(20):
        dq.observe(1.0)  # slow regime
    p50 = dq.quantile(0.50)
    assert p50 is not None and p50 > 0.5, p50
    # Weight reflects decay, not raw counts.
    assert dq.weight() < 25


def test_decaying_quantile_overflow_bucket_answers_top_bound():
    dq = DecayingQuantile(min_weight=4.0)
    for _ in range(10):
        dq.observe(10_000.0)  # beyond every bound
    assert dq.quantile(0.5) == dq.bounds[-1]


# ---------------------------------------------------------------------------
# StreamMeter (runtime/stream.py → the serving histograms)
# ---------------------------------------------------------------------------


def test_stream_meter_records_ttft_tpot_and_slo():
    reg = Registry()
    m = StreamMeter(reg, engine="stream", target=SloTarget(1.0, 0.1))
    m.chunk(0.2, 4)    # first token-bearing chunk → TTFT only
    m.chunk(0.4, 4)    # 0.05/token
    m.chunk(0.6, 4)
    m.chunk(0.6, 0)    # empty chunk: no observations
    assert m.finish("ok") == "good"
    s = reg.summary()
    ttft = s['edgemesh_ttft_seconds{engine="stream"}']
    assert ttft["count"] == 1 and ttft["sum"] == pytest.approx(0.2)
    tpot = s['edgemesh_inter_token_seconds{engine="stream"}']
    assert tpot["count"] == 8  # two post-first chunks, weighted by tokens
    assert tpot["sum"] / tpot["count"] == pytest.approx(0.05)
    assert s['edgemesh_slo_goodput_ratio{engine="stream"}'] == 1.0


def test_stream_meter_goodput_accumulates_across_streams():
    # One SloTracker per (registry, engine): fresh meters (one per stream)
    # must feed a RUNNING goodput ratio, not reset the gauge to the last
    # stream's lone verdict.
    reg = Registry()
    target = SloTarget(ttft_s=1.0, tpot_s=10.0)
    m1 = StreamMeter(reg, engine="stream", target=target)
    m1.chunk(0.1, 2)
    assert m1.finish("ok") == "good"
    m2 = StreamMeter(reg, engine="stream", target=target)
    m2.chunk(5.0, 2)  # late first token
    assert m2.finish("ok") == "ttft"
    s = reg.summary()
    assert s['edgemesh_slo_goodput_ratio{engine="stream"}'] == 0.5
    assert s['edgemesh_slo_requests_total{engine="stream",result="good"}'] == 1
    assert s['edgemesh_slo_requests_total{engine="stream",result="ttft"}'] == 1


def test_stream_meter_misses_are_classified():
    m = StreamMeter(Registry(), engine="stream", target=SloTarget(0.1, 0.01))
    m.chunk(0.5, 2)   # late first token
    m.chunk(1.5, 2)   # 0.5/token
    assert m.finish("ok") == "ttft_tpot"
    # A stream that never produced a token misses TTFT.
    m2 = StreamMeter(Registry(), engine="stream", target=SloTarget(0.1, 0.01))
    assert m2.finish("ok") == "ttft"


# ---------------------------------------------------------------------------
# SpanTracker: EWMA load digest + slo_result in the span record + replay
# ---------------------------------------------------------------------------


def _drive(tracker, rid, segs=(3, 2), status="ok"):
    tr = tracker.submit(rid)
    tracker.admit_start(tr)
    tracker.admitted(tr, prompt_tokens=5)
    for n in segs:
        tracker.tokens(tr, n)
    tracker.retire(tr, status=status)


def test_span_tracker_load_digest_populates_and_smooths():
    tracker = SpanTracker(Registry(), engine="unit")
    d0 = tracker.load_digest()
    assert d0["ewma_queue_s"] is None and d0["slo_goodput_ratio"] is None
    _drive(tracker, 0)
    d1 = tracker.load_digest()
    for key in ("ewma_queue_s", "ewma_prefill_s", "ewma_decode_s",
                "ewma_service_s"):
        assert d1[key] is not None and d1[key] >= 0.0
    assert d1["slo_goodput_ratio"] == 1.0
    # The EWMA blend rule itself: alpha*new + (1-alpha)*old.
    tracker._ewma_update(service=1.0)
    tracker._ewma_update(service=0.0)
    expected = (1.0 - EWMA_ALPHA) * (
        EWMA_ALPHA * 1.0 + (1.0 - EWMA_ALPHA) * d1["ewma_service_s"]
    )
    assert tracker.load_digest()["ewma_service_s"] == pytest.approx(
        expected, abs=1e-6)


def test_span_record_carries_slo_result_and_replays(tmp_path):
    reg = Registry()
    tracker = SpanTracker(reg, tmp_path / "spans.jsonl", engine="unit",
                          slo_target=SloTarget(ttft_s=10.0, tpot_s=10.0))
    _drive(tracker, 0)
    _drive(tracker, 1, status="error")
    records = JsonlLogger(tmp_path / "spans.jsonl").read()
    assert [r["slo_result"] for r in records] == ["good", "error"]
    offline = replay_spans(tmp_path / "spans.jsonl").summary()
    live = reg.summary()
    for key in (
        'edgemesh_slo_requests_total{engine="unit",result="good"}',
        'edgemesh_slo_requests_total{engine="unit",result="error"}',
        'edgemesh_slo_goodput_ratio{engine="unit"}',
    ):
        assert offline[key] == live[key], key


def test_replay_tolerates_pre_slo_logs(tmp_path):
    # A log written before the slo_result field: replay simply skips the
    # SLO family instead of guessing or crashing.
    log = JsonlLogger(tmp_path / "old.jsonl")
    log.log("request_spans", rid=0, engine="unit", status="ok", generated=4,
            queue_s=0.01, prefill_s=0.02, ttft_s=0.05, itl_s=0.004,
            latency_s=0.2, spans=[])
    reg = replay_spans(tmp_path / "old.jsonl")
    s = reg.summary()
    assert s['edgemesh_requests_submitted_total{engine="unit"}'] == 1
    # No verdicts invented: the request/goodput families stay empty (the
    # target gauges register eagerly and are harmless).
    assert not any(k.startswith("edgemesh_slo_requests_total") for k in s)
    assert not any(k.startswith("edgemesh_slo_goodput_ratio") for k in s)


# ---------------------------------------------------------------------------
# `edgemesh obs summary` SLO report
# ---------------------------------------------------------------------------


def test_obs_summary_reports_ttft_tpot_and_goodput(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    tracker = SpanTracker(Registry(), tmp_path / "spans.jsonl", engine="cli",
                          slo_target=SloTarget(ttft_s=10.0, tpot_s=10.0))
    for rid in range(3):
        _drive(tracker, rid)
    assert obs_main(["summary", str(tmp_path / "spans.jsonl")]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 3
    assert report["ttft_s_p99"] > 0
    assert report["tpot_s_p50"] > 0 and report["tpot_s_p99"] > 0
    assert report["slo_classified"] == 3
    assert report["slo_goodput_ratio"] == 1.0
    assert report["metrics"][
        'edgemesh_slo_requests_total{engine="cli",result="good"}'] == 3


def test_obs_summary_pre_slo_log_is_rc0_with_nulls(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    log = JsonlLogger(tmp_path / "old.jsonl")
    log.log("request_spans", rid=0, engine="unit", status="ok", generated=2,
            latency_s=0.2, ttft_s=0.05, spans=[])
    assert obs_main(["summary", str(tmp_path / "old.jsonl")]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 1
    assert report["slo_classified"] == 0
    assert report["slo_goodput_ratio"] is None
    assert report["tpot_s_p50"] is None
