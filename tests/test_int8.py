"""Int8 quantization: numerics of all three matmul paths + end-to-end quality.

The acceptance bar mirrors BASELINE.md: int8 must preserve quality (the
reference's Combo quant deltas were ≤0.0002 absolute) — here pinned as logits
closeness and end-to-end greedy-token agreement on a tiny model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models import init_params
from edgemesh.models.families import tiny_config
from edgemesh.ops.int8 import (
    dequantize_weight,
    int8_matmul,
    int8_matmul_dynamic,
    is_quantized,
    pallas_int8_matmul,
    quantize_activations,
    quantize_params,
    quantize_weight,
)
from edgemesh.runtime import generate



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def test_quantize_weight_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32)) * 0.1
    q, scales = quantize_weight(w)
    assert q.dtype == jnp.int8
    assert scales.shape == (32,)
    w2 = dequantize_weight(q, scales, jnp.float32)
    # per-channel symmetric quant: max error is scale/2 per element
    max_err = np.max(np.abs(np.asarray(w2) - np.asarray(w)))
    assert max_err <= float(jnp.max(scales)) * 0.51


def test_quantize_activations_range():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64)) * 3.0
    q, scale = quantize_activations(x)
    assert q.dtype == jnp.int8
    assert int(jnp.max(jnp.abs(q))) <= 127
    x2 = np.asarray(q, np.float32) * np.asarray(scale)
    np.testing.assert_allclose(x2, np.asarray(x), atol=float(scale.max()) * 0.51)


def test_int8_matmul_close_to_fp():
    k = jax.random.PRNGKey(2)
    x = jax.random.normal(k, (8, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (64, 32), jnp.float32) * 0.05
    ref = x @ w
    q, scales = quantize_weight(w)
    got = int8_matmul(x, q, scales)
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.01, rel


def test_int8_matmul_dynamic_close_to_fp():
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(5), (64, 32), jnp.float32) * 0.05
    ref = x @ w
    q, scales = quantize_weight(w)
    got = int8_matmul_dynamic(x, q, scales)
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.02, rel


def test_pallas_int8_matmul_interpret_matches_xla():
    """The Pallas kernel (interpret mode on CPU) must match the XLA w8a8 path
    tile-for-tile. Uses multi-tile shapes to exercise the K-loop accumulator."""
    x = jax.random.normal(jax.random.PRNGKey(6), (256, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(7), (256, 256), jnp.float32) * 0.05
    q, scales = quantize_weight(w)
    got = pallas_int8_matmul(x, q, scales, tile_m=128, tile_n=128, tile_k=128, interpret=True)
    ref = x @ w
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.02, rel


def test_quantize_params_structure_and_generate():
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    assert is_quantized(qparams) and not is_quantized(params)
    # embeddings/norms untouched, dense leaves transformed
    assert "weight" in qparams["embed"]
    assert "kernel_q" in qparams["layers"]["q"]
    assert "kernel" not in qparams["layers"]["q"]

    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6])
    # int8 quality bar: prefill logits stay close to fp (random-init tiny
    # models have near-flat logits, so token-level agreement is chaotic — the
    # right signal is logit closeness; end-to-end ROUGE deltas are checked on
    # real weights in the integration path).
    from edgemesh.models.transformer import forward_prefill, init_kv_cache

    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 1, 16))
    got, _ = forward_prefill(cfg, qparams, tokens, lengths, init_kv_cache(cfg, 1, 16))
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.05, rel
    # and the quantized model still generates cleanly
    sp = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    r_q = generate(cfg, qparams, tokens, lengths, sp)
    assert int(jnp.sum(r_q.num_generated)) == 8


def test_smoothquant_scales_applied():
    cfg = tiny_config("llama", num_layers=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    h = cfg.hidden_size
    smooth = {"layers": {"q": jnp.full((1, h), 2.0), "gate": jnp.full((1, h), 4.0)}}
    qparams = quantize_params(params, smooth_scales=smooth, alpha=0.5)
    assert "smooth" in qparams["layers"]["q"]
    assert "smooth" not in qparams["layers"]["o"]
    # numerics: dense(smooth) ≈ dense(fp) since W*s then x/s cancels
    from edgemesh.models.transformer import dense

    x = jax.random.normal(jax.random.PRNGKey(2), (2, h), jnp.float32)
    y_fp = x @ params["layers"]["q"]["kernel"][0]
    y_q = dense(jax.tree.map(lambda a: a[0], qparams["layers"]["q"]), x)
    rel = np.linalg.norm(np.asarray(y_q) - np.asarray(y_fp)) / np.linalg.norm(np.asarray(y_fp))
    assert rel < 0.02, rel


def test_int8_matmul_fused_matches_dynamic():
    """Fused-entry wrapper: ND input, M padding, and K/N tile fallback."""
    from edgemesh.ops.int8 import int8_matmul_fused

    w = jax.random.normal(jax.random.PRNGKey(4), (128, 128), jnp.float32) * 0.05
    q, scales = quantize_weight(w)
    # M=3 forces sublane padding; 3D input exercises the reshape.
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 3, 128), jnp.float32)
    got = int8_matmul_fused(x, q, scales, interpret=True)
    ref = int8_matmul_dynamic(x.reshape(3, 128), q, scales).reshape(1, 3, 128)
    assert got.shape == (1, 3, 128)
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    # Block-local vs whole-row activation scales: small but nonzero delta.
    assert rel < 0.02, rel
    # N not a multiple of 128 -> silently routes to the XLA dynamic path.
    w2 = jax.random.normal(jax.random.PRNGKey(6), (128, 96), jnp.float32) * 0.05
    q2, s2 = quantize_weight(w2)
    got2 = int8_matmul_fused(x, q2, s2, interpret=True)
    ref2 = int8_matmul_dynamic(x.reshape(3, 128), q2, s2).reshape(1, 3, 96)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2), rtol=1e-5, atol=1e-5)


def test_int8_matmul_prequant_matches_dynamic_exact():
    """The pre-quantized Pallas path computes the SAME contraction as the XLA
    w8a8 path — identical whole-row activation scales, identical int32
    accumulation — so outputs must agree to float rounding, not just int8
    tolerance (unlike the block-local-quant fused kernel)."""
    from edgemesh.ops.int8 import int8_matmul_prequant

    w = jax.random.normal(jax.random.PRNGKey(7), (128, 128), jnp.float32) * 0.05
    q, scales = quantize_weight(w)
    x = jax.random.normal(jax.random.PRNGKey(8), (1, 3, 128), jnp.float32)
    got = int8_matmul_prequant(x, q, scales, interpret=True)
    ref = int8_matmul_dynamic(x.reshape(3, 128), q, scales).reshape(1, 3, 128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6, atol=1e-6)
    # N not tileable -> routes to the XLA dynamic path.
    w2 = jax.random.normal(jax.random.PRNGKey(9), (128, 96), jnp.float32) * 0.05
    q2, s2 = quantize_weight(w2)
    got2 = int8_matmul_prequant(x, q2, s2, interpret=True)
    ref2 = int8_matmul_dynamic(x.reshape(3, 128), q2, s2).reshape(1, 3, 96)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2), rtol=1e-5, atol=1e-5)


def test_prequant_multi_k_stripe_int32_accumulator():
    """Multi-K-stripe grid: the int32 scratch accumulator across K steps must
    reproduce the single-pass contraction exactly (int32 addition is
    associative — no float accumulation drift by construction)."""
    from edgemesh.ops.int8 import (
        pallas_int8_prequant_matmul,
        quantize_activations,
    )

    w = jax.random.normal(jax.random.PRNGKey(10), (256, 128), jnp.float32) * 0.05
    q, scales = quantize_weight(w)
    x = jax.random.normal(jax.random.PRNGKey(11), (32, 256), jnp.float32)
    x_q, x_scale = quantize_activations(x)
    got = pallas_int8_prequant_matmul(
        x_q, x_scale, q, scales, out_dtype=jnp.float32,
        tile_m=32, tile_n=128, tile_k=128, interpret=True,
    )
    ref = int8_matmul_dynamic(x, q, scales)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("quant_mode", ["w8a8", "w8a8_pallas", "w8a8_pallas_pre"])
def test_w8a8_model_forward_close_to_fp(quant_mode):
    """Model-level parity for the activation-quantized paths (the headline
    int8 execution modes): quantized prefill logits stay close to fp."""
    from edgemesh.models.transformer import forward_prefill, init_kv_cache

    cfg = tiny_config("llama", num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    qcfg = cfg.replace(quant_mode=quant_mode)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 6])
    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 2, 16))
    got, _ = forward_prefill(qcfg, qparams, tokens, lengths, init_kv_cache(cfg, 2, 16))
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.08, (quant_mode, rel)
    # and the w8a8 model decodes end-to-end
    sp = SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0)
    r = generate(qcfg, qparams, tokens, lengths, sp)
    assert int(jnp.sum(r.num_generated)) == 8


def test_quantize_embedding_gather_and_tied_head():
    """int8 embedding: the gather-dequant lookup and the tied w8a16 head both
    see the same dequantized rows, and model outputs stay close to the
    bf16-embedding model (the quantized table is ~0.4% relative error)."""
    import jax

    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import embed_tokens, init_params, lm_head_logits
    from edgemesh.ops.int8 import embedding_table, quantize_embedding

    cfg = tiny_config("llama", vocab_size=128, tie_embeddings=True, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    qp = quantize_embedding(params)
    assert set(qp["embed"]) == {"weight_q", "scales"}
    assert qp["embed"]["weight_q"].dtype == jnp.int8

    table = embedding_table(qp["embed"], jnp.float32)
    # Table error bounded by half a quantization step per row.
    step = np.asarray(qp["embed"]["scales"])[:, None]
    assert (np.abs(np.asarray(table - params["embed"]["weight"])) <= 0.5 * step + 1e-6).all()

    tokens = jnp.asarray([[3, 77, 12, 99]], jnp.int32)
    # Gather path returns exactly the dequantized table rows.
    looked = embed_tokens(cfg, qp, tokens)
    np.testing.assert_allclose(
        np.asarray(looked), np.asarray(table)[np.asarray(tokens)], rtol=1e-6, atol=1e-6
    )
    # Tied head path matches an explicit x @ dequant(W).T within fp tolerance.
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 4, cfg.hidden_size), jnp.float32)
    got = lm_head_logits(cfg, qp, x)
    want = lm_head_logits(cfg, {**qp, "embed": {"weight": table}}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_generate_with_quantized_embedding_runs():
    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.ops.int8 import quantize_embedding
    from edgemesh.runtime import generate

    cfg = tiny_config("llama", vocab_size=128, tie_embeddings=True, dtype="float32")
    params = quantize_embedding(quantize_params(init_params(cfg, jax.random.PRNGKey(0))))
    tokens = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    out = generate(
        cfg, params, tokens, jnp.asarray([4], jnp.int32),
        SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0),
    )
    assert out.tokens.shape == (1, 8)
    assert int(out.num_generated[0]) == 8


def test_fused_single_k_stripe_matches_dynamic():
    """The nk==1 fast path (tile_k == K, no scratch accumulator) must agree
    with the XLA dynamic path to block-quantization tolerance."""
    import numpy as np

    from edgemesh.ops.int8 import int8_matmul_dynamic, int8_matmul_fused, quantize_weight

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (256, 512), jnp.float32)
    w_q, scales = quantize_weight(w)
    got = int8_matmul_fused(x, w_q, scales, interpret=True)
    ref = int8_matmul_dynamic(x, w_q, scales)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=0.05, atol=0.05)


def test_measure_w8a8_mode_off_tpu_is_xla():
    """Off-TPU the auto-pick must resolve to the XLA path without running
    interpret-mode timings."""
    from edgemesh.ops.int8 import measure_w8a8_mode, quantize_params
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params

    cfg = tiny_config("llama")
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    assert measure_w8a8_mode(params) == "w8a8"


def test_w8a8_auto_precision_builds_agent():
    """precision int8_w8a8_auto materializes with the measured quant_mode
    (w8a8 on CPU) and generates."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec

    agent = build_agent(AgentSpec(role="qa", model=ModelSpec(
        precision="int8_w8a8_auto", num_layers=2, hidden_size=64)))
    assert agent.cfg.quant_mode == "w8a8"
    assert "kernel_q" in agent.params["layers"]["q"]
    out = agent.answer("Where is the Louvre?")
    assert isinstance(out["answer"], str)


def test_prefill_quant_mode_runs_per_phase():
    """prefill_quant_mode compiles prefill as a different int8 path than
    decode; generation stays finite and deterministic under greedy."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.ops.int8 import quantize_params
    from edgemesh.runtime.generate import generate

    cfg = tiny_config("llama", num_layers=2, vocab_size=64,
                      hidden_size=32, num_heads=4, num_kv_heads=2,
                      intermediate_size=64).replace(dtype="float32")
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    tokens = jnp.array([[5, 9, 11, 42, 7]], jnp.int32)
    lengths = jnp.array([5], jnp.int32)
    sp = SamplingParams(max_new_tokens=6, temperature=0.0)
    mixed = cfg.replace(quant_mode="w8a8", prefill_quant_mode="w8a16")
    r = generate(mixed, params, tokens, lengths, sp, rng=jax.random.PRNGKey(1))
    assert np.isfinite(np.asarray(r.confidence)).all()
    assert int(r.num_generated[0]) == 6
    # Same-mode override is a no-op vs the plain config.
    same = cfg.replace(quant_mode="w8a8", prefill_quant_mode="w8a8")
    plain = cfg.replace(quant_mode="w8a8")
    a = generate(same, params, tokens, lengths, sp, rng=jax.random.PRNGKey(1))
    b = generate(plain, params, tokens, lengths, sp, rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
