"""Paged KV cache: allocator, scatter writes, kernel numerics, and
end-to-end generate_paged parity with the dense-cache generate()."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
)
from edgemesh.runtime.generate import generate
from edgemesh.runtime.paged_generate import generate_paged
from edgemesh.runtime.paged_kv import (
    allocate,
    gather_dense,
    init_paged_cache,
    pages_needed,
    write_tokens,
)


import pytest

# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _cfg(**kw):
    base = dict(num_heads=4, num_kv_heads=2, hidden_size=32,
                intermediate_size=64, num_layers=2, vocab_size=64, max_seq_len=64)
    base.update(kw)
    return tiny_config("llama", **base).replace(dtype="float32")


def test_allocator_assigns_distinct_pages():
    cfg = _cfg()
    cache = init_paged_cache(cfg, batch=3, total_pages=16, page_size=8, max_pages=4)
    cache = allocate(cache, jnp.array([2, 1, 3], jnp.int32))
    table = np.asarray(cache.page_table)
    used = [table[0, :2], table[1, :1], table[2, :3]]
    flat = np.concatenate(used)
    assert len(set(flat.tolist())) == 6, flat  # all distinct
    assert (flat > 0).all(), "trash page handed out"
    assert int(cache.free_top) == 7  # 1 (trash skip) + 6 popped
    # Unallocated slots still point at trash.
    assert table[1, 1] == 0 and table[0, 2] == 0


def test_allocator_appends_after_existing_pages():
    cfg = _cfg()
    cache = init_paged_cache(cfg, batch=2, total_pages=16, page_size=8, max_pages=4)
    cache = allocate(cache, jnp.array([1, 1], jnp.int32))
    first = np.asarray(cache.page_table).copy()
    # Row 0 now holds 8 tokens (page full) → next token needs a new page.
    cache = cache._replace(lengths=jnp.array([8, 3], jnp.int32))
    need = pages_needed(cache.lengths, jnp.ones((2,), jnp.int32), 8)
    np.testing.assert_array_equal(np.asarray(need), [1, 0])
    cache = allocate(cache, need)
    table = np.asarray(cache.page_table)
    assert table[0, 0] == first[0, 0] and table[0, 1] > 0  # appended, not replaced
    assert table[1, 1] == 0  # row 1 untouched


def test_write_then_gather_roundtrip():
    cfg = _cfg()
    b, s, kh, hd, ps = 2, 10, 2, 8, 4
    cache = init_paged_cache(cfg.replace(num_kv_heads=kh, head_dim=hd),
                             batch=b, total_pages=16, page_size=ps, max_pages=4)
    lengths = jnp.array([10, 6], jnp.int32)
    cache = allocate(cache, pages_needed(cache.lengths, lengths, ps))
    k = jax.random.normal(jax.random.PRNGKey(0), (b, s, kh, hd))
    v = jax.random.normal(jax.random.PRNGKey(1), (b, s, kh, hd))
    kp, vp = write_tokens(
        cache.k[0], cache.v[0], k, v, cache.page_table,
        start=jnp.zeros((b,), jnp.int32), valid_len=lengths,
    )
    dense_k = np.asarray(gather_dense(kp, cache.page_table))  # [b, 16, kh, hd]
    for i, ln in enumerate([10, 6]):
        np.testing.assert_allclose(dense_k[i, :ln], np.asarray(k)[i, :ln], rtol=1e-6)


def test_paged_kernel_matches_xla_oracle():
    b, nh, kh, hd, ps, mp = 2, 8, 2, 64, 16, 4
    cfg = _cfg(num_heads=nh, num_kv_heads=kh, head_dim=hd)
    cache = init_paged_cache(cfg, batch=b, total_pages=12, page_size=ps, max_pages=mp)
    kv_lens = jnp.array([50, 17], jnp.int32)
    cache = allocate(cache, pages_needed(cache.lengths, kv_lens, ps))
    k = jax.random.normal(jax.random.PRNGKey(0), (b, 50, kh, hd))
    v = jax.random.normal(jax.random.PRNGKey(1), (b, 50, kh, hd))
    kp, vp = write_tokens(cache.k[0], cache.v[0], k, v, cache.page_table,
                          start=jnp.zeros((b,), jnp.int32), valid_len=kv_lens)
    q = jax.random.normal(jax.random.PRNGKey(2), (b, nh, hd))
    got = paged_decode_attention(q, kp, vp, cache.page_table, kv_lens, interpret=True)
    want = paged_decode_attention_xla(q, kp, vp, cache.page_table, kv_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_generate_paged_matches_dense_generate():
    """Greedy decode across page boundaries == dense-cache generate()."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = jnp.array([[5, 9, 11, 42, 7, 0, 0], [17, 3, 50, 8, 33, 21, 2]], jnp.int32)
    lengths = jnp.array([5, 7], jnp.int32)
    sp = SamplingParams(max_new_tokens=14, temperature=0.0)
    dense = generate(cfg, params, prompts, lengths, sp, rng=jax.random.PRNGKey(7))
    # page_size=4 → prompt spans 2 pages, decode crosses several boundaries.
    paged = generate_paged(cfg, params, prompts, lengths, sp,
                           rng=jax.random.PRNGKey(7), page_size=4)
    np.testing.assert_array_equal(np.asarray(dense.tokens), np.asarray(paged.tokens))
    np.testing.assert_allclose(np.asarray(dense.confidence),
                               np.asarray(paged.confidence), atol=1e-5)


def test_generate_paged_pool_exhaustion_raises():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = jnp.array([[5, 9, 11]], jnp.int32)
    lengths = jnp.array([3], jnp.int32)
    cache = init_paged_cache(cfg, batch=1, total_pages=2, page_size=4, max_pages=8)
    try:
        generate_paged(cfg, params, prompts, lengths,
                       SamplingParams(max_new_tokens=20), cache=cache)
        raise AssertionError("expected pool-exhaustion ValueError")
    except ValueError as e:
        assert "page pool exhausted" in str(e)


def test_paged_cache_head_sharding_on_mesh():
    """generate-paged forward under tp sharding of the page pool (8-dev CPU
    mesh): head-wise sharded pages produce the same logits as unsharded."""
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.sharding import shard_paged_cache, paged_cache_pspecs
    from edgemesh.runtime.paged_generate import forward_prefill_paged

    cfg = _cfg(num_heads=8, num_kv_heads=4, hidden_size=64, intermediate_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 9, 11, 42, 7, 3], [17, 3, 50, 8, 33, 2]], jnp.int32)
    lengths = jnp.array([6, 5], jnp.int32)

    plain = init_paged_cache(cfg, batch=2, total_pages=9, page_size=4, max_pages=4)
    want, _ = forward_prefill_paged(cfg, params, tokens, lengths, plain)

    mesh = build_mesh(dp=2, tp=4)
    specs = paged_cache_pspecs(cfg, mesh)
    assert specs.k == jax.sharding.PartitionSpec(None, None, "tp", None, None)
    sharded = shard_paged_cache(plain, cfg, mesh)
    got, out_cache = forward_prefill_paged(cfg, params, tokens, lengths, sharded)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=1e-5, rtol=1e-5)

    # Int8 pool: same head-wise sharding covers the scale arrays too.
    from edgemesh.runtime.paged_kv import init_quant_paged_cache

    qplain = init_quant_paged_cache(cfg, batch=2, total_pages=9, page_size=4,
                                    max_pages=4)
    qwant, _ = forward_prefill_paged(cfg, params, tokens, lengths, qplain)
    qspecs = paged_cache_pspecs(cfg, mesh, quant=True)
    assert qspecs.k_scale == jax.sharding.PartitionSpec(None, None, "tp", None, None)
    qsharded = shard_paged_cache(qplain, cfg, mesh)
    qgot, _ = forward_prefill_paged(cfg, params, tokens, lengths, qsharded)
    np.testing.assert_allclose(np.asarray(qwant), np.asarray(qgot), atol=1e-5,
                               rtol=1e-5)


def test_pool_overflow_recorded():
    """Exhausting the free stack hands out trash pages but records it:
    pool_overflowed() flips True (ADVICE r1: silent corruption guard)."""
    from edgemesh.runtime.paged_kv import allocate, init_paged_cache, pool_overflowed

    cfg = tiny_config("llama", num_layers=1)
    cache = init_paged_cache(cfg, batch=2, total_pages=3, page_size=4, max_pages=4)
    assert not pool_overflowed(cache)
    cache = allocate(cache, jnp.array([1, 1]))  # 2 of 2 free pages used
    assert not pool_overflowed(cache)
    # Fill slot 0 and demand a NEW slot with the stack empty -> overflow.
    cache = cache._replace(lengths=jnp.array([4, 4], jnp.int32))
    cache = allocate(cache, jnp.array([1, 0]))
    assert pool_overflowed(cache)


def test_paged_kernel_sliding_window_matches_oracle():
    """Windowed page-table kernel (interpret) == windowed XLA oracle, with
    windows that cut mid-page and span multiple pages."""
    import numpy as np

    from edgemesh.ops.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_xla,
    )

    b, kh, nh, hd, ps, pages, maxp = 2, 2, 4, 64, 8, 10, 4
    q = jax.random.normal(jax.random.PRNGKey(0), (b, nh, hd), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(1), (pages, kh, ps, hd), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(2), (pages, kh, ps, hd), jnp.float32)
    table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]], jnp.int32)
    lens = jnp.asarray([29, 17], jnp.int32)
    for w in (3, 10, 100):
        out = paged_decode_attention(
            q, kp, vp, table, lens, interpret=True, sliding_window=w
        )
        ref = paged_decode_attention_xla(q, kp, vp, table, lens, sliding_window=w)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"window={w}",
        )


def test_paged_generate_windowed_matches_dense():
    """Mistral-style windowed generate over the paged cache == the dense
    path, greedy, token for token."""
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime import generate
    from edgemesh.runtime.paged_generate import generate_paged

    cfg = tiny_config("mistral", vocab_size=64, sliding_window=5, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 64, jnp.int32)
    lengths = jnp.asarray([9, 6], jnp.int32)
    s = SamplingParams(max_new_tokens=14, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, params, tokens, lengths, s)
    out = generate_paged(cfg, params, tokens, lengths, s, page_size=4)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


def test_paged_kernel_soft_cap_and_scale_match_oracle():
    """Gemma-2 score dials in the page-walking kernel (interpret) == the XLA
    oracle: soft cap and fixed query scale, with and without a window."""
    import numpy as np

    from edgemesh.ops.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_xla,
    )

    b, kh, nh, hd, ps, pages, maxp = 2, 2, 4, 64, 8, 10, 4
    q = jax.random.normal(jax.random.PRNGKey(0), (b, nh, hd), jnp.float32)
    kp = jax.random.normal(jax.random.PRNGKey(1), (pages, kh, ps, hd), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(2), (pages, kh, ps, hd), jnp.float32)
    table = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 0]], jnp.int32)
    lens = jnp.asarray([29, 17], jnp.int32)
    for w, cap, scale in ((0, 4.0, None), (6, 4.0, 0.25), (0, 0.0, 0.25)):
        out = paged_decode_attention(
            q, kp, vp, table, lens, scale=scale, interpret=True,
            sliding_window=w, soft_cap=cap,
        )
        ref = paged_decode_attention_xla(
            q, kp, vp, table, lens, scale=scale, sliding_window=w, soft_cap=cap
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"window={w} cap={cap} scale={scale}",
        )


def test_paged_generate_gemma2_matches_dense():
    """Gemma-2 on the paged backend (was a refusal until r3): alternating
    windows via the shared pair scan + soft caps + fixed query scale produce
    the dense path's tokens exactly, greedy."""
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime import generate
    from edgemesh.runtime.paged_generate import generate_paged

    cfg = tiny_config(
        "gemma2", vocab_size=64, sliding_window=5, max_seq_len=64,
        query_pre_attn_scalar=16.0,
    )
    assert cfg.alt_sliding_window and cfg.attn_soft_cap > 0
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 64, jnp.int32)
    lengths = jnp.asarray([9, 6], jnp.int32)
    s = SamplingParams(max_new_tokens=14, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, params, tokens, lengths, s)
    out = generate_paged(cfg, params, tokens, lengths, s, page_size=4)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))
    np.testing.assert_allclose(np.asarray(out.confidence),
                               np.asarray(ref.confidence), atol=1e-5)


def test_quant_paged_kernel_matches_xla_oracle():
    """Int8 page pool: kernel (interpret) == dequantize-then-attend oracle,
    windowed and not."""
    from edgemesh.runtime.paged_kv import (
        allocate,
        init_quant_paged_cache,
        pages_needed,
        write_tokens_quant,
    )

    b, nh, kh, hd, ps, mp = 2, 8, 2, 64, 16, 4
    cfg = _cfg(num_heads=nh, num_kv_heads=kh, head_dim=hd)
    cache = init_quant_paged_cache(cfg, batch=b, total_pages=12, page_size=ps,
                                   max_pages=mp)
    kv_lens = jnp.array([50, 17], jnp.int32)
    cache = allocate(cache, pages_needed(cache.lengths, kv_lens, ps))
    from edgemesh.runtime.quant_kv import quantize_kv

    k = jax.random.normal(jax.random.PRNGKey(0), (b, 50, kh, hd))
    v = jax.random.normal(jax.random.PRNGKey(1), (b, 50, kh, hd))
    kq, ksc = quantize_kv(k)
    vq, vsc = quantize_kv(v)
    kp, vp, ks, vs = write_tokens_quant(
        cache.k[0], cache.v[0], cache.k_scale[0], cache.v_scale[0],
        kq, ksc, vq, vsc, cache.page_table,
        start=jnp.zeros((b,), jnp.int32), valid_len=kv_lens,
    )
    q = jax.random.normal(jax.random.PRNGKey(2), (b, nh, hd))
    for w in (0, 21):
        got = paged_decode_attention(
            q, kp, vp, cache.page_table, kv_lens, interpret=True,
            sliding_window=w, k_scales=ks, v_scales=vs,
        )
        want = paged_decode_attention_xla(
            q, kp, vp, cache.page_table, kv_lens, sliding_window=w,
            k_scales=ks, v_scales=vs,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5, err_msg=f"window={w}")


def test_generate_paged_quant_matches_dense_quant_kv():
    """generate_paged(kv_quant=True) == the dense int8-KV backend
    (runtime/quant_kv.py), greedy, token for token — the two long-context
    levers (paging + int8 KV) compose without changing the numerics."""
    from edgemesh.runtime.quant_kv import generate_quant_kv

    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = jnp.array([[5, 9, 11, 42, 7, 0, 0], [17, 3, 50, 8, 33, 21, 2]],
                        jnp.int32)
    lengths = jnp.array([5, 7], jnp.int32)
    sp = SamplingParams(max_new_tokens=14, temperature=0.0)
    dense = generate_quant_kv(cfg, params, prompts, lengths, sp,
                              rng=jax.random.PRNGKey(7))
    paged = generate_paged(cfg, params, prompts, lengths, sp,
                           rng=jax.random.PRNGKey(7), page_size=4,
                           kv_quant=True)
    np.testing.assert_array_equal(np.asarray(dense.tokens),
                                  np.asarray(paged.tokens))
    np.testing.assert_allclose(np.asarray(dense.confidence),
                               np.asarray(paged.confidence), atol=1e-5)


def test_generate_paged_gpt2_matches_dense():
    """Learned-position family (GPT-2) over the paged cache: the wpe row is
    added at embed via explicit positions on BOTH paths — token-exact."""
    cfg = tiny_config("gpt2", vocab_size=64, max_seq_len=64)
    assert cfg.learned_positions
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 64, jnp.int32)
    lengths = jnp.asarray([9, 6], jnp.int32)
    s = SamplingParams(max_new_tokens=12, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, params, tokens, lengths, s)
    out = generate_paged(cfg, params, tokens, lengths, s, page_size=4)
    np.testing.assert_array_equal(np.asarray(out.tokens), np.asarray(ref.tokens))


def test_suffix_prefill_matches_full_prefill():
    """forward_prefill_paged_at: (template prefill) + (suffix append) must
    match the one-shot full prefill — logits and subsequent greedy decode —
    including a split that cuts MID-page. Both pools."""
    from edgemesh.runtime.paged_generate import (
        forward_prefill_paged,
        forward_prefill_paged_at,
    )
    from edgemesh.runtime.paged_kv import init_quant_paged_cache

    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    full = jnp.array([[5, 9, 11, 42, 7, 33, 21, 2, 17, 3]], jnp.int32)
    n = full.shape[1]
    for quant in (False, True):
        init = init_quant_paged_cache if quant else init_paged_cache
        for split in (4, 6, 8):  # page_size=4: on-boundary and mid-page cuts
            ref_cache = init(cfg, batch=1, total_pages=8, page_size=4, max_pages=5)
            want, _ = forward_prefill_paged(
                cfg, params, full, jnp.asarray([n], jnp.int32), ref_cache
            )
            cache = init(cfg, batch=1, total_pages=8, page_size=4, max_pages=5)
            _, cache = forward_prefill_paged(
                cfg, params, full[:, :split], jnp.asarray([split], jnp.int32), cache
            )
            got, cache = forward_prefill_paged_at(
                cfg, params, full[:, split:], jnp.asarray([n - split], jnp.int32),
                cache, jnp.asarray([split], jnp.int32),
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-5,
                err_msg=f"quant={quant} split={split}",
            )
            assert int(cache.lengths[0]) == n


def test_allocate_rewind_idempotent():
    """Re-allocating slots that kept their pages after a REWIND (speculative
    decoding lowers lengths) reuses them — no fresh pops, no orphaned stack
    entries, table unchanged."""
    cfg = _cfg()
    cache = init_paged_cache(cfg, batch=2, total_pages=16, page_size=4, max_pages=4)
    cache = cache._replace(lengths=jnp.array([0, 0], jnp.int32))
    cache = allocate(cache, jnp.array([3, 2], jnp.int32))
    table0 = np.asarray(cache.page_table).copy()
    top0 = int(cache.free_top)
    # Rewind row 0 to 5 tokens (2 pages' worth) then re-advance over the
    # SAME slots: ceil(5/4)=2 filled, next alloc targets slot 2 — which
    # still maps a page.
    cache = cache._replace(lengths=jnp.array([5, 8], jnp.int32))
    cache = allocate(cache, jnp.array([1, 0], jnp.int32))
    np.testing.assert_array_equal(np.asarray(cache.page_table), table0)
    assert int(cache.free_top) == top0  # nothing popped
    # A genuinely new slot still pops.
    cache = cache._replace(lengths=jnp.array([12, 8], jnp.int32))
    cache = allocate(cache, jnp.array([1, 0], jnp.int32))
    assert int(cache.free_top) == top0 + 1
    assert np.asarray(cache.page_table)[0, 3] > 0


def test_paged_chunk_kernel_matches_gather_oracle():
    """Chunk-query page walk (interpret) == the gather-based append path:
    suffix prefill and verify-style full-width chunks, page-crossing starts,
    ragged suffix lengths, and an active score soft cap. The kernel flag is
    a module attribute captured at import (trace-time constant), so the
    test patches it AND clears jit caches — otherwise the second run would
    reuse the first run's cached executables and compare the gather path
    against itself."""
    import edgemesh.runtime.paged_generate as pg
    from edgemesh.runtime.paged_generate import (
        forward_prefill_paged,
        forward_prefill_paged_at,
        forward_verify_paged,
    )

    from edgemesh.runtime.paged_kv import init_quant_paged_cache

    for cap, quant in ((0.0, False), (4.0, False), (0.0, True), (4.0, True)):
        cfg = _cfg(num_heads=4, num_kv_heads=2, head_dim=64,
                   hidden_size=64, intermediate_size=96).replace(
            attention_impl="flash", attn_soft_cap=cap)
        params = init_params(cfg, jax.random.PRNGKey(0))
        full = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64, jnp.int32)
        lens = jnp.asarray([12, 9], jnp.int32)

        def run(use_kernel):
            jax.clear_caches()
            saved = pg._CHUNK_KERNEL_OPTIN
            pg._CHUNK_KERNEL_OPTIN = use_kernel
            try:
                assert pg._use_chunk_kernel(cfg, quant=quant) == use_kernel
                init = init_quant_paged_cache if quant else init_paged_cache
                cache = init(cfg, batch=2, total_pages=16,
                             page_size=4, max_pages=8)
                _, cache = forward_prefill_paged(
                    cfg, params, full[:, :6], jnp.asarray([6, 6], jnp.int32), cache
                )
                last, cache = forward_prefill_paged_at(
                    cfg, params, full[:, 6:], lens - 6, cache,
                    jnp.asarray([6, 6], jnp.int32),
                )
                vlog, cache = forward_verify_paged(
                    cfg, params, full[:, :3] + 1, cache
                )
                return np.asarray(last), np.asarray(vlog)
            finally:
                pg._CHUNK_KERNEL_OPTIN = saved
                jax.clear_caches()

        last_g, ver_g = run(use_kernel=False)
        last_k, ver_k = run(use_kernel=True)
        np.testing.assert_allclose(last_k, last_g, atol=3e-5, rtol=3e-5,
                                   err_msg=f"cap={cap} quant={quant}")
        np.testing.assert_allclose(ver_k, ver_g, atol=3e-5, rtol=3e-5,
                                   err_msg=f"cap={cap} quant={quant}")


def test_hoisted_decode_matches_xla_path():
    """The TPU decode path (attention_impl="flash" → interpret on CPU) runs
    the hoisted-write design: the layer scan never writes pages (the kernel
    folds the current token as a virtual page), and ONE aliased RMW kernel
    (ops/paged_write.write_decode_all_layers) commits every layer's fresh
    K/V after the scan. Pin it token-exact against the write-then-attend
    XLA path for the bf16 pool, the int8 pool, and a sliding window."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompts = jnp.array(
        [[5, 9, 11, 42, 7, 0, 0], [17, 3, 50, 8, 33, 21, 2]], jnp.int32
    )
    lengths = jnp.array([5, 7], jnp.int32)
    sp = SamplingParams(max_new_tokens=14, temperature=0.0)

    for kw, quant in [({}, False), ({}, True), (dict(sliding_window=8), False)]:
        cfg_x = _cfg(**kw)
        cfg_f = cfg_x.replace(attention_impl="flash")
        ref = generate_paged(cfg_x, params, prompts, lengths, sp,
                             rng=jax.random.PRNGKey(7), page_size=4,
                             kv_quant=quant)
        got = generate_paged(cfg_f, params, prompts, lengths, sp,
                             rng=jax.random.PRNGKey(7), page_size=4,
                             kv_quant=quant)
        np.testing.assert_array_equal(
            np.asarray(ref.tokens), np.asarray(got.tokens),
            err_msg=f"kw={kw} quant={quant}",
        )
        np.testing.assert_allclose(
            np.asarray(ref.confidence), np.asarray(got.confidence),
            atol=2e-5, err_msg=f"kw={kw} quant={quant}",
        )


def test_write_decode_all_layers_matches_scatter():
    """The RMW write kernel == write_tokens(start=lengths, valid_len=1) on
    every layer, including table-unallocated rows landing on the trash
    page."""
    from edgemesh.ops.paged_write import write_decode_all_layers

    cfg = _cfg()
    L, kh, hd, ps, b = cfg.num_layers, cfg.num_kv_heads, cfg.head_size, 4, 3
    cache = init_paged_cache(cfg, b, total_pages=12, page_size=ps, max_pages=6)
    # Rows at assorted positions; row 2 left unallocated (trash-page write).
    cache = cache._replace(
        page_table=jnp.asarray([[3, 5, 0, 0, 0, 0],
                                [7, 0, 0, 0, 0, 0],
                                [0, 0, 0, 0, 0, 0]], jnp.int32),
        lengths=jnp.asarray([5, 2, 1], jnp.int32),
    )
    key = jax.random.PRNGKey(1)
    fk = jax.random.normal(key, (L, b, kh, hd), jnp.float32)
    fv = jax.random.normal(jax.random.fold_in(key, 1), (L, b, kh, hd), jnp.float32)

    got = write_decode_all_layers(cache, fk, fv, interpret=True)
    want_k, want_v = cache.k, cache.v
    for l in range(L):
        want_k = want_k.at[l].set(write_tokens(
            want_k[l], cache.v[l], fk[l][:, None], fv[l][:, None],
            cache.page_table, cache.lengths, jnp.ones((b,), jnp.int32),
        )[0])
        want_v = want_v.at[l].set(write_tokens(
            cache.k[l], want_v[l], fk[l][:, None], fv[l][:, None],
            cache.page_table, cache.lengths, jnp.ones((b,), jnp.int32),
        )[1])
    np.testing.assert_allclose(np.asarray(got.k), np.asarray(want_k), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.v), np.asarray(want_v), atol=1e-6)
