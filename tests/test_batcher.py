"""Dynamic request batching (serve/batcher.py, Agent/Ensemble.answer_batch)."""

import threading
import time

import numpy as np
import pytest

from edgemesh.agents.orchestrator import build_agent, build_ensemble
from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.serve.batcher import DynamicBatcher

GREEDY = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _agent():
    return build_agent(AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY))


def test_answer_batch_matches_single_answers():
    # Greedy batched decode must produce exactly the per-question answers
    # (padding rows/columns are masked, per-row state is independent).
    agent = _agent()
    qs = ["where is the eiffel tower", "who wrote hamlet", "what is jax"]
    singles = [agent.answer(q)["answer"] for q in qs]
    batched = [r["answer"] for r in agent.answer_batch(qs)]
    assert batched == singles


def test_ensemble_answer_batch_matches_single():
    cfg = EdgeMeshConfig(
        agents=[
            AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY),
            AgentSpec(role="qa2", model=ModelSpec(family="neox"), sampling=GREEDY),
            AgentSpec(role="refiner", model=ModelSpec(), sampling=GREEDY),
        ]
    )
    ens = build_ensemble(cfg, use_submeshes=False)
    qs = ["where is the eiffel tower", "who wrote hamlet"]
    singles = [ens.answer(q)["answer"] for q in qs]
    batched = [r["answer"] for r in ens.answer_batch(qs)]
    assert batched == singles


def test_batcher_coalesces_concurrent_requests():
    agent = _agent()
    agent.answer("warmup")  # compile outside the timed window
    batcher = DynamicBatcher(agent.answer_batch, max_batch=4, max_wait_s=0.25)
    qs = [f"question number {i}" for i in range(4)]
    results = {}

    def call(q):
        results[q] = batcher.answer(q)

    threads = [threading.Thread(target=call, args=(q,)) for q in qs]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    batcher.close()
    assert len(results) == 4
    for q in qs:
        assert isinstance(results[q]["answer"], str)
    stats = batcher.stats()
    assert stats["requests"] == 4
    assert stats["largest_batch"] >= 2, stats  # real coalescing happened
    # Order-preservation: each future got ITS question's answer.
    direct = {q: agent.answer(q)["answer"] for q in qs}
    assert {q: r["answer"] for q, r in results.items()} == direct


def test_batcher_error_fails_batch_but_worker_survives():
    calls = []

    def flaky(questions):
        calls.append(list(questions))
        if len(calls) == 1:
            raise RuntimeError("boom")
        return [{"answer": f"ok:{q}"} for q in questions]

    batcher = DynamicBatcher(flaky, max_batch=2, max_wait_s=0.01)
    with pytest.raises(RuntimeError, match="boom"):
        batcher.answer("a")
    assert batcher.answer("b")["answer"] == "ok:b"
    batcher.close()


def test_batcher_rejects_after_close():
    batcher = DynamicBatcher(lambda qs: [{"answer": q} for q in qs], max_batch=2)
    batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit("x")


def test_batcher_composes_with_supervisor():
    """With both configured, each coalesced batch routes through
    supervisor.call — failure tracking and restart stay engaged."""
    from edgemesh.serve.rest import serve_rest
    from edgemesh.serve.supervisor import Supervisor

    state = {"fail_next": True}

    def factory():
        return object()

    def handler(backend, questions):
        assert isinstance(questions, list)
        if state.pop("fail_next", False):
            raise RuntimeError("backend down")
        return [{"answer": f"ok:{q}"} for q in questions]

    sup = Supervisor(factory, handler, max_consecutive_failures=1)
    cfg = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY)])
    ens = build_ensemble(cfg, use_submeshes=False)
    server = serve_rest(ens, host="127.0.0.1", port=0, block=False,
                        supervisor=sup, batch=4)
    import json
    import urllib.request

    port = server.server_address[1]
    try:
        def post(q):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps({"question": q}).encode(),
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=60)

        with pytest.raises(urllib.error.HTTPError) as exc_info:
            post("a")
        assert exc_info.value.code == 500
        with post("b") as resp:
            assert json.loads(resp.read())["answer"] == "ok:b"
        health = sup.health()
        assert health["total_failures"] == 1 and health["total_requests"] == 2
        assert health["restarts"] == 1  # max_consecutive_failures=1 tripped it
    finally:
        server.shutdown()


def test_rest_generate_through_batcher():
    import json
    import urllib.request

    from edgemesh.serve.rest import serve_rest

    cfg = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY)])
    ens = build_ensemble(cfg, use_submeshes=False)
    server = serve_rest(ens, host="127.0.0.1", port=0, block=False, batch=4)
    port = server.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"question": "where is the eiffel tower"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            body = json.loads(resp.read())
        assert isinstance(body["answer"], str)
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats", timeout=30) as resp:
            metrics = json.loads(resp.read())
        assert metrics["batcher"]["requests"] == 1
    finally:
        server.shutdown()
