"""The compute observatory: launch ledger, roofline attribution, the
speculative round ledger, and the offline span-log twin.

Pins the contracts the serving stack and the CLI depend on:

- sampling rule: first-key launches are NEVER timed (they pay the
  compile), the fence fires 1-in-N afterwards;
- CPU cost capture: ``aot_cost_analysis`` yields flops/bytes for the
  dense decode loop AND the paged decode boundary (the acceptance pin —
  the roofline column is real, not always-None);
- ``summarize_compute`` forward-compat in BOTH directions: unknown keys
  ignored, missing keys read as None, pre-compute logs return None;
- the CLI renders tables / ``--diff`` / ``--json`` and exits 0 on a
  pre-compute log;
- ``replay_spans`` reconstructs the launch counter from the cumulative
  ``launches`` field so an offline scrape matches the live one despite
  1-in-N sampling.
"""

import json

import jax
import jax.numpy as jnp
import pytest

from edgemesh.obs import (
    ComputeLedger,
    Registry,
    SpecRoundLedger,
    diff_compute,
    ledger_scope,
    replay_spans,
    spec_draft_frac,
    summarize_compute,
)
from edgemesh.obs.compute import roofline_fraction
from edgemesh.utils.tracing import JsonlLogger

PEAKS = (1e12, 1e11)  # flops/s, bytes/s — a fixed synthetic device


def _ledger(tmp_path=None, sample=1, **kw):
    return ComputeLedger(
        registry=Registry(), engine="t", sample=sample, peaks=PEAKS,
        span_log=None if tmp_path is None else tmp_path / "spans.jsonl",
        **kw)


@jax.jit
def _axpy(a, x, y):
    return a * x + y


# ---------------------------------------------------------------------------
# Roofline math
# ---------------------------------------------------------------------------


def test_roofline_fraction_math():
    # Memory-bound: intensity 1 flop/byte → attainable = 1e11 flops/s.
    # Achieved 5e10 → fraction 0.5.
    assert roofline_fraction(1e9, 1e9, 0.02, PEAKS) == pytest.approx(0.5)
    # Compute-bound: intensity 100 → attainable = peak flops. Achieved
    # 5e11 → 0.5 again, through the other roof.
    assert roofline_fraction(1e10, 1e8, 0.02, PEAKS) == pytest.approx(0.5)
    # Capped at 1.0 (timer jitter can overshoot the model).
    assert roofline_fraction(1e12, 1e10, 0.5, PEAKS) == 1.0
    # Any unknown input → no claim.
    assert roofline_fraction(None, 1e9, 0.02, PEAKS) is None
    assert roofline_fraction(1e9, None, 0.02, PEAKS) is None
    assert roofline_fraction(1e9, 1e9, 0.02, None) is None


# ---------------------------------------------------------------------------
# Ledger mechanics: sampling, cost capture, digests
# ---------------------------------------------------------------------------


def test_first_key_launch_is_never_timed():
    led = _ledger(sample=1)
    x = jnp.ones((8,), jnp.float32)
    led.launch("axpy", _axpy, 2.0, x, x, key="b8")
    roll = led.rollup()["axpy"]
    # The compile launch dispatched but was not fenced/timed...
    assert roll["launches"] == 1 and roll["measured"] == 0
    # ...while its cost table WAS captured (pre-dispatch spec snapshot).
    assert roll["compiles"] == 1
    led.launch("axpy", _axpy, 2.0, x, x, key="b8")
    roll = led.rollup()["axpy"]
    assert roll["launches"] == 2 and roll["measured"] == 1
    assert roll["ewma_launch_s"] > 0
    # A NEW shape bucket compiles again — and again is not timed.
    y = jnp.ones((16,), jnp.float32)
    led.launch("axpy", _axpy, 2.0, y, y, key="b16")
    roll = led.rollup()["axpy"]
    assert roll["compiles"] == 2 and roll["measured"] == 1
    assert roll["shape_buckets"] == {"b8": 2, "b16": 1}


def test_sampling_rate_gates_the_fence():
    led = _ledger(sample=4)
    x = jnp.ones((4,), jnp.float32)
    for _ in range(13):
        led.launch("axpy", _axpy, 2.0, x, x, key="b4")
    roll = led.rollup()["axpy"]
    # Launch 1 compiles (never timed), launch 2 seeds the EWMA (measured
    # == 0 forces one early sample), then 1-in-4 fences at launches 6 and
    # 10: 13 launches → 3 measurements.
    assert roll["launches"] == 13
    assert roll["measured"] == 3


def test_disabled_ledger_is_pure_passthrough():
    led = _ledger(sample=0)
    assert led.enabled is False
    x = jnp.ones((4,), jnp.float32)
    out = led.launch("axpy", _axpy, 2.0, x, x, key="b4")
    assert out.shape == (4,)
    assert led.rollup() == {}
    # wrap() returns the bare fn — zero per-call overhead when off.
    assert led.wrap("axpy", _axpy) is _axpy
    # Runtime toggle (the bench ledger-off arm): enabled=False on a live
    # ledger short-circuits the launch path.
    led2 = _ledger(sample=1)
    led2.enabled = False
    led2.launch("axpy", _axpy, 2.0, x, x, key="b4")
    assert led2.rollup() == {}


def test_cost_capture_dense_and_paged_decode_on_cpu():
    """Acceptance pin: cost_analysis-backed flops/bytes present for the
    dense decode loop and the paged decode boundary on CPU."""
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime.generate import generate
    from edgemesh.runtime.paged_generate import LEDGER_BOUNDARIES
    from edgemesh.runtime.paged_kv import init_paged_cache
    from edgemesh.utils.compat import aot_cost_analysis

    cfg = tiny_config(
        "llama", num_heads=2, num_kv_heads=2, hidden_size=16,
        intermediate_size=32, num_layers=1, vocab_size=32, max_seq_len=32,
    ).replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    # Dense: the ambient ledger instruments generate()'s jitted
    # boundaries; the decode loop must carry a cost row.
    led = _ledger(sample=1)
    prompts = jnp.array([[5, 9, 11, 0]], jnp.int32)
    lengths = jnp.array([3], jnp.int32)
    # Twice: the first pass per key compiles (never timed), the second
    # hits the cache and gets fenced — the roofline column needs both a
    # cost row and a measurement.
    with ledger_scope(led):
        for _ in range(2):
            generate(cfg, params, prompts, lengths,
                     SamplingParams(max_new_tokens=3, temperature=0.0),
                     rng=jax.random.PRNGKey(1))
    roll = led.rollup()
    for boundary in ("prefill", "decode_loop"):
        assert roll[boundary]["flops"] and roll[boundary]["flops"] > 0
        assert roll[boundary]["bytes"] and roll[boundary]["bytes"] > 0
    # Measured + cost + synthetic peaks → the roofline column is live.
    assert 0 < roll["decode_loop"]["roofline_fraction"] <= 1.0

    # Paged: the boundary catalog's decode entry, costed directly via the
    # compat shim (same path the ledger's first-key capture takes).
    cache = init_paged_cache(cfg, 1, total_pages=5, page_size=4, max_pages=4)
    cost = aot_cost_analysis(
        LEDGER_BOUNDARIES["paged_decode"],
        (cfg, params, jnp.array([7], jnp.int32), cache))
    assert cost["flops"] and cost["flops"] > 0
    assert cost["bytes_accessed"] and cost["bytes_accessed"] > 0
    # An un-lowerable fn degrades to None, never raises.
    assert aot_cost_analysis(lambda x: x, (np.zeros(2),)) is None


def test_consume_measured_pops_once():
    led = _ledger(sample=1)
    x = jnp.ones((4,), jnp.float32)
    assert led.consume_measured("axpy") is None
    led.launch("axpy", _axpy, 2.0, x, x, key="b4")  # compile, untimed
    assert led.consume_measured("axpy") is None
    led.launch("axpy", _axpy, 2.0, x, x, key="b4")
    dt = led.consume_measured("axpy")
    assert dt is not None and dt > 0
    assert led.consume_measured("axpy") is None  # popped


def test_digest_costs_and_measured_tok_s():
    led = _ledger(sample=1)
    x = jnp.ones((4,), jnp.float32)
    assert led.digest_costs() is None  # nothing measured yet
    led.launch("decode_loop", _axpy, 2.0, x, x, key="b4", tokens=32)
    assert led.digest_costs() is None  # compile launch: still unmeasured
    led.launch("decode_loop", _axpy, 2.0, x, x, key="b4", tokens=32)
    digest = led.digest_costs()
    assert digest["decode_loop"]["ewma_launch_s"] > 0
    assert digest["decode_loop"]["tok_s"] > 0
    assert digest["decode_loop"]["launches"] == 2
    assert led.measured_tok_s() == digest["decode_loop"]["tok_s"]
    # Scoping: a prefill boundary's (much higher) tok/s must not leak
    # into the decode capacity claim.
    big = jnp.ones((256,), jnp.float32)
    led.launch("prefill", _axpy, 2.0, big, big, key="b256", tokens=4096)
    led.launch("prefill", _axpy, 2.0, big, big, key="b256", tokens=4096)
    assert led.measured_tok_s() == digest["decode_loop"]["tok_s"]


# ---------------------------------------------------------------------------
# Speculative round ledger
# ---------------------------------------------------------------------------


def test_spec_round_ledger_accounting_and_split():
    rl = SpecRoundLedger(engine="t", draft_frac=0.25)
    assert rl.summary() is None  # no rounds yet
    rl.on_segment(-1, 2, 3)  # pool reset mid-flight: skipped whole
    assert rl.summary() is None
    rl.on_segment(4, 10, 16, measured_s=0.8)
    rl.on_segment(2, 4, 8)  # unmeasured segment still counts rounds
    s = rl.summary()
    assert s["rounds"] == 6 and s["accepted"] == 14 and s["proposed"] == 24
    assert s["rejected"] == 10
    assert s["accept_rate"] == pytest.approx(14 / 24, abs=1e-4)
    assert s["segments"] == 2 and s["measured_segments"] == 1
    assert s["round_s"] == pytest.approx(0.2)
    # The analytic split is labeled, and partitions measured_s exactly.
    assert s["split"] == "analytic-flops"
    assert s["draft_s"] == pytest.approx(0.2)
    assert s["verify_s"] == pytest.approx(0.6)
    assert s["draft_s"] + s["verify_s"] == pytest.approx(s["measured_s"])


def test_spec_round_ledger_writes_span_records(tmp_path):
    led = _ledger(tmp_path, sample=1)
    rl = SpecRoundLedger(ledger=led, engine="t", draft_frac=0.5)
    rl.on_segment(2, 3, 4, measured_s=0.1)
    rl.on_segment(1, 1, 2)  # unmeasured: counted, not logged
    recs = [r for r in JsonlLogger(tmp_path / "spans.jsonl").read()
            if r.get("event") == "spec_rounds"]
    assert len(recs) == 1
    assert recs[0]["rounds"] == 2 and recs[0]["split"] == "analytic-flops"
    assert recs[0]["draft_s"] == pytest.approx(0.05)


def test_spec_draft_frac_prices_live_trees():
    pt = {"w": jnp.ones((100,)), "b": jnp.ones((10,))}
    pd = {"w": jnp.ones((40,))}
    # gamma=2: draft = 2*2*40 = 160, verify = 3*2*110 = 660.
    assert spec_draft_frac(pt, pd, 2) == pytest.approx(160 / 820, abs=1e-4)
    assert spec_draft_frac({}, {}, 2) is None


# ---------------------------------------------------------------------------
# Offline twin: summarize_compute / diff_compute
# ---------------------------------------------------------------------------


def _launch_rec(**kw):
    base = {"event": "launch", "engine": "e1", "boundary": "decode_loop",
            "key": "b8", "measured_s": 0.01, "flops": 1e9, "bytes": 1e8,
            "output_bytes": 1e6, "achieved_flops_s": 1e11,
            "roofline_fraction": 0.4, "tokens": 32, "launches": 16}
    base.update(kw)
    return base


def test_summarize_compute_aggregates_per_boundary():
    recs = [
        _launch_rec(measured_s=0.01, launches=16),
        _launch_rec(measured_s=0.03, launches=32, roofline_fraction=0.6),
        _launch_rec(boundary="prefill", key="b8p64", measured_s=0.06,
                    launches=4, tokens=512),
        {"event": "spec_rounds", "engine": "e1", "rounds": 4, "accepted": 10,
         "proposed": 16, "measured_s": 0.8, "draft_s": 0.2, "verify_s": 0.6,
         "draft_frac": 0.25, "split": "analytic-flops"},
    ]
    s = summarize_compute(recs)
    assert s["launch_records"] == 3
    assert s["total_device_s"] == pytest.approx(0.1)
    dl = s["boundaries"]["decode_loop"]
    # ``launches`` is cumulative at record time: newest wins (32), NOT
    # the record count — that keeps 1-in-N-sampled logs honest.
    assert dl["launches"] == 32 and dl["measured"] == 2
    assert dl["mean_s"] == pytest.approx(0.02)
    assert dl["share"] == pytest.approx(0.4)
    assert dl["roofline_fraction"] == pytest.approx(0.5)
    assert dl["top_keys"] == {"b8": 2}
    assert s["boundaries"]["prefill"]["share"] == pytest.approx(0.6)
    sp = s["spec_rounds"]
    assert sp["rounds"] == 4 and sp["accept_rate"] == 0.625
    assert sp["draft_s"] == pytest.approx(0.2)
    assert sp["split"] == "analytic-flops"


def test_summarize_compute_forward_compat_both_directions():
    # A NEWER build's record: unknown keys ignored, the record counts.
    newer = _launch_rec(dma_stall_s=0.001, hbm_residency=0.9)
    # An OLDER build's record: cost fields absent read as None.
    older = {"event": "launch", "engine": "e0", "boundary": "bridge",
             "measured_s": 0.005}
    s = summarize_compute([newer, older])
    assert s["launch_records"] == 2
    assert s["boundaries"]["decode_loop"]["flops"] == 1e9
    br = s["boundaries"]["bridge"]
    assert br["flops"] is None and br["roofline_fraction"] is None
    assert br["launches"] is None  # pre-cumulative-counter log
    assert br["device_s"] == pytest.approx(0.005)


def test_summarize_compute_pre_compute_log_is_none():
    spans_only = [
        {"event": "request_spans", "rid": "r1", "spans": []},
        {"event": "checkpoint_saved", "step": 3},
        "torn line",
    ]
    assert summarize_compute(spans_only) is None
    assert summarize_compute([]) is None


def test_diff_compute_rows_and_one_sided_boundaries():
    a = summarize_compute([_launch_rec(measured_s=0.02)])
    b = summarize_compute([
        _launch_rec(measured_s=0.01),
        _launch_rec(boundary="paged_splice", key="s16", measured_s=0.004),
    ])
    d = diff_compute(a, b)
    dl = d["boundaries"]["decode_loop"]
    assert dl["ratio"] == pytest.approx(0.5)
    assert dl["a_share"] == 1.0
    # A boundary present only on one side still gets a row — appearing
    # or vanishing between two runs IS the finding.
    ps = d["boundaries"]["paged_splice"]
    assert ps["a_mean_s"] is None and ps["b_mean_s"] == pytest.approx(0.004)
    assert ps["ratio"] is None
    assert d["a_total_device_s"] == pytest.approx(0.02)


# ---------------------------------------------------------------------------
# CLI: edgemesh obs compute / summary integration
# ---------------------------------------------------------------------------


@pytest.fixture()
def compute_log(tmp_path):
    lg = JsonlLogger(tmp_path / "spans.jsonl")
    lg.log("launch", **{k: v for k, v in _launch_rec().items()
                        if k != "event"})
    lg.log("launch", **{k: v for k, v in
                        _launch_rec(measured_s=0.03, launches=32).items()
                        if k != "event"})
    lg.log("spec_rounds", engine="e1", rounds=4, accepted=10, proposed=16,
           measured_s=0.8, draft_s=0.2, verify_s=0.6, draft_frac=0.25,
           split="analytic-flops")
    return lg.path


def test_obs_compute_cli_table_and_json(compute_log, capsys):
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["compute", str(compute_log)]) == 0
    out = capsys.readouterr().out
    assert "decode_loop" in out and "BOUNDARY" in out
    assert "spec rounds" in out and "analytic-flops" in out

    assert obs_main(["compute", str(compute_log), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["boundaries"]["decode_loop"]["measured"] == 2
    assert report["spec_rounds"]["accept_rate"] == 0.625


def test_obs_compute_cli_diff(compute_log, tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    other = JsonlLogger(tmp_path / "b.jsonl")
    other.log("launch", **{k: v for k, v in
                           _launch_rec(measured_s=0.02).items()
                           if k != "event"})
    assert obs_main(["compute", str(compute_log),
                     "--diff", str(other.path)]) == 0
    out = capsys.readouterr().out
    assert "decode_loop" in out and "B/A" in out
    # Missing diff file is a usage error, same as a missing log.
    assert obs_main(["compute", str(compute_log),
                     "--diff", str(tmp_path / "nope.jsonl")]) == 2


def test_obs_compute_cli_pre_compute_log_rc0(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    lg = JsonlLogger(tmp_path / "old.jsonl")
    lg.log("request_spans", rid="r1", spans=[])
    assert obs_main(["compute", str(lg.path)]) == 0
    assert "no launch records" in capsys.readouterr().out
    # And the summary's compute block reads null — never a crash.
    assert obs_main(["summary", str(lg.path)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["compute"] is None


def test_obs_summary_carries_compute_block(compute_log, capsys):
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["summary", str(compute_log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["compute"]["launch_records"] == 2
    assert report["compute"]["spec_rounds"]["rounds"] == 4


# ---------------------------------------------------------------------------
# replay_spans: offline scrape == live scrape
# ---------------------------------------------------------------------------


def test_replay_reconstructs_launch_counter_from_cumulative(compute_log):
    registry = Registry()
    replay_spans(JsonlLogger(compute_log).read(), registry)
    prom = registry.render()
    # Two sampled records, but the cumulative counter says 32 dispatches:
    # the replayed counter must match what a live scrape showed.
    assert ('edgemesh_launches_total{engine="e1",boundary="decode_loop"}'
            ' 32') in prom
    assert ('edgemesh_launch_seconds_count'
            '{engine="e1",boundary="decode_loop"} 2') in prom
    assert ('edgemesh_launch_roofline_ratio'
            '{engine="e1",boundary="decode_loop"} 0.4') in prom


def test_replay_tolerates_cumulative_less_records(tmp_path):
    # Pre-cumulative logs (no ``launches`` field) fall back to one inc
    # per record; the families still register idempotently.
    lg = JsonlLogger(tmp_path / "spans.jsonl")
    for _ in range(3):
        lg.log("launch", engine="e1", boundary="bridge", measured_s=0.001)
    registry = Registry()
    replay_spans(lg.read(), registry)
    assert ('edgemesh_launches_total{engine="e1",boundary="bridge"} 3'
            in registry.render())
