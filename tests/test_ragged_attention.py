"""Ragged paged attention (ops/paged_attention.ragged_paged_attention).

CPU parity in interpret mode against the gather oracle and the dense
reference across the edge shapes serving produces: zero-length rows,
single-token decode rows, kv lengths landing exactly on page boundaries,
sliding window, soft cap, int8 pools, and the fresh-fold mode the hoisted
serving forward uses. Fast tier — everything here is interpret-mode Pallas
plus tiny XLA programs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params
from edgemesh.ops.paged_attention import (
    paged_decode_attention,
    ragged_paged_attention,
    ragged_paged_attention_xla,
)
from edgemesh.runtime.paged_generate import forward_prefill_paged, forward_ragged_paged
from edgemesh.runtime.paged_kv import init_paged_cache


def _pool(b=4, kh=2, nh=4, hd=64, ps=8, pages=20, mp=4, seed=0):
    k_pages = jax.random.normal(jax.random.PRNGKey(seed), (pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(jax.random.PRNGKey(seed + 1), (pages, kh, ps, hd), jnp.float32)
    table = jnp.asarray(np.arange(1, 1 + b * mp).reshape(b, mp) % pages, jnp.int32)
    return k_pages, v_pages, table


def _ragged(q_lens, seed=2, nh=4, hd=64):
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(q_lens)]), jnp.int32)
    T = int(cu[-1])
    q = jax.random.normal(jax.random.PRNGKey(seed), (T, nh, hd), jnp.float32)
    return q, cu


# The edge-shape battery: decode rows, chunks, a zero-length row, and kv
# lengths landing exactly on page boundaries (seq 3: 16 = 2 full 8-pages).
EDGE_Q = np.array([1, 5, 0, 8])
EDGE_KV = np.array([12, 17, 9, 16])


@pytest.mark.parametrize("window,cap", [(0, 0.0), (6, 0.0), (0, 30.0), (5, 20.0)])
def test_ragged_kernel_matches_oracle_pages_mode(window, cap):
    k_pages, v_pages, table = _pool()
    q, cu = _ragged(EDGE_Q)
    kv = jnp.asarray(EDGE_KV, jnp.int32)
    out = ragged_paged_attention(
        q, k_pages, v_pages, table, kv, cu, interpret=True,
        sliding_window=window, soft_cap=cap,
    )
    ref = ragged_paged_attention_xla(
        q, k_pages, v_pages, table, kv, cu, sliding_window=window, soft_cap=cap
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("window,cap", [(0, 0.0), (6, 0.0), (0, 30.0)])
def test_ragged_kernel_matches_oracle_fresh_mode(window, cap):
    """fold-fresh: the chunk's K/V ride packed fresh blocks, pages hold only
    the committed prefix — the serving boundary's configuration."""
    k_pages, v_pages, table = _pool()
    q, cu = _ragged(EDGE_Q)
    kv = jnp.asarray(EDGE_KV, jnp.int32)
    T = q.shape[0]
    fk = jax.random.normal(jax.random.PRNGKey(3), (T, 2, 64), jnp.float32)
    fv = jax.random.normal(jax.random.PRNGKey(4), (T, 2, 64), jnp.float32)
    out = ragged_paged_attention(
        q, k_pages, v_pages, table, kv, cu, interpret=True,
        sliding_window=window, soft_cap=cap, fresh_k=fk, fresh_v=fv,
    )
    ref = ragged_paged_attention_xla(
        q, k_pages, v_pages, table, kv, cu, sliding_window=window,
        soft_cap=cap, fresh_k=fk, fresh_v=fv,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_decode_only_matches_decode_kernel():
    """A batch of pure decode rows (q_lens all 1, fresh fold) must agree
    with the dedicated decode kernel's fold-fresh mode — the two kernels'
    shared math, pinned kernel-to-kernel."""
    b = 4
    k_pages, v_pages, table = _pool(b=b)
    q_lens = np.ones(b, np.int64)
    q, cu = _ragged(q_lens, seed=5)
    kv = jnp.asarray([3, 9, 16, 25], jnp.int32)
    fk = jax.random.normal(jax.random.PRNGKey(6), (b, 2, 64), jnp.float32)
    fv = jax.random.normal(jax.random.PRNGKey(7), (b, 2, 64), jnp.float32)
    out = ragged_paged_attention(
        q, k_pages, v_pages, table, kv, cu, interpret=True,
        fresh_k=fk, fresh_v=fv,
    )
    ref = paged_decode_attention(
        q, k_pages, v_pages, table, kv, interpret=True, fresh_k=fk, fresh_v=fv
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_kernel_quantized_pool_with_fresh():
    b, kh, hd, ps, pages, mp = 3, 2, 64, 8, 16, 4
    q_lens = np.array([1, 6, 3])
    q, cu = _ragged(q_lens, seed=8)
    T = q.shape[0]
    kv = jnp.asarray([9, 14, 3], jnp.int32)
    table = jnp.asarray(np.arange(1, 1 + b * mp).reshape(b, mp) % pages, jnp.int32)
    key = jax.random.PRNGKey
    kq = jax.random.randint(key(9), (pages, kh, ps, hd), -127, 128, jnp.int32).astype(jnp.int8)
    vq = jax.random.randint(key(10), (pages, kh, ps, hd), -127, 128, jnp.int32).astype(jnp.int8)
    ks = jax.random.uniform(key(11), (pages, kh, 1, ps), jnp.float32, 0.01, 0.03)
    vs = jax.random.uniform(key(12), (pages, kh, 1, ps), jnp.float32, 0.01, 0.03)
    fkq = jax.random.randint(key(13), (T, kh, hd), -127, 128, jnp.int32).astype(jnp.int8)
    fvq = jax.random.randint(key(14), (T, kh, hd), -127, 128, jnp.int32).astype(jnp.int8)
    fks = jax.random.uniform(key(15), (T, kh), jnp.float32, 0.01, 0.03)
    fvs = jax.random.uniform(key(16), (T, kh), jnp.float32, 0.01, 0.03)
    out = ragged_paged_attention(
        q, kq, vq, table, kv, cu, interpret=True, k_scales=ks, v_scales=vs,
        fresh_k=fkq, fresh_v=fvq, fresh_ks=fks, fresh_vs=fvs,
    )
    ref = ragged_paged_attention_xla(
        q, kq, vq, table, kv, cu, k_scales=ks, v_scales=vs,
        fresh_k=fkq, fresh_v=fvq, fresh_ks=fks, fresh_vs=fvs,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_kernel_full_pool_layer_addressing():
    """5D stacked pool + ``layer`` scalar: each layer's launch reads its own
    page blocks (the layer-scan mode the hoisted serving forward drives)."""
    b, kh, hd, ps, pages, mp, L = 3, 2, 64, 8, 16, 4, 2
    q_lens = np.array([2, 0, 4])
    q, cu = _ragged(q_lens, seed=17)
    T = q.shape[0]
    kv = jnp.asarray([8, 5, 11], jnp.int32)
    table = jnp.asarray(np.arange(1, 1 + b * mp).reshape(b, mp) % pages, jnp.int32)
    k5 = jax.random.normal(jax.random.PRNGKey(18), (L, pages, kh, ps, hd), jnp.float32)
    v5 = jax.random.normal(jax.random.PRNGKey(19), (L, pages, kh, ps, hd), jnp.float32)
    fk = jax.random.normal(jax.random.PRNGKey(20), (T, kh, hd), jnp.float32)
    fv = jax.random.normal(jax.random.PRNGKey(21), (T, kh, hd), jnp.float32)
    for l in range(L):
        out = ragged_paged_attention(
            q, k5, v5, table, kv, cu, interpret=True,
            layer=jnp.asarray(l), fresh_k=fk, fresh_v=fv,
        )
        ref = ragged_paged_attention_xla(
            q, k5[l], v5[l], table, kv, cu, fresh_k=fk, fresh_v=fv
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_rejects_layer_on_4d_pool():
    k_pages, v_pages, table = _pool()
    q, cu = _ragged(np.array([1, 1, 1, 1]))
    with pytest.raises(ValueError, match="5D"):
        ragged_paged_attention(
            q, k_pages, v_pages, table, jnp.asarray(EDGE_KV, jnp.int32), cu,
            interpret=True, layer=jnp.asarray(0),
        )


@pytest.mark.parametrize("impl", ["xla", "flash"])
def test_forward_ragged_paged_matches_dense_reference(impl):
    """The serving-boundary forward end to end: mixed prefill chunks +
    decode rows in ONE launch match the dense forward over each row's full
    prefix — then a second (pure-decode) ragged step proves the hoisted
    writes landed exactly where decode reads them."""
    cfg = tiny_config("llama", vocab_size=128, max_seq_len=64).replace(
        attention_impl=impl, dtype="float32"
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    b = 3
    plens = np.array([5, 9, 3])
    prompts = np.random.RandomState(0).randint(1, 128, (b, int(plens.max())))
    cache = init_paged_cache(cfg, b, total_pages=1 + b * 8, page_size=8, max_pages=8)
    _, cache = forward_prefill_paged(
        cfg, params, jnp.asarray(prompts, jnp.int32),
        jnp.asarray(plens, jnp.int32), cache,
    )

    q_lens = np.array([1, 4, 2])  # decode row + two chunks
    extras = [
        np.random.RandomState(10 + i).randint(1, 128, (n,))
        for i, n in enumerate(q_lens)
    ]
    packed = jnp.asarray(np.concatenate(extras), jnp.int32)
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(q_lens)]), jnp.int32)
    last, cache2 = forward_ragged_paged(cfg, params, packed, cu, cache, 4)
    assert np.asarray(cache2.lengths).tolist() == (plens + q_lens).tolist()

    def dense_last(rows):
        L = max(len(r) for r in rows)
        padded = np.zeros((b, L), np.int64)
        for i, r in enumerate(rows):
            padded[i, : len(r)] = r
        ref, _ = forward_prefill(
            cfg, params, jnp.asarray(padded, jnp.int32),
            jnp.asarray([len(r) for r in rows], jnp.int32),
            init_kv_cache(cfg, b, 64),
        )
        return np.asarray(ref)

    full = [np.concatenate([prompts[i, : plens[i]], extras[i]]) for i in range(b)]
    np.testing.assert_allclose(np.asarray(last), dense_last(full), atol=2e-4)

    nxt = np.random.RandomState(99).randint(1, 128, (b,))
    last2, _ = forward_ragged_paged(
        cfg, params, jnp.asarray(nxt, jnp.int32),
        jnp.asarray([0, 1, 2, 3], jnp.int32), cache2, 1,
    )
    full2 = [np.concatenate([f, [nxt[i]]]) for i, f in enumerate(full)]
    np.testing.assert_allclose(np.asarray(last2), dense_last(full2), atol=2e-4)


def test_forward_ragged_paged_pops_no_pages_when_premapped():
    """The host-owned-allocator contract the serving tripwire checks: a
    boundary whose rows are fully pre-mapped must leave free_top at 1."""
    cfg = tiny_config("llama", vocab_size=64, max_seq_len=64).replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    b = 2
    cache = init_paged_cache(cfg, b, total_pages=1 + b * 8, page_size=8, max_pages=8)
    # Pre-map every slot host-style and park lengths at 0.
    table = np.zeros((b, 8), np.int32)
    table[0] = np.arange(1, 9)
    table[1] = np.arange(9, 17)
    cache = cache._replace(page_table=jnp.asarray(table))
    tokens = jnp.asarray(np.random.RandomState(1).randint(1, 64, (12,)), jnp.int32)
    cu = jnp.asarray([0, 5, 12], jnp.int32)
    _, cache2 = forward_ragged_paged(cfg, params, tokens, cu, cache, 8)
    assert int(cache2.free_top) == 1
