"""Multi-host (DCN-spanning) initialization and cross-process collectives.

The reference's distributed fabric was hand-run gRPC processes on two
physical Jetsons with static IPs — testable only on that hardware
(``Code/gRPC/README.md:9-44``). The TPU-native replacement is
``jax.distributed`` + a global Mesh; THIS test actually runs it: two local
processes, 4 virtual CPU devices each, one 8-device global mesh, and a
jitted program whose reduction crosses the process boundary (gloo transport
standing in for DCN). That's the edgemesh analog of the reference's
server/client smoke test (expected-output comment, ``client.py:19``) —
except automated, with real tensors crossing the wire.
"""

from __future__ import annotations

import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

CHILD = textwrap.dedent(
    """
    import os, sys
    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["EDGEMESH_COORDINATOR"] = f"localhost:{port}"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU dialing from children
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    from edgemesh.parallel.mesh import initialize_multihost, build_mesh
    initialize_multihost(num_processes=2, process_id=pid)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    assert len(jax.local_devices()) == 4

    # Cross-process reduction: each process contributes its local shard.
    mesh = build_mesh(dp=8)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")),
        np.arange(4, dtype=np.float32) + 4 * pid,
        (8,),
    )
    total = jax.jit(lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P()))(arr)
    assert float(np.asarray(total)) == 28.0, float(np.asarray(total))

    # One dp x tp train step on the global mesh: the gradient psum over dp
    # crosses the process boundary (the DCN hop on a real multi-host slice).
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.parallel.sharding import batch_sharding, param_pspecs
    from edgemesh.training import init_train_state, make_optimizer, make_train_step

    cfg = tiny_config("llama", vocab_size=256, num_heads=4, num_kv_heads=4,
                      hidden_size=64, intermediate_size=128)
    mesh2 = build_mesh(dp=2, tp=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    specs = param_pspecs(cfg, mesh2)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh2, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P),
    )
    state = init_train_state(cfg, params, make_optimizer())
    step = make_train_step(cfg, make_optimizer())
    tokens_np = np.random.default_rng(0).integers(0, 256, (4, 16)).astype(np.int32)
    tokens = jax.make_array_from_process_local_data(
        NamedSharding(mesh2, P("dp")), tokens_np[2 * pid : 2 * pid + 2], (4, 16)
    )
    lengths = jax.make_array_from_process_local_data(
        NamedSharding(mesh2, P("dp")), np.full((2,), 16, np.int32), (4,)
    )
    state, loss = step(state, tokens, lengths)
    loss = float(np.asarray(jax.device_get(loss)))
    assert loss == loss and loss > 0, loss
    print(f"proc {pid} OK loss={loss:.4f}", flush=True)
    """
) % {"repo": str(REPO)}



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_mesh_and_train_step(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost child timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"proc {pid} OK" in out, out[-2000:]
