"""Chunked prefill (runtime/chunked_prefill.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params
from edgemesh.runtime import generate
from edgemesh.runtime.chunked_prefill import generate_chunked_prefill, prefill_chunked

GREEDY = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _model():
    cfg = tiny_config("llama", vocab_size=128, max_seq_len=128, dtype="float32")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


@pytest.mark.parametrize("chunk", [4, 7, 64])  # divides / ragged / one-shot
def test_chunked_prefill_matches_one_shot(chunk):
    cfg, params = _model()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 20), 0, cfg.vocab_size, jnp.int32)
    lengths = jnp.asarray([20, 13, 5], jnp.int32)  # ragged rows cross chunk bounds
    ref, ref_cache = forward_prefill(
        cfg, params, tokens, lengths, init_kv_cache(cfg, 3, 40)
    )
    got, cache = prefill_chunked(
        cfg, params, tokens, lengths, init_kv_cache(cfg, 3, 40), chunk=chunk
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(cache.lengths), np.asarray(lengths))
    # KV for real positions matches the one-shot cache.
    for row, ln in enumerate([20, 13, 5]):
        np.testing.assert_allclose(
            np.asarray(cache.k[:, row, :ln]), np.asarray(ref_cache.k[:, row, :ln]),
            rtol=2e-4, atol=2e-4,
        )


def test_generate_chunked_matches_plain_greedy():
    cfg, params = _model()
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 24), 0, cfg.vocab_size, jnp.int32)
    lengths = jnp.asarray([24, 17], jnp.int32)
    ref = generate(cfg, params, tokens, lengths, GREEDY)
    got = generate_chunked_prefill(
        cfg, params, tokens, lengths, GREEDY, prefill_chunk=8
    )
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(ref.tokens))
