"""Ring attention vs dense causal attention: must be exact (fp tolerance)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.ops.attention import LayerKV, attend
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.ring_attention import ring_attention


def _dense_reference(q, k, v, positions, valid):
    cache = LayerKV(k=k, v=v)
    return attend(q, cache, positions, valid)


@pytest.mark.parametrize("kv_heads", [4, 2])  # MHA and GQA
def test_ring_matches_dense(devices, kv_heads):
    mesh = build_mesh(sp=8)
    b, seq, heads, d = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, seq, heads, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, seq, kv_heads, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, seq, kv_heads, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    valid = jnp.ones((b, seq), bool)

    ref = _dense_reference(q, k, v, positions, valid)
    got = ring_attention(q, k, v, positions, valid, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_with_padding(devices):
    """Rows with padded (invalid) tail positions must match dense attention."""
    mesh = build_mesh(sp=8)
    b, seq, heads, d = 2, 24, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (b, seq, heads, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, seq, heads, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, seq, heads, d), jnp.float32)
    lengths = jnp.array([24, 13])
    positions = jnp.minimum(
        jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq)), (lengths - 1)[:, None]
    )
    valid = jnp.arange(seq)[None, :] < lengths[:, None]

    ref = _dense_reference(q, k, v, positions, valid)
    got = ring_attention(q, k, v, positions, valid, mesh)
    # compare only real positions (padded-query outputs are ignored downstream)
    for row, ln in enumerate([24, 13]):
        np.testing.assert_allclose(
            np.asarray(got)[row, :ln], np.asarray(ref)[row, :ln], rtol=2e-5, atol=2e-5
        )


def test_ring_first_token_sees_only_itself(devices):
    """Causality probe: output at position 0 must equal v[0] exactly."""
    mesh = build_mesh(sp=8)
    b, seq, heads, d = 1, 16, 2, 8
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, seq, heads, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, seq, heads, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, seq, heads, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    valid = jnp.ones((b, seq), bool)
    got = ring_attention(q, k, v, positions, valid, mesh)
    np.testing.assert_allclose(np.asarray(got)[0, 0], np.asarray(v)[0, 0], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window,cap", [(5, 0.0), (0, 4.0), (5, 4.0)])
def test_ring_window_and_soft_cap_match_dense(devices, window, cap):
    """Sliding window and score soft cap (Mistral / Gemma-2 dials) must match
    the dense op exactly — these previously silently fell back to full
    uncapped attention in the sequence-parallel schemes."""
    mesh = build_mesh(sp=8)
    b, seq, heads, d = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, seq, heads, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, seq, 2, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, seq, 2, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    valid = positions < jnp.array([seq, seq - 5])[:, None]

    ref = attend(q, LayerKV(k, v), positions, valid,
                 sliding_window=window, soft_cap=cap)
    got = ring_attention(q, k, v, positions, valid, mesh,
                         sliding_window=window, soft_cap=cap)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
