"""Request-level expert routing (agents/experts.py) — the working realization
of the reference's planned Expert Models registry (13 domains, classifier vs
summarizer routing)."""

import numpy as np
import pytest

from edgemesh.agents.experts import (
    DEFAULT_DOMAINS,
    EmbeddingClassifier,
    ExpertRouter,
    ExpertSpec,
    KeywordClassifier,
    build_expert_router,
)
from edgemesh.eval.metrics import HashingEmbedder


class FakeAgent:
    def __init__(self, domain):
        self.domain = domain
        self.calls = []

    def answer(self, question, prompt=None):
        self.calls.append(question)
        return {"answer": f"{self.domain}-answer", "role": "qa",
                "confidence": 0.5, "tps": 1.0, "ttft_s": 0.0}

    def answer_batch(self, questions, prompts=None):
        return [self.answer(q) for q in questions]


def _router(domains=("science", "sports", "general"), **kw):
    agents = {d: FakeAgent(d) for d in domains}
    return build_expert_router(agents, **kw), agents


def test_thirteen_default_domains():
    assert len(DEFAULT_DOMAINS) == 13  # the Expert Models sheet's count
    assert "general" in DEFAULT_DOMAINS


def test_keyword_routing_dispatches_to_domain_expert():
    router, agents = _router()
    out = router.answer("Which team won the championship game last season?")
    assert out["domain"] == "sports"
    assert out["answer"] == "sports-answer"
    assert agents["sports"].calls and not agents["science"].calls


def test_keyword_fallback_to_general():
    router, agents = _router()
    out = router.answer("What is the airspeed velocity of an unladen swallow?")
    assert out["domain"] == "general"


def test_embedding_classifier_routes_by_descriptor_similarity():
    specs = [ExpertSpec(domain=d, agent=FakeAgent(d)) for d in ("science", "sports")]
    clf = EmbeddingClassifier(specs, HashingEmbedder())
    # The hashing embedder sees heavy ngram overlap with the sports descriptor.
    assert clf("championship league player game season") == "sports"


def test_router_requires_experts():
    with pytest.raises(ValueError, match="at least one"):
        ExpertRouter(experts=[])


def test_route_all_merges_without_refiner():
    router, agents = _router(domains=("science", "sports"))
    out = router.route_all("Any question at all?")
    # best-confidence draft wins; both experts were consulted
    assert len(out["drafts"]) == 2
    assert agents["science"].calls and agents["sports"].calls


def test_unknown_classifier_rejected():
    with pytest.raises(ValueError, match="unknown classifier"):
        _router(classifier="nope")


def test_embedding_classifier_requires_embedder():
    with pytest.raises(ValueError, match="needs an embedder"):
        _router(classifier="embedding")


def test_router_from_config_example_yaml():
    """The shipped examples/experts.yaml builds a working router whose
    documented usage snippet is true."""
    from pathlib import Path

    from edgemesh.agents.experts import router_from_config
    from edgemesh.config import load_config

    path = Path(__file__).resolve().parent.parent / "examples" / "experts.yaml"
    router = router_from_config(load_config(path))
    assert router.route("who won the world cup final").domain == "sports"
    assert router.route("what is the chemical formula of water").domain == "science"
    out = router.answer("who won the world cup final")
    assert out["domain"] == "sports" and isinstance(out["answer"], str)
