"""Prefill/decode disaggregation end-to-end (slow tier): REAL paged replica
subprocesses behind the real router, mixed long-prefill/chatty open-loop
workload, homogeneous vs tiered arms.

The acceptance pins (ISSUE 13 / ROADMAP "Prefill/decode disaggregation"):

- the tiered fleet beats the homogeneous fleet on the chatty tenant's TTFT
  p99 at equal-or-better SLO goodput (the non-streaming front door's
  response latency IS its TTFT);
- a decode-tier replica serves a request whose prefill ran elsewhere with
  ZERO prefill recompute — asserted from the span phase split
  (``kv_import_tokens`` + a one-token prefill span) and the
  ``edgemesh_prefix_remote_hits_total`` / transfer-bytes metrics;
- tier membership is dynamic (digest-EWMA-driven) and visible on
  ``/fleetz``;
- transfer failures never surface to clients (the generator sees zero
  errors in the tiered arm).
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

# max_seq_len is explicit: the bulk tenant's prompts must tokenize well
# under it WITH decode room, and the long/short contrast is the mechanism
# under test. The contrast must be STRUCTURAL, not statistical (the same
# rationale as the adaptive-router e2e's 6x-degraded replica): a ~790-token
# prefill against 4-token chat decodes makes the homogeneous arm's
# interference large enough that the strict p99 comparison is not a timing
# coin-flip on a loaded CI host.
REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 2, hidden_size: 64, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 128, max_seq_len: 1024}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""

LONG_CHARS = 850  # ~800 prompt tokens: a real prefill stall on this model
CHAT_CHARS = 60
THRESHOLD = 300


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path: Path, port: int, span_log: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2", "--kv-backend", "paged",
         "--span-log", str(span_log)],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0)
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never became ready"


def _get(url: str, timeout_s: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.status, r.read()


def test_disaggregated_fleet_beats_homogeneous_on_chat_ttft_p99(tmp_path):
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.loadgen import (
        LengthMix,
        OpenLoopGenerator,
        PoissonProcess,
        TenantSpec,
        Workload,
        http_target,
    )
    from edgemesh.obs import Registry
    from edgemesh.utils.tracing import JsonlLogger

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    ports = [_free_port() for _ in range(3)]
    span_logs = {p: tmp_path / f"spans-{p}.jsonl" for p in ports}
    procs = [_spawn_replica(cfg, p, span_logs[p]) for p in ports]
    transport = HttpTransport()
    probers, fronts = [], []
    long_q = "why does the long context question keep going on? " * (
        LONG_CHARS // 49)
    chat_q = "short chat warmup question?"
    try:
        _wait_ready(transport, ports)
        # Warm every replica's compile ladder for BOTH prompt shapes plus
        # the export gather, outside any measured window.
        for p in ports:
            for q in (chat_q, long_q):
                status, _ = transport.post_json(
                    f"http://127.0.0.1:{p}/generate", {"question": q},
                    timeout_s=300.0)
                assert status == 200
            status, body = transport.post_json(
                f"http://127.0.0.1:{p}/kv/export", {"question": long_q},
                timeout_s=300.0)
            assert status == 200 and body["tokens"] > 100

        # Calibrate offered load from warm closed-loop chat latency.
        lats = []
        for p in ports:
            t0 = time.perf_counter()
            transport.post_json(f"http://127.0.0.1:{p}/generate",
                                {"question": chat_q}, timeout_s=300.0)
            lats.append(time.perf_counter() - t0)
        per_replica_rps = 1.0 / max(lats)
        chat_rate = max(1.0, 0.8 * per_replica_rps * len(ports) * 0.5)
        bulk_rate = max(0.4, chat_rate / 3.0)
        slo_latency_s = max(3.0, 20.0 * max(lats))
        duration_s = 10.0

        def make_workload():
            return Workload([
                TenantSpec(name="chat",
                           arrival=PoissonProcess(chat_rate, seed=11),
                           prompt_mix=LengthMix(median=CHAT_CHARS, sigma=0.0,
                                                lo=CHAT_CHARS, hi=CHAT_CHARS),
                           lane="interactive"),
                TenantSpec(name="bulk",
                           arrival=PoissonProcess(bulk_rate, seed=13),
                           prompt_mix=LengthMix(median=LONG_CHARS, sigma=0.0,
                                                lo=LONG_CHARS, hi=LONG_CHARS),
                           lane="batch"),
            ], seed=5)

        def run_arm(tiered: bool):
            obs = Registry()
            registry = ReplicaRegistry(
                (f"replica-{i}", f"http://127.0.0.1:{p}")
                for i, p in enumerate(ports)
            )
            router = FleetRouter(
                registry, balancer="least_outstanding", transport=transport,
                obs_registry=obs, attempt_timeout_s=120.0,
                default_deadline_s=240.0, max_attempts=2, tiered=tiered,
                prefill_threshold_chars=THRESHOLD,
            )
            prober = HealthProber(registry, transport=transport,
                                  interval_s=0.5, obs_registry=obs,
                                  on_digest=router.note_digest).start()
            probers.append(prober)
            front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
            fronts.append(front)
            front_url = f"http://127.0.0.1:{front.server_address[1]}"
            target = http_target(f"{front_url}/generate", timeout_s=300.0)
            if tiered:
                # Prime THIS router's transfer path + tier split.
                status, _, = target({"question": long_q}, {})
                assert status == 200
            gen = OpenLoopGenerator(
                target, make_workload().build_schedule(duration_s),
                slo_latency_s=slo_latency_s, duration_s=duration_s)
            report = gen.run()
            return report, obs, router, front_url

        homog, _, _, _ = run_arm(tiered=False)
        # Tear the homogeneous arm down before the tiered arm measures —
        # its prober polling every replica would be asymmetric background
        # load on exactly the arm whose p99 the assertion credits. (The
        # outer finally re-stops idempotently.)
        probers[0].stop()
        fronts[0].shutdown()
        tiered_rep, tiered_obs, tiered_router, front_url = run_arm(tiered=True)

        # ---- dynamic tier membership, visible on /fleetz -----------------
        status, raw = _get(f"{front_url}/fleetz")
        assert status == 200
        fleetz = json.loads(raw)
        tiers = fleetz["tiers"]
        assert tiers is not None and tiers["prefill"] and tiers["decode"]
        assert set(tiers["prefill"]) | set(tiers["decode"]) == {
            "replica-0", "replica-1", "replica-2"}
        # Digest-driven: the prefill tier's observed prefill share exceeds
        # the decode tier's (membership derived from live EWMAs, not
        # static config).
        by_rid = {r["id"]: r for r in fleetz["replicas"]}

        def share(rid):
            load = by_rid[rid].get("load") or {}
            pt = load.get("ewma_prefill_tokens") or 0.0
            dt = load.get("ewma_decode_tokens") or 0.0
            return pt / (pt + dt) if pt + dt else 0.5

        assert min(share(r) for r in tiers["prefill"]) >= max(
            share(r) for r in tiers["decode"])

        # ---- no client-visible transfer errors ---------------------------
        assert tiered_rep["errors"] == 0
        assert tiered_rep["shed"] == 0

        # ---- the headline: chat TTFT p99, at equal-or-better goodput -----
        h_chat = homog["tenants"]["chat"]
        t_chat = tiered_rep["tenants"]["chat"]
        assert t_chat["latency_s_p99"] < h_chat["latency_s_p99"], (
            f"tiered chat p99 {t_chat['latency_s_p99']} did not beat "
            f"homogeneous {h_chat['latency_s_p99']}")
        assert tiered_rep["goodput_ratio"] >= homog["goodput_ratio"]

        # ---- transfers actually happened and moved bytes -----------------
        fleet = tiered_obs.summary(prefix="edgemesh_fleet_")
        kv_bytes = sum(
            v for k, v in fleet.items()
            if k.startswith("edgemesh_fleet_kv_transfer_bytes_total")
            and not isinstance(v, dict))
        assert kv_bytes > 0
        tiered_ok = sum(
            v for k, v in fleet.items()
            if k.startswith("edgemesh_fleet_tiered_total")
            and 'outcome="tiered"' in k)
        assert tiered_ok >= 1

        # ---- zero prefill recompute on a decode-tier replica -------------
        # A decode-tier replica's /metrics shows remote-prefix hits, and
        # its span log holds an imported request whose prefill span
        # computed exactly the one-token suffix.
        decode_ports = [
            ports[int(rid.split("-")[1])] for rid in tiers["decode"]]
        hits = 0
        for p in decode_ports:
            _, metrics = _get(f"http://127.0.0.1:{p}/metrics")
            for line in metrics.decode().splitlines():
                if line.startswith("edgemesh_prefix_remote_hits_total"):
                    hits += float(line.rsplit(" ", 1)[1])
        assert hits >= 1
        imported = []
        for p in ports:
            if not span_logs[p].exists():
                continue
            for rec in JsonlLogger(span_logs[p]).read():
                if rec.get("event") == "request_spans" and rec.get(
                        "kv_import_tokens"):
                    imported.append(rec)
        assert imported, "no span record shows an imported admission"
        rec = max(imported, key=lambda r: r["kv_import_tokens"])
        prefill_spans = [s for s in rec["spans"] if s["name"] == "prefill"]
        assert prefill_spans
        # The phase split: a >100-token prompt whose prefill computed ONE
        # token — the imported prefix did the rest.
        assert rec["kv_import_tokens"] > 100
        assert prefill_spans[0]["prefill_tokens"] == 1
        assert rec["generated"] > 0
    finally:
        for prober in probers:
            prober.stop()
        for front in fronts:
            front.shutdown()
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
