"""4D-parallel (dp x pp x sp x tp) SPMD train step tests on the 8-device
virtual CPU mesh.

The correctness pin: the manual 4D program (GPipe ppermute pipeline + ring
attention + Megatron tp psums + dp reduction) must produce EXACTLY the same
causal-LM loss as the plain single-device forward in edgemesh.training —
same params, same batch, every family's architecture dials.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.spmd import (
    make_spmd_loss,
    make_spmd_train_step,
    place_spmd,
)
from edgemesh.training import causal_lm_loss, init_train_state, make_optimizer



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _tiny(family: str):
    # fp32 so the parity check is tight despite different reduction orders.
    return tiny_config(
        family,
        num_layers=4,
        num_heads=4,
        num_kv_heads=2 if family == "llama" else 4,
        hidden_size=32,
        intermediate_size=64,
        vocab_size=128,
        max_seq_len=64,
        dtype="float32",
    )


def _batch(cfg, batch=4, seq=16, seed=1):
    tokens = jax.random.randint(
        jax.random.PRNGKey(seed), (batch, seq), 0, cfg.vocab_size, jnp.int32
    )
    lengths = jnp.array([seq, seq - 3, seq - 1, 5], jnp.int32)[:batch]
    return tokens, lengths


@pytest.fixture(scope="module")
def mesh4d(devices):
    return build_mesh(dp=1, pp=2, sp=2, tp=2, devices=devices)


@pytest.mark.parametrize("family", ["llama", "neox", "phi2", "qwen2", "qwen3", "gemma"])
def test_spmd_loss_matches_single_device(family, mesh4d):
    cfg = _tiny(family)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _batch(cfg)

    ref = causal_lm_loss(cfg, params, tokens, lengths)

    sharded = place_spmd(params, cfg, mesh4d)
    loss_fn = make_spmd_loss(cfg, mesh4d, num_micro=2)
    got = jax.jit(loss_fn)(sharded, tokens, lengths)

    np.testing.assert_allclose(float(got), float(ref), rtol=2e-4, atol=2e-4)


def test_spmd_loss_dp_axis(devices):
    """Same pin with a real dp split (dp=2, pp=2, sp=1, tp=2)."""
    cfg = _tiny("llama")
    mesh = build_mesh(dp=2, pp=2, sp=1, tp=2, devices=devices)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _batch(cfg)

    ref = causal_lm_loss(cfg, params, tokens, lengths)
    sharded = place_spmd(params, cfg, mesh)
    got = jax.jit(make_spmd_loss(cfg, mesh, num_micro=2))(sharded, tokens, lengths)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-4, atol=2e-4)


def test_spmd_train_step_learns(mesh4d):
    cfg = _tiny("llama")
    params = place_spmd(init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh4d)
    optimizer = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, params, optimizer)
    step = make_spmd_train_step(cfg, mesh4d, optimizer, num_micro=2)

    tokens, lengths = _batch(cfg)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5


def test_spmd_grads_match_single_device(mesh4d):
    """Gradients through the 4D program equal single-device gradients."""
    cfg = _tiny("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _batch(cfg)

    ref_grads = jax.grad(lambda p: causal_lm_loss(cfg, p, tokens, lengths))(params)

    sharded = place_spmd(params, cfg, mesh4d)
    loss_fn = make_spmd_loss(cfg, mesh4d, num_micro=2)
    got_grads = jax.jit(jax.grad(loss_fn))(sharded, tokens, lengths)

    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat_got = jax.tree.leaves(got_grads)
    assert len(flat_ref) == len(flat_got)
    for (path, r), g in zip(flat_ref, flat_got):
        np.testing.assert_allclose(
            np.asarray(g, np.float32),
            np.asarray(r, np.float32),
            rtol=5e-3,
            atol=5e-4,
            err_msg=jax.tree_util.keystr(path),
        )


def test_spmd_moe_loss_matches_single_device(devices):
    """MoE in the manual 4D program (ep=2 x pp=2 x tp=2): with ample expert
    capacity (no token drops) routing decisions are shard-invariant, so the
    CE loss must match the single-device MoE forward. Aux is weighted 0 here
    because the single-chip aux averages routing stats over the WHOLE batch
    while the 4D program averages per (shard, microbatch) — same estimator,
    different denominator."""
    cfg = _tiny("llama").replace(
        num_experts=4, experts_per_token=2, expert_capacity_factor=8.0
    )
    mesh = build_mesh(dp=1, pp=2, sp=1, ep=2, tp=2, devices=devices)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _batch(cfg)

    ref = causal_lm_loss(cfg, params, tokens, lengths, moe_aux_weight=0.0)

    sharded = place_spmd(params, cfg, mesh)
    loss_fn = make_spmd_loss(cfg, mesh, num_micro=2, moe_aux_weight=0.0)
    got = jax.jit(loss_fn)(sharded, tokens, lengths)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-4, atol=2e-4)

    # With the aux term on, the 4D estimator averages routing stats per
    # (shard, microbatch) while single-chip uses the whole batch — same
    # statistic, different denominator, so ~1e-3 agreement, not exact.
    ref_aux = causal_lm_loss(cfg, params, tokens, lengths)
    got_aux = jax.jit(make_spmd_loss(cfg, mesh, num_micro=2))(sharded, tokens, lengths)
    np.testing.assert_allclose(float(got_aux), float(ref_aux), rtol=3e-3)


def test_spmd_moe_train_step_learns(devices):
    """Full MoE train step (with the aux load-balance term) optimizes."""
    cfg = _tiny("llama").replace(
        num_experts=4, experts_per_token=2, expert_capacity_factor=2.0
    )
    mesh = build_mesh(dp=1, pp=2, sp=1, ep=2, tp=2, devices=devices)
    params = place_spmd(init_params(cfg, jax.random.PRNGKey(0)), cfg, mesh)
    optimizer = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, params, optimizer)
    step = make_spmd_train_step(cfg, mesh, optimizer, num_micro=2)

    tokens, lengths = _batch(cfg)
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("sp_impl", ["ring", "ulysses"])
@pytest.mark.parametrize(
    "family,extra",
    [
        # Mistral-class plain sliding window — previously silently DROPPED by
        # both sp schemes (round-2 advisor finding): full attention in the 4D
        # program vs windowed everywhere else.
        ("mistral", dict(sliding_window=7)),
        # Gemma-2: post-sublayer norms, score soft cap, fixed query scale,
        # alternating windows via the shared pair scan (was a refusal).
        ("gemma2", dict(sliding_window=8, query_pre_attn_scalar=32.0)),
    ],
)
def test_spmd_windowed_families_match_single_device(family, extra, sp_impl, mesh4d):
    cfg = _tiny(family).replace(**extra)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _batch(cfg)

    ref = causal_lm_loss(cfg, params, tokens, lengths)

    sharded = place_spmd(params, cfg, mesh4d)
    loss_fn = make_spmd_loss(cfg, mesh4d, num_micro=2, sp_impl=sp_impl)
    got = jax.jit(loss_fn)(sharded, tokens, lengths)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-4, atol=2e-4)


def test_spmd_alt_window_needs_even_layers_per_stage(devices):
    """Alternating windows require stages to start on even global layers;
    an odd layers-per-stage split is refused at build time."""
    cfg = _tiny("gemma2").replace(sliding_window=8, num_layers=4)
    mesh = build_mesh(dp=1, pp=4, sp=1, tp=2, devices=devices)
    with pytest.raises(ValueError, match="even layer count per pp stage"):
        make_spmd_loss(cfg, mesh)
