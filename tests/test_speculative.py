"""Speculative decoding (runtime/speculative.py).

The load-bearing property is EXACTNESS: the emitted sequence must follow the
target model's own sampling distribution, draft quality only changing speed.
Greedy mode makes that testable token-for-token; sampled mode is pinned by
acceptance-rate structure and first-token distribution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import (
    forward_decode,
    forward_prefill,
    forward_verify,
    init_kv_cache,
    init_params,
)
from edgemesh.runtime import generate
from edgemesh.runtime.speculative import generate_speculative



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _models(seed_t=0, seed_d=1, vocab=64):
    cfg = tiny_config("llama", vocab_size=vocab, max_seq_len=128)
    pt = init_params(cfg, jax.random.PRNGKey(seed_t))
    pd = init_params(cfg, jax.random.PRNGKey(seed_d))
    return cfg, pt, pd


def _prompt(batch=2, s=8, vocab=64, seed=7):
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (batch, s), 0, vocab, jnp.int32)
    return tokens, jnp.full((batch,), s, jnp.int32)


def test_verify_chunk_matches_sequential_decode():
    # forward_verify over a chunk == the same tokens fed one decode at a time.
    cfg, pt, _ = _models()
    tokens, lengths = _prompt()
    b = tokens.shape[0]
    cache1 = init_kv_cache(cfg, b, 64)
    cache2 = init_kv_cache(cfg, b, 64)
    _, cache1 = forward_prefill(cfg, pt, tokens, lengths, cache1)
    _, cache2 = forward_prefill(cfg, pt, tokens, lengths, cache2)
    chunk = jax.random.randint(jax.random.PRNGKey(3), (b, 4), 0, cfg.vocab_size, jnp.int32)

    vlogits, vcache = forward_verify(cfg, pt, chunk, cache1)
    for j in range(4):
        slogits, cache2 = forward_decode(cfg, pt, chunk[:, j], cache2)
        np.testing.assert_allclose(
            np.asarray(vlogits[:, j], np.float32), np.asarray(slogits, np.float32),
            rtol=2e-2, atol=2e-2,
        )
    assert int(vcache.lengths[0]) == int(cache2.lengths[0])


@pytest.mark.parametrize("same_draft", [True, False])
def test_greedy_spec_matches_greedy_dense(same_draft):
    # Greedy speculative decode must equal greedy target decoding EXACTLY,
    # whatever the draft model proposes.
    cfg, pt, pd = _models()
    if same_draft:
        pd = pt
    tokens, lengths = _prompt()
    sampling = SamplingParams(max_new_tokens=16, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, pt, tokens, lengths, sampling)
    spec, stats = generate_speculative(cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=3)
    np.testing.assert_array_equal(np.asarray(spec.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(np.asarray(spec.num_generated), np.asarray(ref.num_generated))
    if same_draft:
        # Identical models agree everywhere → every proposal accepted.
        assert stats.accepted == stats.proposed > 0


def test_greedy_spec_matches_dense_with_repetition_penalty():
    cfg, pt, pd = _models()
    tokens, lengths = _prompt()
    sampling = SamplingParams(max_new_tokens=12, do_sample=False, repetition_penalty=1.3)
    ref = generate(cfg, pt, tokens, lengths, sampling)
    spec, _ = generate_speculative(cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=4)
    np.testing.assert_array_equal(np.asarray(spec.tokens), np.asarray(ref.tokens))


def test_sampled_spec_with_identical_models_accepts_everything():
    # p == q pointwise → acceptance ratio 1 → every draft token accepted.
    cfg, pt, _ = _models()
    tokens, lengths = _prompt()
    sampling = SamplingParams(
        max_new_tokens=16, do_sample=True, temperature=0.9, top_k=8, top_p=0.9,
        repetition_penalty=1.1,
    )
    _, stats = generate_speculative(cfg, pt, cfg, pt, tokens, lengths, sampling, gamma=3)
    assert stats.proposed > 0
    assert stats.accepted == stats.proposed


def test_sampled_first_token_matches_target_distribution():
    # Slot 0 comes straight from target prefill logits — its empirical
    # distribution over seeds must match the dense path's exactly (same
    # sample_token call on the same logits).
    cfg, pt, pd = _models()
    tokens, lengths = _prompt(batch=1)
    sampling = SamplingParams(
        max_new_tokens=2, do_sample=True, temperature=1.0, top_k=8, top_p=1.0,
        repetition_penalty=1.0,
    )
    firsts_spec, firsts_dense = [], []
    for seed in range(60):
        rng = jax.random.PRNGKey(seed)
        spec, _ = generate_speculative(
            cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=2, rng=rng
        )
        dense = generate(cfg, pt, tokens, lengths, sampling, rng=rng)
        firsts_spec.append(int(spec.tokens[0, 0]))
        firsts_dense.append(int(dense.tokens[0, 0]))
    assert firsts_spec == firsts_dense  # same rng split → identical slot 0


def test_eos_truncates_round():
    # Force EOS as the only samplable token: the run must stop at slot 0/1,
    # not emit a full round of gamma+1 tokens.
    cfg, pt, pd = _models()
    tokens, lengths = _prompt(batch=2)
    sampling = SamplingParams(max_new_tokens=12, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, pt, tokens, lengths, sampling, eos_id=5)
    spec, _ = generate_speculative(
        cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=3, eos_id=5
    )
    np.testing.assert_array_equal(np.asarray(spec.tokens), np.asarray(ref.tokens))
    np.testing.assert_array_equal(
        np.asarray(spec.num_generated), np.asarray(ref.num_generated)
    )


def test_sampled_sequence_distribution_matches_dense():
    # The whole point: sampled speculative output follows the TARGET's
    # distribution. Tiny scale (1 row, 2 new tokens, vocab 16): empirical
    # first-two-token joint over many seeds must match the dense path's
    # within statistical tolerance, despite a different draft model and a
    # different RNG consumption pattern.
    cfg = tiny_config("llama", vocab_size=16, max_seq_len=64, num_layers=1)
    pt = init_params(cfg, jax.random.PRNGKey(0))
    pd = init_params(cfg, jax.random.PRNGKey(9))
    tokens = jnp.asarray([[3, 1, 4]], jnp.int32)
    lengths = jnp.asarray([3], jnp.int32)
    sampling = SamplingParams(
        max_new_tokens=2, do_sample=True, temperature=1.2, top_k=6, top_p=0.95,
        repetition_penalty=1.1,
    )
    n = 400
    counts_spec = np.zeros((16, 16))
    counts_dense = np.zeros((16, 16))
    for seed in range(n):
        rng = jax.random.PRNGKey(1000 + seed)
        spec, _ = generate_speculative(
            cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=2, rng=rng
        )
        dense = generate(cfg, pt, tokens, lengths, sampling, rng=jax.random.PRNGKey(5000 + seed))
        counts_spec[int(spec.tokens[0, 0]), int(spec.tokens[0, 1])] += 1
        counts_dense[int(dense.tokens[0, 0]), int(dense.tokens[0, 1])] += 1
    # Compare marginals (tighter than the joint at this sample size).
    for axis in (0, 1):
        ms = counts_spec.sum(axis=axis) / n
        md = counts_dense.sum(axis=axis) / n
        np.testing.assert_allclose(ms, md, atol=0.09)


def test_spec_validates_inputs():
    cfg, pt, pd = _models()
    cfg2 = tiny_config("llama", vocab_size=32, max_seq_len=128)
    tokens, lengths = _prompt()
    sampling = SamplingParams(max_new_tokens=4, do_sample=True, top_k=8)
    with pytest.raises(ValueError, match="shared vocab"):
        generate_speculative(cfg, pt, cfg2, init_params(cfg2, jax.random.PRNGKey(2)),
                             tokens, lengths, sampling)
    with pytest.raises(ValueError, match="top_k"):
        generate_speculative(cfg, pt, cfg, pd, tokens, lengths,
                             SamplingParams(max_new_tokens=4, do_sample=True, top_k=0))
    with pytest.raises(ValueError, match="gamma"):
        generate_speculative(cfg, pt, cfg, pd, tokens, lengths, sampling, gamma=0)


def test_streaming_speculative_matches_plain_greedy():
    """Segmented speculative streaming (VERDICT r2 weak #8: spec + streaming
    now compose): concatenated segment tokens == plain greedy generate ==
    non-streamed speculative, and the generator's return value carries the
    same stats shape."""
    from edgemesh.runtime.speculative import generate_speculative_stream

    cfg, pt, pd = _models()
    tokens, lengths = _prompt()
    s = SamplingParams(max_new_tokens=24, do_sample=False, repetition_penalty=1.0)

    ref = generate(cfg, pt, tokens, lengths, s)
    spec, _ = generate_speculative(cfg, pt, cfg, pd, tokens, lengths, s, gamma=3)

    gen = generate_speculative_stream(cfg, pt, cfg, pd, tokens, lengths, s,
                                      gamma=3, rounds_per_segment=2)
    per_row = [[], []]
    n_segments = 0
    result = None
    while True:
        try:
            seg = next(gen)
        except StopIteration as stop:
            result = stop.value
            break
        n_segments += 1
        for b in range(2):
            c = int(seg.counts[b])
            per_row[b].extend(int(t) for t in seg.tokens[b][:c])
    assert n_segments >= 2  # actually segmented, not one burst
    res, stats = result
    assert stats.rounds > 0 and stats.proposed > 0
    for b in range(2):
        n = int(ref.num_generated[b])
        assert per_row[b][:n] == [int(t) for t in ref.tokens[b][:n]]
        assert per_row[b][:n] == [int(t) for t in spec.tokens[b][:n]]
        assert int(res.num_generated[b]) == int(spec.num_generated[b])


def test_agent_answer_stream_uses_draft():
    """An agent with a draft model streams deltas whose concatenation equals
    its non-streamed answer (greedy)."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec

    spec = AgentSpec(
        role="qa",
        model=ModelSpec(num_layers=2, hidden_size=64, max_seq_len=256),
        draft=ModelSpec(num_layers=1, hidden_size=64, max_seq_len=256),
        spec_gamma=3,
        sampling=SamplingParams(max_new_tokens=16, do_sample=False,
                                repetition_penalty=1.0),
    )
    agent = build_agent(spec)
    assert agent.draft_cfg is not None
    plain = agent.answer("Where is the Eiffel Tower?")["answer"]
    text, final = "", None
    for item in agent.answer_stream("Where is the Eiffel Tower?"):
        if item.get("done"):
            final = item
        else:
            text = text[: len(text) - item.get("rewind", 0)] + item["delta"]
    assert final is not None and final["answer"] == plain
    assert text == plain or plain.startswith(text)


def test_streaming_speculative_sampled_matches_nonstreamed():
    """Sampled mode: same rng seed → the segmented stream commits exactly
    the non-streamed speculative tokens (both run the same jitted rounds;
    segmentation must not perturb the rng path)."""
    from edgemesh.runtime.speculative import generate_speculative_stream

    cfg, pt, pd = _models()
    tokens, lengths = _prompt()
    s = SamplingParams(max_new_tokens=20, do_sample=True, temperature=0.9,
                       top_k=8, top_p=1.0, repetition_penalty=1.1, seed=5)

    ref, _ = generate_speculative(cfg, pt, cfg, pd, tokens, lengths, s, gamma=3,
                                  rng=jax.random.PRNGKey(5))
    gen = generate_speculative_stream(cfg, pt, cfg, pd, tokens, lengths, s,
                                      gamma=3, rng=jax.random.PRNGKey(5),
                                      rounds_per_segment=2)
    per_row = [[], []]
    while True:
        try:
            seg = next(gen)
        except StopIteration:
            break
        for b in range(2):
            per_row[b].extend(int(t) for t in seg.tokens[b][: int(seg.counts[b])])
    for b in range(2):
        n = int(ref.num_generated[b])
        assert per_row[b][:n] == [int(t) for t in ref.tokens[b][:n]]


def test_streaming_speculative_rejects_bad_segment_budget():
    from edgemesh.runtime.speculative import generate_speculative_stream

    cfg, pt, pd = _models()
    tokens, lengths = _prompt()
    s = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    with pytest.raises(ValueError, match="rounds_per_segment"):
        next(generate_speculative_stream(cfg, pt, cfg, pd, tokens, lengths, s,
                                         rounds_per_segment=0))


def test_speculative_paged_matches_dense():
    """Speculative decoding over the paged pools == the dense-cache spec
    path, greedy, token for token — the rewind (lengths rollback) is safe on
    pages because the allocator reuses slots that kept their pages."""
    cfg, params_t, params_d = _models()
    cfg_t = cfg_d = cfg
    tokens = jnp.array([[5, 9, 11, 42, 7], [17, 3, 50, 8, 0]], jnp.int32)
    lengths = jnp.array([5, 4], jnp.int32)
    s = SamplingParams(max_new_tokens=16, do_sample=False, repetition_penalty=1.0)
    dense, st_dense = generate_speculative(
        cfg_t, params_t, cfg_d, params_d, tokens, lengths, s,
        gamma=3, rng=jax.random.PRNGKey(3),
    )
    paged, st_paged = generate_speculative(
        cfg_t, params_t, cfg_d, params_d, tokens, lengths, s,
        gamma=3, rng=jax.random.PRNGKey(3), kv_backend="paged", page_size=4,
    )
    np.testing.assert_array_equal(np.asarray(dense.tokens), np.asarray(paged.tokens))
    assert st_paged.accepted == st_dense.accepted
    assert st_paged.rounds == st_dense.rounds


def test_speculative_paged_sampled_matches_dense():
    """Sampled mode: identical rng → identical tokens across cache backends
    (the acceptance/residual math never touches the cache layout)."""
    cfg, params_t, params_d = _models()
    cfg_t = cfg_d = cfg
    tokens = jnp.array([[5, 9, 11, 42, 7]], jnp.int32)
    lengths = jnp.array([5], jnp.int32)
    s = SamplingParams(max_new_tokens=12, do_sample=True, temperature=0.9,
                      top_k=20, top_p=0.95, repetition_penalty=1.1)
    dense, _ = generate_speculative(
        cfg_t, params_t, cfg_d, params_d, tokens, lengths, s,
        gamma=3, rng=jax.random.PRNGKey(11),
    )
    paged, _ = generate_speculative(
        cfg_t, params_t, cfg_d, params_d, tokens, lengths, s,
        gamma=3, rng=jax.random.PRNGKey(11), kv_backend="paged", page_size=4,
    )
    np.testing.assert_array_equal(np.asarray(dense.tokens), np.asarray(paged.tokens))


def test_speculative_paged_int8_matches_plain_paged_int8():
    """Speculative decoding over int8 page pools emits exactly what plain
    int8-paged decoding emits (greedy): the verify chunk's per-token
    quantize_kv scales are identical to the decode step's, so the target's
    int8 KV trajectory — and therefore its argmax at every position — is
    the same with or without a draft."""
    from edgemesh.runtime.paged_generate import generate_paged

    cfg, params_t, params_d = _models()
    tokens = jnp.array([[5, 9, 11, 42, 7], [17, 3, 50, 8, 0]], jnp.int32)
    lengths = jnp.array([5, 4], jnp.int32)
    s = SamplingParams(max_new_tokens=16, do_sample=False, repetition_penalty=1.0)
    plain = generate_paged(cfg, params_t, tokens, lengths, s, eos_id=-1,
                           kv_quant=True, page_size=4)
    spec, stats = generate_speculative(
        cfg, params_t, cfg, params_d, tokens, lengths, s,
        gamma=3, eos_id=-1, rng=jax.random.PRNGKey(3),
        kv_backend="paged_int8", page_size=4,
    )
    np.testing.assert_array_equal(np.asarray(plain.tokens), np.asarray(spec.tokens))
    assert stats.proposed > 0
