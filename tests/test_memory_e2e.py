"""The memory observatory end-to-end (slow tier) — the ISSUE acceptance
scenarios on REAL engines and a real in-process fleet front:

1. the conservation invariant (free + resident + reserved == total) holds
   at every quiesce across ragged/segmented × paged/paged_int8 engine
   runs, across a KV-transfer export→import hop, and across an
   abort-mid-prefill (a request too big for the pool);
2. an injected leak — pages popped through the ledger seam whose owner
   retires without freeing — fires the ``pool_leak`` anomaly by itself
   from the engine's own quiesce scan, and the flight dump names the
   leaking request; a sibling replica adopting the incident id lands its
   ring in the SAME incident directory (the fleet-wide dump);
3. exhaustion-aware admission: under a pool-exhausting batch flood the
   fleet front defers/sheds the batch lane on the digest's ``mem``
   forecast — zero batch requests reach the engine while pressured —
   while interactive traffic keeps flowing with zero client-visible
   500s, and ``/fleetz`` reports the fleet mem rollup.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from edgemesh.agents.orchestrator import build_agent, build_ensemble
from edgemesh.config import (
    AgentSpec,
    EdgeMeshConfig,
    ModelSpec,
    SamplingParams,
)
from edgemesh.serve.continuous import ContinuousEngine

pytestmark = pytest.mark.slow


def _sampling(max_new=24):
    return SamplingParams(max_new_tokens=max_new, do_sample=False,
                          repetition_penalty=1.0)


def _agent(max_new=24):
    return build_agent(
        AgentSpec(role="qa", model=ModelSpec(), sampling=_sampling(max_new)))


def _quiesce_ok(eng):
    """One explicit quiesce check on top of the loop's own: the invariant
    must hold on the final state, and the tripwire must never have fired."""
    with eng._cond:
        free = len(eng._free_pages)
    assert eng.mem.check_conservation(free) is True
    return eng.mem.rollup()


# ---------------------------------------------------------------------------
# 1. Conservation at quiesce, across the engine matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend,ragged", [
    ("paged", None),        # ragged boundary launches (the default)
    ("paged", False),       # segmented per-request admission prefills
    ("paged_int8", None),
    ("paged_int8", False),
])
def test_conservation_holds_at_quiesce(backend, ragged):
    """Overcommitted stream (5 requests, 2 slots) with tenant attribution:
    every page comes home, the books balance, no tenant page leaks."""
    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend=backend,
                           page_size=8, ragged=ragged)
    try:
        futs = [eng.submit(f"question {i}?", tenant=f"team-{i % 2}")
                for i in range(5)]
        results = [f.result(timeout=600) for f in futs]
        assert all(isinstance(r["answer"], str) for r in results)
        roll = _quiesce_ok(eng)
        assert roll["conservation_breaks"] == 0
        assert roll["leaked_pages"] == 0
        # Attribution: both tenants held pages and drained to zero.
        for t in ("team-0", "team-1"):
            assert roll["tenants"][t]["peak_pages"] > 0
            assert roll["tenants"][t]["pages"] == 0
        assert roll["events"]["retire"]["pages"] > 0
        # The digest's mem block is live and self-consistent.
        mem = eng.load_digest()["mem"]
        assert mem["total_pages"] == eng.total_pages
        assert mem["free_pages"] + mem["resident_pages"] \
            + eng.mem.reserved_overhead == eng.total_pages
    finally:
        eng.close()


def test_conservation_holds_across_kv_import():
    """Prefill/decode disaggregation: the export scratch pages and the
    import-spliced pages both flow through the ledger seam — BOTH pools'
    books balance after the hop, and the import is attributed."""
    agent = _agent(max_new=12)
    src = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    dst = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        q = "what city hosts the eiffel tower?"
        exp = src.submit_export(q).result(timeout=600)
        got = dst.answer(q, kv_import=exp["kv_bytes"], tenant="mover")
        assert isinstance(got["answer"], str) and got["answer"]
        src_roll = _quiesce_ok(src)
        assert src_roll["conservation_breaks"] == 0
        assert src_roll["events"]["export"]["pages"] > 0
        dst_roll = _quiesce_ok(dst)
        assert dst_roll["conservation_breaks"] == 0
        assert dst_roll["events"]["import"]["pages"] > 0
        assert dst_roll["tenants"]["mover"]["pages"] == 0
        assert dst_roll["leaked_pages"] == 0
    finally:
        src.close()
        dst.close()


def test_conservation_holds_across_abort_mid_prefill():
    """An admission the pool can never satisfy aborts cleanly before any
    page moves; the books stay balanced and the next fitting request
    completes on the same engine."""
    agent = _agent(max_new=64)
    # 14 pages: the templated "hi?" needs ~9 (prompt + budget + overshoot)
    # and fits; the 64-token-budget request needs ~22 and can never fit.
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, total_pages=14)
    try:
        with pytest.raises(ValueError, match="pool holds"):
            eng.answer("this request cannot ever fit in this pool?")
        roll = eng.mem.rollup()
        if roll:  # template-only state is legal (no request page ever moved)
            assert roll["conservation_breaks"] == 0
        short = eng.answer("hi?", max_new=2)
        assert isinstance(short["answer"], str)
        roll = _quiesce_ok(eng)
        assert roll["conservation_breaks"] == 0
        assert roll["leaked_pages"] == 0
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# 2. Injected leak → pool_leak → fleet-wide flight dump
# ---------------------------------------------------------------------------


def test_injected_leak_fires_pool_leak_with_fleet_wide_dump(tmp_path):
    from edgemesh.obs import AnomalyMonitor, FlightRecorder, Registry
    from edgemesh.obs.anomaly import PoolLeakDetector

    dump_dir = tmp_path / "incidents"
    agent = _agent(max_new=8)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        flight = FlightRecorder(registry=eng.obs.registry,
                                replica="replica-leaky",
                                snapshot_source=eng.load_digest)
        monitor = AnomalyMonitor(flight, dump_dir,
                                 registry=eng.obs.registry,
                                 pool_leak=PoolLeakDetector(age_s=0.2))
        eng.obs.flight = flight
        eng.obs.anomaly = monitor
        eng.answer("warmup?")
        # Inject the leak THROUGH the seam: pages popped for a request
        # that retires without freeing them — attribution intact, which
        # is exactly what lets the dump name the culprit.
        eng._pop_pages(2, rid="leaky-rid", tenant="evil", cause="admit")
        eng.mem.on_retired("leaky-rid")
        time.sleep(0.4)  # past the detector's age bound
        # The engine's own quiesce scan (no operator action) must fire it:
        # the nudge request drains and the idle loop runs leak_scan.
        eng.answer("nudge?")
        deadline = time.time() + 60
        while not monitor.incidents() and time.time() < deadline:
            time.sleep(0.05)
        incidents = monitor.incidents()
        assert incidents, "engine quiesce scan never fired pool_leak"
        inc = incidents[0]
        assert inc["kind"] == "pool_leak"
        assert inc["detail"]["rid"] == "leaky-rid"
        assert inc["detail"]["engine"] == "continuous"
        # The local dump names the leaking request in its header.
        dump = dump_dir / inc["id"] / "flight-replica-leaky.jsonl"
        assert dump.exists()
        header = json.loads(dump.read_text().splitlines()[0])
        assert header["kind"] == "pool_leak"
        assert header["rid"] == "leaky-rid"
        # Fleet-wide: a sibling replica adopting the propagated incident
        # id (the router's broadcast path) lands its ring BESIDE the
        # leaker's, under the same incident directory.
        sibling = FlightRecorder(registry=Registry(), replica="replica-b")
        sibling.record("span", {"rid": "bystander"})
        AnomalyMonitor(sibling, dump_dir, registry=Registry()).note_incident(
            inc["id"], kind="propagated", detail=inc["detail"])
        dumps = sorted(p.name for p in (dump_dir / inc["id"]).iterdir())
        assert dumps == ["flight-replica-b.jsonl",
                         "flight-replica-leaky.jsonl"]
        # A leak is lost ATTRIBUTION, not lost pages: conservation holds.
        with eng._cond:
            assert eng.mem.check_conservation(len(eng._free_pages)) is True
        assert eng.mem.rollup()["leaked_pages"] == 2
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# 3. Exhaustion-aware admission under a pool-exhausting flood
# ---------------------------------------------------------------------------


def _post(url, payload, tenant=None, timeout_s=300.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **({"X-Edgemesh-Tenant": tenant} if tenant else {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_batch_deferral_keeps_interactive_goodput_under_flood(tmp_path):
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.fleet.admission import AdmissionController, TenantPolicy
    from edgemesh.obs import Registry
    from edgemesh.serve.rest import serve_rest

    cfg = EdgeMeshConfig(agents=[
        AgentSpec(role="qa", model=ModelSpec(), sampling=_sampling(16))])
    ens = build_ensemble(cfg, use_submeshes=False)
    replica = serve_rest(ens, host="127.0.0.1", port=0, block=False,
                         continuous=True, kv_backend="paged",
                         kv_page_size=8, batch=2, registry=Registry())
    prober = None
    front = None
    try:
        rp = replica.server_address[1]
        obs = Registry()
        registry = ReplicaRegistry([("replica-0", f"http://127.0.0.1:{rp}")])
        # Horizon sized so the flood's forecast lands under it on any host
        # speed: the pool holds ~2 worst-case admissions, so even a slow
        # CPU's arrival EWMA forecasts well under a minute to empty.
        admission = AdmissionController(
            max_inflight=8, mem_horizon_s=60.0,
            policies={"bulk": TenantPolicy(lane="batch")})
        router = FleetRouter(registry, transport=HttpTransport(),
                             obs_registry=obs, admission=admission,
                             max_attempts=3, attempt_timeout_s=120.0,
                             default_deadline_s=300.0)
        prober = HealthProber(registry, transport=HttpTransport(),
                              interval_s=0.2, timeout_s=5.0,
                              obs_registry=obs,
                              on_digest=router.note_digest).start()
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        url = f"http://127.0.0.1:{front.server_address[1]}/generate"

        # Phase A — the flood: concurrent bulk requests. The first wave
        # establishes the engine's arrival EWMA, so the digest's mem
        # forecast collapses below the horizon and the prober feeds it to
        # the admission controller.
        results = []

        def bulk(i):
            results.append(_post(url, {"question": f"bulk {i}?"}, "bulk"))

        threads = [threading.Thread(target=bulk, args=(i,))
                   for i in range(10)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=300.0)
        assert all(s in (200, 503) for s, _ in results), results
        deadline = time.monotonic() + 60
        while admission.stats()["mem_forecast_s"] is None \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        st = admission.stats()
        assert st["mem_forecast_s"] is not None, \
            "digest mem forecast never reached the admission controller"
        assert st["mem_forecast_s"] < 60.0

        # Phase B — pressured: a bulk-only burst admits ZERO requests to
        # the engine (every verdict a deferral-shed, never a 500) ...
        served_before = replica.batcher.stats()["requests"]
        burst = [_post(url, {"question": f"late bulk {i}?"}, "bulk")
                 for i in range(6)]
        assert [s for s, _ in burst] == [503] * 6, burst
        assert replica.batcher.stats()["requests"] == served_before
        assert admission.stats()["mem_deferrals"] >= 6

        # ... while interactive traffic keeps flowing: zero client-visible
        # 500s, every answer real.
        inter = [_post(url, {"question": f"chat {i}?"}, "alice")
                 for i in range(6)]
        assert [s for s, _ in inter] == [200] * 6, inter
        assert all("answer" in b for _, b in inter)

        # The fleet surface tells the story: /fleetz carries the mem
        # rollup with the tight forecast attributed to the replica.
        status, fleetz = _post_get(
            f"http://127.0.0.1:{front.server_address[1]}/fleetz")
        assert status == 200
        mem = fleetz["mem"]
        assert mem is not None
        assert mem["min_forecast_s"] is not None
        assert "replica-0" in mem["replicas"]
        assert mem["replicas"]["replica-0"]["total_pages"] is not None
        assert mem["fleet_conservation_breaks"] == 0

        # And the pool itself never wedged or miscounted.
        roll = replica.batcher.mem.rollup()
        assert roll["conservation_breaks"] == 0
        assert roll["leaked_pages"] == 0
    finally:
        if prober is not None:
            prober.stop()
        if front is not None:
            front.shutdown()
        replica.shutdown()


def _post_get(url, timeout_s=30.0):
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.status, json.load(r)
