"""Config system: YAML round-trip and the `is not None` override semantics
(the correct reference idiom, combiner_fp.py:404-410 — NOT the falsy-dropping
`or` variant of Llama_bf16_updated.py:154-161)."""

import textwrap

from edgemesh.config import EdgeMeshConfig, load_config


def test_defaults_match_reference_sampling_knobs():
    cfg = EdgeMeshConfig()
    # config_2.yaml:11-14
    s = cfg.agents[0].sampling if cfg.agents else __import__("edgemesh.config", fromlist=["SamplingParams"]).SamplingParams()
    assert s.max_new_tokens == 100
    assert s.temperature == 0.7
    assert s.top_k == 50
    assert s.top_p == 0.9
    assert s.repetition_penalty == 1.2


def test_yaml_load_and_agents(tmp_path):
    yaml_text = textwrap.dedent(
        """
        seed: 7
        mesh: {dp: 2, tp: 4}
        agents:
          - role: qa
            model: {path: /m/phi, family: phi2, precision: int8}
            sampling: {max_new_tokens: 64, temperature: 0.5}
          - role: refiner
            model: {path: /m/llama, family: llama}
        """
    )
    p = tmp_path / "c.yaml"
    p.write_text(yaml_text)
    cfg = load_config(p)
    assert cfg.seed == 7
    assert cfg.mesh.dp == 2 and cfg.mesh.tp == 4 and cfg.mesh.num_devices == 8
    assert len(cfg.agents) == 2
    assert cfg.agents[0].model.family == "phi2"
    assert cfg.agents[0].model.precision == "int8"
    assert cfg.agents[0].sampling.max_new_tokens == 64
    assert cfg.agents[1].role == "refiner"


def test_override_semantics_none_vs_falsy(tmp_path):
    p = tmp_path / "c.yaml"
    p.write_text("seed: 5\nmesh: {tp: 4}\n")
    # None → YAML value kept
    cfg = load_config(p, {"seed": None})
    assert cfg.seed == 5
    # Falsy-but-not-None MUST override (the reference's `or` idiom loses this)
    cfg = load_config(p, {"seed": 0})
    assert cfg.seed == 0
    # dotted path into nested dataclass
    cfg = load_config(p, {"mesh.tp": 2, "eval.num_samples": 10})
    assert cfg.mesh.tp == 2
    assert cfg.eval.num_samples == 10
