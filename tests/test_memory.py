"""The memory observatory (obs/memory.py) — fast tier.

Pins the contracts the serving stack, the fleet, and the CLI depend on:

- the ledger books: reserve/commit/free lifecycle, per-tenant residency
  + peak watermarks through ``bounded_label``, the frag decomposition
  (internal = reserved-minus-committed by cause, external = admission
  remainder);
- the conservation invariant: quiet when the books balance, tripwire
  counter + ``pool_mem`` record when they do not — never "fixed";
- the leak path: ``on_retired`` starts the clock, ``leak_scan`` hands
  candidates to the anomaly monitor, ``pool_leak`` fires once per rid;
- the exhaustion forecast and its digest ``mem`` block (None until the
  first transition — pre-mem digests stay byte-identical);
- the kill switch: ``EDGEMESH_MEM_LEDGER=0`` turns every hook into a
  no-op (the overhead-gate off arm);
- offline twins: ``summarize_mem`` / ``diff_mem`` forward-compat in
  BOTH directions (pre-mem logs → None rc 0, unknown keys ignored),
  ``replay_spans`` routing pool records into the same registry families;
- the fleet consumers: batch-lane deferral under a short forecast
  (fleet/admission.py), the autoscaler's memory-pressure vote
  (fleet/autoscale.py), the balancer's soft penalty, the /fleetz rollup;
- the ``edgemesh obs mem`` CLI: table / --json / --diff, rc 0 on a
  pre-mem log.
"""

import json

import pytest

from edgemesh.fleet.admission import AdmissionController, TenantPolicy
from edgemesh.fleet.autoscale import AutoScaler
from edgemesh.fleet.balancer import TelemetryBalancer
from edgemesh.fleet.registry import ReplicaRegistry
from edgemesh.obs import (
    AnomalyMonitor,
    PoolLedger,
    Registry,
    diff_mem,
    replay_spans,
    summarize_mem,
)
from edgemesh.obs.memory import POOL_RECORD_EVENT, replay_pool_record
from edgemesh.utils.tracing import JsonlLogger


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def _ledger(tmp_path=None, clock=None, **kw):
    kw.setdefault("total_pages", 65)
    kw.setdefault("page_size", 16)
    kw.setdefault("per_row_worst", 8)
    kw.setdefault("reserved_overhead", 1)
    kw.setdefault("enabled", True)
    return PoolLedger(
        registry=Registry(), engine="t",
        span_log=None if tmp_path is None else tmp_path / "spans.jsonl",
        clock=clock or Clock(), **kw)


def _gauge(reg, name, labelnames, **labels):
    return reg.gauge(name, "", labelnames).labels(**labels).value


def _counter(reg, name, labelnames, **labels):
    return reg.counter(name, "", labelnames).labels(**labels).value


# ---------------------------------------------------------------------------
# The books: lifecycle, tenants, fragmentation
# ---------------------------------------------------------------------------


def test_reserve_commit_free_lifecycle_and_tenant_attribution():
    led = _ledger()
    led.on_reserve(8, rid="r1", tenant="acme", cause="admit", free=56)
    led.on_reserve(8, rid="r2", tenant="globex", cause="admit", free=48)
    led.on_commit("r1", add_tokens=20)  # ceil(20/16) = 2 pages committed
    roll = led.rollup()
    assert roll["resident_pages"] == 16
    assert roll["peak_resident_pages"] == 16
    assert roll["free_pages"] == 48
    assert roll["tenants"]["acme"] == {"pages": 8, "peak_pages": 8}
    assert roll["tenants"]["globex"] == {"pages": 8, "peak_pages": 8}
    assert roll["events"]["admit"] == {"count": 2, "pages": 16}
    # Internal frag: r1 sits on 8-2=6 uncommitted pages, r2 on all 8.
    assert roll["frag"]["internal_pages"] == 14
    assert roll["frag"]["internal_by_cause"] == {"admit": 14}
    # External: 48 free % 8 per-row-worst = 0 (whole admissions fit).
    assert roll["frag"]["external_pages"] == 0
    assert _gauge(led.registry, "edgemesh_pool_tenant_pages",
                  ("engine", "tenant"), engine="t", tenant="acme") == 8
    led.on_free(8, rid="r1", cause="retire", free=56)
    roll = led.rollup()
    assert roll["resident_pages"] == 8
    assert roll["peak_resident_pages"] == 16  # watermark survives the free
    assert roll["tenants"]["acme"] == {"pages": 0, "peak_pages": 8}
    assert _gauge(led.registry, "edgemesh_pool_tenant_pages",
                  ("engine", "tenant"), engine="t", tenant="acme") == 0


def test_commit_is_floored_capped_and_monotonic():
    led = _ledger()
    led.on_reserve(4, rid="r", tenant="a", cause="admit")
    led.on_commit("r", add_tokens=16)  # 1 page
    led.on_commit("r", add_tokens=16)  # accumulates to 2
    assert led.rollup()["frag"]["internal_pages"] == 2
    led.on_commit("r", committed_pages=1)  # never regresses
    assert led.rollup()["frag"]["internal_pages"] == 2
    led.on_commit("r", add_tokens=10_000)  # capped at the holding's pages
    assert led.rollup()["frag"]["internal_pages"] == 0
    led.on_commit("missing")  # unknown rid: no-op, no crash


def test_external_frag_is_the_admission_remainder():
    led = _ledger(per_row_worst=8)
    led.on_reserve(3, rid="r", tenant="a", cause="admit", free=13)
    # 13 free pages = one whole worst-case admission + 5 stranded.
    assert led.rollup()["frag"]["external_pages"] == 5


def test_reset_zeroes_the_books_and_records_reclaimed_pages():
    led = _ledger()
    led.on_reserve(8, rid="r1", tenant="acme", cause="admit")
    led.on_reserve(4, rid="r2", tenant="globex", cause="cow")
    led.on_reset(reason="regrow")
    roll = led.rollup()
    assert roll["resident_pages"] == 0
    assert roll["resets"] == 1
    assert roll["events"]["reset"] == {"count": 1, "pages": 12}
    assert roll["tenants"]["acme"]["pages"] == 0
    assert roll["tenants"]["acme"]["peak_pages"] == 8  # history survives


def test_disabled_ledger_is_inert():
    led = _ledger(enabled=False)
    led.enabled = False
    led.on_reserve(8, rid="r", tenant="a", cause="admit")
    led.on_retired("r")
    led.on_reset()
    assert led.rollup() == {}
    assert led.digest_mem(free_pages=10, arrival_ewma_s=1.0) is None
    assert led.check_conservation(0) is True
    assert led.leak_scan() == []


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("EDGEMESH_MEM_LEDGER", "0")
    led = PoolLedger(registry=Registry(), engine="t", total_pages=10)
    assert led.enabled is False
    monkeypatch.setenv("EDGEMESH_MEM_LEDGER", "1")
    assert PoolLedger(registry=Registry(), engine="t").enabled is True


# ---------------------------------------------------------------------------
# Conservation + tripwire
# ---------------------------------------------------------------------------


def test_conservation_holds_then_breaks_then_counts(tmp_path):
    led = _ledger(tmp_path, total_pages=65, reserved_overhead=1)
    led.on_reserve(8, rid="r", tenant="a", cause="admit", free=56)
    # 56 free + 8 resident + 1 trash page == 65 total: books balance.
    assert led.check_conservation(56) is True
    assert led.rollup()["conservation_breaks"] == 0
    # Two pages vanish (the failure EM115 exists to prevent).
    assert led.check_conservation(54) is False
    assert led.rollup()["conservation_breaks"] == 1
    assert _counter(led.registry, "edgemesh_pool_conservation_breaks_total",
                    ("engine",), engine="t") == 1
    recs = JsonlLogger(tmp_path / "spans.jsonl").read()
    brk = [r for r in recs if r.get("cause") == "conservation_break"]
    assert len(brk) == 1 and brk[0]["delta"] == -2
    assert brk[0]["expected"] == 64 and brk[0]["total"] == 65


def test_conservation_is_silent_before_first_transition():
    led = _ledger(total_pages=65)
    # A cold pool (free list not even counted yet) must not false-alarm.
    assert led.check_conservation(0) is True
    assert led.rollup() == {}


# ---------------------------------------------------------------------------
# Leak detection → pool_leak anomaly
# ---------------------------------------------------------------------------


def test_injected_leak_fires_pool_leak_once(tmp_path):
    clock = Clock()
    monitor = AnomalyMonitor(registry=Registry())
    led = _ledger(tmp_path, clock=clock, anomaly_source=lambda: monitor)
    led.on_reserve(8, rid="leaky", tenant="acme", cause="admit")
    led.on_retired("leaky")  # retires WITHOUT freeing: the injected leak
    clock.tick(5.0)
    assert led.leak_scan() != []  # candidate reported...
    assert monitor.incidents() == []  # ...but too young to fire (30s bound)
    clock.tick(60.0)
    leaks = led.leak_scan()
    assert leaks == [{"rid": "leaky", "tenant": "acme", "pages": 8,
                      "age_s": 65.0, "cause": "admit"}]
    incidents = monitor.incidents()
    assert len(incidents) == 1
    assert incidents[0]["kind"] == "pool_leak"
    assert incidents[0]["detail"]["rid"] == "leaky"
    assert incidents[0]["detail"]["engine"] == "t"
    # Fire-once per rid: the next scan still reports, never re-triggers.
    clock.tick(60.0)
    assert led.leak_scan() != []
    assert len(monitor.incidents()) == 1
    # The fired leak left a replayable record.
    recs = JsonlLogger(tmp_path / "spans.jsonl").read()
    assert [r for r in recs if r.get("cause") == "leak"]
    assert led.digest_mem()["leak"] == {"requests": 1, "pages": 8}


def test_clean_retirement_never_starts_the_leak_clock():
    clock = Clock()
    monitor = AnomalyMonitor(registry=Registry())
    led = _ledger(clock=clock, anomaly_source=lambda: monitor)
    led.on_reserve(8, rid="r", tenant="a", cause="admit")
    led.on_free(8, rid="r", cause="retire")
    led.on_retired("r")
    clock.tick(1000.0)
    assert led.leak_scan() == []
    assert monitor.incidents() == []


# ---------------------------------------------------------------------------
# Forecast + digest
# ---------------------------------------------------------------------------


def test_forecast_math_and_unknowns():
    led = _ledger(per_row_worst=8)
    # 40 free pages / (8 pages per request / 0.5 s per arrival) = 2.5 s.
    assert led.forecast(40, 0.5) == pytest.approx(2.5)
    assert led.forecast(0, 0.5) == 0.0
    assert led.forecast(40, None) is None  # no arrivals observed yet
    assert led.forecast(40, 0.0) is None
    assert _ledger(per_row_worst=0).forecast(40, 0.5) is None


def test_digest_mem_is_none_until_first_transition_then_complete():
    led = _ledger()
    assert led.digest_mem(free_pages=64, arrival_ewma_s=1.0) is None
    led.on_reserve(8, rid="r", tenant="acme", cause="admit", free=56)
    led.on_commit("r", committed_pages=3)
    d = led.digest_mem(free_pages=56, arrival_ewma_s=0.5)
    assert d["total_pages"] == 65
    assert d["free_pages"] == 56
    assert d["resident_pages"] == 8
    assert d["committed_pages"] == 3
    assert d["per_row_worst"] == 8
    assert d["tenants"] == {"acme": 8}
    assert d["frag"]["internal_pages"] == 5
    assert d["leak"] == {"requests": 0, "pages": 0}
    assert d["forecast_s"] == pytest.approx(3.5)
    assert d["conservation_breaks"] == 0
    # drift is None on CPU (memory_stats withheld) — reported, not guessed.
    assert d["drift"] is None


# ---------------------------------------------------------------------------
# Offline twins: summarize / diff / replay
# ---------------------------------------------------------------------------


def test_summarize_mem_rebuilds_the_rollup_from_the_log(tmp_path):
    led = _ledger(tmp_path)
    led.on_reserve(8, rid="r1", tenant="acme", cause="admit", free=56)
    led.on_reserve(4, rid="r2", tenant="globex", cause="cow", free=52)
    led.on_free(8, rid="r1", cause="retire", free=60)
    led.check_conservation(50)  # a deliberate break, for the counter
    summ = summarize_mem(JsonlLogger(tmp_path / "spans.jsonl").read())
    assert summ["pool_records"] == 4
    assert summ["engines"] == ["t"]
    assert summ["total_pages"] == 65
    assert summ["peak_resident_pages"] == 12
    assert summ["last_resident_pages"] == 4
    assert summ["last_free_pages"] == 60
    assert summ["events"]["admit"] == {"count": 1, "pages": 8}
    assert summ["events"]["cow"] == {"count": 1, "pages": 4}
    assert summ["events"]["retire"] == {"count": 1, "pages": 8}
    assert summ["tenants"]["acme"] == {"pages": 0, "peak_pages": 8}
    assert summ["tenants"]["globex"] == {"pages": 4, "peak_pages": 4}
    assert summ["conservation_breaks"] == 1


def test_summarize_mem_compat_both_directions():
    # A pre-mem log is an answer, not an error.
    assert summarize_mem([]) is None
    assert summarize_mem([{"event": "span", "rid": "r"}]) is None
    # Forward: unknown keys on future records are ignored; known-but-
    # missing keys read as None/0 — the record never KeyErrors.
    summ = summarize_mem([
        {"event": POOL_RECORD_EVENT, "cause": "admit", "delta": 4,
         "tenant": "a", "future_key": {"nested": True}},
        {"event": POOL_RECORD_EVENT},  # everything missing
    ])
    assert summ["pool_records"] == 2
    assert summ["tenants"]["a"]["peak_pages"] == 4
    assert summ["total_pages"] is None


def test_diff_mem_rows_survive_one_sided_tenants():
    a = summarize_mem([
        {"event": POOL_RECORD_EVENT, "cause": "admit", "delta": 8,
         "tenant": "acme", "resident": 8},
    ])
    b = summarize_mem([
        {"event": POOL_RECORD_EVENT, "cause": "import", "delta": 4,
         "tenant": "globex", "resident": 4},
    ])
    doc = diff_mem(a, b)
    assert doc["peak_ratio"] == pytest.approx(0.5)
    assert doc["tenants"]["acme"] == {"a_peak_pages": 8, "b_peak_pages": None}
    assert doc["tenants"]["globex"]["b_peak_pages"] == 4
    assert doc["events"]["admit"]["a_pages"] == 8
    assert doc["events"]["import"]["b_pages"] == 4
    # Null-safe on both sides (two pre-mem logs).
    assert diff_mem(None, None)["peak_ratio"] is None


def test_replay_spans_routes_pool_records_into_registry(tmp_path):
    led = _ledger(tmp_path)
    led.on_reserve(8, rid="r1", tenant="acme", cause="admit", free=56)
    led.on_free(3, rid="r1", cause="abort", free=59)
    led.check_conservation(0)  # break → tripwire on replay too
    reg = replay_spans(tmp_path / "spans.jsonl", registry=Registry())
    assert _gauge(reg, "edgemesh_pool_tenant_pages", ("engine", "tenant"),
                  engine="t", tenant="acme") == 5
    assert _counter(reg, "edgemesh_pool_events_total", ("engine", "cause"),
                    engine="t", cause="admit") == 1
    assert _counter(reg, "edgemesh_pool_conservation_breaks_total",
                    ("engine",), engine="t") == 1


def test_replay_pool_record_bounds_foreign_tenant_labels():
    reg = Registry()
    state = {}
    for i in range(200):  # a hand-edited log minting hostile cardinality
        state = replay_pool_record(reg, {
            "event": POOL_RECORD_EVENT, "engine": "t", "cause": "admit",
            "delta": 1, "tenant": f"hostile-{i}"}, state)
    fam = reg.gauge("edgemesh_pool_tenant_pages", "", ("engine", "tenant"))
    labels = {key[1] for key, _ in fam.items()}
    assert len(labels) <= 33  # bounded_label's 32-cap + the overflow bucket


# ---------------------------------------------------------------------------
# Fleet consumers: admission deferral, autoscaler vote, balancer penalty
# ---------------------------------------------------------------------------


def _mem_load(forecast_s):
    return {"mem": {"forecast_s": forecast_s, "free_pages": 4,
                    "resident_pages": 60}}


def test_admission_defers_batch_lane_under_short_forecast():
    adm = AdmissionController(
        max_inflight=4, mem_horizon_s=10.0,
        policies={"bulk": TenantPolicy(lane="batch")})
    assert adm.acquire("bulk") == "ok"  # no forecast yet: legacy verdicts
    adm.release()
    adm.note_mem_forecast(_mem_load(3.0), replica="r0")
    # Batch defers (no queue budget → sheds); interactive is untouched.
    assert adm.acquire("bulk") == "overload"
    assert adm.acquire("alice") == "ok"
    st = adm.stats()
    assert st["mem_horizon_s"] == 10.0
    assert st["mem_forecast_s"] == 3.0
    assert st["mem_deferrals"] == 1
    # Recovery clears the pressure; batch flows again.
    adm.note_mem_forecast(_mem_load(60.0), replica="r0")
    assert adm.acquire("bulk") == "ok"


def test_admission_pressure_is_fleet_minimum_and_clears_per_replica():
    adm = AdmissionController(
        max_inflight=4, mem_horizon_s=10.0,
        policies={"bulk": TenantPolicy(lane="batch")})
    adm.note_mem_forecast(_mem_load(60.0), replica="r0")
    adm.note_mem_forecast(_mem_load(2.0), replica="r1")
    assert adm.acquire("bulk") == "overload"  # the tightest pool rules
    # A forgotten/recovered replica clears ITS entry (None load).
    adm.note_mem_forecast(None, replica="r1")
    assert adm.acquire("bulk") == "ok"


def test_admission_mem_horizon_zero_is_legacy():
    adm = AdmissionController(
        max_inflight=4, policies={"bulk": TenantPolicy(lane="batch")})
    adm.note_mem_forecast(_mem_load(0.1), replica="r0")
    assert adm.acquire("bulk") == "ok"  # horizon 0 = feature off
    assert adm.stats()["mem_deferrals"] == 0


def test_admission_ignores_malformed_mem_blocks():
    adm = AdmissionController(
        max_inflight=4, mem_horizon_s=10.0,
        policies={"bulk": TenantPolicy(lane="batch")})
    for load in ({}, {"mem": None}, {"mem": {"forecast_s": "soon"}},
                 {"mem": {"forecast_s": -1}}):
        adm.note_mem_forecast(load, replica="r0")
        assert adm.acquire("bulk") == "ok"
        adm.release()


def test_autoscaler_mem_pressure_votes_scale_up():
    # Calm demand (util well under the watermark) but a 2 s forecast.
    class FakeLauncher:
        def __init__(self):
            self.spawned = []

        def spawn(self):
            self.spawned.append(f"scale-{len(self.spawned)}")
            return self.spawned[-1]

        def stop(self, rid):
            pass

        def pending(self):
            return 0

    clock = Clock(0.0)
    reg = ReplicaRegistry([("r0", "http://x:0")])
    reg.update_load("r0", {
        "ewma_arrival_s": 1.0,  # 1 rps demand
        "capacity": {"slots": 8, "est_req_s": 10.0},  # util 0.1
        "mem": {"forecast_s": 2.0},
    })
    launcher = FakeLauncher()
    sc = AutoScaler(reg, launcher, min_replicas=1, max_replicas=4,
                    up_after=2, cooldown_s=5.0, mem_pressure_s=30.0,
                    obs_registry=Registry(), now=clock)
    assert sc.evaluate() is None  # streak 1 of 2: same discipline as util
    clock.tick(1.0)
    action = sc.evaluate()
    assert action["action"] == "up"
    assert action["reason"] == "mem_pressure"  # util alone wouldn't vote
    assert action["mem_forecast_s"] == 2.0
    assert launcher.spawned == ["scale-0"]
    # Forecast recovers → pressure off → no further votes.
    reg.update_load("r0", {
        "ewma_arrival_s": 1.0,
        "capacity": {"slots": 8, "est_req_s": 10.0},
        "mem": {"forecast_s": 600.0},
    })
    clock.tick(10.0)
    assert sc.evaluate() is None
    assert sc.evaluate() is None


def test_balancer_mem_penalty_is_soft_and_null_safe():
    pen = TelemetryBalancer._mem_penalty
    assert pen({}) == 0.0
    assert pen({"mem": None}) == 0.0
    assert pen({"mem": {"forecast_s": None}}) == 0.0
    assert pen({"mem": {"forecast_s": 60.0}}) == 0.0  # roomy pool: free
    assert pen({"mem": {"forecast_s": 5.0}}) == pytest.approx(0.5)
    assert pen({"mem": {"forecast_s": 0.0}}) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# CLI: edgemesh obs mem
# ---------------------------------------------------------------------------


def _write_pool_log(tmp_path, name="mem.jsonl", tenant="acme"):
    led = PoolLedger(registry=Registry(), engine="t", enabled=True,
                     total_pages=65, page_size=16, per_row_worst=8,
                     span_log=tmp_path / name, clock=Clock())
    led.on_reserve(8, rid="r1", tenant=tenant, cause="admit", free=56)
    led.on_free(8, rid="r1", cause="retire", free=64)
    return tmp_path / name


def test_cli_mem_table_json_and_diff(tmp_path, capsys):
    from edgemesh.obs.cli import cmd_mem

    log_a = _write_pool_log(tmp_path, "a.jsonl", tenant="acme")
    log_b = _write_pool_log(tmp_path, "b.jsonl", tenant="globex")
    assert cmd_mem(str(log_a)) == 0
    out = capsys.readouterr().out
    assert "acme" in out and "admit" in out and "peak_resident=8" in out
    assert cmd_mem(str(log_a), as_json=True) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["peak_resident_pages"] == 8
    assert cmd_mem(str(log_a), diff=str(log_b)) == 0
    out = capsys.readouterr().out
    assert "acme" in out and "globex" in out
    assert cmd_mem(str(log_a), diff=str(tmp_path / "missing.jsonl")) == 2


def test_cli_mem_pre_mem_log_is_rc_zero(tmp_path, capsys):
    from edgemesh.obs.cli import cmd_mem

    empty = tmp_path / "empty.jsonl"
    JsonlLogger(empty).log("span", rid="r")  # a log, but no pool records
    assert cmd_mem(str(empty)) == 0
    assert "no pool records" in capsys.readouterr().out
    assert cmd_mem(str(empty), as_json=True) == 0
    assert json.loads(capsys.readouterr().out) is None
