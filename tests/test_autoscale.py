"""Autoscaler control law (fleet/autoscale.py), the deregister-purge
bugfix, and the capacity model's digest blocks — all fast-tier: the
scaler runs against a fake launcher and hand-stamped digests, the
capacity math against its pure helpers."""

import json

import pytest

from edgemesh.fleet.autoscale import AutoScaler
from edgemesh.fleet.balancer import TierManager
from edgemesh.fleet.registry import Replica, ReplicaRegistry
from edgemesh.fleet.router import FleetRouter
from edgemesh.obs import Registry


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class FakeLauncher:
    def __init__(self):
        self.spawned = []
        self.stopped = []
        self._pending = 0

    def spawn(self):
        rid = f"scale-{len(self.spawned)}"
        self.spawned.append(rid)
        return rid

    def stop(self, rid):
        self.stopped.append(rid)

    def pending(self):
        return self._pending


def hot_digest(arrival_rps=20.0, est_req_s=10.0, slots=8):
    return {"ewma_arrival_s": 1.0 / arrival_rps,
            "capacity": {"slots": slots, "est_req_s": est_req_s,
                         "est_tok_s": est_req_s * 8}}


def make_scaler(n=2, arrival_rps=20.0, est_req_s=10.0, **kw):
    reg = ReplicaRegistry((f"r{i}", f"http://x:{i}") for i in range(n))
    for i in range(n):
        reg.update_load(f"r{i}", hot_digest(arrival_rps, est_req_s))
    clock = Clock()
    launcher = FakeLauncher()
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_after", 3)
    kw.setdefault("cooldown_s", 5.0)
    kw.setdefault("obs_registry", Registry())
    sc = AutoScaler(reg, launcher, now=clock, **kw)
    return sc, reg, launcher, clock


def test_scale_up_needs_a_streak_then_cools_down():
    # 2 replicas at 20 rps arrivals / 10 rps capacity each: util = 2.0.
    sc, reg, launcher, clock = make_scaler()
    assert sc.evaluate() is None  # streak 1 of up_after=2
    clock.tick(1.0)
    action = sc.evaluate()
    assert action["action"] == "up" and launcher.spawned == ["scale-0"]
    assert action["utilization"] == pytest.approx(2.0)
    clock.tick(1.0)
    assert sc.evaluate() is None  # cooling down
    clock.tick(10.0)
    sc.evaluate()
    assert len(launcher.spawned) == 2  # streak rebuilt after cooldown


def test_scale_up_respects_max_and_pending():
    sc, reg, launcher, clock = make_scaler(max_replicas=2)
    clock.tick(1.0)
    sc.evaluate()
    clock.tick(1.0)
    assert sc.evaluate() is None  # 2 routable = max: never a third
    assert launcher.spawned == []
    # Pending spawns count toward the bound: one slow boot cannot
    # trigger a second.
    sc.max_replicas = 3
    launcher._pending = 1
    for _ in range(4):
        clock.tick(10.0)
        sc.evaluate()
    assert launcher.spawned == []


def test_scale_down_drains_the_least_loaded_to_min():
    sc, reg, launcher, clock = make_scaler(
        n=3, arrival_rps=0.5, est_req_s=10.0, min_replicas=2)
    reg.get("r1").outstanding = 3  # r0/r2 tie on outstanding; lowest rid drains
    actions = []
    for _ in range(10):
        clock.tick(10.0)
        a = sc.evaluate()
        if a:
            actions.append(a)
    assert [a["action"] for a in actions] == ["down"]
    assert actions[0]["replica"] == "r0"
    assert launcher.stopped == ["r0"]
    # min_replicas=2 holds: r1/r2 stay even under zero load.
    assert {r.rid for r in reg.replicas()} == {"r1", "r2"}


def test_incident_is_an_immediate_scale_up_with_its_own_cooldown():
    sc, reg, launcher, clock = make_scaler(arrival_rps=0.1)  # idle fleet
    assert sc.note_incident({"id": "inc-1", "kind": "slo_burst"}) is True
    # Duplicate within the incident cooldown is dropped.
    assert sc.note_incident({"id": "inc-2", "kind": "slo_burst"}) is False
    action = sc.evaluate()
    assert action["action"] == "incident_up"
    assert action["incident"] == "inc-1"
    assert launcher.spawned == ["scale-0"]
    clock.tick(120.0)  # past incident_cooldown_s
    assert sc.note_incident({"id": "inc-3", "kind": "error_spike"}) is True


def test_cold_fleet_scores_neutral_supply_not_zero():
    # No digests at all: supply falls back to slots/neutral_service_s and
    # demand is 0 — the scaler must sit still, not divide by zero.
    reg = ReplicaRegistry([("r0", "http://x:0")])
    sc = AutoScaler(reg, FakeLauncher(), obs_registry=Registry(),
                    now=Clock())
    assert sc.evaluate() is None
    assert sc.status()["last_eval"]["utilization"] == 0.0


def test_autoscaler_validation_and_status():
    reg = ReplicaRegistry()
    with pytest.raises(ValueError):
        AutoScaler(reg, FakeLauncher(), min_replicas=0,
                   obs_registry=Registry())
    with pytest.raises(ValueError):
        AutoScaler(reg, FakeLauncher(), min_replicas=3, max_replicas=2,
                   obs_registry=Registry())
    with pytest.raises(ValueError):
        AutoScaler(reg, FakeLauncher(), low_watermark=0.9,
                   high_watermark=0.8, obs_registry=Registry())
    sc, *_ = make_scaler()
    st = sc.status()
    assert {"min_replicas", "max_replicas", "high_watermark",
            "low_watermark", "last_eval", "recent_events"} <= set(st)


# -- the deregister/removal purge (the satellite bugfix) ---------------------


def test_removed_replica_load_digest_is_purged():
    reg = ReplicaRegistry([("r0", "http://x:0")])
    reg.update_load("r0", hot_digest())
    assert reg.get("r0").load is not None
    reg.set_state("r0", "removed")
    snap = reg.snapshot()[0]
    assert "load" not in snap and reg.get("r0").load is None


def test_revive_after_removal_starts_cold_but_live_reregister_keeps_digest():
    reg = ReplicaRegistry([("r0", "http://x:0")])
    reg.update_load("r0", hot_digest())
    # Idempotent heartbeat re-register of a LIVE replica keeps its digest.
    reg.register("r0", "http://x:0")
    assert reg.get("r0").load is not None
    # But reviving one that left rotation starts cold: the old digest
    # described the dead incarnation.
    reg.set_state("r0", "draining")
    reg.register("r0", "http://x:0")
    assert reg.get("r0").state == "healthy" and reg.get("r0").load is None


def test_tier_manager_forget_purges_hysteresis_membership():
    tm = TierManager(prefill_fraction=0.5, refresh_s=100.0, now=Clock())
    reps = [Replica(rid=f"r{i}", base_url=f"http://x:{i}") for i in range(2)]
    reps[0].load = {"ewma_prefill_tokens": 100.0, "ewma_decode_tokens": 1.0}
    reps[1].load = {"ewma_prefill_tokens": 1.0, "ewma_decode_tokens": 100.0}
    out = tm.assign(reps)
    assert [r.rid for r in out["prefill"]] == ["r0"]
    tm.forget("r0")
    # The cached assignment dropped with it: the next assign recomputes
    # and r0's incumbency bonus is gone.
    assert "r0" not in tm._prefill_rids
    out2 = tm.assign(reps[1:])
    assert out2["prefill"] == [] and [r.rid for r in out2["decode"]] == ["r1"]


def test_router_forget_replica_purges_everything():
    reg = ReplicaRegistry([("r0", "http://x:0"), ("r1", "http://x:1")])
    reg.update_load("r0", hot_digest())
    router = FleetRouter(reg, obs_registry=Registry(), tiered=True)
    router.observe_incident("r0", {"id": "inc-r0", "kind": "slo_burst"})
    router.observe_incident("r1", {"id": "inc-r1", "kind": "slo_burst"})
    assert {i["id"] for i in router.recent_incidents()} == \
        {"inc-r0", "inc-r1"}
    assert router.forget_replica("r0") is True
    # Registry entry (and its digest) gone; r1's incident survives; the
    # dedupe window no longer holds r0's id, so a re-registered r0 can
    # propagate a fresh incarnation of it.
    assert reg.get("r0") is None
    assert {i["id"] for i in router.recent_incidents()} == {"inc-r1"}
    reg.register("r0", "http://x:0")
    assert router.observe_incident(
        "r0", {"id": "inc-r0", "kind": "slo_burst"}) is True
    # Unknown replica: False, no raise.
    assert router.forget_replica("ghost") is False


def test_frontend_deregister_routes_through_forget(tmp_path):
    import urllib.request

    from edgemesh.fleet import serve_fleet

    reg = ReplicaRegistry([("r0", "http://x:0"), ("r1", "http://x:1")])
    reg.update_load("r0", hot_digest())
    router = FleetRouter(reg, obs_registry=Registry(), tiered=True)
    router.observe_incident("r0", {"id": "inc-z", "kind": "slo_burst"})
    front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    try:
        url = f"http://127.0.0.1:{front.server_address[1]}"
        req = urllib.request.Request(
            f"{url}/replicas/deregister",
            data=json.dumps({"id": "r0"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.load(r)["deregistered"] is True
        assert reg.get("r0") is None
        assert router.recent_incidents() == []
    finally:
        front.shutdown()


# -- the capacity model's digest blocks --------------------------------------


def test_estimate_capacity_derivation_and_cold_nulls():
    from edgemesh.serve.continuous import estimate_capacity

    cap = estimate_capacity(8, ewma_decode_s=0.01, ewma_service_s=1.0,
                            ewma_decode_tokens=16.0)
    # 8 slots / 10ms per token = 800 tok/s; / 16 tokens per request = 50 rps.
    assert cap == {"slots": 8, "est_tok_s": 800.0, "est_req_s": 50.0,
                   "measured_tok_s": None}
    # The compute ledger's fenced-launch tok/s REPLACES the host-EWMA
    # derivation when present — and ships raw so consumers can tell
    # which model produced the estimate.
    cap = estimate_capacity(8, ewma_decode_s=0.01, ewma_decode_tokens=16.0,
                            measured_tok_s=640.0)
    assert cap["est_tok_s"] == 640.0 and cap["measured_tok_s"] == 640.0
    assert cap["est_req_s"] == 40.0
    # No decode EWMA yet: req/s falls back to slots/service.
    cap = estimate_capacity(4, ewma_service_s=2.0)
    assert cap["est_tok_s"] is None and cap["est_req_s"] == 2.0
    # Cold: no claims.
    assert estimate_capacity(8) == {"slots": 8, "est_tok_s": None,
                                    "est_req_s": None,
                                    "measured_tok_s": None}


def test_pool_state_occupancy_fragmentation_headroom():
    from edgemesh.serve.continuous import pool_state

    st = pool_state(total=100, free=40, reserved=50, template=10,
                    page_size=64, per_row_worst=9, pending_tokens=640)
    assert st["occupancy_ratio"] == 0.6
    # 640 pending tokens over 50*64 reserved capacity = 0.2.
    assert st["fragmentation_ratio"] == 0.2
    assert st["free_page_headroom"] == 4  # 40 // 9
    # Empty pool degrades to zeros, never a division error.
    st = pool_state(total=0, free=0, reserved=0, template=0, page_size=64,
                    per_row_worst=9)
    assert st["occupancy_ratio"] == 0.0
    assert st["fragmentation_ratio"] == 0.0


def test_span_tracker_arrival_ewma_rides_the_digest():
    from edgemesh.obs import SpanTracker

    tr = SpanTracker(Registry())
    assert tr.load_digest()["ewma_arrival_s"] is None  # < 2 submits
    tr.submit(0)
    assert tr.load_digest()["ewma_arrival_s"] is None
    tr.submit(1)
    dig = tr.load_digest()
    assert dig["ewma_arrival_s"] is not None and dig["ewma_arrival_s"] >= 0


def test_compile_cache_state_shape():
    from edgemesh.obs.trace import compile_cache_state

    st = compile_cache_state()
    assert {"enabled", "dir", "hits", "misses"} <= set(st)
    assert isinstance(st["enabled"], bool)
    assert st["hits"] >= 0 and st["misses"] >= 0


def test_router_status_capacity_rollup_and_autoscale_surface():
    reg = ReplicaRegistry([("r0", "http://x:0"), ("r1", "http://x:1")])
    reg.update_load("r0", hot_digest(arrival_rps=20.0, est_req_s=10.0))
    # r1 cold: contributes nothing, reports nothing — never a zero claim.
    router = FleetRouter(reg, obs_registry=Registry(), admission_auto=True)
    st = router.status()
    cap = st["capacity"]
    assert cap["fleet_est_req_s"] == 10.0
    assert cap["fleet_arrival_rps"] == pytest.approx(20.0)
    assert set(cap["replicas"]) == {"r0"}
    assert st["autoscale"] is None
    assert st["admission"]["tuner"]["mode"] == "auto"
    # Attach a scaler: its status surfaces.
    sc, *_ = make_scaler()
    router.autoscaler = sc
    assert router.status()["autoscale"]["min_replicas"] == 1


def test_subprocess_launcher_contract_without_spawning():
    import argparse

    from edgemesh.fleet import HttpTransport
    from edgemesh.fleet.cli import SubprocessLauncher, _replica_cmd

    args = argparse.Namespace(config="cfg.yaml", replica_extra="--continuous",
                              compile_cache_dir="/tmp/cc")
    cmd = _replica_cmd(args, 8123)
    assert "--compile-cache-dir" in cmd and "/tmp/cc" in cmd
    assert "--continuous" in cmd and "--config" in cmd
    launcher = SubprocessLauncher(args, ReplicaRegistry(), HttpTransport(),
                                  obs_registry=Registry())
    assert launcher.pending() == 0
    launcher.stop("never-spawned")  # no raise


def test_arrival_ewma_grows_with_idle_gap():
    # After traffic stops the digest must report the growing idle gap as
    # the effective inter-arrival — otherwise demand stays at the burst
    # era's level forever and scale-down is unreachable.
    import time as _time

    from edgemesh.obs import SpanTracker

    tr = SpanTracker(Registry())
    tr.submit(0)
    tr.submit(1)
    burst_arrival = tr.load_digest()["ewma_arrival_s"]
    _time.sleep(0.05)
    idle_arrival = tr.load_digest()["ewma_arrival_s"]
    assert idle_arrival > burst_arrival
    assert idle_arrival >= 0.05


def test_scale_down_only_reaps_launcher_owned_replicas():
    # A boot-time replica the launcher cannot stop must never be the
    # victim — draining it would leave a zombie process out of rotation.
    sc, reg, launcher, clock = make_scaler(
        n=3, arrival_rps=0.5, est_req_s=10.0, min_replicas=1)
    owned = {"r2"}
    launcher.owns = lambda rid: rid in owned
    for _ in range(10):
        clock.tick(10.0)
        a = sc.evaluate()
        if a:
            assert a["replica"] == "r2"
    assert launcher.stopped == ["r2"]
    # Nothing owned left: the down branch is a no-op, boot replicas stay.
    for _ in range(10):
        clock.tick(10.0)
        sc.evaluate()
    assert {r.rid for r in reg.replicas()} == {"r0", "r1"}


def test_phantom_down_never_consumes_the_cooldown():
    # Launcher owns nothing: the down branch finds no victim, and that
    # non-action must not stamp the cooldown — a genuine scale-up right
    # after an idle stretch has to fire on schedule.
    sc, reg, launcher, clock = make_scaler(
        n=2, arrival_rps=0.1, est_req_s=10.0, min_replicas=1, up_after=1)
    launcher.owns = lambda rid: False
    for _ in range(8):  # well past down_after: still no victim, no stamp
        clock.tick(10.0)
        assert sc.evaluate() is None
    assert launcher.stopped == []
    # Load spikes: the very next pass must scale up, not sit in a
    # cooldown a phantom down armed.
    for i in range(2):
        reg.update_load(f"r{i}", hot_digest(arrival_rps=50.0, est_req_s=10.0))
    clock.tick(1.0)
    action = sc.evaluate()
    assert action is not None and action["action"] == "up"
