"""Prompt-prefix KV reuse (runtime/prefix_cache.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.runtime import generate
from edgemesh.runtime.prefix_cache import (
    build_prefix_cache,
    generate_with_prefix,
    match_length,
)

GREEDY = SamplingParams(max_new_tokens=10, do_sample=False, repetition_penalty=1.0)


import pytest

# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _model():
    cfg = tiny_config("llama", vocab_size=128, max_seq_len=128, dtype="float32")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def test_match_length():
    cfg, params = _model()
    pc = build_prefix_cache(cfg, params, [5, 6, 7, 8])
    assert pc.length == 4
    assert match_length(pc, [5, 6, 7, 8, 20, 21]) == 4
    assert match_length(pc, [5, 6, 9, 8, 20]) == 2  # diverges at index 2
    assert match_length(pc, [1, 2, 3]) == 0
    # Cap: at least one suffix token must remain to prefill.
    assert match_length(pc, [5, 6, 7, 8]) == 3


def test_warm_matches_cold_greedy():
    """Greedy decode from the prefix-seeded cache is token-identical to the
    cold full-prompt prefill (same tokens → same KV)."""
    cfg, params = _model()
    prefix_ids = list(range(40, 60))  # 20-token shared prefix
    pc = build_prefix_cache(cfg, params, prefix_ids)
    for suffix in ([7, 9, 23], [99, 3, 61, 2, 17, 5, 44]):
        ids = prefix_ids + suffix
        tokens = jnp.asarray([ids], jnp.int32)
        lengths = jnp.asarray([len(ids)], jnp.int32)
        cold = generate(cfg, params, tokens, lengths, GREEDY)
        warm = generate_with_prefix(cfg, params, tokens, lengths, GREEDY, pc)
        np.testing.assert_array_equal(np.asarray(warm.tokens), np.asarray(cold.tokens))
        np.testing.assert_allclose(
            np.asarray(warm.confidence), np.asarray(cold.confidence), rtol=1e-4
        )


def test_short_match_falls_back():
    cfg, params = _model()
    pc = build_prefix_cache(cfg, params, list(range(40, 60)))
    ids = [1, 2, 3, 4, 5, 6]  # shares nothing with the prefix
    tokens = jnp.asarray([ids], jnp.int32)
    lengths = jnp.asarray([len(ids)], jnp.int32)
    cold = generate(cfg, params, tokens, lengths, GREEDY)
    warm = generate_with_prefix(cfg, params, tokens, lengths, GREEDY, pc)
    np.testing.assert_array_equal(np.asarray(warm.tokens), np.asarray(cold.tokens))


def test_agent_answers_identically_with_and_without_prefix_cache():
    from edgemesh.agents.orchestrator import build_agent

    sampling = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    spec = AgentSpec(role="qa", model=ModelSpec(), sampling=sampling)
    warm_agent = build_agent(spec)
    cold_agent = build_agent(spec)
    cold_agent.prefix_cache = False
    q = "where is the eiffel tower located?"
    a_warm = warm_agent.answer(q)
    a_cold = cold_agent.answer(q)
    assert a_warm["answer"] == a_cold["answer"]
    # The cache was actually built and used (template prefix >= 8 tokens).
    assert warm_agent._prefix is not None
