"""checkify kernel-contract asserts (ops/checks.py, SURVEY.md §5.2).

Every kernel runs in interpret mode on CPU; the checks live in the JAX-level
wrappers so they functionalize identically on real TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import checkify

from edgemesh.ops.checks import checked
from edgemesh.ops.flash_attention import flash_attention
from edgemesh.ops.int8 import int8_matmul_fused, quantize_weight
from edgemesh.ops.paged_attention import (
    paged_decode_attention,
    ragged_paged_attention,
)


def _paged_inputs(bad_table=False, bad_lens=False):
    b, kh, nh, hd, ps, pages, maxp = 2, 2, 4, 64, 8, 6, 3
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, nh, hd), jnp.float32)
    k_pages = jax.random.normal(rng, (pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(jax.random.PRNGKey(1), (pages, kh, ps, hd), jnp.float32)
    table = jnp.array([[1, 2, 0], [3, 4, 5]], jnp.int32)
    if bad_table:
        table = table.at[0, 1].set(pages + 7)  # outside the physical pool
    lens = jnp.array([12, 20], jnp.int32)
    if bad_lens:
        lens = lens.at[1].set(maxp * ps + 1)  # beyond table capacity
    return q, k_pages, v_pages, table, lens


def test_paged_check_passes_on_valid_inputs():
    q, kp, vp, table, lens = _paged_inputs()
    fn = checked(
        lambda *a: paged_decode_attention(*a, interpret=True, check=True)
    )
    out = fn(q, kp, vp, table, lens)
    ref = paged_decode_attention(q, kp, vp, table, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_paged_check_catches_out_of_pool_page():
    q, kp, vp, table, lens = _paged_inputs(bad_table=True)
    fn = checked(
        lambda *a: paged_decode_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="page-table entry"):
        fn(q, kp, vp, table, lens)


def test_paged_check_catches_overlong_kv_lens():
    q, kp, vp, table, lens = _paged_inputs(bad_lens=True)
    fn = checked(
        lambda *a: paged_decode_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="kv_lens"):
        fn(q, kp, vp, table, lens)


def _ragged_inputs(bad_table=False, bad_lens=False, bad_cu=False, long_cu=False):
    b, kh, nh, hd, ps, pages, maxp = 2, 2, 4, 64, 8, 6, 3
    rng = jax.random.PRNGKey(0)
    k_pages = jax.random.normal(rng, (pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(jax.random.PRNGKey(1), (pages, kh, ps, hd), jnp.float32)
    table = jnp.array([[1, 2, 0], [3, 4, 5]], jnp.int32)
    if bad_table:
        table = table.at[0, 1].set(pages + 7)
    lens = jnp.array([12, 20], jnp.int32)
    if bad_lens:
        lens = lens.at[1].set(maxp * ps + 1)
    cu = jnp.array([0, 1, 6], jnp.int32)
    if bad_cu:
        cu = jnp.array([0, 3, 2], jnp.int32)  # decreasing
    if long_cu:
        cu = jnp.array([0, 1, 9], jnp.int32)  # past the packed rows
    q = jax.random.normal(jax.random.PRNGKey(2), (6, nh, hd), jnp.float32)
    return q, k_pages, v_pages, table, lens, cu


def test_ragged_check_passes_on_valid_inputs():
    q, kp, vp, table, lens, cu = _ragged_inputs()
    fn = checked(
        lambda *a: ragged_paged_attention(*a, interpret=True, check=True)
    )
    out = fn(q, kp, vp, table, lens, cu)
    ref = ragged_paged_attention(q, kp, vp, table, lens, cu, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_ragged_check_catches_out_of_pool_page():
    q, kp, vp, table, lens, cu = _ragged_inputs(bad_table=True)
    fn = checked(
        lambda *a: ragged_paged_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="page-table entry"):
        fn(q, kp, vp, table, lens, cu)


def test_ragged_check_catches_overlong_kv_lens():
    q, kp, vp, table, lens, cu = _ragged_inputs(bad_lens=True)
    fn = checked(
        lambda *a: ragged_paged_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="kv_lens"):
        fn(q, kp, vp, table, lens, cu)


def test_ragged_check_catches_bad_cu_q_lens():
    q, kp, vp, table, lens, cu = _ragged_inputs(bad_cu=True)
    fn = checked(
        lambda *a: ragged_paged_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="non-decreasing"):
        fn(q, kp, vp, table, lens, cu)
    q, kp, vp, table, lens, cu = _ragged_inputs(long_cu=True)
    with pytest.raises(checkify.JaxRuntimeError, match="packed query"):
        fn(q, kp, vp, table, lens, cu)


def test_flash_check_catches_overlong_kv_lens():
    b, s, nh, hd = 1, 16, 4, 64
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, s, nh, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, nh, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, nh, hd), jnp.float32)
    fn = checked(
        lambda *a: flash_attention(*a, interpret=True, check=True)
    )
    out = fn(q, k, v, jnp.array([s], jnp.int32))  # valid: passes
    assert out.shape == q.shape
    with pytest.raises(checkify.JaxRuntimeError, match="kv_lens exceeds"):
        fn(q, k, v, jnp.array([s + 1], jnp.int32))


def test_flash_check_catches_nan_query():
    b, s, nh, hd = 1, 8, 2, 64
    q = jnp.full((b, s, nh, hd), jnp.nan, jnp.float32)
    k = jnp.ones((b, s, nh, hd), jnp.float32)
    fn = checked(
        lambda *a: flash_attention(*a, interpret=True, check=True)
    )
    with pytest.raises(checkify.JaxRuntimeError, match="non-finite query"):
        fn(q, k, k, jnp.array([s], jnp.int32))


def test_int8_check_catches_bad_scales():
    x = jnp.ones((4, 128), jnp.float32)
    w_q, scales = quantize_weight(jax.random.normal(jax.random.PRNGKey(0), (128, 128)))
    fn = checked(
        lambda *a: int8_matmul_fused(*a, interpret=True, check=True)
    )
    out = fn(x, w_q, scales)  # valid: passes
    assert out.shape == (4, 128)
    with pytest.raises(checkify.JaxRuntimeError, match="scales"):
        fn(x, w_q, scales.at[0].set(jnp.nan))
