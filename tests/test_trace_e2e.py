"""Distributed tracing end-to-end (slow tier): one trace id spans the REAL
router process and two real replica subprocesses. A hedged request leaves
spans in three span logs (router + both replicas); a retried request shows
the failed attempt as a sibling span; `edgemesh obs trace` assembles the
whole thing into one tree whose critical-path durations sum to within 5%
of the client-observed latency. Same multi-minute territory as the fleet
e2e: each replica is a full `edgemesh serve --continuous` process."""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 32, do_sample: false, repetition_penalty: 1.0}
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path: Path, port: int, span_log: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2", "--span-log", str(span_log)],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0
                )
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never became ready"


def _post(url: str, payload: dict, timeout_s: float = 300.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _wait_for_trace_in(log: Path, trace_id: str, timeout_s: float = 120.0):
    from edgemesh.utils.tracing import JsonlLogger

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if any(r.get("trace_id") == trace_id for r in JsonlLogger(log).read()):
            return
        time.sleep(0.5)
    raise AssertionError(f"trace {trace_id} never appeared in {log}")


def test_one_trace_spans_router_and_two_replicas_with_critical_path(tmp_path):
    from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, \
        serve_fleet
    from edgemesh.obs import Registry, load_trace
    from edgemesh.obs.trace import TRACE_HEADER, TraceContext
    from edgemesh.utils.tracing import JsonlLogger

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    ports = [_free_port() for _ in range(2)]
    rep_logs = [tmp_path / f"replica-{i}.jsonl" for i in range(2)]
    router_log = tmp_path / "router.jsonl"
    procs = [_spawn_replica(cfg, p, lg) for p, lg in zip(ports, rep_logs)]
    transport = HttpTransport()
    front = None
    stopped_pid = None
    try:
        _wait_ready(transport, ports)
        # Warm each replica's decode compile directly — and pin the compile
        # hook e2e: the engine's span log must carry compile records.
        for p in ports:
            status, _, _ = _post(f"http://127.0.0.1:{p}/generate",
                                 {"question": "warmup?"})
            assert status == 200

        obs = Registry()
        registry = ReplicaRegistry(
            (f"replica-{i}", f"http://127.0.0.1:{p}")
            for i, p in enumerate(ports)
        )
        # round_robin: candidate order is registration order, so the FIRST
        # routed request deterministically dials replica-0.
        router = FleetRouter(
            registry, balancer="round_robin", transport=transport,
            obs_registry=obs, max_attempts=3, attempt_timeout_s=30.0,
            default_deadline_s=240.0, backoff_base_s=0.4, demote_after=1,
            span_log=router_log,
        )
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        url = f"http://127.0.0.1:{front.server_address[1]}"

        # ---- Phase A: a hedged request touches BOTH replicas under one
        # trace id. SIGSTOP replica-0 (round_robin picks it first): the
        # primary attempt stalls, the hedge fires at replica-1 and wins,
        # then SIGCONT lets replica-0 finish the abandoned attempt and
        # flush ITS span record too — three processes, one trace.
        router.hedge_after_s = 0.3
        procs[0].send_signal(signal.SIGSTOP)
        stopped_pid = procs[0].pid
        status, body, headers = _post(f"{url}/generate", {"question": "hedge?"})
        assert status == 200 and "answer" in body
        hedge_ctx = TraceContext.parse(headers[TRACE_HEADER])
        assert hedge_ctx is not None and hedge_ctx.sampled
        procs[0].send_signal(signal.SIGCONT)
        stopped_pid = None
        router.hedge_after_s = 0.0
        for log in (router_log, *rep_logs):
            _wait_for_trace_in(log, hedge_ctx.trace_id)
        doc = load_trace(hedge_ctx.trace_id,
                         [router_log, *map(str, rep_logs)])
        assert doc["processes"] == 3, doc["processes"]
        attempts = [c for c in doc["tree"]["children"]
                    if c["name"] == "attempt"]
        assert len(attempts) == 2
        hedges = [a for a in attempts if a.get("hedge")]
        assert len(hedges) == 1 and hedges[0]["outcome"] == "ok"
        # Both replicas' engine spans attached somewhere in the tree.
        engines = [a["replica"] for a in attempts]
        assert set(engines) == {"replica-0", "replica-1"}
        servers = [n for a in attempts for n in a["children"]
                   if n["name"] == "server"]
        assert len(servers) == 2, "both replicas' spans must stitch in"

        # ---- Phase B: a retried request shows the failed attempt as a
        # sibling span, and the assembled critical path matches the
        # client-observed latency. Drain replica-0 directly (the router
        # keeps routing to it): its 503 is a real replica-side refusal,
        # the retry lands on replica-1.
        status, _, _ = _post(f"http://127.0.0.1:{ports[0]}/drain", {})
        assert status == 200
        retry_ctx, client_s = None, None
        for i in range(4):  # round_robin: replica-0 comes up within 2 tries
            # Pre-opened connection: the 5% bar prices the REQUEST (what
            # the trace can see), not TCP connect + the server's
            # per-connection thread spawn, which happen before it is sent.
            conn = http.client.HTTPConnection(
                "127.0.0.1", front.server_address[1], timeout=300
            )
            conn.connect()
            payload = json.dumps({"question": f"retry {i}?"}).encode()
            t0 = time.monotonic()
            conn.request("POST", "/generate", payload,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.load(resp)
            elapsed = time.monotonic() - t0
            status, headers = resp.status, dict(resp.headers)
            conn.close()
            assert status == 200 and "answer" in body
            if int(headers.get("X-Edgemesh-Attempts", "1")) >= 2:
                retry_ctx = TraceContext.parse(headers[TRACE_HEADER])
                client_s = elapsed
                break
        assert retry_ctx is not None, "no request was retried"
        _wait_for_trace_in(router_log, retry_ctx.trace_id)
        _wait_for_trace_in(rep_logs[1], retry_ctx.trace_id)
        doc = load_trace(retry_ctx.trace_id,
                         [router_log, *map(str, rep_logs)])
        assert doc["processes"] >= 2
        attempts = [c for c in doc["tree"]["children"]
                    if c["name"] == "attempt"]
        failed = [a for a in attempts if a["outcome"] == "status_503"]
        winners = [a for a in attempts if a["outcome"] == "ok"]
        assert len(failed) == 1 and failed[0]["replica"] == "replica-0"
        assert len(winners) == 1 and winners[0]["replica"] == "replica-1"
        assert failed[0]["span_id"] != winners[0]["span_id"]
        servers = [n for n in winners[0]["children"] if n["name"] == "server"]
        assert servers and servers[0]["process"] == "continuous"
        names = [s["name"] for s in servers[0]["children"]]
        assert "queued" in names and "prefill" in names and "decode" in names
        cp = doc["critical_path"]
        parts = (cp["retry_wasted_s"] + cp["wire_s"] + cp["queue_s"]
                 + cp["prefill_s"] + cp["decode_s"] + cp["other_s"])
        assert parts == pytest.approx(cp["total_s"], abs=1e-6)
        # The acceptance bar: the assembled trace accounts for what the
        # client actually waited (frontend + loopback wire is the slack).
        assert cp["total_s"] == pytest.approx(client_s, rel=0.05), \
            (cp, client_s)
        assert cp["retry_wasted_s"] > 0  # the failed attempt + backoff
        assert cp["decode_s"] > 0

        # ---- Phase C: operator surfaces. /fleetz lists both traces,
        # /debug/traces/<id> serves the router-side assembly, and the
        # replica span logs carry compile records from the warmup (the
        # compile hook rode the engine's span log).
        with urllib.request.urlopen(f"{url}/fleetz", timeout=30) as r:
            fleetz = json.load(r)
        recent_ids = {t["trace_id"] for t in fleetz["recent_traces"]}
        assert {hedge_ctx.trace_id, retry_ctx.trace_id} <= recent_ids
        with urllib.request.urlopen(
            f"{url}/debug/traces/{retry_ctx.trace_id}", timeout=30
        ) as r:
            served = json.load(r)
        assert served["trace_id"] == retry_ctx.trace_id
        assert served["tree"]["name"] == "request"
        assert any(
            rec.get("event") == "compile"
            for lg in rep_logs for rec in JsonlLogger(lg).read()
        ), "engine span logs should carry compile records"
    finally:
        if front is not None:
            front.shutdown()
        if stopped_pid is not None:
            try:
                os.kill(stopped_pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
