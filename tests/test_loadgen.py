"""edgemesh.loadgen fast tier: arrival-process schedules, workload mixes
(long-tail lengths, shared-prefix sessions, tenant splits), the open-loop
generator's coordinated-omission-proof accounting, curve/knee math, and
the loadgen + obs loadreport CLIs — all against in-process callables (one
loopback stub server only where the HTTP adapter itself is under test)."""

import json
import threading
import time

import pytest

from edgemesh.loadgen import (
    ConstantProcess,
    DiurnalBurstProcess,
    OpenLoopGenerator,
    PoissonProcess,
    TenantSpec,
    Workload,
    find_knee,
    run_curve,
)
from edgemesh.loadgen.generator import TRANSPORT_ERROR_STATUS, summarize
from edgemesh.loadgen.workload import LengthMix
from edgemesh.serve.httputil import TENANT_HEADER

# ---------------------------------------------------------------------------
# Arrival processes
# ---------------------------------------------------------------------------


def test_poisson_schedule_rate_and_determinism():
    p = PoissonProcess(rate_rps=50.0, seed=3)
    s = p.schedule(10.0)
    # Count within 4 sigma of rate*duration; sorted; in-window.
    assert abs(len(s) - 500) < 4 * (500 ** 0.5)
    assert s == sorted(s) and all(0 <= t < 10.0 for t in s)
    assert s == PoissonProcess(rate_rps=50.0, seed=3).schedule(10.0)
    assert s != PoissonProcess(rate_rps=50.0, seed=4).schedule(10.0)
    # Mean inter-arrival gap ~ 1/rate.
    gaps = [b - a for a, b in zip(s, s[1:])]
    assert 0.015 < sum(gaps) / len(gaps) < 0.025


def test_diurnal_burst_modulates_rate():
    d = DiurnalBurstProcess(base_rps=5.0, peak_rps=60.0, period_s=4.0,
                            burst_rps=200.0, burst_every_s=10.0,
                            burst_len_s=0.5, seed=1)
    s = d.schedule(4.0)
    # Trough window measured OUTSIDE the t<0.5 burst; peak at mid-period.
    trough = sum(1 for t in s if 0.5 <= t < 1.0)
    peak = sum(1 for t in s if 1.75 <= t < 2.25)
    assert peak > 2 * trough  # the sinusoid is visible in the counts
    # The t=0 burst window rides ON TOP of the trough rate.
    burst = sum(1 for t in s if t < 0.5)
    assert burst > 4 * max(1, trough)  # ~(trough + 200 rps) * 0.5 s
    with pytest.raises(ValueError):
        DiurnalBurstProcess(base_rps=10.0, peak_rps=5.0, period_s=4.0)


def test_constant_process_fixed_gaps():
    assert ConstantProcess(4.0).schedule(1.0) == [0.0, 0.25, 0.5, 0.75]


# ---------------------------------------------------------------------------
# Workload: mixes, sessions, tenants
# ---------------------------------------------------------------------------


def test_length_mix_long_tail_and_bounds():
    import random

    mix = LengthMix(median=50, sigma=0.8, lo=10, hi=400)
    rng = random.Random(0)
    xs = [mix.sample(rng) for _ in range(2000)]
    assert all(10 <= x <= 400 for x in xs)
    xs.sort()
    median = xs[len(xs) // 2]
    assert 35 < median < 70
    # Long tail: p99 is several times the median (a constant mix is not).
    assert xs[int(0.99 * len(xs))] > 3 * median
    assert LengthMix(median=64, sigma=0.0).sample(rng) == 64


def test_workload_schedule_merges_tenants_sorted_and_deterministic():
    wl = Workload([
        TenantSpec(name="chat", arrival=PoissonProcess(20, seed=1)),
        TenantSpec(name="bulk", arrival=PoissonProcess(10, seed=2),
                   lane="batch"),
    ], seed=7)
    sched = wl.build_schedule(4.0)
    assert [r.at_s for r in sched] == sorted(r.at_s for r in sched)
    assert {r.tenant for r in sched} == {"chat", "bulk"}
    assert all(r.lane == "batch" for r in sched if r.tenant == "bulk")
    # Deterministic: the spec IS the traffic (A/B arms replay it).
    again = wl.build_schedule(4.0)
    assert [(r.at_s, r.tenant, r.prompt) for r in sched] == \
           [(r.at_s, r.tenant, r.prompt) for r in again]


def test_sessions_share_prefixes_across_turns():
    wl = Workload([TenantSpec(name="t", arrival=ConstantProcess(10.0),
                              sessions=2, turns_mean=100.0)], seed=1)
    sched = wl.build_schedule(2.0)
    by_session = {}
    for r in sched:
        by_session.setdefault(r.session, []).append(r)
    assert len(by_session) == 2
    for reqs in by_session.values():
        assert len(reqs) > 3
        # Every turn of a session starts with the SAME prefix — the
        # affinity/caching key prefix_affinity and the replica prefix
        # caches key on — and turns are numbered monotonically.
        prefix = reqs[0].prompt.split(" turn ")[0]
        assert len(prefix) > 20
        assert all(r.prompt.startswith(prefix) for r in reqs)
        assert [r.turn for r in reqs] == list(range(1, len(reqs) + 1))
    # Distinct sessions carry distinct prefixes.
    prefixes = {reqs[0].prompt.split(" turn ")[0]
                for reqs in by_session.values()}
    assert len(prefixes) == 2


def test_session_reset_rotates_prefix():
    wl = Workload([TenantSpec(name="t", arrival=ConstantProcess(10.0),
                              sessions=1, turns_mean=2.0)], seed=1)
    sched = wl.build_schedule(3.0)
    prefixes = {r.prompt.split(" turn ")[0] for r in sched}
    assert len(prefixes) > 3  # geometric resets minted fresh conversations


def test_max_new_budget_attaches_only_when_enabled():
    base = dict(arrival=ConstantProcess(5.0), sessions=1)
    on = Workload([TenantSpec(name="t", send_max_new=True, **base)], seed=0)
    off = Workload([TenantSpec(name="t", send_max_new=False, **base)], seed=0)
    assert all(isinstance(r.max_new, int) and r.max_new >= 4
               for r in on.build_schedule(1.0))
    assert all(r.max_new is None for r in off.build_schedule(1.0))
    req = on.build_schedule(1.0)[0]
    assert req.payload()["max_new"] == req.max_new
    assert "max_new" not in off.build_schedule(1.0)[0].payload()


def test_workload_rejects_duplicate_tenants_and_empty():
    with pytest.raises(ValueError):
        Workload([])
    with pytest.raises(ValueError):
        Workload([TenantSpec(name="a", arrival=ConstantProcess(1.0)),
                  TenantSpec(name="a", arrival=ConstantProcess(1.0))])


# ---------------------------------------------------------------------------
# The open-loop generator
# ---------------------------------------------------------------------------


def _schedule(n, gap_s, tenant="t"):
    # One long-lived session: exactly one "turn 1:" prompt in the run.
    wl = Workload([TenantSpec(name=tenant, arrival=ConstantProcess(1.0 / gap_s),
                              sessions=1, turns_mean=1e9)], seed=0)
    return wl.build_schedule(n * gap_s)


def test_open_loop_launches_do_not_wait_for_completions():
    """The anti-coordinated-omission property itself: a stalled FIRST
    request must not delay later launches — their launch skew stays tiny
    while the stalled request's latency grows."""
    release = threading.Event()

    def target(payload, headers):
        if "turn 1:" in payload["question"]:
            release.wait(timeout=10.0)  # request 1 stalls until the end
        return 200, {}

    sched = _schedule(8, 0.05)
    report_box = {}

    def run():
        gen = OpenLoopGenerator(target, sched, slo_latency_s=0.5,
                                duration_s=0.4)
        report_box["r"] = gen.run()

    th = threading.Thread(target=run)
    th.start()
    time.sleep(1.0)  # every launch slot has passed; request 1 still stalled
    release.set()
    th.join(timeout=10.0)
    r = report_box["r"]
    assert r["scheduled"] == 8 and r["ok"] == 8
    # Launches tracked the schedule despite the stall.
    assert r["max_launch_skew_s"] < 0.25
    # The stalled request blew the SLO; the other 7 met it.
    assert r["good"] == 7


def test_latency_measured_from_schedule_not_send():
    """A single-capacity target serving back-to-back arrivals: measured
    latency must grow with queue position (service time accrues from the
    SCHEDULED arrival), even though each individual call is fast."""
    lock = threading.Lock()

    def target(payload, headers):
        with lock:  # capacity 1: requests serialize
            time.sleep(0.05)
        return 200, {}

    sched = _schedule(6, 0.001)  # all arrive (nearly) at once
    gen = OpenLoopGenerator(target, sched, slo_latency_s=10.0)
    r = gen.run()
    assert r["ok"] == 6
    # 6 serialized 50ms services from one arrival instant: p99 covers the
    # LAST position's wait (~0.3s), p50 the middle — the queueing delay a
    # closed-loop driver structurally cannot see.
    assert r["latency_s_p99"] > 0.25
    assert r["latency_s_p50"] > 0.12


def test_report_accounting_and_tenant_split():
    statuses = {"a": 200, "b": 503, "c": 429, "d": TRANSPORT_ERROR_STATUS}

    def target(payload, headers):
        return statuses[headers[TENANT_HEADER]], {}

    wl = Workload([
        TenantSpec(name=n, arrival=ConstantProcess(10.0), sessions=1)
        for n in statuses
    ], seed=0)
    r = OpenLoopGenerator(target, wl.build_schedule(1.0),
                          slo_latency_s=5.0, duration_s=1.0).run()
    assert r["scheduled"] == 40 and r["ok"] == 10
    assert r["shed"] == 20          # 503 + 429
    assert r["ratelimited"] == 10   # 429 only
    assert r["errors"] == 10        # transport failures
    assert r["good"] == 10 and r["goodput_ratio"] == 0.25
    t = r["tenants"]
    assert t["a"]["goodput_ratio"] == 1.0
    assert t["b"]["shed"] == 10 and t["b"]["goodput_ratio"] == 0.0
    assert t["c"]["ratelimited"] == 10
    assert t["d"]["errors"] == 10


def test_generator_sends_tenant_header():
    seen = []

    def target(payload, headers):
        seen.append(headers.get(TENANT_HEADER))
        return 200, {}

    OpenLoopGenerator(target, _schedule(3, 0.01, tenant="acme"),
                      slo_latency_s=1.0).run()
    assert seen == ["acme"] * 3


def test_summarize_goodput_counts_against_scheduled():
    # Direct unit pin of the open-loop asymmetry: sheds are goodput
    # misses even though they never produced a latency sample.
    from edgemesh.loadgen.generator import RequestOutcome

    outcomes = [
        RequestOutcome("t", "interactive", "s", 0.0, 0.0, 0.1, 200, True),
        RequestOutcome("t", "interactive", "s", 0.1, 0.0, 9.0, 200, True),
        RequestOutcome("t", "interactive", "s", 0.2, 0.0, 0.0, 503, False),
    ]
    r = summarize(outcomes, duration_s=1.0, slo_latency_s=1.0)
    assert r["scheduled"] == 3 and r["good"] == 1
    assert r["goodput_ratio"] == pytest.approx(1 / 3, abs=1e-4)


# ---------------------------------------------------------------------------
# Curve + knee
# ---------------------------------------------------------------------------


def test_find_knee_monotone_then_collapse():
    pts = [
        {"offered_rps": 5.0, "goodput_rps": 5.0},
        {"offered_rps": 10.0, "goodput_rps": 9.5},
        {"offered_rps": 20.0, "goodput_rps": 4.0},
    ]
    k = find_knee(pts)
    assert k["knee_offered_rps"] == 10.0
    assert k["knee_goodput_rps"] == 9.5
    assert k["collapsed"] is True
    # Flat past the knee (saturated, not collapsed).
    pts[2]["goodput_rps"] = 9.4
    assert find_knee(pts)["collapsed"] is False
    assert find_knee([]) == {"knee_offered_rps": None,
                             "knee_goodput_rps": None, "collapsed": False}


def test_run_curve_schema_and_knee():
    def make_run(rate):
        good = min(rate, 12.0) if rate < 20 else 3.0
        return {
            "duration_s": 1.0, "slo_latency_s": 0.5,
            "max_launch_skew_s": 0.001, "scheduled": int(rate),
            "offered_rps": rate, "ok": int(good), "shed": 0,
            "ratelimited": 0, "errors": 0, "good": int(good),
            "goodput_rps": good, "goodput_ratio": good / rate,
            "latency_s_p50": 0.1, "latency_s_p99": 0.4,
            "tenants": {"t": {"scheduled": int(rate), "goodput_rps": good}},
        }

    curve = run_curve(make_run, [5.0, 10.0, 40.0])
    assert [p["offered_rps"] for p in curve["points"]] == [5.0, 10.0, 40.0]
    assert curve["knee_offered_rps"] == 10.0
    assert curve["collapsed"] is True
    assert curve["slo_latency_s"] == 0.5
    assert curve["points"][0]["tenants"]["t"]["scheduled"] == 5


# ---------------------------------------------------------------------------
# CLIs: edgemesh loadgen + edgemesh obs loadreport
# ---------------------------------------------------------------------------


@pytest.fixture()
def stub_gateway():
    """A loopback /generate stub: 200 after a tiny sleep, no model."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            self.rfile.read(length)
            time.sleep(0.005)
            body = json.dumps({"answer": "ok"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_address[1]}/generate"
    srv.shutdown()


def test_loadgen_cli_single_run_and_loadreport(stub_gateway, tmp_path, capsys):
    from edgemesh.cli import main as cli_main

    out = tmp_path / "report.json"
    rc = cli_main([
        "loadgen", "--url", stub_gateway, "--rate", "30", "--duration", "1",
        "--tenant", "chat=3:interactive", "--tenant", "bulk=1:batch",
        "--slo-latency-s", "2.0", "--seed", "1", "--out", str(out),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] > 0 and report["goodput_ratio"] > 0.9
    assert set(report["tenants"]) == {"chat", "bulk"}
    # ~3:1 share split.
    assert report["tenants"]["chat"]["scheduled"] > \
        2 * report["tenants"]["bulk"]["scheduled"]
    assert json.loads(out.read_text()) == report

    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["loadreport", str(out)]) == 0
    text = capsys.readouterr().out
    assert "open-loop run" in text and "chat" in text and "bulk" in text


def test_loadgen_cli_sweep_emits_curve(stub_gateway, tmp_path, capsys):
    from edgemesh.cli import main as cli_main

    out = tmp_path / "curve.json"
    rc = cli_main([
        "loadgen", "--url", stub_gateway, "--sweep", "10,20",
        "--duration", "1", "--slo-latency-s", "2.0", "--out", str(out),
    ])
    assert rc == 0
    curve = json.loads(capsys.readouterr().out)
    assert len(curve["points"]) == 2
    assert [p["requested_rps"] for p in curve["points"]] == [10.0, 20.0]
    # The knee is reported in ACTUAL offered rps (the Poisson draw), which
    # must match one of the swept points.
    assert curve["knee_offered_rps"] in {
        p["offered_rps"] for p in curve["points"]
    }
    assert "collapsed" in curve

    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["loadreport", str(out)]) == 0
    text = capsys.readouterr().out
    assert "goodput vs offered load" in text and "knee" in text


def test_loadreport_missing_file_is_usage_error(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["loadreport", str(tmp_path / "nope.json")]) == 2
    assert "no such report" in capsys.readouterr().err
