"""Ensemble-over-the-fleet end-to-end (slow tier): a REAL heterogeneous
subprocess fleet — two QA pools (qa-a is 2 replicas, paged KV, so the pool
tiers and the shared question prefix rides the fleet prefix cache; qa-b is
1 replica) plus a passthrough-template refiner pool — behind the real
router and frontend, answering ``POST /ensemble``.

The acceptance pins (ISSUE 19 / ROADMAP "Ensemble serving"):

- both QA branches are provably CONCURRENT: their branch spans in the
  assembled cross-process trace have overlapping wall intervals;
- the shared question prefix hits the fleet prefix cache
  (``edgemesh_fleet_tiered_total{outcome="cache_hit"}``) once repeated
  ensembles make it hot;
- SIGKILLing a QA replica mid-load yields ZERO client-visible ensemble
  failures (retries absorb it inside the branch);
- killing an entire QA pool degrades to single-candidate refine
  (outcome ``degraded_qa``), killing the refiner falls back to the best
  QA candidate (outcome ``refiner_fallback``) — both counted AND
  span-labeled;
- ``edgemesh obs trace`` assembles the full fan-out tree across the
  router's and every replica's span logs.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

QA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""

# The refiner-pool replica serves a PASSTHROUGH template: the coordinator
# composes the full refiner prompt fleet-side (agents/prompts.py), so the
# replica must not wrap it again. Role stays "qa" — the refiner ROLE lives
# in the registry's model descriptor, not in the replica process.
REFINER_YAML = """
agents:
  - role: qa
    prompt_template: "{question}"
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path: Path, port: int, span_log: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2", "--kv-backend", "paged",
         "--span-log", str(span_log)],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0)
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never became ready"


def _post(url: str, payload: dict, timeout_s: float = 300.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def _branch_children(tree: dict) -> list[dict]:
    return [c for c in tree["children"] if c.get("name") == "branch"]


def test_ensemble_fleet_fanout_degradation_and_trace(tmp_path):
    from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, \
        serve_fleet
    from edgemesh.obs import Registry
    from edgemesh.obs.trace import load_trace
    from edgemesh.serve.httputil import TRACE_HEADER

    qa_cfg = tmp_path / "qa.yaml"
    qa_cfg.write_text(QA_YAML)
    ref_cfg = tmp_path / "refiner.yaml"
    ref_cfg.write_text(REFINER_YAML)

    # qa-a is the 2-replica pool: big enough to tier (prefill + decode),
    # so the shared question prefix can ride the pool's KV cache.
    fleet = [
        ("qa-a-0", qa_cfg, {"pool": "qa-a", "role": "qa"}),
        ("qa-a-1", qa_cfg, {"pool": "qa-a", "role": "qa"}),
        ("qa-b-0", qa_cfg, {"pool": "qa-b", "role": "qa"}),
        ("refiner-0", ref_cfg, {"pool": "refiner", "role": "refiner"}),
    ]
    ports = {rid: _free_port() for rid, _, _ in fleet}
    span_logs = {rid: tmp_path / f"spans-{rid}.jsonl" for rid, _, _ in fleet}
    procs = {rid: _spawn_replica(cfg, ports[rid], span_logs[rid])
             for rid, cfg, _ in fleet}
    router_spans = tmp_path / "router-spans.jsonl"
    transport = HttpTransport()
    front = None
    try:
        _wait_ready(transport, list(ports.values()))
        # Warm every replica's decode compile (and qa-a's export gather)
        # outside any measured or fault window.
        for rid, _, _ in fleet:
            status, _ = transport.post_json(
                f"http://127.0.0.1:{ports[rid]}/generate",
                {"question": "warmup?"}, timeout_s=300.0)
            assert status == 200
        for rid in ("qa-a-0", "qa-a-1"):
            status, body = transport.post_json(
                f"http://127.0.0.1:{ports[rid]}/kv/export",
                {"question": "warm the export path, please?"},
                timeout_s=300.0)
            assert status == 200 and body.get("kv")

        obs = Registry()
        registry = ReplicaRegistry()
        for rid, _, model in fleet:
            registry.register(rid, f"http://127.0.0.1:{ports[rid]}",
                              model=model)
        router = FleetRouter(
            registry, balancer="least_outstanding", transport=transport,
            obs_registry=obs, max_attempts=3, attempt_timeout_s=60.0,
            default_deadline_s=240.0, backoff_base_s=0.05, demote_after=1,
            tiered=True, prefix_hot_after=2,
            span_log=router_spans, trace_sample=1.0,
        )
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        url = f"http://127.0.0.1:{front.server_address[1]}"

        # ---- Phase A: one ensemble request — full pipeline, one trace.
        status, body, headers = _post(f"{url}/ensemble",
                                      {"question": "what is the answer?"})
        assert status == 200, body
        assert body["outcome"] == "ok" and body["refined"] is True
        assert sorted(c["pool"] for c in body["candidates"]) == ["qa-a", "qa-b"]
        assert isinstance(body["answer"], str) and body["answer"]
        trace_header = headers[TRACE_HEADER]
        trace_id = trace_header.split("-")[1]

        # Cross-process assembly: router record + engine records from the
        # QA branches and the refiner, one tree.
        logs = [str(router_spans)] + [str(p) for p in span_logs.values()]
        doc = load_trace(trace_id, logs)
        tree = doc["tree"]
        assert tree is not None and doc["processes"] >= 3, doc
        branches = _branch_children(tree)
        assert sorted(b["pool"] for b in branches) == ["qa-a", "qa-b"]
        assert all(b["outcome"] == "ok" for b in branches)
        # The concurrency proof: both branches' wall intervals OVERLAP —
        # each starts before either finishes.
        assert max(b["t0"] for b in branches) < min(b["t1"] for b in branches), \
            branches
        refines = [c for c in tree["children"] if c.get("name") == "refine"]
        assert refines and refines[0]["outcome"] == "ok"
        # Replica engine records attached under the winning attempts.
        servers = [g for c in tree["children"]
                   for g in c.get("children", ()) if g.get("name") == "server"]
        assert len(servers) >= 3, tree

        # The CLI renders the same assembly (scripts' entry point).
        out = subprocess.run(
            [sys.executable, "-m", "edgemesh.cli", "obs", "trace",
             trace_id, "--logs", *logs],
            capture_output=True, text=True, timeout=120,
            cwd=Path(__file__).resolve().parent.parent,
        )
        assert out.returncode == 0, out.stderr
        cli_doc = json.loads(out.stdout)
        assert cli_doc["processes"] == doc["processes"]
        assert sorted(b["pool"] for b in _branch_children(cli_doc["tree"])) \
            == ["qa-a", "qa-b"]

        # ---- Phase B: the shared question prefix rides the fleet prefix
        # cache. Repeats of one question make its prefix key hot inside
        # the qa-a pool (2 sightings), the prefix exports ONCE, and later
        # requests import the cached payload: cache_hit.
        hot_q = "which prefix does every ensemble request share, again?"
        for _ in range(4):
            status, body, _ = _post(f"{url}/ensemble", {"question": hot_q})
            assert status == 200 and body["outcome"] == "ok", body
        m = obs.summary(prefix="edgemesh_fleet_")
        hits = sum(v for k, v in m.items()
                   if k.startswith("edgemesh_fleet_tiered_total")
                   and 'outcome="cache_hit"' in k)
        assert hits >= 1, m

        # ---- Phase C: SIGKILL one qa-a replica mid-load. The bar: ZERO
        # client-visible ensemble failures — the branch retries onto the
        # pool's survivor inside its own budget.
        results, errors = [], []

        def client(i):
            try:
                results.append(_post(f"{url}/ensemble",
                                     {"question": f"fan-out under fire {i}?"}))
            except Exception as e:  # a transport-level failure IS a failure
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(10)]
        for i, t in enumerate(threads):
            t.start()
            if i == 3:
                procs["qa-a-1"].kill()  # SIGKILL mid-load
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=240.0)
        assert not errors, errors
        assert len(results) == 10
        assert all(status == 200 for status, _, _ in results), results
        assert all("answer" in body for _, body, _ in results)
        assert all(body["outcome"] in ("ok", "degraded_qa")
                   for _, body, _ in results)

        # ---- Phase D: kill the WHOLE qa-b pool → that branch fails, the
        # refiner runs over the single surviving candidate: degraded_qa,
        # still 200, counted and span-labeled.
        procs["qa-b-0"].kill()
        procs["qa-b-0"].wait(timeout=15)
        status, body, headers = _post(f"{url}/ensemble",
                                      {"question": "who survives the cull?"})
        assert status == 200, body
        assert body["outcome"] == "degraded_qa" and body["refined"] is True
        assert [c["pool"] for c in body["candidates"]] == ["qa-a"]
        fates = {b["pool"]: b["outcome"] for b in body["branches"]}
        assert fates["qa-b"] == "failed" and fates["qa-a"] == "ok"
        em = obs.summary(prefix="edgemesh_ensemble_")
        assert em.get('edgemesh_ensemble_total{outcome="degraded_qa"}', 0) >= 1
        assert sum(v for k, v in em.items()
                   if k.startswith("edgemesh_ensemble_branch_total")
                   and 'pool="qa-b"' in k and 'outcome="failed"' in k) >= 1
        d_trace = headers[TRACE_HEADER].split("-")[1]
        d_tree = load_trace(d_trace, logs)["tree"]
        d_fates = {b["pool"]: b["outcome"] for b in _branch_children(d_tree)}
        assert d_fates["qa-b"] == "failed" and d_fates["qa-a"] == "ok"

        # ---- Phase E: kill the refiner → best-QA-candidate fallback:
        # refiner_fallback, still 200.
        procs["refiner-0"].kill()
        procs["refiner-0"].wait(timeout=15)
        status, body, _ = _post(f"{url}/ensemble",
                                {"question": "and without a refiner?"})
        assert status == 200, body
        assert body["outcome"] == "refiner_fallback" and body["refined"] is False
        assert body["answer"] == body["candidates"][0]["answer"]
        em = obs.summary(prefix="edgemesh_ensemble_")
        assert em.get(
            'edgemesh_ensemble_total{outcome="refiner_fallback"}', 0) >= 1

        # /fleetz carries the live ensemble stats block end-to-end.
        with urllib.request.urlopen(f"{url}/fleetz", timeout=30) as r:
            fleetz = json.load(r)
        ens = fleetz["ensemble"]
        assert ens["qa_pools"] == ["qa-a", "qa-b"]
        assert ens["refiner_pool"] == "refiner"
        assert ens["outcomes"]["degraded_qa"] >= 1
        assert ens["outcomes"]["refiner_fallback"] >= 1
    finally:
        if front is not None:
            front.shutdown()
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
