"""Config-driven training loop (edgemesh.training.run_training, `edgemesh train`)."""

import json

import pytest

from edgemesh.config import (
    AgentSpec,
    EdgeMeshConfig,
    MeshSpec,
    ModelSpec,
    TrainSpec,
)
from edgemesh.training import run_training



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _cfg(**train_kw):
    return EdgeMeshConfig(
        agents=[AgentSpec(role="qa", model=ModelSpec(num_layers=2, hidden_size=64))],
        train=TrainSpec(steps=12, batch_size=4, seq_len=64, lr=3e-3,
                        log_every=6, **train_kw),
    )


def test_loss_decreases_on_tiny_model():
    report = run_training(_cfg())
    assert report["steps_run"] == 12
    assert report["first_loss"] > 0 and report["final_loss"] > 0
    # 12 adamw steps at lr 3e-3 on a tiny model must make clear progress.
    assert report["final_loss"] < report["first_loss"] * 0.9, report


def test_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / "ck")
    r1 = run_training(_cfg(checkpoint_dir=ckpt, checkpoint_every=6))
    assert r1["resumed_from"] is None
    # Same config, more steps: resumes from step 12, runs only the delta.
    cfg2 = _cfg(checkpoint_dir=ckpt, checkpoint_every=6)
    cfg2.train.steps = 18
    r2 = run_training(cfg2)
    assert r2["resumed_from"] == 12
    assert r2["steps_run"] == 6


def test_resume_at_or_past_target_is_noop(tmp_path):
    ckpt = str(tmp_path / "ck")
    run_training(_cfg(checkpoint_dir=ckpt, checkpoint_every=6))  # trains to 12
    cfg2 = _cfg(checkpoint_dir=ckpt)
    cfg2.train.steps = 8  # below the restored step
    report = run_training(cfg2)
    assert report["steps_run"] == 0
    assert report["first_loss"] is None and report["final_loss"] is None
    assert report["resumed_from"] == 12


def test_resume_continues_batch_stream(tmp_path, monkeypatch):
    # A resumed run must draw the CONTINUATION of the batch stream (seeds
    # (seed, start..steps)), not replay draws 0..N. Record the seeds
    # run_training actually feeds the generator.
    import numpy as np

    seen: list = []
    real = np.random.default_rng

    def recording(seed=None):
        if isinstance(seed, tuple):
            seen.append(seed)
        return real(seed)

    monkeypatch.setattr(np.random, "default_rng", recording)
    ckpt = str(tmp_path / "ck")
    run_training(_cfg(checkpoint_dir=ckpt, checkpoint_every=6))  # steps 0..11
    fresh = list(seen)
    assert [s for _, s in fresh] == list(range(12))
    seen.clear()
    cfg2 = _cfg(checkpoint_dir=ckpt, checkpoint_every=6)
    cfg2.train.steps = 18
    run_training(cfg2)  # resumes at 12
    assert [s for _, s in seen] == list(range(12, 18)), seen


def test_sharded_training_on_mesh():
    cfg = _cfg()
    cfg.mesh = MeshSpec(dp=2, tp=4)
    report = run_training(cfg)
    assert report["final_loss"] < report["first_loss"]


def test_sharded_training_on_submesh():
    # dp*tp < device_count: optimizer scalars must be replicated onto the
    # SUB-mesh, not left on device 0 (regression: "incompatible devices").
    cfg = _cfg()
    cfg.mesh = MeshSpec(dp=2, tp=2)
    report = run_training(cfg)
    assert report["final_loss"] < report["first_loss"]


def test_quantized_precision_rejected():
    cfg = _cfg()
    cfg.agents[0].model.precision = "int8"
    with pytest.raises(ValueError, match="float precision"):
        run_training(cfg)


def test_cli_train_prints_report(tmp_path, capsys):
    from edgemesh.cli import main

    cfg_yaml = tmp_path / "t.yaml"
    cfg_yaml.write_text(
        """
agents:
  - role: qa
    model:
      num_layers: 1
      hidden_size: 32
train:
  steps: 4
  batch_size: 2
  seq_len: 32
  log_every: 2
"""
    )
    rc = main(["train", "--config", str(cfg_yaml)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["steps_run"] == 4 and report["final_loss"] > 0


def test_train_split_selection():
    """skip_samples/num_samples carve disjoint train splits; an empty split
    is refused."""
    r = run_training(_cfg(num_samples=5, skip_samples=3))
    assert r["steps_run"] == 12
    with pytest.raises(ValueError, match="empty train split"):
        run_training(_cfg(skip_samples=10**9))


def test_agent_loads_train_checkpoint(tmp_path):
    """ModelSpec.train_checkpoint swaps finetuned weights into an agent
    before precision transforms — int8 rows quantize the TRAINED weights."""
    import numpy as np

    from edgemesh.agents.orchestrator import build_agent

    ckpt = str(tmp_path / "ck")
    run_training(_cfg(checkpoint_dir=ckpt, checkpoint_every=6))

    spec = ModelSpec(num_layers=2, hidden_size=64)  # same arch as training
    fresh = build_agent(AgentSpec(role="qa", model=spec))
    spec_t = ModelSpec(num_layers=2, hidden_size=64, train_checkpoint=ckpt)
    trained = build_agent(AgentSpec(role="qa", model=spec_t))
    # Trained weights differ from the random init...
    assert not np.allclose(
        np.asarray(fresh.params["embed"]["weight"], np.float32),
        np.asarray(trained.params["embed"]["weight"], np.float32),
    )
    # ...and the quantized variant carries them too (int8 leaves present).
    spec_q = ModelSpec(num_layers=2, hidden_size=64, train_checkpoint=ckpt,
                       precision="int8")
    quant = build_agent(AgentSpec(role="qa", model=spec_q))
    assert "kernel_q" in quant.params["layers"]["q"]
    ans = quant.answer("What is the capital of France?")
    assert isinstance(ans["answer"], str)

    with pytest.raises(ValueError, match="no training checkpoint"):
        build_agent(AgentSpec(role="qa", model=ModelSpec(
            num_layers=2, hidden_size=64, train_checkpoint=str(tmp_path / "none"))))
