"""SmoothQuant calibration (ops/smoothquant.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.ops.int8 import quantize_params
from edgemesh.ops.smoothquant import calibrate_and_quantize, collect_activation_scales
from edgemesh.training import forward_train



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _calib_batch(cfg, b=2, s=12, seed=3):
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (b, s), 0, cfg.vocab_size)
    lengths = jnp.asarray([s, s - 4], jnp.int32)
    return tokens.astype(jnp.int32), lengths


@pytest.mark.parametrize("family", ["llama", "phi2"])  # sequential + parallel block
def test_scales_shapes_and_positive(family):
    cfg = tiny_config(family, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _calib_batch(cfg)
    scales = collect_activation_scales(cfg, params, tokens, lengths)
    layers = scales["layers"]
    h, L = cfg.hidden_size, cfg.num_layers
    for key in ("q", "k", "v", "up"):
        assert layers[key].shape == (L, h), key
        assert bool(jnp.all(layers[key] > 0)), key
    assert ("gate" in layers) == cfg.gated


def test_smoothing_reduces_w8a8_error_on_outlier_channels():
    """Inject strong per-channel activation outliers (scaled embedding
    columns); per-token w8a8 activation quantization suffers, and smoothing
    (outliers migrated into the weights) must recover accuracy."""
    cfg = tiny_config("llama", dtype="float32").replace(quant_mode="w8a8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    # Blow up 4 embedding channels -> those channels dominate every row's
    # absmax, crushing the per-token quantization resolution of the rest.
    boost = jnp.ones((cfg.hidden_size,)).at[:4].set(60.0)
    params["embed"]["weight"] = params["embed"]["weight"] * boost[None, :]

    tokens, lengths = _calib_batch(cfg)
    ref = forward_train(cfg, params, tokens, lengths)

    plain = quantize_params(params)
    smooth = calibrate_and_quantize(cfg, params, tokens, lengths, alpha=0.5)

    def err(qp):
        out = forward_train(cfg, qp, tokens, lengths)
        return float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))

    e_plain, e_smooth = err(plain), err(smooth)
    assert e_smooth < e_plain, (e_plain, e_smooth)


def test_smoothed_model_generates():
    from edgemesh.config import SamplingParams
    from edgemesh.runtime import generate

    cfg = tiny_config("llama", dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens, lengths = _calib_batch(cfg)
    qp = calibrate_and_quantize(cfg, params, tokens, lengths)
    out = generate(
        cfg, qp, tokens, lengths,
        SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0),
    )
    assert int(out.num_generated[0]) == 6


def test_agent_calibration_wiring(tmp_path):
    """ModelSpec.calibration: the agent build runs calibrate_and_quantize on
    the prompts file and the resulting params carry smooth vectors."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

    calib = tmp_path / "calib.txt"
    calib.write_text("where is the eiffel tower?\nwho wrote hamlet?\n")
    agent = build_agent(
        AgentSpec(
            role="qa",
            model=ModelSpec(precision="int8_w8a8", calibration=str(calib)),
            sampling=SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0),
        )
    )
    assert "smooth" in agent.params["layers"]["q"]
    r = agent.answer("what is the capital of france?")
    assert isinstance(r["answer"], str)


def test_calibration_rejected_for_weight_only_int8(tmp_path):
    """w8a16 keeps activations in fp — smoothing would only coarsen the
    weight quantization, so the build refuses it."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

    calib = tmp_path / "calib.txt"
    calib.write_text("a question?\n")
    with pytest.raises(ValueError, match="w8a8"):
        build_agent(
            AgentSpec(
                role="qa",
                model=ModelSpec(precision="int8", calibration=str(calib)),
                sampling=SamplingParams(max_new_tokens=4),
            )
        )
