"""Tensor-parallel shard_map inference engine (parallel/tp_infer.py).

The round-1 gap this closes (VERDICT r1 weak #4): Pallas kernels must fire
under distribution. Here the flash kernel runs INSIDE shard_map on local
head shards (interpret mode on the CPU mesh) and the engine's logits are
pinned against the single-device forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models import init_params
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill, init_kv_cache
from edgemesh.ops.int8 import quantize_params
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.tp_infer import TPInferenceEngine



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _cfg(family="llama", **kw):
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 4)
    kw.setdefault("num_layers", 2)
    return tiny_config(family, **kw)


def _ref_last_logits(cfg, params, tokens, lengths, max_seq):
    b = tokens.shape[0]
    last, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, b, max_seq))
    return np.asarray(last, np.float32)


@pytest.mark.parametrize("family", ["llama", "phi2", "gemma2"])
def test_tp_prefill_matches_single_device(devices, family):
    cfg = _cfg(family)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=2, tp=4)
    eng = TPInferenceEngine(cfg, params, mesh, attention_impl="xla")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 4, 6, 5])
    cache = eng.init_cache(4, 16)
    got, _ = eng.prefill(tokens, lengths, cache)
    ref = _ref_last_logits(cfg, params, tokens, lengths, 16)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=2e-2, atol=2e-2)


def test_tp_flash_kernel_fires_in_shard_map(devices):
    """attention_impl='flash' runs the Pallas kernel per shard (interpret on
    CPU) — the multi-device kernel-exercising test VERDICT r1 asked for."""
    cfg = _cfg("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, tp=4)
    eng = TPInferenceEngine(cfg, params, mesh, attention_impl="flash")
    assert eng.lcfg.attention_impl == "flash"
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    lengths = jnp.array([8, 6])
    cache = eng.init_cache(2, 16)
    got, cache = eng.prefill(tokens, lengths, cache)
    ref = _ref_last_logits(cfg, params, tokens, lengths, 16)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=3e-2, atol=3e-2)
    # and decode continues from the flash-prefilled cache
    nxt = jnp.argmax(got, axis=-1).astype(jnp.int32)
    logits2, cache = eng.decode(nxt, cache)
    assert np.all(np.isfinite(np.asarray(logits2, np.float32)))
    assert int(cache.lengths[0]) == 9


def test_tp_generate_matches_single_device_greedy(devices):
    from edgemesh.config import SamplingParams
    from edgemesh.runtime import generate

    cfg = _cfg("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, tp=4)
    eng = TPInferenceEngine(cfg, params, mesh, attention_impl="xla")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    lengths = jnp.array([5, 5])
    got = eng.generate_greedy(tokens, lengths, max_new=6)
    sp = SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, params, tokens, lengths, sp)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref.tokens))


def test_tp_int8_w8a8(devices):
    """Quantized params (w8a8 dynamic) run under the tp shard_map too."""
    cfg = _cfg("llama").replace(quant_mode="w8a8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(params)
    mesh = build_mesh(dp=1, tp=4)
    eng = TPInferenceEngine(cfg, qparams, mesh, attention_impl="xla")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 6])
    cache = eng.init_cache(2, 16)
    got, _ = eng.prefill(tokens, lengths, cache)
    ref = _ref_last_logits(cfg, params, tokens, lengths, 16)
    rel = np.linalg.norm(np.asarray(got, np.float32) - ref) / np.linalg.norm(ref)
    assert rel < 0.08, rel


def test_tp_rejects_indivisible_heads(devices):
    cfg = _cfg("llama", num_heads=6, num_kv_heads=6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, tp=4)
    with pytest.raises(ValueError, match="divide"):
        TPInferenceEngine(cfg, params, mesh)


def test_pipeline_flash_opt_in(devices):
    """PipelineEngine's attention_impl flag: flash fires inside the pp
    shard_map stage body (interpret on CPU) and matches the xla engine."""
    from edgemesh.parallel.pipeline import PipelineEngine

    cfg = _cfg("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(pp=2, tp=1)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 6])
    eng_flash = PipelineEngine(cfg, params, mesh, num_micro=2, attention_impl="flash")
    assert eng_flash.cfg.attention_impl == "flash"
    eng_xla = PipelineEngine(cfg, params, mesh, num_micro=2, attention_impl="xla")
    out_flash = eng_flash.generate_greedy(tokens, lengths, max_new=4)
    out_xla = eng_xla.generate_greedy(tokens, lengths, max_new=4)
    np.testing.assert_array_equal(np.asarray(out_flash), np.asarray(out_xla))


@pytest.mark.parametrize("group_size", [0, 16])
def test_tp_int4(devices, group_size):
    """int4 (nibble-packed) under the per-shard TP engine: adjacent-pair
    packing keeps a packed-row shard == a contiguous global-row shard, and
    grouped scales shard their G axis with the kernel's in dim — the prefill
    must match the single-device int4 forward for BOTH granularities (the
    code-review regression: split-half packing silently corrupted row-sharded
    layers here)."""
    from edgemesh.ops.int4 import quantize_params_int4

    cfg = _cfg("llama", hidden_size=64, intermediate_size=128, dtype="float32")
    params = quantize_params_int4(
        init_params(cfg, jax.random.PRNGKey(0)), group_size=group_size
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 4])
    ref = _ref_last_logits(cfg, params, tokens, lengths, 16)

    mesh = build_mesh(dp=1, tp=4)
    eng = TPInferenceEngine(cfg, params, mesh, attention_impl="xla")
    cache = eng.init_cache(2, 16)
    got, _ = eng.prefill(tokens, lengths, cache)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=2e-2, atol=2e-2)


def test_tp_moe(devices):
    """MoE under the per-shard TP engine: the router is replicated (identical
    top-k on every shard), expert FFN widths split over tp, and the
    down-projection partials psum-join — prefill must match the single-device
    MoE forward."""
    cfg = _cfg("llama", hidden_size=32, intermediate_size=64, dtype="float32").replace(
        num_experts=4, experts_per_token=2, expert_capacity_factor=4.0
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 4])
    ref = _ref_last_logits(cfg, params, tokens, lengths, 16)

    mesh = build_mesh(dp=1, tp=4)
    eng = TPInferenceEngine(cfg, params, mesh, attention_impl="xla")
    cache = eng.init_cache(2, 16)
    got, _ = eng.prefill(tokens, lengths, cache)
    np.testing.assert_allclose(np.asarray(got, np.float32), ref, rtol=2e-2, atol=2e-2)
