"""Metric suite correctness on hand-computable cases."""

import numpy as np
import pytest

from edgemesh.eval.metrics import (
    HashingEmbedder,
    bertscore,
    bleu,
    cosine_similarity,
    rouge_scores,
    tokenize,
)


def test_tokenize_and_stem():
    assert tokenize("The Cats are running!", stem=False) == ["the", "cats", "are", "running"]
    toks = tokenize("running runs", stem=True)
    assert toks[0] == toks[1] == "run"


def test_rouge_identical():
    s = rouge_scores("the cat sat on the mat", "the cat sat on the mat")
    assert s["rouge1"] == pytest.approx(1.0)
    assert s["rouge2"] == pytest.approx(1.0)
    assert s["rougeL"] == pytest.approx(1.0)
    assert s["avg_rouge"] == pytest.approx(1.0)


def test_rouge_disjoint():
    s = rouge_scores("alpha beta gamma", "delta epsilon zeta")
    assert s["rouge1"] == 0.0 and s["rouge2"] == 0.0 and s["rougeL"] == 0.0


def test_rouge1_hand_computed():
    # pred: "a b c"  ref: "a b d"  → unigram matches 2; P=R=2/3 → F1=2/3
    s = rouge_scores("a b c", "a b d", stem=False)
    assert s["rouge1"] == pytest.approx(2 / 3)
    # bigrams: pred {ab, bc}, ref {ab, bd} → 1 match; P=R=1/2
    assert s["rouge2"] == pytest.approx(1 / 2)
    # LCS "a b" len 2 → F1 = 2/3
    assert s["rougeL"] == pytest.approx(2 / 3)


def test_rougeL_subsequence_not_substring():
    # LCS of "a x b y c" vs "a b c" is "a b c" (len 3): P=3/5, R=1 → F1=0.75
    s = rouge_scores("a x b y c", "a b c", stem=False)
    assert s["rougeL"] == pytest.approx(2 * (3 / 5) * 1.0 / (3 / 5 + 1.0))


def test_bleu_identical_and_disjoint():
    assert bleu("the cat sat on the mat down", "the cat sat on the mat down") == pytest.approx(1.0)
    assert bleu("alpha beta gamma delta", "epsilon zeta eta theta") == 0.0


def test_bleu_brevity_penalty():
    # prediction shorter than reference → BP < 1 even with perfect precision
    full = "a b c d e f g h"
    short = "a b c d e f"
    assert 0 < bleu(short, full) < 1.0


def test_cosine_bounds_and_symmetry():
    emb = HashingEmbedder()
    same = cosine_similarity("hello world", "hello world", emb)
    diff = cosine_similarity("hello world", "quantum flapjacks", emb)
    assert same == pytest.approx(1.0, abs=1e-9)
    assert -1.0 <= diff < same


def test_bertscore_identical_is_one():
    s = bertscore("the cat sat", "the cat sat")
    assert s["f1"] == pytest.approx(1.0, abs=1e-9)
    assert s["precision"] == pytest.approx(1.0, abs=1e-9)


def test_bertscore_partial():
    s = bertscore("the cat sat", "the dog sat")
    assert 0.0 < s["f1"] < 1.0


def test_hashing_embedder_deterministic():
    e1, e2 = HashingEmbedder(), HashingEmbedder()
    v1 = e1(["some text here"])
    v2 = e2(["some text here"])
    np.testing.assert_array_equal(v1, v2)


def test_unknown_metric_name_rejected():
    import pytest
    from edgemesh.eval.harness import score_sample

    with pytest.raises(ValueError, match="unknown metrics"):
        score_sample("a", "b", metrics=["rouge"])  # the real keys are rouge1/2/L
