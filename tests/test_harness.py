"""Eval harness: zero-fill policy, JSONL persistence, resume, aggregation."""

import json
import os

import pytest

from edgemesh.eval.data import QASample, load_qa_csv
from edgemesh.eval.harness import aggregate, run_eval

# The reference repo's golden-dataset snapshot; only present on machines that
# checked out the reference alongside this repo. CSV *parsing* is covered by
# test_load_csv_fixture below either way.
REFERENCE_CSV = "/root/reference/Code/Dataset/natural_questions_1000.csv"


def _samples(n=4):
    return [QASample(i, f"question {i}?", f"answer {i}") for i in range(n)]


def test_run_eval_aggregates_and_persists(tmp_path):
    out = tmp_path / "r.jsonl"

    def answer_fn(q):
        return {"answer": q.replace("question", "answer").rstrip("?"), "tps": 10.0}

    report = run_eval(_samples(), answer_fn, out, resume=False)
    assert report["num_samples"] == 4
    assert report["rouge1"] > 0.5  # "answer i" vs "answer i"
    assert report["tps"] == 10.0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(rows) == 4


def test_zero_fill_on_error(tmp_path):
    def answer_fn(q):
        if "2" in q:
            raise RuntimeError("boom")
        return {"answer": "answer"}

    report = run_eval(_samples(), answer_fn, tmp_path / "r.jsonl", resume=False)
    assert report["num_samples"] == 4  # failed sample zero-filled, run continued
    rows = [json.loads(l) for l in (tmp_path / "r.jsonl").read_text().splitlines()]
    bad = [r for r in rows if "error" in r]
    assert len(bad) == 1 and bad[0]["rouge1"] == 0.0


def test_resume_skips_done(tmp_path):
    out = tmp_path / "r.jsonl"
    calls = []

    def answer_fn(q):
        calls.append(q)
        return {"answer": "a"}

    run_eval(_samples(2), answer_fn, out, resume=True)
    assert len(calls) == 2
    run_eval(_samples(4), answer_fn, out, resume=True)
    assert len(calls) == 4  # only the 2 new samples were answered


def test_resume_reanswers_on_question_mismatch(tmp_path):
    """A results.jsonl from a DIFFERENT dataset must not be silently merged."""
    out = tmp_path / "r.jsonl"
    run_eval([QASample(0, "old question?", "old")], lambda q: {"answer": "x"}, out)
    calls = []

    def answer_fn(q):
        calls.append(q)
        return {"answer": "y"}

    report = run_eval([QASample(0, "NEW question?", "new")], answer_fn, out, resume=True)
    assert calls == ["NEW question?"]  # re-answered despite same index
    assert report["num_samples"] == 1


def test_metrics_selection_skips_unrequested(tmp_path):
    report = run_eval(
        _samples(2),
        lambda q: {"answer": "answer"},
        tmp_path / "r.jsonl",
        resume=False,
        metrics=["rouge1", "bleu"],
    )
    assert "rouge1" in report and "bleu" in report
    assert "bertscore" not in report and "cosine" not in report


def test_aggregate_ignores_missing_keys():
    rows = [{"rouge1": 1.0, "bleu": 0.5}, {"rouge1": 0.0}]
    rep = aggregate(rows)
    assert rep["rouge1"] == 0.5
    assert rep["bleu"] == 0.5


@pytest.mark.skipif(
    not os.path.exists(REFERENCE_CSV),
    reason="reference natural_questions_1000.csv snapshot not checked out "
    "on this machine (parsing itself is covered by test_load_csv_fixture)",
)
def test_load_reference_csv():
    samples = load_qa_csv(REFERENCE_CSV, limit=5)
    assert len(samples) == 5
    assert samples[0].question and samples[0].answer


def test_load_csv_fixture(tmp_path):
    """Same loader, committed-fixture shape: runs everywhere the reference
    snapshot does not exist."""
    p = tmp_path / "qa.csv"
    p.write_text(
        "question,answer\n"
        '"who wrote hamlet","william shakespeare"\n'
        '"capital of france","paris"\n',
        encoding="utf-8",
    )
    samples = load_qa_csv(p, limit=2)
    assert len(samples) == 2
    assert samples[0].question == "who wrote hamlet"
    assert samples[1].answer == "paris"


def test_batched_eval_matches_sequential(tmp_path):
    """batch_size>1 answers through answer_batch_fn; rows, order, scores and
    resume behavior are identical to the sequential path."""
    from edgemesh.eval.data import QASample
    from edgemesh.eval.harness import run_eval

    samples = [QASample(i, f"q{i}", f"answer {i}") for i in range(7)]

    def answer(q):
        return {"answer": f"answer {q[1:]}", "tps": 1.0}

    calls = []

    def answer_batch(questions):
        calls.append(len(questions))
        return [answer(q) for q in questions]

    seq = run_eval(samples, answer, output_jsonl=tmp_path / "a.jsonl", resume=False)
    bat = run_eval(
        samples, answer, output_jsonl=tmp_path / "b.jsonl", resume=False,
        answer_batch_fn=answer_batch, batch_size=3,
    )
    assert calls == [3, 3, 1]  # 7 samples in batches of 3
    for key in ("rouge1", "bleu", "num_samples"):
        assert seq[key] == bat[key]
    import json

    rows = [json.loads(l) for l in open(tmp_path / "b.jsonl")]
    assert [r["index"] for r in rows] == list(range(7))  # order preserved


def test_batched_eval_zero_fills_failed_batch(tmp_path):
    from edgemesh.eval.data import QASample
    from edgemesh.eval.harness import run_eval

    samples = [QASample(i, f"q{i}", "a") for i in range(4)]
    calls = []

    def answer_batch(questions):
        calls.append(list(questions))
        if len(calls) == 1:
            raise RuntimeError("device fell over")
        return [{"answer": "a"} for _ in questions]

    report = run_eval(
        samples, lambda q: {"answer": "a"}, output_jsonl=tmp_path / "r.jsonl",
        resume=False, answer_batch_fn=answer_batch, batch_size=2,
    )
    assert report["num_samples"] == 4
    import json

    rows = [json.loads(l) for l in open(tmp_path / "r.jsonl")]
    assert [("error" in r) for r in rows] == [True, True, False, False]
    # Resume retries exactly the zero-filled rows.
    calls.clear()
    report2 = run_eval(
        samples, lambda q: {"answer": "a"}, output_jsonl=tmp_path / "r.jsonl",
        resume=True, answer_batch_fn=answer_batch, batch_size=2,
    )
    assert calls == [["q0", "q1"]]
    assert report2["num_samples"] == 4


def test_compare_runs(tmp_path):
    """Paired bootstrap comparison (eval/compare.py): a uniformly-better run
    B clears the interval; identical runs show no significant difference."""
    import json

    import numpy as np

    from edgemesh.eval.compare import compare_runs

    rng = np.random.default_rng(0)
    a_path, b_path = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    with open(a_path, "w") as fa, open(b_path, "w") as fb:
        for i in range(100):
            base = float(rng.uniform(0.2, 0.4))
            row = {"index": i, "rouge1": base, "bleu": base / 2, "tps": 100.0}
            fa.write(json.dumps(row) + "\n")
            fb.write(json.dumps({**row, "rouge1": base + 0.05}) + "\n")
    rep = compare_runs(a_path, b_path)
    assert rep["n_common"] == 100
    r1 = rep["metrics"]["rouge1"]
    assert r1["better"] is True and r1["ci95"][0] > 0
    assert abs(r1["delta"] - 0.05) < 1e-9
    assert rep["metrics"]["bleu"]["better"] is None  # identical
    assert rep["metrics"]["tps"]["better"] is None


def test_compare_cli(tmp_path, capsys):
    import json

    from edgemesh.cli import main

    p1, p2 = tmp_path / "r1.jsonl", tmp_path / "r2.jsonl"
    for p in (p1, p2):
        with open(p, "w") as f:
            for i in range(5):
                f.write(json.dumps({"index": i, "rouge1": 0.3, "bleu": 0.1}) + "\n")
    rc = main(["compare", str(p1), str(p2)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["n_common"] == 5


def test_compare_excludes_error_rows(tmp_path):
    """Zero-filled error rows (infra failures) must not read as quality
    deltas: they are excluded per-row and COUNTED in the report."""
    import json

    from edgemesh.eval.compare import compare_runs

    a_path, b_path = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    with open(a_path, "w") as fa, open(b_path, "w") as fb:
        for i in range(20):
            row_a = {"index": i, "rouge1": 0.3}
            if i < 5:  # run A failed on the first five samples
                row_a = {"index": i, "rouge1": 0.0, "error": "OOM"}
            fa.write(json.dumps(row_a) + "\n")
            fb.write(json.dumps({"index": i, "rouge1": 0.3}) + "\n")
    rep = compare_runs(a_path, b_path)
    assert rep["excluded_error_rows"] == 5
    r1 = rep["metrics"]["rouge1"]
    assert r1["n"] == 15 and r1["better"] is None  # clean rows are identical

    # All-error pairing refuses outright.
    allerr = tmp_path / "err.jsonl"
    with open(allerr, "w") as f:
        for i in range(20):
            f.write(json.dumps({"index": i, "rouge1": 0.0, "error": "OOM"}) + "\n")
    import pytest

    with pytest.raises(ValueError, match="carry errors"):
        compare_runs(allerr, allerr)


def test_load_qa_hf_from_disk(tmp_path):
    """HF-datasets dialect (combiner_fp.py:413 parity): a save_to_disk
    dataset loads offline through the unified load_qa entry; CSV paths keep
    the CSV parser."""
    import datasets as hfd

    from edgemesh.eval.data import load_qa

    ds = hfd.Dataset.from_dict({
        "query": ["q one", "q two", "q three"],
        "answer": ["a one", "a two", "a three"],
    })
    d = tmp_path / "nq_tiny"
    ds.save_to_disk(str(d))
    samples = load_qa(d, split="train", limit=2)
    assert [s.question for s in samples] == ["q one", "q two"]
    assert samples[1].answer == "a two"

    dd = tmp_path / "nq_dict"
    hfd.DatasetDict({"train": ds}).save_to_disk(str(dd))
    samples = load_qa(dd, split="train[:1000]")
    assert len(samples) == 3
    # Slices APPLY on the save_to_disk branch too (same rows as a hub id).
    samples = load_qa(dd, split="train[1:]")
    assert [s.question for s in samples] == ["q two", "q three"]
    samples = load_qa(dd, split="train[:2]")
    assert len(samples) == 2

    import pytest

    with pytest.raises(ValueError, match="columns"):
        bad = tmp_path / "bad"
        hfd.Dataset.from_dict({"x": ["1"]}).save_to_disk(str(bad))
        load_qa(bad)
