"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.ops.attention import LayerKV, attend
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.spmd import make_spmd_loss, place_spmd
from edgemesh.parallel.ulysses import ulysses_attention
from edgemesh.training import causal_lm_loss



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _dense_reference(q, k, v, positions, valid):
    """Causal attention via the dense cache op (keys at slot j hold position j)."""
    return attend(q, LayerKV(k, v), positions, valid)


@pytest.mark.parametrize("kv_heads", [8, 2])  # a2a path / all-gather GQA fallback
def test_ulysses_matches_dense(devices, kv_heads):
    b, s, nh, hd = 2, 32, 8, 16
    mesh = build_mesh(sp=4, devices=devices[:4])
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, nh, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kv_heads, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kv_heads, hd), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    valid = positions < jnp.asarray([[s], [s - 5]])

    ref = _dense_reference(q, k, v, positions, valid)
    got = ulysses_attention(q, k, v, positions, valid, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = build_mesh(sp=4, devices=devices[:4])
    b, s, hd = 1, 16, 8
    q = jnp.zeros((b, s, 6, hd))  # 6 heads % sp=4 != 0
    k = v = jnp.zeros((b, s, 6, hd))
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    with pytest.raises(ValueError, match="num_heads"):
        ulysses_attention(q, k, v, positions, positions < s, mesh)


def test_spmd_4d_with_ulysses_matches_single_device(devices):
    """The full 4D program with sp_impl='ulysses' (pp=2 x sp=2 x tp=2)
    reproduces the single-device loss — the same pin the ring variant holds."""
    cfg = tiny_config(
        "llama", num_layers=4, num_heads=4, num_kv_heads=2, hidden_size=32,
        intermediate_size=64, vocab_size=128, max_seq_len=64, dtype="float32",
    )
    mesh = build_mesh(dp=1, pp=2, sp=2, tp=2, devices=devices)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32)
    lengths = jnp.array([16, 13, 15, 5], jnp.int32)

    ref = causal_lm_loss(cfg, params, tokens, lengths)
    sharded = place_spmd(params, cfg, mesh)
    loss_fn = make_spmd_loss(cfg, mesh, num_micro=2, sp_impl="ulysses")
    got = jax.jit(loss_fn)(sharded, tokens, lengths)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("window,cap", [(5, 0.0), (0, 4.0), (5, 4.0)])
def test_ulysses_window_and_soft_cap_match_dense(devices, window, cap):
    """Same window/soft-cap pin as the ring scheme: the dials must survive
    the head<->sequence all-to-all exchange."""
    mesh = build_mesh(sp=4)
    b, seq, heads, d = 2, 32, 4, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (b, seq, heads, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, seq, 2, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, seq, 2, d), jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    valid = positions < jnp.array([seq, seq - 5])[:, None]

    ref = attend(q, LayerKV(k, v), positions, valid,
                 sliding_window=window, soft_cap=cap)
    got = ulysses_attention(q, k, v, positions, valid, mesh,
                            sliding_window=window, soft_cap=cap)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5)
