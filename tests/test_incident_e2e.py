"""The incident observatory end-to-end (slow tier): a REAL 3-replica fleet
where one replica degrades mid-run (a single-session bulk flood of
long-budget requests, pinned to one replica by prefix-affinity routing —
no operator action anywhere near the triggers). The acceptance chain:

1. the degraded replica's SLO-burst trigger fires by itself;
2. the incident id propagates through the router (prober digest →
   observe_incident → POST /incident fan-out) and EVERY replica's flight
   ring lands in one incident directory;
3. ``obs incident`` names the degraded replica in the trigger-window
   critical path, with the goodput dip visible in the phase split;
4. ``obs replay`` of the captured spans rebuilds the workload, and the
   UNMODIFIED OpenLoopGenerator reproduces the goodput dip (replayed
   goodput ratio within 15% of the live incident's).
"""

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64, max_seq_len: 512}
    sampling: {max_new_tokens: 256, do_sample: false, repetition_penalty: 1.0}
"""

#: Client-side == replica-side SLO: answered within 0.5 s of the scheduled
#: arrival / first token within 0.5 s of submit. Idle tiny-model requests
#: run ~0.1 s, flood-queued ones run seconds — the target sits between.
SLO_S = 0.5


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path, port, rid, span_log, flight_dir):
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "EDGEMESH_REPLICA_ID": rid,
        "EDGEMESH_SLO_TTFT_S": str(SLO_S),
        "EDGEMESH_SLO_TPOT_S": "0.5",
        # Isolate the SLO-burst trigger: the queue/error/compile detectors
        # are effectively disarmed so warmup compiles cannot claim the
        # incident first, and the burst thresholds are sized to the ~20
        # requests the degraded replica sees inside the flood window.
        "EDGEMESH_ANOMALY_SLO_WINDOW": "16",
        "EDGEMESH_ANOMALY_SLO_MISSES": "6",
        "EDGEMESH_ANOMALY_SLO_RATIO": "0.4",
        "EDGEMESH_ANOMALY_SLO_FACTOR": "1.5",
        "EDGEMESH_ANOMALY_SLO_MIN_WEIGHT": "6",
        "EDGEMESH_ANOMALY_QUEUE_DEPTH": "10000",
        "EDGEMESH_ANOMALY_ERRORS": "10000",
        "EDGEMESH_ANOMALY_COMPILES": "10000",
        "EDGEMESH_ANOMALY_COOLDOWN_S": "5",
    })
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2",
         "--span-log", str(span_log),
         "--flight-dir", str(flight_dir), "--flight-capacity", "512"],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0)
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never ready"


def _incident_workload(seed: int, n_bulk: int):
    """Interactive chatter + a single-session bulk flood arriving mid-run.
    The bulk session's stable prefix makes prefix-affinity routing pin the
    whole flood to ONE replica — which is the replica that degrades.
    ``n_bulk`` is sized from the measured per-request service time so the
    flood's total decode work exceeds its arrival window by seconds on
    ANY host speed (the backlog, not the host, is the incident)."""
    from edgemesh.loadgen.arrivals import ConstantProcess, PoissonProcess
    from edgemesh.loadgen.workload import LengthMix, TenantSpec, Workload

    chat = Workload([
        TenantSpec(
            name="chat", arrival=PoissonProcess(6.0, seed=seed),
            lane="interactive",
            prompt_mix=LengthMix(median=70, sigma=0.3, lo=40, hi=140),
            output_mix=LengthMix(median=8, sigma=0.4, lo=4, hi=16),
            sessions=6, turns_mean=1e9, send_max_new=True),
    ], seed=seed).build_schedule(14.0)
    bulk = Workload([
        TenantSpec(
            name="bulk",
            arrival=ConstantProcess(max(0.5, n_bulk / 2.5)), lane="batch",
            prompt_mix=LengthMix(median=100, sigma=0.0),
            sessions=1, turns_mean=1e9),
    ], seed=seed + 1).build_schedule(2.5)[:n_bulk]
    for req in bulk:
        req.at_s += 6.0  # the degradation arrives MID-run
        # Long-budget requests: FEW and HEAVY, so the pinned replica's
        # backlog is set by total decode work, not by arrival-edge jitter
        # (a high-rate burst of tiny requests replays with its recorded
        # pipeline delays baked in, which smooths the backlog ramp and
        # biases the replay's goodput upward).
        req.max_new = 256
    out = chat + bulk
    out.sort(key=lambda r: r.at_s)
    return out


def _goodput_phases(doc):
    return {k: doc["phases"][k]["goodput_ratio"] for k in
            ("before", "during", "after")}


def test_incident_fires_propagates_assembles_and_replays(tmp_path):
    from edgemesh.fleet import FleetRouter, HealthProber, HttpTransport, \
        ReplicaRegistry, serve_fleet
    from edgemesh.loadgen.generator import OpenLoopGenerator, http_target
    from edgemesh.obs import Registry
    from edgemesh.obs.cli import main as obs_main
    from edgemesh.obs.flight import DUMP_EVENT, assemble_incident
    from edgemesh.utils.tracing import JsonlLogger

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    flight_dir = tmp_path / "incidents"
    span_dir = tmp_path / "spans"
    span_dir.mkdir()
    ports = [_free_port() for _ in range(3)]
    rids = [f"r{i}" for i in range(3)]
    procs = [
        _spawn_replica(cfg, p, rid, span_dir / f"spans-{rid}.jsonl",
                       flight_dir)
        for rid, p in zip(rids, ports)
    ]
    transport = HttpTransport()
    prober = None
    front = None
    try:
        _wait_ready(transport, ports)
        obs = Registry()
        registry = ReplicaRegistry(
            (rid, f"http://127.0.0.1:{p}") for rid, p in zip(rids, ports))
        from edgemesh.fleet.balancer import PrefixAffinityBalancer

        # Hard affinity (no least-outstanding spill): the flood must stay
        # pinned to one replica — the incident IS the pinning. Live and
        # replay both route through this same policy.
        router = FleetRouter(
            registry,
            balancer=PrefixAffinityBalancer(spill_margin=10 ** 6),
            transport=transport,
            obs_registry=obs, attempt_timeout_s=120.0,
            default_deadline_s=240.0, max_inflight=512,
        )
        prober = HealthProber(registry, transport=transport,
                              interval_s=0.5, timeout_s=5.0,
                              obs_registry=obs,
                              on_incident=router.observe_incident).start()
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        url = f"http://127.0.0.1:{front.server_address[1]}/generate"
        target = http_target(url, timeout_s=240.0)

        # Warmup: compile every prefill bucket / decode shape with DIRECT
        # sequential requests (an open-loop warmup pass would pile its own
        # queue behind the first compile and poison the SLO windows), then
        # FLUSH each replica's burst window with quick good requests so
        # warmup-compile misses cannot masquerade as a live burst.
        def direct(port, question, max_new):
            t0 = time.monotonic()
            status, _ = transport.post_json(
                f"http://127.0.0.1:{port}/generate",
                {"question": question, "max_new": max_new}, timeout_s=240.0)
            assert status == 200
            return time.monotonic() - t0

        t_bulk = 0.0
        for port in ports:
            for chars, max_new in ((40, 8), (70, 16), (100, 16), (140, 16),
                                   (100, 64)):
                question = ("warm compile ladder " * 8)[:chars] + "?"
                direct(port, question, max_new)
            t_bulk = max(t_bulk, direct(
                port, ("warm compile ladder " * 8)[:100] + "?", 256))
            for i in range(20):  # flush: window=16 of recent goods
                direct(port, f"flush the burst window {i}?", 4)
        # Flood sizing: total decode work ≈ 4x its 2.5 s arrival window on
        # THIS host, so the pinned replica's backlog peaks at seconds
        # regardless of how fast the tiny model runs here.
        n_bulk = int(min(60, max(8, 10.0 / max(t_bulk, 0.05))))
        # Quiet gap: any warmup-era incident id is minted (cooldown) and
        # snapshotted away before the measured run begins.
        time.sleep(6.0)
        warmup_incidents = set(
            p.name for p in flight_dir.glob("*")) if flight_dir.exists() else set()

        # ---- The live incident run (measured).
        live_schedule = _incident_workload(seed=5, n_bulk=n_bulk)
        live = OpenLoopGenerator(target, live_schedule, slo_latency_s=SLO_S,
                                 duration_s=14.0).run()
        assert live["scheduled"] == len(live_schedule)

        # ---- 1+2: the SLO-burst trigger fired with no operator action and
        # every replica's ring landed in ONE incident directory.
        def fresh_incident_dirs():
            if not flight_dir.exists():
                return []
            return [d for d in flight_dir.iterdir()
                    if d.is_dir() and d.name not in warmup_incidents]

        deadline = time.monotonic() + 30.0
        complete = None
        while time.monotonic() < deadline and complete is None:
            for d in fresh_incident_dirs():
                if len(list(d.glob("flight-*.jsonl"))) == 3:
                    complete = d
                    break
            time.sleep(0.5)
        assert complete is not None, (
            f"no fleet-wide incident directory appeared; dirs="
            f"{[(d.name, len(list(d.glob('*.jsonl')))) for d in fresh_incident_dirs()]}")
        headers = []
        for f in complete.glob("flight-*.jsonl"):
            recs = JsonlLogger(f).read()
            headers.append(recs[0])
            assert recs[0]["event"] == DUMP_EVENT
        kinds = {h["replica"]: h["kind"] for h in headers}
        local = [r for r, k in kinds.items() if k == "slo_burst"]
        assert local, f"no local slo_burst dump in {kinds}"
        degraded = local[0]
        assert sorted(kinds) == rids  # every replica dumped
        assert all(k in ("slo_burst", "propagated")
                   for k in kinds.values()), kinds
        # The router surfaced + counted it.
        status = router.status()
        assert any(i["id"] == complete.name for i in status["incidents"])
        m = obs.summary(prefix="edgemesh_fleet_")
        assert m.get(
            'edgemesh_fleet_incidents_total{kind="slo_burst"}', 0) >= 1

        # ---- 3: the postmortem names the degraded replica and shows the
        # goodput dip in the phase split (CLI exit contract included).
        doc = assemble_incident(
            sorted(complete.glob("*.jsonl")), window_s=6.0)
        assert doc["incident_id"] == complete.name
        assert doc["critical_path"]["slowest_replica"] == degraded
        phases = _goodput_phases(doc)
        assert phases["before"] is not None and phases["during"] is not None
        assert phases["during"] < phases["before"], phases
        assert obs_main(["incident", str(complete)]) == 0

        # ---- 4: replay the captured spans through the UNMODIFIED
        # OpenLoopGenerator and reproduce the goodput dip. The span logs
        # (trace_sample defaults to 1.0) are the complete capture; the
        # workload document is rebuilt by the standard CLI.
        workload_json = tmp_path / "workload.json"
        assert obs_main(["replay", str(span_dir),
                         "--out", str(workload_json)]) == 0
        from edgemesh.loadgen.workload import ReplayWorkload

        wl = ReplayWorkload.from_doc(json.loads(workload_json.read_text()))
        # The rebuilt schedule covers warmup + flush + live; the ≥6 s
        # quiet gap before the live run is the LAST multi-second
        # inter-arrival (warmup compiles produce big gaps too, but all of
        # them precede it; live arrivals at 6 rps never gap past ~2 s) —
        # replay only the live window.
        reqs = wl.build_schedule()
        cuts = [i for i in range(1, len(reqs))
                if reqs[i].at_s - reqs[i - 1].at_s > 3.0]
        assert cuts, "the pre-live quiet gap is missing from the rebuild"
        schedule = reqs[cuts[-1]:]
        live_t0 = schedule[0].at_s
        for r in schedule:
            r.at_s -= live_t0
        assert len(schedule) >= live["scheduled"]
        replayed = OpenLoopGenerator(
            target, schedule, slo_latency_s=SLO_S, duration_s=14.0).run()
        live_ratio = live["goodput_ratio"]
        rep_ratio = replayed["goodput_ratio"]
        assert live_ratio < 0.97, f"the live run never dipped: {live_ratio}"
        assert abs(rep_ratio - live_ratio) <= max(0.15 * live_ratio, 0.05), (
            f"replayed goodput {rep_ratio} vs live {live_ratio}")
    finally:
        if prober is not None:
            prober.stop()
        if front is not None:
            front.shutdown()
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
