"""The quality observatory end-to-end (slow tier): a REAL 2-replica fleet
where one replica serves a corrupted checkpoint (``EDGEMESH_QUALITY_NOISE``
perturbs the output head at load time) — it passes ``/readyz``, answers
``/generate`` with 200s at normal latency, and is undetectable to every
latency-side monitor. The acceptance chain:

1. a golden set is pinned from the HEALTHY replica's own greedy answers
   (greedy decoding is deterministic, so healthy reproduces its references
   exactly and the degraded replica diverges);
2. the canary prober catches the degraded replica mid-load: its score
   collapses, the healthy replica's does not, and the collapse mints a
   ``quality_drift`` incident whose flight dumps land fleet-wide in ONE
   incident directory;
3. the engine-side quality signals ride the wire: span records carry the
   ``quality`` block, ``/loadz`` digests carry confidence EWMAs, and the
   router's ``/fleetz`` quality rollup names the worst canary replica;
4. ``edgemesh obs quality`` and ``obs incident`` name the degraded
   replica from the logs alone.
"""

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64, max_seq_len: 512}
    sampling: {max_new_tokens: 24, do_sample: false, repetition_penalty: 1.0}
"""

GOLDEN_QUESTIONS = [
    "What is the capital of France?",
    "How many days are there in a week?",
    "What color is the sky on a clear day?",
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path, port, rid, span_log, flight_dir, noise=0.0):
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "EDGEMESH_REPLICA_ID": rid,
        # Disarm every latency-side detector: the point of the test is
        # that ONLY the quality path (canary → quality_drift) can catch
        # this failure — a corrupted head serves garbage at full speed.
        "EDGEMESH_ANOMALY_SLO_MIN_WEIGHT": "1000000",
        "EDGEMESH_ANOMALY_QUEUE_DEPTH": "10000",
        "EDGEMESH_ANOMALY_ERRORS": "10000",
        "EDGEMESH_ANOMALY_COMPILES": "10000",
        # And the replica-local drift detector: the degraded replica is
        # corrupted from boot, so it has no healthy baseline to drift
        # from — the CANARY is what must catch it.
        "EDGEMESH_ANOMALY_QUALITY_MIN_WEIGHT": "1000000",
        "EDGEMESH_ANOMALY_COOLDOWN_S": "5",
    })
    if noise:
        env["EDGEMESH_QUALITY_NOISE"] = str(noise)
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2",
         "--span-log", str(span_log),
         "--flight-dir", str(flight_dir), "--flight-capacity", "256"],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0)
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never ready"


def test_canary_catches_degraded_replica_and_fires_quality_drift(tmp_path):
    from edgemesh.fleet import CanaryProber, FleetRouter, HttpTransport, \
        ReplicaRegistry
    from edgemesh.obs import Registry
    from edgemesh.obs.cli import main as obs_main
    from edgemesh.obs.flight import DUMP_EVENT
    from edgemesh.utils.tracing import JsonlLogger

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    flight_dir = tmp_path / "incidents"
    span_dir = tmp_path / "spans"
    span_dir.mkdir()
    good_port, bad_port = _free_port(), _free_port()
    procs = [
        _spawn_replica(cfg, good_port, "r-good",
                       span_dir / "spans-r-good.jsonl", flight_dir),
        _spawn_replica(cfg, bad_port, "r-bad",
                       span_dir / "spans-r-bad.jsonl", flight_dir,
                       noise=0.8),
    ]
    transport = HttpTransport()
    try:
        _wait_ready(transport, [good_port, bad_port])

        def generate(port, question):
            status, body = transport.post_json(
                f"http://127.0.0.1:{port}/generate",
                {"question": question}, timeout_s=240.0)
            assert status == 200, body
            assert isinstance(body.get("answer"), str)
            return body

        # ---- 1: pin the golden set from the healthy replica's own
        # greedy answers (warming its compile cache in the same pass).
        golden_path = tmp_path / "golden.jsonl"
        with open(golden_path, "w") as f:
            for q in GOLDEN_QUESTIONS:
                f.write(json.dumps({
                    "question": q,
                    "reference": generate(good_port, q)["answer"]}) + "\n")
        # The degraded replica is indistinguishable on the health axis:
        # ready, 200s, a string answer — just the WRONG string.
        bad_body = generate(bad_port, GOLDEN_QUESTIONS[0])
        golden = [json.loads(l) for l in golden_path.read_text().splitlines()]
        assert bad_body["answer"] != golden[0]["reference"]
        # The serving result carries the decode loop's confidence signal.
        assert "confidence" in bad_body

        # ---- 2: the canary prober catches it. In-process router +
        # prober, probe rounds driven explicitly (deterministic timing).
        obs = Registry()
        registry = ReplicaRegistry([
            ("r-good", f"http://127.0.0.1:{good_port}"),
            ("r-bad", f"http://127.0.0.1:{bad_port}"),
        ])
        router = FleetRouter(registry, transport=transport, obs_registry=obs,
                             span_log=span_dir / "router.jsonl",
                             attempt_timeout_s=120.0)
        collapses = []
        prober = CanaryProber(
            registry, transport=transport, router=router,
            golden_path=str(golden_path), timeout_s=240.0,
            min_probes=2, collapse_below=0.3, obs_registry=obs,
            trace_log=router._trace_log,
            on_collapse=lambda rid, inc: collapses.append((rid, inc)))
        # Mid-load: interleave live traffic with the probe rounds — the
        # fleet keeps serving while the canary closes in.
        for i in range(3):
            generate(good_port, f"live question {i}?")
            generate(bad_port, f"live question {i}?")
            prober.probe_once()

        good, bad = registry.get("r-good"), registry.get("r-bad")
        # Healthy reproduces its own references exactly; degraded diverges.
        assert good.canary["score"] > 0.9, good.canary
        assert bad.canary["score"] < 0.3, bad.canary
        assert good.canary["collapsed"] is False
        assert bad.canary["collapsed"] is True
        # The collapse fired exactly once, for the degraded replica only.
        assert [rid for rid, _ in collapses] == ["r-bad"]
        incident_id = collapses[0][1]["id"]
        assert collapses[0][1]["kind"] == "quality_drift"

        # The incident propagated fleet-wide: BOTH replicas' flight rings
        # land in the one incident directory (direct POST to the degraded
        # source + router broadcast to the rest).
        incident_dir = flight_dir / incident_id

        def dump_files():
            if not incident_dir.exists():
                return []
            return sorted(incident_dir.glob("flight-*.jsonl"))

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(dump_files()) < 2:
            time.sleep(0.5)
        dumps = dump_files()
        assert len(dumps) == 2, list(flight_dir.glob("**/*"))
        headers = {JsonlLogger(f).read()[0]["replica"]:
                   JsonlLogger(f).read()[0] for f in dumps}
        assert sorted(headers) == ["r-bad", "r-good"]
        for h in headers.values():
            assert h["event"] == DUMP_EVENT
            assert h["origin_kind"] == "quality_drift"
        # The router surfaced it (status + the incident span-log record).
        status = router.status()
        assert any(i["id"] == incident_id for i in status["incidents"])

        # ---- 3: the quality signals ride the wire end to end.
        # /loadz: the engine's digest quality block (confidence EWMAs).
        for port in (good_port, bad_port):
            st, digest = transport.get_json(
                f"http://127.0.0.1:{port}/loadz", timeout_s=10.0)
            assert st == 200
            q = digest["quality"]
            assert q["requests"] >= 1
            assert 0.0 <= q["confidence_ewma"] <= 1.0
        # /fleetz rollup: the worst canary replica is named.
        assert status["quality"]["min_canary_replica"] == "r-bad"
        assert status["quality"]["min_canary_score"] < 0.3
        # Span records: the quality block rides each replica's span log.
        recs = JsonlLogger(span_dir / "spans-r-bad.jsonl").read()
        quality_recs = [r for r in recs
                        if isinstance(r.get("quality"), dict)]
        assert quality_recs, "no quality block on the degraded span log"
        assert all(isinstance(r["quality"]["confidence_mean"], float)
                   for r in quality_recs)

        # ---- 4: the offline lens names the degraded replica. The span
        # dir holds the router's log (canary records + the incident
        # record) and both replicas' span logs (quality blocks).
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            assert obs_main(["quality", str(span_dir), "--json"]) == 0
        view = json.loads(buf.getvalue())
        assert view["canary"]["r-bad"]["score_last"] < 0.3
        assert view["canary"]["r-good"]["score_last"] > 0.9
        assert view["degraded_replicas"] == ["r-bad"]
        assert [d["incident_id"] for d in view["drift_incidents"]] == [
            incident_id]
        assert view["confidence"]["engines"]  # engine-side signals folded
        # The human table renders without error too.
        with redirect_stdout(io.StringIO()):
            assert obs_main(["quality", str(span_dir)]) == 0
        # And the incident postmortem assembles from the dump directory.
        with redirect_stdout(io.StringIO()):
            assert obs_main(["incident", str(incident_dir)]) == 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
