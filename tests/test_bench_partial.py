"""Stall-resilience contract of the driver bench (edgemesh/benchmarks.py).

Round 2's bench printed its JSON only at the finish line; a TPU-tunnel wedge
mid-run left the driver with rc=3 and nothing parseable (VERDICT r2 weak #1).
The contract now: the headline int8 stage runs first, every completed stage
re-emits the refreshed result line, and the stall watchdog re-prints the
partial before exiting — so the LAST JSON line on stdout is always the most
complete measurement.
"""

import json

from edgemesh import benchmarks


def test_emit_partial_prints_and_records(capsys):
    r = {"metric": "decode_tok_s_x", "value": 1.0, "unit": "tok/s/chip",
         "vs_baseline": 0.1}
    benchmarks.emit_partial(r)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line) == r
    assert benchmarks._PARTIAL == r
    # A refresh replaces, never merges stale keys.
    r2 = {"metric": "decode_tok_s_x", "value": 2.0, "unit": "tok/s/chip",
          "vs_baseline": 0.2}
    benchmarks.emit_partial(r2)
    assert benchmarks._PARTIAL == r2
    assert "1.0" not in capsys.readouterr().out


def test_emit_partial_without_metric_is_silent(capsys):
    benchmarks.emit_partial({"incomplete": True})
    assert capsys.readouterr().out == ""


def test_headline_serving_schema_gains_ragged_and_spec_keys(monkeypatch, capsys):
    """The ragged-ablation schema contract: a headline run must carry the
    serving_ragged_tok_s headline, the segmented baseline, the
    batch-shape-sweep keys, and the speculative selfcheck — pinned with
    faked stages so a partial (stalled-after-serving) artifact still has
    the keys the PERFORMANCE.md targets reference."""

    def fake_build(preset, precision, quant_mode):
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    def fake_serving(preset, *a, built=None, kv_backend="paged", ragged=None,
                     **kw):
        value = 900.0 if ragged is None else 700.0  # segmented arm slower
        return {"metric": "serving", "value": value, "wave_tok_s": [value],
                "spread_pct": 1.0, "req_s": 2.0, "generated": 100,
                "latency_s_p50": 0.5, "latency_s_p95": 0.9,
                "stats": {"segments": 9, "max_concurrent": 8,
                          "ragged_boundaries": 9, "ragged_prefill_tokens": 300,
                          "ragged_decode_tokens": 60}, "obs": {}}

    def fake_ablation(preset, built=None, **kw):
        out = {}
        for shape in ("decode_heavy", "prefill_heavy", "mixed_50_50"):
            out[f"serving_ragged_{shape}_tok_s"] = 900.0
            out[f"serving_segmented_{shape}_tok_s"] = 700.0
            out[f"ragged_over_segmented_{shape}"] = 1.286
        return out

    def fake_spec(preset, built=None, **kw):
        return {"spec_tok_s": 80.0, "plain_tok_s": 60.0, "spec_speedup": 1.33,
                "accept_rate": 0.4, "selfcheck_accept_rate": 1.0,
                "gamma": 4, "draft_layers": 4, "draft_mode": "truncate",
                "kv_backend": kw.get("kv_backend", "dense")}

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)
    monkeypatch.setattr(benchmarks, "serving_benchmark", fake_serving)
    monkeypatch.setattr(benchmarks, "ragged_ablation_benchmark", fake_ablation)
    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "n_requests": 24,
                "concurrency": 6, "slo_target_s": 0.25,
                "least_outstanding_p50_s": 0.1,
                "least_outstanding_p99_s": 0.7,
                "least_outstanding_goodput": 0.8,
                "least_outstanding_routed_to_slow": 4,
                "adaptive_p50_s": 0.09, "adaptive_p99_s": 0.5,
                "adaptive_goodput": 1.0, "adaptive_routed_to_slow": 0,
                "adaptive_hedged": 1}

    def fake_load_curve(**kw):
        return {"metric": "load_curve_knee_rps", "value": 12.0,
                "unit": "req/s", "n_replicas": 2, "duration_s": 4.0,
                "estimated_capacity_rps": 11.5, "slo_latency_s": 0.4,
                "knee_goodput_rps": 11.0, "collapsed": True,
                "points": [
                    {"requested_rps": 6.0, "offered_rps": 5.8,
                     "goodput_rps": 5.8, "goodput_ratio": 1.0, "shed": 0,
                     "errors": 0, "latency_s_p50": 0.1,
                     "latency_s_p99": 0.2,
                     "tenants": {"interactive": {"goodput_ratio": 1.0},
                                 "batch": {"goodput_ratio": 1.0}}},
                    {"requested_rps": 12.0, "offered_rps": 12.0,
                     "goodput_rps": 11.0, "goodput_ratio": 0.92, "shed": 0,
                     "errors": 0, "latency_s_p50": 0.15,
                     "latency_s_p99": 0.39,
                     "tenants": {"interactive": {"goodput_ratio": 0.95},
                                 "batch": {"goodput_ratio": 0.88}}},
                    {"requested_rps": 46.0, "offered_rps": 46.2,
                     "goodput_rps": 3.1, "goodput_ratio": 0.07, "shed": 80,
                     "errors": 0, "latency_s_p50": 2.5,
                     "latency_s_p99": 4.0,
                     "tenants": {"interactive": {"goodput_ratio": 0.07},
                                 "batch": {"goodput_ratio": 0.06}}},
                ]}

    monkeypatch.setattr(benchmarks, "speculative_benchmark", fake_spec)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark", fake_adaptive)
    monkeypatch.setattr(benchmarks, "load_curve_benchmark", fake_load_curve)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ADMIT", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ENSEMBLE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_PRESET", "llama1b")

    out = benchmarks.headline_benchmark(preset="llama1b", batch=2,
                                        decode_steps=8, sweep_batches=())
    # Headline + ablation serving keys.
    assert out["serving_paged_tok_s"] == out["serving_ragged_tok_s"] == 900.0
    assert out["serving_segmented_tok_s"] == 700.0
    assert out["serving_ragged_boundaries"] == 9
    assert out["serving_ragged_prefill_tokens"] == 300
    for shape in ("decode_heavy", "prefill_heavy", "mixed_50_50"):
        assert out[f"serving_ragged_{shape}_tok_s"] == 900.0
        assert out[f"serving_segmented_{shape}_tok_s"] == 700.0
        assert out[f"ragged_over_segmented_{shape}"] == 1.286
    # Telemetry-loop stage: the adaptive-vs-least-outstanding comparison
    # rides the BENCH JSON (p99 ratio + goodput per arm + the mechanism).
    assert out["adaptive_over_least_outstanding_p99"] == 1.4
    assert out["least_outstanding_goodput"] == 0.8
    assert out["adaptive_goodput"] == 1.0
    assert out["adaptive_routed_to_slow"] == 0
    assert out["slo_target_s"] == 0.25
    # Load-observatory stage: the goodput-vs-offered-load curve rides the
    # BENCH JSON — >=3 points, per-tenant splits, the saturation knee and
    # the collapse flag (the load_curve stage schema contract).
    assert out["load_curve_knee_rps"] == 12.0
    assert out["load_curve_knee_goodput_rps"] == 11.0
    assert out["load_curve_collapsed"] is True
    assert out["load_curve_slo_latency_s"] == 0.4
    assert len(out["load_curve_points"]) >= 3
    for p in out["load_curve_points"]:
        assert {"offered_rps", "goodput_rps", "goodput_ratio", "shed",
                "latency_s_p99", "tenants"} <= set(p)
        assert {"interactive", "batch"} <= set(p["tenants"])
    # Speculative arm: the selfcheck key distinguishes machinery-broken
    # (selfcheck < 1) from draft-weak (accept low, selfcheck 1.0).
    assert out["spec_selfcheck_accept_rate"] == 1.0
    assert out["spec_draft_mode"] == "truncate"
    assert out["spec_accept_rate"] == 0.4
    # Every completed stage refreshed the partial line; the last line
    # carries the full schema.
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert "serving_ragged_tok_s" in lines[-1]


def test_ragged_ablation_benchmark_shapes(monkeypatch):
    """ragged_ablation_benchmark sweeps all three shapes x both arms and
    derives the ratio keys (faked serving_benchmark — no device work)."""
    calls = []

    def fake_serving(preset, *a, ragged=None, max_new=None, prompt_pad=0,
                     budgets=None, **kw):
        calls.append((ragged, max_new, prompt_pad, budgets))
        return {"value": 500.0 if ragged else 400.0, "latency_s_p50": 0.4}

    monkeypatch.setattr(benchmarks, "serving_benchmark", fake_serving)

    class _Cfg:
        max_seq_len = 2048

    out = benchmarks.ragged_ablation_benchmark("tiny", built=(_Cfg(), "params"))
    assert len(calls) == 6  # 3 shapes x 2 arms
    assert any(pad == 512 for _, _, pad, _ in calls)  # prefill-heavy shape
    assert any(b == (8, 96) for _, _, _, b in calls)  # 50/50 budget cycling
    for shape in ("decode_heavy", "prefill_heavy", "mixed_50_50"):
        assert out[f"ragged_over_segmented_{shape}"] == 1.25


def test_router_overhead_stage_schema_pins_recorder_arm(monkeypatch, capsys):
    """The flight-recorder bench contract: a headline run carries the
    router/tracing/recorder overhead split — `recorder_overhead_p50/p99`
    alongside the absolute arm percentiles — so the 'always-on is cheap'
    claim (recorder p50 within 2% of the recorder-off arm) is a tracked
    number in BENCH JSON (faked stage — no replicas spun)."""

    def fake_build(preset, precision, quant_mode):
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    def fake_overhead(**kw):
        return {"metric": "router_overhead_p50_s", "value": 0.0021,
                "unit": "s", "n_requests": 40,
                "direct_p50_s": 0.010, "direct_p99_s": 0.015,
                "routed_p50_s": 0.0121, "routed_p99_s": 0.018,
                "overhead_p99_s": 0.003,
                "traced_p50_s": 0.013, "traced_p99_s": 0.019,
                "tracing_overhead_p50_s": 0.0009,
                "tracing_overhead_p99_s": 0.001,
                "recorder_p50_s": 0.01215, "recorder_p99_s": 0.0181,
                "recorder_overhead_p50_s": 0.00005,
                "recorder_overhead_p99_s": 0.0001,
                "recorder_ring_records": 41,
                "sample_trace": None, "obs": {}}

    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "slo_target_s": 0.25}

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)
    monkeypatch.setattr(benchmarks, "router_overhead_benchmark", fake_overhead)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark", fake_adaptive)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SERVE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ENSEMBLE", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert out["router_overhead_p50_s"] == 0.0021
    assert out["router_overhead_p99_s"] == 0.003
    assert out["tracing_overhead_p50_s"] == 0.0009
    # The recorder arm keys the acceptance gate reads.
    assert out["recorder_p50_s"] == 0.01215
    assert out["recorder_overhead_p50_s"] == 0.00005
    assert out["recorder_overhead_p99_s"] == 0.0001
    assert out["recorder_ring_records"] == 41
    # Within-2% gate is checkable from the artifact alone.
    assert abs(out["recorder_overhead_p50_s"]) <= 0.02 * out["routed_p50_s"]
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert "recorder_overhead_p50_s" in lines[-1]


def test_router_overhead_stage_is_skippable_via_env(monkeypatch):
    """EDGEMESH_BENCH_FLEET=0 must skip the router_overhead stage (it
    spins a live replica + frontend) — no keys, no error recorded."""
    _fake_stage1(monkeypatch)

    def boom(**kw):
        raise AssertionError("router_overhead_benchmark ran despite the gate")

    monkeypatch.setattr(benchmarks, "router_overhead_benchmark", boom)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SERVE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_FLEET", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith(("router_overhead", "recorder_")) for k in out)


def test_load_curve_stage_is_skippable_via_env(monkeypatch, capsys):
    """EDGEMESH_BENCH_LOADGEN=0 must skip the load_curve stage entirely —
    no replicas spun, no keys emitted, no error recorded."""

    def fake_build(preset, precision, quant_mode):
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    def boom(**kw):
        raise AssertionError("load_curve_benchmark ran despite the gate")

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)
    monkeypatch.setattr(benchmarks, "load_curve_benchmark", boom)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SERVE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_FLEET", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith("load_curve") for k in out)


def _fake_stage1(monkeypatch):
    """Shared stage-1 fakes: a headline int8 decode that succeeds without
    touching a device, everything heavier gated off by callers."""

    def fake_build(preset, precision, quant_mode):
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)


_TP8_GATES = ("EDGEMESH_BENCH_8B", "EDGEMESH_BENCH_SERVE",
              "EDGEMESH_BENCH_FLEET", "EDGEMESH_BENCH_SPEC",
              "EDGEMESH_BENCH_LOADGEN", "EDGEMESH_BENCH_DISAGG",
              "EDGEMESH_BENCH_AUTOSCALE")


def test_tp8_stage_schema_pins(monkeypatch, capsys):
    """The quantized-collective schema contract: a headline run carries the
    serving_tp8_tok_s headline (mode/dtype/wire bytes alongside) and the
    collective_ablation keys — per-arm tok/s at b8/b32, the qpsum-vs-psum
    and overlap-vs-qpsum ratios, and the greedy-agreement quality delta the
    PERFORMANCE.md targets reference."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")

    def fake_tp_serving(preset, built=None, **kw):
        return {"metric": "serving_tp8_tok_s", "value": 1500.0, "unit": "tok/s",
                "tp": 8, "collective_mode": "qpsum_overlap",
                "collective_dtype": "int8", "wave_tok_s": [1500.0],
                "req_s": 4.0, "latency_s_p50": 0.4, "latency_s_p95": 0.8,
                "collective_bytes": 123456, "stats": {"tp": 8}}

    def fake_ablation(preset, built=None, **kw):
        out = {"collective_tp": 8, "collective_batches": [8, 32]}
        for b in (8, 32):
            out[f"collective_psum_b{b}_tok_s"] = 1000.0
            out[f"collective_qpsum_b{b}_tok_s"] = 1200.0
            out[f"collective_qpsum_overlap_b{b}_tok_s"] = 1350.0
            out[f"qpsum_over_psum_b{b}"] = 1.2
            out[f"qpsum_overlap_over_psum_b{b}"] = 1.35
            out[f"overlap_over_qpsum_b{b}"] = 1.125
            out[f"qpsum_greedy_agreement_b{b}"] = 0.9995
            out[f"qpsum_overlap_greedy_agreement_b{b}"] = 0.9995
        return out

    monkeypatch.setattr(benchmarks, "tp_serving_benchmark", fake_tp_serving)
    monkeypatch.setattr(benchmarks, "collective_ablation_benchmark",
                        fake_ablation)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert out["serving_tp8_tok_s"] == 1500.0
    assert out["serving_tp8_collective_mode"] == "qpsum_overlap"
    assert out["serving_tp8_collective_dtype"] == "int8"
    assert out["serving_tp8_collective_bytes"] == 123456
    for b in (8, 32):
        assert out[f"collective_psum_b{b}_tok_s"] == 1000.0
        assert out[f"collective_qpsum_b{b}_tok_s"] == 1200.0
        assert out[f"collective_qpsum_overlap_b{b}_tok_s"] == 1350.0
        assert out[f"qpsum_over_psum_b{b}"] == 1.2
        assert out[f"overlap_over_qpsum_b{b}"] == 1.125
        # The quality-delta column must be populated.
        assert out[f"qpsum_greedy_agreement_b{b}"] == 0.9995
        assert out[f"qpsum_overlap_greedy_agreement_b{b}"] == 0.9995
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert "serving_tp8_tok_s" in lines[-1]


def test_tp8_stage_is_skippable_via_env(monkeypatch, capsys):
    """EDGEMESH_BENCH_TP8=0 must skip BOTH tp8 stages entirely — no engine
    built, no keys, no error recorded (mirrors the loadgen gate)."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")

    def boom(*a, **kw):
        raise AssertionError("tp8 stage ran despite the gate")

    monkeypatch.setattr(benchmarks, "tp_serving_benchmark", boom)
    monkeypatch.setattr(benchmarks, "collective_ablation_benchmark", boom)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any("tp8" in k or k.startswith("collective_") for k in out)


def test_disagg_stage_schema_pins(monkeypatch, capsys):
    """The disaggregation schema contract: a headline run carries the
    homogeneous-vs-tiered TTFT p99 ratio, per-arm goodput/tenant splits,
    the KV wire bytes the tiered arm moved, and the live tier assignment —
    pinned with a faked stage so a partial artifact still has the keys the
    acceptance gate reads (no replicas spun)."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.delenv("EDGEMESH_BENCH_DISAGG", raising=False)

    def fake_disagg(**kw):
        return {"metric": "disagg_ttft_p99_ratio", "value": 1.31,
                "unit": "x", "n_replicas": 3, "duration_s": 4.0,
                "slo_latency_s": 0.8, "estimated_capacity_rps": 6.0,
                "prefill_threshold_chars": 250,
                "homogeneous_chat_p99_s": 0.9, "tiered_chat_p99_s": 0.687,
                "homogeneous_goodput_ratio": 0.91,
                "tiered_goodput_ratio": 0.97,
                "homogeneous_tenants": {
                    "chat": {"latency_s_p99": 0.9, "goodput_ratio": 0.9},
                    "bulk": {"latency_s_p99": 1.4, "goodput_ratio": 0.93}},
                "tiered_tenants": {
                    "chat": {"latency_s_p99": 0.687, "goodput_ratio": 0.99},
                    "bulk": {"latency_s_p99": 1.5, "goodput_ratio": 0.95}},
                "kv_transfer_bytes": 1030288,
                "tiered_outcomes": {"tiered": 8, "cache_hit": 3},
                "tiers": {"prefill": ["replica-0"],
                          "decode": ["replica-1", "replica-2"],
                          "prefill_threshold_chars": 250,
                          "prefix_chars": 64,
                          "kv_cache": {"entries": 5, "capacity": 32,
                                       "hot_keys": 2}}}

    monkeypatch.setattr(benchmarks, "disagg_benchmark", fake_disagg)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    # The acceptance-gate keys: ratio > 1 at equal-or-better goodput,
    # with real bytes on the wire.
    assert out["disagg_ttft_p99_ratio"] == 1.31
    assert out["disagg_kv_transfer_bytes"] == 1030288
    assert out["disagg_tiered_goodput_ratio"] >= out["disagg_homogeneous_goodput_ratio"]
    assert out["disagg_homogeneous_chat_p99_s"] == 0.9
    assert out["disagg_tiered_chat_p99_s"] == 0.687
    assert {"chat", "bulk"} <= set(out["disagg_tiered_tenants"])
    assert out["disagg_tiered_outcomes"]["tiered"] == 8
    # Tier membership rides the artifact (the /fleetz view at run end).
    assert out["disagg_tiers"]["prefill"] == ["replica-0"]
    assert len(out["disagg_tiers"]["decode"]) == 2
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert "disagg_ttft_p99_ratio" in lines[-1]


def test_disagg_stage_is_skippable_via_env(monkeypatch):
    """EDGEMESH_BENCH_DISAGG=0 must skip the disagg stage entirely — no
    replicas spun, no keys emitted, no error recorded."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")

    def boom(**kw):
        raise AssertionError("disagg_benchmark ran despite the gate")

    monkeypatch.setattr(benchmarks, "disagg_benchmark", boom)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith("disagg") for k in out)


def test_headline_stage1_emits_before_bf16(monkeypatch, capsys):
    """The headline int8 stage must produce a parseable driver line BEFORE
    any other stage runs, and later-stage failures must keep earlier keys."""
    calls = []

    def fake_build(preset, precision, quant_mode):
        calls.append(("build", precision))
        if precision == "bf16":
            raise RuntimeError("tunnel wedged")  # bf16 stage dies
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        calls.append(("decode", precision, quant_mode, kw.get("kv_backend", "dense")))
        if precision != "int8" or quant_mode != "w8a16":
            raise RuntimeError("only stage 1 succeeds in this fake")
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    # Stage ordering is under test, not the fleet: the adaptive-router,
    # load-curve, and disagg stages would spin real in-process replicas.
    monkeypatch.setenv("EDGEMESH_BENCH_FLEET", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")

    out = benchmarks.headline_benchmark(preset="tiny", batch=2, decode_steps=8,
                                        sweep_batches=())
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    # First emitted line is the pure stage-1 headline (int8 w8a16, pre-bf16).
    assert lines[0]["value"] == 100.0
    assert lines[0]["int8_mode"] == "w8a16"
    assert "bf16_tok_s" not in lines[0]
    # stage ordering: the int8 build+decode strictly precede the bf16 build.
    assert calls.index(("decode", "int8", "w8a16", "dense")) < calls.index(("build", "bf16"))
    # bf16 death did not kill the run; the error is recorded, headline kept.
    assert out["value"] == 100.0
    assert "tunnel wedged" in out["bf16_error"]
    assert "int8_w8a8_error" in out  # later fenced stages also recorded


def test_cold_start_and_autoscale_stage_schema_pins(monkeypatch, capsys):
    """The capacity-observatory schema contract: a headline run carries the
    warm cold-start-to-first-token headline with the cold/warm split and
    cache-entry count, and the autoscale stage's time-to-scale plus the
    knee tuner's final state — pinned with faked stages so a partial
    artifact still has the keys PERFORMANCE.md's cold-start targets and
    the acceptance gate read (no subprocesses spawned)."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.delenv("EDGEMESH_BENCH_AUTOSCALE", raising=False)

    def fake_cold_start(**kw):
        return {"metric": "cold_start_first_token_s", "value": 4.2,
                "unit": "s", "cold_start_cold_s": 21.0,
                "cold_start_warm_s": 4.2,
                "cold_start_warm_over_cold": 0.2,
                "cold_start_cache_entries": 17}

    def fake_autoscale(**kw):
        return {"metric": "autoscale_time_to_scale_s", "value": 5.5,
                "unit": "s", "autoscale_scaled": True,
                "autoscale_replicas": 2,
                "autoscale_events": [{"action": "up"}],
                "autoscale_offered_rps": 12.0,
                "autoscale_capacity_rps": 4.0,
                "autoscale_goodput_ratio": 0.7,
                "tuner_limit": 9,
                "tuner_knee": {"knee_offered_rps": 4.1,
                               "knee_goodput_rps": 3.9, "collapsed": True},
                "tuner_windows": 12}

    monkeypatch.setattr(benchmarks, "cold_start_benchmark", fake_cold_start)
    monkeypatch.setattr(benchmarks, "autoscale_benchmark", fake_autoscale)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert out["cold_start_first_token_s"] == 4.2
    assert out["cold_start_cold_s"] == 21.0
    assert out["cold_start_warm_s"] == 4.2
    # The warm-start claim: the shared compilation cache beat cache-cold.
    assert out["cold_start_warm_over_cold"] < 1.0
    assert out["cold_start_cache_entries"] == 17
    assert out["autoscale_time_to_scale_s"] == 5.5
    assert out["autoscale_scaled"] is True
    assert out["autoscale_replicas"] == 2
    assert out["tuner_limit"] == 9
    assert out["tuner_knee"]["knee_offered_rps"] == 4.1
    assert out["tuner_windows"] == 12
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert "cold_start_first_token_s" in lines[-1]
    assert "autoscale_time_to_scale_s" in lines[-1]


def test_cold_start_and_autoscale_stages_are_skippable_via_env(monkeypatch):
    """EDGEMESH_BENCH_AUTOSCALE=0 must skip BOTH capacity-observatory
    stages entirely — no subprocess spawned, no replica booted, no keys,
    no error recorded (mirrors the disagg gate)."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")

    def boom(**kw):
        raise AssertionError("capacity stage ran despite the gate")

    monkeypatch.setattr(benchmarks, "cold_start_benchmark", boom)
    monkeypatch.setattr(benchmarks, "autoscale_benchmark", boom)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith(("cold_start", "autoscale", "tuner_"))
                   for k in out)


def test_compute_ledger_keys_ride_bench_json(monkeypatch, capsys):
    """The compute-observatory schema contract: the serving stage carries
    the per-boundary ledger rollup (`serving_compute`), the spec stage the
    round-attribution block (`spec_round_ledger`, split labeled), and the
    router_overhead stage the ledger-on/off arm (`ledger_overhead_ratio`
    <= 1.02 — the gate PERFORMANCE.md pins). Faked stages: the schema must
    survive a partial artifact, and the keys must vanish under the same
    env skip-gates the stages already honor."""
    _fake_stage1(monkeypatch)

    compute_block = {
        "decode_loop": {"launches": 40, "measured": 3, "compiles": 1,
                        "device_s": 0.12, "ewma_launch_s": 0.04,
                        "roofline_fraction": 0.41, "flops": 1e9,
                        "bytes": 2e8, "shape_buckets": {"b8c32": 40}},
    }
    round_block = {"rounds": 12, "accepted": 30, "proposed": 48,
                   "rejected": 18, "accept_rate": 0.625,
                   "accepted_per_round": 2.5, "segments": 2,
                   "measured_segments": 2, "measured_s": 0.5,
                   "round_s": 0.0417, "draft_s": 0.15, "verify_s": 0.35,
                   "draft_frac": 0.3, "split": "analytic-flops"}

    def fake_serving(preset, *a, built=None, kv_backend="paged", ragged=None,
                     **kw):
        value = 900.0 if ragged is None else 700.0
        return {"metric": "serving", "value": value, "wave_tok_s": [value],
                "spread_pct": 1.0, "req_s": 2.0, "generated": 100,
                "latency_s_p50": 0.5, "latency_s_p95": 0.9,
                "stats": {"segments": 9, "max_concurrent": 8,
                          "ragged_boundaries": 9,
                          "ragged_prefill_tokens": 300,
                          "ragged_decode_tokens": 60},
                "obs": {}, "compute": compute_block}

    def fake_ablation(preset, built=None, **kw):
        out = {}
        for shape in ("decode_heavy", "prefill_heavy", "mixed_50_50"):
            out[f"serving_ragged_{shape}_tok_s"] = 900.0
            out[f"serving_segmented_{shape}_tok_s"] = 700.0
            out[f"ragged_over_segmented_{shape}"] = 1.286
        return out

    def fake_spec(preset, built=None, **kw):
        return {"spec_tok_s": 80.0, "plain_tok_s": 60.0,
                "spec_speedup": 1.33, "accept_rate": 0.4,
                "selfcheck_accept_rate": 1.0, "gamma": 4, "draft_layers": 4,
                "draft_mode": "truncate",
                "kv_backend": kw.get("kv_backend", "dense"),
                "spec_round_ledger": round_block,
                "compute": compute_block}

    def fake_overhead(**kw):
        return {"metric": "router_overhead_p50_s", "value": 0.0021,
                "unit": "s", "n_requests": 40,
                "direct_p50_s": 0.010, "direct_p99_s": 0.015,
                "routed_p50_s": 0.0121, "routed_p99_s": 0.018,
                "overhead_p99_s": 0.003,
                "traced_p50_s": 0.013, "traced_p99_s": 0.019,
                "tracing_overhead_p50_s": 0.0009,
                "tracing_overhead_p99_s": 0.001,
                "recorder_p50_s": 0.01215, "recorder_p99_s": 0.0181,
                "recorder_overhead_p50_s": 0.00005,
                "recorder_overhead_p99_s": 0.0001,
                "recorder_ring_records": 41,
                "ledgeroff_p50_s": 0.0120,
                "ledger_overhead_p50_s": 0.0001,
                "ledger_overhead_ratio": 1.0083,
                "compute": compute_block,
                "sample_trace": None, "obs": {}}

    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "slo_target_s": 0.25}

    monkeypatch.setattr(benchmarks, "serving_benchmark", fake_serving)
    monkeypatch.setattr(benchmarks, "ragged_ablation_benchmark",
                        fake_ablation)
    monkeypatch.setattr(benchmarks, "speculative_benchmark", fake_spec)
    monkeypatch.setattr(benchmarks, "router_overhead_benchmark",
                        fake_overhead)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark",
                        fake_adaptive)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ADMIT", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ENSEMBLE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_PRESET", "llama1b")

    out = benchmarks.headline_benchmark(preset="llama1b", batch=2,
                                        decode_steps=8, sweep_batches=())
    # Serving stage: per-boundary rollup rides the artifact.
    assert out["serving_compute"] == compute_block
    assert out["serving_compute"]["decode_loop"]["roofline_fraction"] == 0.41
    # Spec stage: the round-attribution block, split explicitly labeled so
    # the modeled draft/verify partition is never mistaken for a measured
    # quantity.
    assert out["spec_round_ledger"] == round_block
    assert out["spec_round_ledger"]["split"] == "analytic-flops"
    # Router-overhead stage: the ledger arm + the <=1.02 gate, checkable
    # from the artifact alone.
    assert out["ledgeroff_p50_s"] == 0.0120
    assert out["ledger_overhead_ratio"] == 1.0083
    assert out["ledger_overhead_ratio"] <= 1.02
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert "serving_compute" in lines[-1]
    assert "spec_round_ledger" in lines[-1]
    assert "ledger_overhead_ratio" in lines[-1]


def test_mem_ledger_keys_ride_bench_json(monkeypatch, capsys):
    """The memory-observatory schema contract: the serving stage carries
    the pool-ledger rollup (`serving_mem`), router_overhead the mem-ledger
    on/off arm (`mem_ledger_overhead_ratio` <= 1.02 — the PERFORMANCE.md
    gate), load_curve the per-point pool snapshots + forecast-at-knee, and
    disagg the per-replica rollups. Faked stages: the schema must survive
    a partial artifact and vanish under the existing env skip-gates."""
    _fake_stage1(monkeypatch)

    mem_block = {
        "engine": "continuous", "total_pages": 64, "free_pages": 40,
        "resident_pages": 23, "peak_resident_pages": 31,
        "events": {"admit": {"count": 9, "pages": 27}},
        "tenants": {"default": {"pages": 20, "peak_pages": 28}},
        "frag": {"internal_pages": 3, "internal_by_cause": {"admit": 3},
                 "external_pages": 1},
        "leaked_pages": 0, "conservation_breaks": 0, "resets": 0,
    }
    mem_points = [
        {"requested_rps": 2.0, "min_forecast_s": 44.0,
         "peak_resident_pages": 30},
        {"requested_rps": 4.0, "min_forecast_s": 6.5,
         "peak_resident_pages": 55},
    ]

    def fake_serving(preset, *a, built=None, kv_backend="paged", ragged=None,
                     **kw):
        value = 900.0 if ragged is None else 700.0
        return {"metric": "serving", "value": value, "wave_tok_s": [value],
                "spread_pct": 1.0, "req_s": 2.0, "generated": 100,
                "latency_s_p50": 0.5, "latency_s_p95": 0.9,
                "stats": {"segments": 9, "max_concurrent": 8,
                          "ragged_boundaries": 9,
                          "ragged_prefill_tokens": 300,
                          "ragged_decode_tokens": 60},
                "obs": {}, "compute": None, "mem": mem_block}

    def fake_ablation(preset, built=None, **kw):
        out = {}
        for shape in ("decode_heavy", "prefill_heavy", "mixed_50_50"):
            out[f"serving_ragged_{shape}_tok_s"] = 900.0
            out[f"serving_segmented_{shape}_tok_s"] = 700.0
            out[f"ragged_over_segmented_{shape}"] = 1.286
        return out

    def fake_overhead(**kw):
        return {"metric": "router_overhead_p50_s", "value": 0.0021,
                "unit": "s", "n_requests": 40,
                "direct_p50_s": 0.010, "direct_p99_s": 0.015,
                "routed_p50_s": 0.0121, "routed_p99_s": 0.018,
                "overhead_p99_s": 0.003,
                "traced_p50_s": 0.013, "traced_p99_s": 0.019,
                "tracing_overhead_p50_s": 0.0009,
                "tracing_overhead_p99_s": 0.001,
                "recorder_p50_s": 0.01215, "recorder_p99_s": 0.0181,
                "recorder_overhead_p50_s": 0.00005,
                "recorder_overhead_p99_s": 0.0001,
                "recorder_ring_records": 41,
                "ledgeroff_p50_s": 0.0120,
                "ledger_overhead_p50_s": 0.0001,
                "ledger_overhead_ratio": 1.0083,
                "memledgeroff_p50_s": 0.01205,
                "mem_ledger_overhead_p50_s": 0.00005,
                "mem_ledger_overhead_ratio": 1.0041,
                "compute": None, "mem": mem_block,
                "sample_trace": None, "obs": {}}

    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "slo_target_s": 0.25}

    def fake_load_curve(**kw):
        return {"metric": "load_curve_knee_rps", "value": 4.0,
                "unit": "req/s", "knee_goodput_rps": 3.6, "collapsed": False,
                "slo_latency_s": 0.5, "estimated_capacity_rps": 4.2,
                "points": [], "mem_points": mem_points,
                "mem_forecast_at_knee_s": 6.5,
                "mem_peak_resident_pages": 55}

    def fake_disagg(**kw):
        return {"metric": "disagg_ttft_p99_ratio", "value": 1.3, "unit": "x",
                "kv_transfer_bytes": 4096,
                "homogeneous_chat_p99_s": 0.9, "tiered_chat_p99_s": 0.7,
                "homogeneous_goodput_ratio": 0.95,
                "tiered_goodput_ratio": 0.97,
                "homogeneous_tenants": {}, "tiered_tenants": {},
                "tiered_outcomes": {}, "slo_latency_s": 0.5,
                "prefill_threshold_chars": 250, "tiers": None,
                "mem": {"replica-0": mem_block}}

    monkeypatch.setattr(benchmarks, "serving_benchmark", fake_serving)
    monkeypatch.setattr(benchmarks, "ragged_ablation_benchmark",
                        fake_ablation)
    monkeypatch.setattr(benchmarks, "router_overhead_benchmark",
                        fake_overhead)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark",
                        fake_adaptive)
    monkeypatch.setattr(benchmarks, "load_curve_benchmark", fake_load_curve)
    monkeypatch.setattr(benchmarks, "disagg_benchmark", fake_disagg)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ADMIT", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_ENSEMBLE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_PRESET", "llama1b")

    out = benchmarks.headline_benchmark(preset="llama1b", batch=2,
                                        decode_steps=8, sweep_batches=())
    # Serving stage: the pool rollup rides the artifact.
    assert out["serving_mem"] == mem_block
    assert out["serving_mem"]["peak_resident_pages"] == 31
    # Router-overhead stage: the mem-ledger arm + the <=1.02 gate,
    # checkable from the artifact alone.
    assert out["memledgeroff_p50_s"] == 0.01205
    assert out["mem_ledger_overhead_ratio"] == 1.0041
    assert out["mem_ledger_overhead_ratio"] <= 1.02
    # Load-curve stage: per-point snapshots + the knee forecast.
    assert out["load_curve_mem_points"] == mem_points
    assert out["load_curve_mem_forecast_at_knee_s"] == 6.5
    assert out["load_curve_mem_peak_resident_pages"] == 55
    # Disagg stage: per-replica rollups.
    assert out["disagg_mem"]["replica-0"] == mem_block
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert "serving_mem" in lines[-1]
    assert "mem_ledger_overhead_ratio" in lines[-1]
    assert "load_curve_mem_forecast_at_knee_s" in lines[-1]
    assert "disagg_mem" in lines[-1]


def test_mem_ledger_keys_honor_stage_skip_gates(monkeypatch):
    """With the serving/fleet/loadgen/disagg stages env-gated off, none of
    the memory-observatory keys appear — the same no-keys-no-error
    contract every other skippable stage pins."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(
        k in ("serving_mem", "memledgeroff_p50_s",
              "mem_ledger_overhead_p50_s", "mem_ledger_overhead_ratio",
              "disagg_mem")
        or k.startswith("load_curve_mem")
        for k in out)


def test_compute_ledger_keys_honor_stage_skip_gates(monkeypatch):
    """With the serving/spec/fleet stages env-gated off, none of the
    compute-observatory keys appear — the same no-keys-no-error contract
    every other skippable stage pins."""
    _fake_stage1(monkeypatch)
    for gate in _TP8_GATES:
        monkeypatch.setenv(gate, "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(
        k in ("serving_compute", "spec_round_ledger", "ledgeroff_p50_s",
              "ledger_overhead_p50_s", "ledger_overhead_ratio")
        for k in out)


def _fake_fleet_side_stages(monkeypatch):
    """Fakes for the OTHER two stages riding EDGEMESH_BENCH_FLEET, so a
    test can leave the fleet gate on without spinning real replicas."""

    def fake_overhead(**kw):
        return {"metric": "router_overhead_p50_s", "value": 0.0021,
                "unit": "s", "n_requests": 40,
                "direct_p50_s": 0.010, "direct_p99_s": 0.015,
                "routed_p50_s": 0.0121, "routed_p99_s": 0.018,
                "overhead_p99_s": 0.003,
                "traced_p50_s": 0.013, "traced_p99_s": 0.019,
                "tracing_overhead_p50_s": 0.0009,
                "tracing_overhead_p99_s": 0.001,
                "recorder_p50_s": 0.01215, "recorder_p99_s": 0.0181,
                "recorder_overhead_p50_s": 0.00005,
                "recorder_overhead_p99_s": 0.0001,
                "recorder_ring_records": 41,
                "sample_trace": None, "obs": {}}

    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "slo_target_s": 0.25}

    monkeypatch.setattr(benchmarks, "router_overhead_benchmark",
                        fake_overhead)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark",
                        fake_adaptive)


def test_ensemble_stage_schema_pins(monkeypatch, capsys):
    """The ensemble-serving schema contract: a headline run carries the
    ensemble-vs-single p99 latency ratio, the per-arm percentiles, the
    degradation-outcome counts, and the eval quality delta — pinned with
    a faked stage so a partial artifact still has the keys docs/FLEET.md
    'Ensemble serving' references (no replicas spun)."""
    _fake_stage1(monkeypatch)
    _fake_fleet_side_stages(monkeypatch)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SERVE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.delenv("EDGEMESH_BENCH_ENSEMBLE", raising=False)

    def fake_ensemble(**kw):
        return {"metric": "ensemble_latency_p99_ratio", "value": 1.8,
                "unit": "ratio", "n_requests": 12,
                "ensemble_p50_s": 0.041, "ensemble_p99_s": 0.09,
                "single_p50_s": 0.02, "single_p99_s": 0.05,
                "outcomes": {"degraded_qa": 1, "ok": 10,
                             "refiner_fallback": 1},
                "qa_pools": ["qa-a", "qa-b"], "refiner_pool": "refiner",
                "ensemble_quality": 0.31, "single_quality": 0.27,
                "quality_delta": 0.04, "eval_samples": 8, "obs": {}}

    monkeypatch.setattr(benchmarks, "fleet_ensemble_benchmark",
                        fake_ensemble)
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert out["ensemble_latency_p99_ratio"] == 1.8
    assert out["ensemble_p99_s"] == 0.09
    assert out["ensemble_single_p99_s"] == 0.05
    # Every degradation outcome the coordinator counted rides the artifact.
    assert out["ensemble_outcomes"]["ok"] == 10
    assert out["ensemble_outcomes"]["degraded_qa"] == 1
    assert out["ensemble_outcomes"]["refiner_fallback"] == 1
    assert out["ensemble_quality_delta"] == 0.04
    assert out["ensemble_eval_samples"] == 8
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert "ensemble_latency_p99_ratio" in lines[-1]


def test_ensemble_stage_is_skippable_via_env(monkeypatch):
    """EDGEMESH_BENCH_ENSEMBLE=0 must skip the ensemble stage even with
    the fleet gate on — no replicas spun, no keys emitted, no error
    recorded — and EDGEMESH_BENCH_FLEET=0 skips it too (the stage spins
    an in-process fleet, so it rides both gates)."""
    _fake_stage1(monkeypatch)
    _fake_fleet_side_stages(monkeypatch)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SERVE", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")

    def boom(**kw):
        raise AssertionError("fleet_ensemble_benchmark ran despite the gate")

    monkeypatch.setattr(benchmarks, "fleet_ensemble_benchmark", boom)
    monkeypatch.setenv("EDGEMESH_BENCH_ENSEMBLE", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith("ensemble") for k in out)

    monkeypatch.delenv("EDGEMESH_BENCH_ENSEMBLE", raising=False)
    monkeypatch.setenv("EDGEMESH_BENCH_FLEET", "0")
    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    assert not any(k.startswith("ensemble") for k in out)


def test_bench_quality_block_schema_and_skip_gate(monkeypatch):
    """The quality observatory's bench block, pinned at the source: a
    fixed six-key schema projected from a QualityTracker rollup (extra
    rollup keys dropped, missing ones null), and EDGEMESH_BENCH_QUALITY=0
    drops the whole block (None) — the same no-keys-no-error convention
    as every other skippable bench dimension."""
    monkeypatch.delenv(benchmarks.QUALITY_GATE_ENV, raising=False)
    block = benchmarks.bench_quality_block(
        {"requests": 3, "low_confidence_requests": 1,
         "confidence_ewma": 0.51, "confidence_min_seen": 0.12,
         "entropy_ewma": 2.1, "tenants": {"a": {}},  # dropped: not schema
         "future_key": "ignored"},
        agreement=0.9)
    assert block == {"requests": 3, "low_confidence_requests": 1,
                     "confidence_ewma": 0.51, "confidence_min_seen": 0.12,
                     "entropy_ewma": 2.1, "agreement_ewma": 0.9}
    # An empty rollup (spec engine, tracker disabled) still yields the
    # schema — zero requests, null signals.
    empty = benchmarks.bench_quality_block({})
    assert empty == {"requests": 0, "low_confidence_requests": 0,
                     "confidence_ewma": None, "confidence_min_seen": None,
                     "entropy_ewma": None, "agreement_ewma": None}
    assert benchmarks.bench_quality_block(None) == empty
    monkeypatch.setenv(benchmarks.QUALITY_GATE_ENV, "0")
    assert benchmarks.bench_quality_block({"requests": 3}) is None
    assert benchmarks.bench_quality_block(None, agreement=0.9) is None


def test_quality_block_keys_ride_bench_json(monkeypatch, capsys):
    """The quality observatory's bench schema contract: the serving stage
    carries its tracker rollup (`serving_quality`), router_overhead the
    tracker on/off arm (`quality_overhead_ratio` <= 1.02 — the
    PERFORMANCE.md gate), and the ensemble stage its agreement block —
    pinned with faked stages so a partial artifact still has the keys
    docs/OBSERVABILITY.md references. A stage faked from an older schema
    (no quality key) folds to null, never an error."""
    _fake_stage1(monkeypatch)

    quality_block = {"requests": 40, "low_confidence_requests": 2,
                     "confidence_ewma": 0.81, "confidence_min_seen": 0.12,
                     "entropy_ewma": 1.4, "agreement_ewma": None}

    def fake_serving(preset, *a, built=None, kv_backend="paged", ragged=None,
                     **kw):
        value = 900.0 if ragged is None else 700.0
        return {"metric": "serving", "value": value, "wave_tok_s": [value],
                "spread_pct": 1.0, "req_s": 2.0, "generated": 100,
                "latency_s_p50": 0.5, "latency_s_p95": 0.9,
                "stats": {"segments": 9, "max_concurrent": 8,
                          "ragged_boundaries": 9,
                          "ragged_prefill_tokens": 300,
                          "ragged_decode_tokens": 60},
                "obs": {}, "quality": dict(quality_block)}

    def fake_ablation(preset, built=None, **kw):
        return {}

    def fake_overhead(**kw):
        return {"metric": "router_overhead_p50_s", "value": 0.0021,
                "unit": "s", "n_requests": 40,
                "direct_p50_s": 0.010, "direct_p99_s": 0.015,
                "routed_p50_s": 0.0121, "routed_p99_s": 0.018,
                "overhead_p99_s": 0.003,
                "traced_p50_s": 0.013, "traced_p99_s": 0.019,
                "tracing_overhead_p50_s": 0.0009,
                "tracing_overhead_p99_s": 0.001,
                "recorder_p50_s": 0.01215, "recorder_p99_s": 0.0181,
                "recorder_overhead_p50_s": 0.00005,
                "recorder_overhead_p99_s": 0.0001,
                "recorder_ring_records": 41,
                "qualityoff_p50_s": 0.01205,
                "quality_overhead_p50_s": 0.00005,
                "quality_overhead_ratio": 1.0041,
                "sample_trace": None, "obs": {}}

    def fake_adaptive(**kw):
        return {"metric": "adaptive_over_least_outstanding_p99",
                "value": 1.4, "unit": "x", "slo_target_s": 0.25}

    def fake_ensemble(**kw):
        # An OLDER-schema ensemble fake: no quality key → folds to null.
        return {"metric": "ensemble_latency_p99_ratio", "value": 1.8,
                "unit": "ratio", "n_requests": 12,
                "ensemble_p50_s": 0.041, "ensemble_p99_s": 0.09,
                "single_p50_s": 0.02, "single_p99_s": 0.05,
                "outcomes": {"ok": 12}, "qa_pools": ["qa-a", "qa-b"],
                "refiner_pool": "refiner", "ensemble_quality": 0.31,
                "single_quality": 0.27, "quality_delta": 0.04,
                "eval_samples": 8, "obs": {}}

    monkeypatch.setattr(benchmarks, "serving_benchmark", fake_serving)
    monkeypatch.setattr(benchmarks, "ragged_ablation_benchmark",
                        fake_ablation)
    monkeypatch.setattr(benchmarks, "router_overhead_benchmark",
                        fake_overhead)
    monkeypatch.setattr(benchmarks, "adaptive_router_benchmark",
                        fake_adaptive)
    monkeypatch.setattr(benchmarks, "fleet_ensemble_benchmark",
                        fake_ensemble)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_SPEC", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_TP8", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_LOADGEN", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_DISAGG", "0")
    monkeypatch.setenv("EDGEMESH_BENCH_AUTOSCALE", "0")
    monkeypatch.delenv("EDGEMESH_BENCH_ENSEMBLE", raising=False)

    out = benchmarks.headline_benchmark(preset="tiny", batch=2,
                                        decode_steps=8, sweep_batches=())
    # Serving stage: the tracker rollup rides the artifact.
    assert out["serving_quality"] == quality_block
    # Router-overhead stage: the tracker arm + the <=1.02 gate,
    # checkable from the artifact alone.
    assert out["qualityoff_p50_s"] == 0.01205
    assert out["quality_overhead_ratio"] == 1.0041
    assert out["quality_overhead_ratio"] <= 1.02
    # Ensemble stage from the older fake: null block, not a KeyError.
    assert out["ensemble_quality_signals"] is None
    lines = [json.loads(l)
             for l in capsys.readouterr().out.strip().splitlines()]
    assert "serving_quality" in lines[-1]
    assert "quality_overhead_ratio" in lines[-1]
