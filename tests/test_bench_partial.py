"""Stall-resilience contract of the driver bench (edgemesh/benchmarks.py).

Round 2's bench printed its JSON only at the finish line; a TPU-tunnel wedge
mid-run left the driver with rc=3 and nothing parseable (VERDICT r2 weak #1).
The contract now: the headline int8 stage runs first, every completed stage
re-emits the refreshed result line, and the stall watchdog re-prints the
partial before exiting — so the LAST JSON line on stdout is always the most
complete measurement.
"""

import json

from edgemesh import benchmarks


def test_emit_partial_prints_and_records(capsys):
    r = {"metric": "decode_tok_s_x", "value": 1.0, "unit": "tok/s/chip",
         "vs_baseline": 0.1}
    benchmarks.emit_partial(r)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(line) == r
    assert benchmarks._PARTIAL == r
    # A refresh replaces, never merges stale keys.
    r2 = {"metric": "decode_tok_s_x", "value": 2.0, "unit": "tok/s/chip",
          "vs_baseline": 0.2}
    benchmarks.emit_partial(r2)
    assert benchmarks._PARTIAL == r2
    assert "1.0" not in capsys.readouterr().out


def test_emit_partial_without_metric_is_silent(capsys):
    benchmarks.emit_partial({"incomplete": True})
    assert capsys.readouterr().out == ""


def test_headline_stage1_emits_before_bf16(monkeypatch, capsys):
    """The headline int8 stage must produce a parseable driver line BEFORE
    any other stage runs, and later-stage failures must keep earlier keys."""
    calls = []

    def fake_build(preset, precision, quant_mode):
        calls.append(("build", precision))
        if precision == "bf16":
            raise RuntimeError("tunnel wedged")  # bf16 stage dies
        return ("cfg", "params")

    def fake_decode(preset, precision, quant_mode="w8a16", batch=8, **kw):
        calls.append(("decode", precision, quant_mode, kw.get("kv_backend", "dense")))
        if precision != "int8" or quant_mode != "w8a16":
            raise RuntimeError("only stage 1 succeeds in this fake")
        return {"metric": "m", "value": 100.0, "unit": "tok/s/chip",
                "vs_baseline": 3.9, "ttft_s": 0.01, "hbm_eff_gbs": 1.0,
                "hbm_util": 0.1, "weight_gb": 1.0, "batch": batch,
                "decode_steps": 8}

    monkeypatch.setattr(benchmarks, "_build", fake_build)
    monkeypatch.setattr(benchmarks, "decode_benchmark", fake_decode)
    monkeypatch.setenv("EDGEMESH_BENCH_8B", "0")

    out = benchmarks.headline_benchmark(preset="tiny", batch=2, decode_steps=8,
                                        sweep_batches=())
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    # First emitted line is the pure stage-1 headline (int8 w8a16, pre-bf16).
    assert lines[0]["value"] == 100.0
    assert lines[0]["int8_mode"] == "w8a16"
    assert "bf16_tok_s" not in lines[0]
    # stage ordering: the int8 build+decode strictly precede the bf16 build.
    assert calls.index(("decode", "int8", "w8a16", "dense")) < calls.index(("build", "bf16"))
    # bf16 death did not kill the run; the error is recorded, headline kept.
    assert out["value"] == 100.0
    assert "tunnel wedged" in out["bf16_error"]
    assert "int8_w8a8_error" in out  # later fenced stages also recorded
