"""Numerical parity vs HuggingFace reference implementations.

The reference trusts HF transformers for the model math
(``Code/C-DAC Server/combiner_fp.py:274-284``); edgemesh reimplements it
natively in JAX. These tests pin the ingest + forward against HF's own
output for each family: tiny random-init HF models are saved to disk,
ingested via edgemesh.models.hf_ingest, and full-sequence logits must agree
to fp32 tolerance. This is the test that catches RoPE-convention, qkv-fusion
and parallel-block mistakes (SURVEY.md §7 hard part (c)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from edgemesh.models.hf_ingest import config_from_checkpoint, load_params  # noqa: E402
from edgemesh.models.transformer import forward_prefill, init_kv_cache  # noqa: E402


def _compare(ckpt_dir, hf_model, seq=12, atol=2e-3, **cfg_overrides):
    cfg = config_from_checkpoint(
        ckpt_dir, dtype="float32", max_seq_len=64, **cfg_overrides
    )
    cfg2, params = load_params(ckpt_dir, cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, seq))

    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.float().numpy()

    max_seq = max(32, seq + 8)
    cache = init_kv_cache(cfg, 1, max_seq)
    # forward_prefill returns last-token logits; compare full sequence by
    # calling the underlying forward through prefill at each prefix length.
    from edgemesh.models.transformer import _forward

    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (1, seq))
    kv_valid = jnp.arange(max_seq)[None, :] < seq
    ours, _, _ = _forward(
        cfg, params, jnp.asarray(tokens), positions, cache, kv_valid, is_decode=False
    )
    np.testing.assert_allclose(
        np.asarray(ours[0]), hf_logits[0], atol=atol, rtol=1e-3
    )


def test_llama_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_llama_tied_embeddings_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_pythia_neox_parity(tmp_path):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, layer_norm_eps=1e-5,
    )
    torch.manual_seed(2)
    model = GPTNeoXForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_phi2_parity(tmp_path):
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        layer_norm_eps=1e-5,
    )
    torch.manual_seed(3)
    model = PhiForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_llama3_rope_scaling_parity(tmp_path):
    """Llama-3.2-style rope_scaling (rope_type=llama3): positions past the
    'original' context exercise all three wavelength bands. Catches
    frequency-rescale mistakes that plain short-context parity cannot."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3", "factor": 4.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 16,
        },
    )
    torch.manual_seed(4)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path, dtype="float32")
    assert cfg.rope_scaling_type == "llama3" and cfg.rope_scaling_factor == 4.0
    _compare(tmp_path, model, seq=40)  # spans wavelengths beyond orig_max=16


def test_sharded_safetensors_ingest(tmp_path):
    """Real 1B+ checkpoints ship sharded safetensors with an index json;
    ingest must reassemble them identically to a single-file save."""
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    torch.manual_seed(5)
    model = LlamaForCausalLM(hf_cfg).eval()
    single, sharded = tmp_path / "single", tmp_path / "sharded"
    model.save_pretrained(single)
    model.save_pretrained(sharded, max_shard_size="50KB")
    assert (sharded / "model.safetensors.index.json").exists(), "test setup: not sharded"
    _, p1 = load_params(single)
    _, p2 = load_params(sharded)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        p1, p2,
    )
    _compare(sharded, model)


def test_phi2_head_dim_80_parity(tmp_path):
    """The real Phi-2's head_dim is 80 (2560/32) — not a lane multiple; the
    XLA attention path must stay exact there (the TPU kernel paths pad or
    fall back; this pins the numerics)."""
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(
        vocab_size=128, hidden_size=160, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=2,  # head_dim = 80
        max_position_embeddings=64, partial_rotary_factor=0.4,
        layer_norm_eps=1e-5,
    )
    torch.manual_seed(6)
    model = PhiForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path, dtype="float32")
    assert cfg.head_size == 80 and cfg.rotary_dim == 32
    _compare(tmp_path, model)


def test_mistral_sliding_window_parity(tmp_path):
    """Mistral = llama dialect + sliding-window attention. window < seq makes
    the window mask load-bearing: full-causal attention would diverge."""
    from transformers import MistralConfig, MistralForCausalLM

    hf_cfg = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, sliding_window=8,
        attn_implementation="eager",  # sdpa ignores sliding_window in some versions
    )
    torch.manual_seed(7)
    model = MistralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path, dtype="float32")
    assert cfg.sliding_window == 8
    _compare(tmp_path, model, seq=24)  # 24 > window: windowed rows differ

    # And the window must MATTER: the same checkpoint forced to full
    # attention diverges from HF on the windowed rows.
    cfg_full = config_from_checkpoint(
        tmp_path, dtype="float32", max_seq_len=64, sliding_window=0
    )
    from edgemesh.models.hf_ingest import load_params as _lp

    _, params = _lp(tmp_path, cfg_full)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg_full.vocab_size, size=(1, 24))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.float().numpy()
    from edgemesh.models.transformer import _forward

    cache = init_kv_cache(cfg_full, 1, 32)
    positions = jnp.broadcast_to(jnp.arange(24)[None, :], (1, 24))
    kv_valid = jnp.arange(32)[None, :] < 24
    ours, _, _ = _forward(
        cfg_full, params, jnp.asarray(tokens), positions, cache, kv_valid,
        is_decode=False,
    )
    assert not np.allclose(np.asarray(ours[0, -1]), hf_logits[0, -1], atol=2e-3)


def test_qwen3_qk_norm_parity(tmp_path):
    """Qwen3 = llama dialect + per-head QK-RMSNorm before RoPE (replacing
    qwen2's qkv biases) + explicit head_dim. Parity pins the norm placement
    — applying it after RoPE, or over the full projection instead of per
    head, diverges immediately."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    hf_cfg = Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24,  # != hidden/heads: pins explicit-head_dim handling
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(13)
    model = Qwen3ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path, dtype="float32")
    assert cfg.qk_norm and cfg.head_size == 24
    _compare(tmp_path, model)


def test_mixtral_moe_parity(tmp_path):
    """Mixtral = mistral dialect with a routed-MoE FFN. Parity pins BOTH the
    weight map (router transpose, per-expert w1/w3/w2 stacking) and the
    routing math (softmax over all experts → top-k → renormalize, exactly
    HF's MixtralSparseMoeBlock). Runs with the ingest-computed DEFAULT
    capacity factor (E/k → capacity = num_tokens, dropless): HF drops no
    tokens, so a regression that reintroduces GShard capacity drops fails
    parity here."""
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, sliding_window=None,
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    model = MixtralForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path, dtype="float32")
    assert cfg.num_experts == 4 and cfg.experts_per_token == 2
    assert cfg.expert_capacity_factor == 2.0  # E/k: C = ceil(T/E*k*E/k) = T
    _compare(tmp_path, model)


def test_qwen2_parity(tmp_path):
    """Qwen2: llama dialect + attention qkv biases (+ tied embeddings on the
    small variants)."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=True,
    )
    torch.manual_seed(5)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.qkv_bias and cfg.tie_embeddings
    _compare(tmp_path, model)


def test_gemma_parity(tmp_path):
    """Gemma: unit-offset RMSNorm, GeGLU (gated gelu_tanh), sqrt(h)-scaled
    embeddings, wide fixed head_dim, always-tied head — every dial differs
    from llama, so this pins all four at once."""
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=64, rms_norm_eps=1e-5,
    )
    torch.manual_seed(6)
    model = GemmaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.norm_unit_offset and cfg.gated and cfg.embed_scale
    assert cfg.head_size == 32 and cfg.tie_embeddings
    _compare(tmp_path, model)


def test_phi3_parity(tmp_path):
    """Phi-3: llama dialect with FUSED checkpoint weights (qkv_proj,
    gate_up_proj — split at ingest) and an always-on sliding window."""
    from transformers import Phi3Config, Phi3ForCausalLM

    hf_cfg = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, sliding_window=8,
        pad_token_id=0,  # default 32000 asserts against tiny vocabs
    )
    torch.manual_seed(7)
    model = Phi3ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.sliding_window == 8 and not cfg.tie_embeddings
    # seq=12 > window=8 so the window actually masks history.
    _compare(tmp_path, model, seq=12)


def test_gemma2_parity(tmp_path):
    """Gemma-2: gemma's dials plus post-sublayer norms, attention-score and
    final-logit soft caps, fixed query scale, and ALTERNATING sliding
    windows (even layers windowed, odd layers full). window < seq and the
    soft caps at their real defaults, so every new dial shapes the logits."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    hf_cfg = Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-5,
        sliding_window=8, query_pre_attn_scalar=16,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
    )
    torch.manual_seed(8)
    model = Gemma2ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.post_block_norms and cfg.alt_sliding_window
    assert cfg.attn_soft_cap == 50.0 and cfg.logit_soft_cap == 30.0
    assert cfg.sliding_window == 8 and cfg.query_pre_attn_scalar == 16
    _compare(tmp_path, model, seq=12)  # seq > window: the window binds


def test_gpt2_parity(tmp_path):
    """GPT-2: learned absolute positions (wpe added to wte — no rotary),
    pre-LN with biases, fused c_attn split on COLUMNS (Conv1D [in, out]
    storage, no transpose at ingest), gelu_new MLP, tied head."""
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        n_inner=None, activation_function="gelu_new",
    )
    torch.manual_seed(3)
    model = GPT2LMHeadModel(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.learned_positions and cfg.rotary_dim == 0
    assert cfg.tie_embeddings and cfg.intermediate_size == 256
    _compare(tmp_path, model, seq=12)


def test_falcon_multiquery_parity(tmp_path):
    """Falcon 7B dialect: MULTI-QUERY attention (one kv head), parallel block
    with a single shared input norm, gelu MLP, full rotary, no biases."""
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=True, parallel_attn=True,
        new_decoder_architecture=False, bias=False, alibi=False,
        tie_word_embeddings=True,
    )
    torch.manual_seed(5)
    model = FalconForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.num_kv_heads == 1 and cfg.parallel_block and cfg.shared_input_norm
    _compare(tmp_path, model, seq=12)


def test_falcon_new_decoder_gqa_parity(tmp_path):
    """Falcon 40B/Falcon2 dialect: new-decoder GQA (grouped fused qkv rows),
    dual ln_attn/ln_mlp input norms."""
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2, new_decoder_architecture=True,
        bias=False, alibi=False, tie_word_embeddings=True,
    )
    torch.manual_seed(6)
    model = FalconForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    cfg = config_from_checkpoint(tmp_path)
    assert cfg.num_kv_heads == 2 and not cfg.shared_input_norm
    _compare(tmp_path, model, seq=12)


def test_bert_encoder_parity(tmp_path):
    """Encoder family (MiniLM-class) hidden-state parity vs HF BertModel,
    including right-padded rows: the bidirectional mask must exclude padding
    as both query context and key (reference analog: the MiniLM/roberta
    scorers, combiner_fp.py:302-316,421)."""
    from transformers import BertConfig, BertModel

    from edgemesh.models import encoder

    hf_cfg = BertConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=96,
        max_position_embeddings=32, type_vocab_size=2, layer_norm_eps=1e-12,
    )
    torch.manual_seed(7)
    model = BertModel(hf_cfg, add_pooling_layer=False).eval()
    model.save_pretrained(tmp_path)

    cfg, params = encoder.load_encoder(tmp_path)
    assert cfg.num_layers == 2 and cfg.hidden_size == 48

    rng = np.random.default_rng(0)
    lengths = np.array([12, 7], np.int32)  # second row right-padded
    tokens = rng.integers(0, 96, size=(2, 12))
    tokens[1, 7:] = 0  # pad id — must not influence row 1's states
    attn = (np.arange(12)[None, :] < lengths[:, None]).astype(np.int64)

    with torch.no_grad():
        hf_hidden = model(
            torch.tensor(tokens), attention_mask=torch.tensor(attn)
        ).last_hidden_state.numpy()

    ours = np.asarray(
        encoder.forward_hidden(cfg, params, jnp.asarray(tokens), jnp.asarray(lengths))
    )
    for row, n in enumerate(lengths):
        np.testing.assert_allclose(
            ours[row, :n], hf_hidden[row, :n], atol=2e-3, rtol=1e-3
        )


def test_bert_prefixed_checkpoint_and_decoder_refusal(tmp_path):
    """Task-head checkpoints carry a ``bert.`` key prefix — ingest strips
    it; the decoder runtime refuses bert checkpoints with a pointer at the
    encoder (it has no LM head/decode semantics for them)."""
    from transformers import BertConfig, BertForMaskedLM

    from edgemesh.models import encoder

    hf_cfg = BertConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=16,
    )
    torch.manual_seed(8)
    BertForMaskedLM(hf_cfg).eval().save_pretrained(tmp_path)

    cfg, params = encoder.load_encoder(tmp_path)
    out = encoder.forward_hidden(
        cfg, params, jnp.zeros((1, 4), jnp.int32), jnp.array([4], jnp.int32)
    )
    assert np.all(np.isfinite(np.asarray(out)))

    with pytest.raises(ValueError, match="encoder.load_encoder"):
        load_params(tmp_path)
    with pytest.raises(ValueError, match="ENCODER"):
        config_from_checkpoint(tmp_path)
