"""Numerical parity vs HuggingFace reference implementations.

The reference trusts HF transformers for the model math
(``Code/C-DAC Server/combiner_fp.py:274-284``); edgemesh reimplements it
natively in JAX. These tests pin the ingest + forward against HF's own
output for each family: tiny random-init HF models are saved to disk,
ingested via edgemesh.models.hf_ingest, and full-sequence logits must agree
to fp32 tolerance. This is the test that catches RoPE-convention, qkv-fusion
and parallel-block mistakes (SURVEY.md §7 hard part (c)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from edgemesh.models.hf_ingest import config_from_checkpoint, load_params  # noqa: E402
from edgemesh.models.transformer import forward_prefill, init_kv_cache  # noqa: E402


def _compare(ckpt_dir, hf_model, seq=12, atol=2e-3):
    cfg = config_from_checkpoint(ckpt_dir, dtype="float32", max_seq_len=64)
    cfg2, params = load_params(ckpt_dir, cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, seq))

    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.float().numpy()

    cache = init_kv_cache(cfg, 1, 32)
    # forward_prefill returns last-token logits; compare full sequence by
    # calling the underlying forward through prefill at each prefix length.
    from edgemesh.models.transformer import _forward

    positions = jnp.broadcast_to(jnp.arange(seq)[None, :], (1, seq))
    kv_valid = jnp.arange(32)[None, :] < seq
    ours, _, _ = _forward(
        cfg, params, jnp.asarray(tokens), positions, cache, kv_valid, is_decode=False
    )
    np.testing.assert_allclose(
        np.asarray(ours[0]), hf_logits[0], atol=atol, rtol=1e-3
    )


def test_llama_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_llama_tied_embeddings_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_pythia_neox_parity(tmp_path):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM

    hf_cfg = GPTNeoXConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, layer_norm_eps=1e-5,
    )
    torch.manual_seed(2)
    model = GPTNeoXForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)


def test_phi2_parity(tmp_path):
    from transformers import PhiConfig, PhiForCausalLM

    hf_cfg = PhiConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        layer_norm_eps=1e-5,
    )
    torch.manual_seed(3)
    model = PhiForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)
    _compare(tmp_path, model)
