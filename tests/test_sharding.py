"""Mesh + sharding: TP/DP forward parity on 8 emulated devices.

The invariant that matters (SURVEY.md §4's planned strategy): the SAME model
produces the SAME logits whether it runs replicated on one device or
TP/DP-sharded across the mesh — XLA inserts the psums/all-gathers, the math
must not change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from edgemesh.config import SamplingParams
from edgemesh.models import init_kv_cache, init_params
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill
from edgemesh.ops.int8 import quantize_params
from edgemesh.parallel.mesh import build_mesh, submeshes
from edgemesh.parallel.sharding import (
    batch_sharding,
    cache_pspecs,
    param_pspecs,
    quantized_pspecs,
    shard_cache,
    shard_params,
)
from edgemesh.runtime import generate



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def test_build_mesh_axes(devices):
    mesh = build_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "ep": 1, "tp": 4}
    with pytest.raises(ValueError):
        build_mesh(dp=4, tp=4)  # 16 > 8 devices


def test_submeshes_disjoint(devices):
    groups = submeshes(2)
    assert len(groups) == 2
    d0 = {d.id for d in groups[0].devices.flat}
    d1 = {d.id for d in groups[1].devices.flat}
    assert d0.isdisjoint(d1)
    assert len(d0) == len(d1) == 4


def test_param_pspecs_match_structure():
    cfg = tiny_config("llama", num_heads=4, num_kv_heads=4)
    mesh = build_mesh(dp=2, tp=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    specs = param_pspecs(cfg, mesh)
    # identical tree structure
    jax.tree.map(lambda a, b: None, params, specs,
                 is_leaf=lambda x: isinstance(x, P))


def test_tp_sharded_forward_matches_replicated():
    cfg = tiny_config("llama", num_heads=4, num_kv_heads=4, hidden_size=64,
                      intermediate_size=128, vocab_size=256)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    lengths = jnp.array([8, 5])

    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 2, 16))

    mesh = build_mesh(dp=2, tp=4)
    sp = shard_params(params, cfg, mesh)
    cache = shard_cache(init_kv_cache(cfg, 2, 16), cfg, mesh)
    toks_sh = jax.device_put(tokens, batch_sharding(mesh))
    len_sh = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    got, new_cache = forward_prefill(cfg, sp, toks_sh, len_sh, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_tp_generate_matches_replicated():
    cfg = tiny_config("llama", num_heads=4, num_kv_heads=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 4])
    samp = SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.1)

    r_ref = generate(cfg, params, tokens, lengths, samp)

    mesh = build_mesh(dp=1, tp=8)
    sp = shard_params(params, cfg, mesh)
    r_sh = generate(cfg, sp, tokens, lengths, samp)
    np.testing.assert_array_equal(np.asarray(r_ref.tokens), np.asarray(r_sh.tokens))


def test_int8_sharded_generate():
    cfg = tiny_config("llama", num_heads=4, num_kv_heads=4)
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    mesh = build_mesh(dp=1, tp=8)
    sp = shard_params(params, cfg, mesh)  # exercises quantized_pspecs
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 5), 0, cfg.vocab_size)
    r = generate(cfg, sp, tokens, jnp.array([5]),
                 SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0))
    assert int(jnp.sum(r.num_generated)) == 4


def test_submeshes_reject_overlapping_tp(devices):
    with pytest.raises(ValueError, match="disjoint"):
        submeshes(3, tp=4)  # 8 devices / 3 groups = 2 each; tp=4 would overlap


def test_smoothquant_params_shard(devices):
    cfg = tiny_config("llama", num_heads=4, num_kv_heads=4, num_layers=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    smooth = {"layers": {"q": jnp.ones((1, cfg.hidden_size))}}
    qparams = quantize_params(params, smooth_scales=smooth)
    mesh = build_mesh(tp=8)
    sp = shard_params(qparams, cfg, mesh)  # must not crash on the smooth leaf
    assert "smooth" in sp["layers"]["q"]


def test_uneven_heads_fall_back_to_replicated():
    # tp=8 does not divide 3 kv heads → spec must not shard those leaves
    cfg = tiny_config("llama", num_heads=6, num_kv_heads=3, hidden_size=48)
    mesh = build_mesh(tp=8)
    specs = param_pspecs(cfg, mesh)
    assert specs["layers"]["q"]["kernel"] == P(None, None, None)
    cache_spec = cache_pspecs(cfg, mesh)
    assert cache_spec.k == P(None, "dp", None, None, None)
