"""REST gateway + CLI end-to-end (loopback HTTP, real sockets)."""

import json
import urllib.request
import urllib.error

import pytest

from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.agents import build_ensemble
from edgemesh.serve import serve_rest



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _tiny_cfg():
    def spec(role):
        return AgentSpec(
            role=role,
            model=ModelSpec(family="llama", num_layers=1, hidden_size=32,
                            num_heads=4, num_kv_heads=4, intermediate_size=64),
            sampling=SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0),
        )

    return EdgeMeshConfig(agents=[spec("qa"), spec("refiner")])


@pytest.fixture(scope="module")
def server():
    ens = build_ensemble(_tiny_cfg(), use_submeshes=False)
    srv = serve_rest(ens, host="127.0.0.1", port=0, block=False)
    yield srv
    srv.shutdown()


def _url(server, path):
    return f"http://127.0.0.1:{server.server_address[1]}{path}"


def test_health(server):
    with urllib.request.urlopen(_url(server, "/")) as r:
        body = json.load(r)
    assert body["status"] == "ok"
    assert body["agents"] == ["qa", "refiner"]
    assert len(body["devices"]) == 8


def test_generate(server):
    req = urllib.request.Request(
        _url(server, "/generate"),
        data=json.dumps({"question": "hello?"}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as r:
        body = json.load(r)
    assert "answer" in body and "drafts" in body


def test_generate_missing_question(server):
    req = urllib.request.Request(_url(server, "/generate"), data=b"{}")
    try:
        urllib.request.urlopen(req)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert "question" in json.load(e)["error"]


def test_generate_bad_json(server):
    req = urllib.request.Request(_url(server, "/generate"), data=b"not json")
    try:
        urllib.request.urlopen(req)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_unknown_path(server):
    try:
        urllib.request.urlopen(_url(server, "/nope"))
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_cli_eval_tiny(tmp_path, capsys):
    from edgemesh.cli import main

    cfg_yaml = tmp_path / "c.yaml"
    cfg_yaml.write_text(
        """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4, num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false}
eval:
  num_samples: 2
"""
    )
    rc = main([
        "eval", "--config", str(cfg_yaml),
        "--eval.output_jsonl", str(tmp_path / "r.jsonl"),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["num_samples"] == 2
    assert "rouge1" in report and "tps" in report


def test_cli_download_reports_synthetic(capsys):
    from edgemesh.cli import main

    rc = main(["download"])
    assert rc == 0


def test_cli_download_materializes_from_hub_cache(tmp_path, capsys):
    """--src resolves a hub-cache snapshot (symlinked blobs and all) into the
    flat save_pretrained layout the ingest expects — the offline analog of the
    reference's save_transformer_model (download.py:20-24)."""
    from edgemesh.cli import main

    # Fake hub cache: blobs/ holds content, snapshots/<rev>/ symlinks into it.
    cache = tmp_path / "hub_cache"
    model = cache / "models--acme--tiny-lm"
    blobs = model / "blobs"
    snap = model / "snapshots" / "abc123"
    blobs.mkdir(parents=True)
    snap.mkdir(parents=True)
    (blobs / "b1").write_text('{"model_type": "llama"}')
    (blobs / "b2").write_bytes(b"\x00weights")
    (snap / "config.json").symlink_to(blobs / "b1")
    (snap / "model.safetensors").symlink_to(blobs / "b2")
    # Snapshots can carry subdirectories (e.g. Llama's original/ PT folder);
    # materialization must skip them, not crash.
    (snap / "original").mkdir()
    (snap / "original" / "consolidated.00.pth").write_bytes(b"x")

    dest = tmp_path / "checkpoints" / "tiny-lm"
    cfg_yaml = tmp_path / "cfg.yaml"
    cfg_yaml.write_text(
        f"""
agents:
  - role: qa
    model:
      path: {dest}
      hub_id: acme/tiny-lm
"""
    )
    rc = main(["download", "--src", str(cache), "--config", str(cfg_yaml)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "materialized acme/tiny-lm" in out and "[ok]" in out
    assert (dest / "config.json").read_text() == '{"model_type": "llama"}'
    assert not (dest / "config.json").is_symlink()  # self-contained copy
    # Second run: already complete, verify-only.
    rc = main(["download", "--src", str(cache), "--config", str(cfg_yaml)])
    assert rc == 0
    assert "[ok]" in capsys.readouterr().out


def test_rest_continuous_speculative_end_to_end():
    """The REST --continuous path auto-selects the speculative engine for a
    draft-carrying agent on the paged backend; /generate answers through
    pool-wide draft→verify rounds and /stats carries acceptance counters."""
    from edgemesh.agents.orchestrator import Ensemble, build_agent

    base = dict(family="llama", vocab_size=260, num_layers=1, hidden_size=32,
                num_heads=4, num_kv_heads=4, intermediate_size=64,
                max_seq_len=128)
    agent = build_agent(AgentSpec(
        role="qa", model=ModelSpec(**base),
        draft=ModelSpec(**base), spec_gamma=2,
        sampling=SamplingParams(max_new_tokens=6, do_sample=False,
                                repetition_penalty=1.0),
    ))
    srv = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                     block=False, continuous=True, kv_backend="paged", batch=2)
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        body = json.dumps({"question": "where is the eiffel tower?"}).encode()
        req = urllib.request.Request(
            f"{url}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            resp = json.load(r)
        assert "answer" in resp and resp["generated"] > 0
        with urllib.request.urlopen(f"{url}/stats", timeout=60) as r:
            metrics = json.load(r)
        stats = metrics["batcher"]
        assert stats["gamma"] == 2
        assert stats["spec_rounds"] > 0 and stats["spec_proposed"] > 0
    finally:
        srv.shutdown()
        if srv.batcher is not None:
            srv.batcher.close()


def test_rest_per_request_budget_and_sjf_admission():
    """/generate accepts a per-request "max_new" under continuous serving
    (engine budget cap rides the JSON body) and serve_rest forwards the
    admission policy to the engine; non-continuous servers reject max_new
    with a 400, not a silent ignore."""
    from edgemesh.agents.orchestrator import Ensemble, build_agent

    agent = build_agent(AgentSpec(
        role="qa",
        model=ModelSpec(family="llama", vocab_size=260, num_layers=1,
                        hidden_size=32, num_heads=4, num_kv_heads=4,
                        intermediate_size=64, max_seq_len=128),
        sampling=SamplingParams(max_new_tokens=12, do_sample=False,
                                repetition_penalty=1.0),
    ))
    srv = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                     block=False, continuous=True, kv_backend="paged",
                     kv_page_size=16, batch=2, admission="sjf")
    try:
        assert srv.batcher.admission == "sjf"
        url = f"http://127.0.0.1:{srv.server_address[1]}"
        body = json.dumps({"question": "where is the eiffel tower?",
                           "max_new": 3}).encode()
        req = urllib.request.Request(
            f"{url}/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            resp = json.load(r)
        assert 0 < resp["generated"] <= 3, resp
        for bad_body in (
            {"question": "q", "max_new": 0},      # out of range
            {"question": "q", "max_new": True},   # bool is not a budget
        ):
            bad = urllib.request.Request(
                f"{url}/generate",
                data=json.dumps(bad_body).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(bad, timeout=60)
                raise AssertionError(f"accepted {bad_body}")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        # Stream path: max_new is rejected, never silently ignored.
        sreq = urllib.request.Request(
            f"{url}/generate_stream",
            data=json.dumps({"question": "q?", "max_new": 3}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(sreq, timeout=60)
            raise AssertionError("stream accepted max_new")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.shutdown()
        if srv.batcher is not None:
            srv.batcher.close()

    # Non-continuous server: max_new is a 400.
    srv2 = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                      block=False)
    try:
        url = f"http://127.0.0.1:{srv2.server_address[1]}"
        req = urllib.request.Request(
            f"{url}/generate",
            data=json.dumps({"question": "q?", "max_new": 3}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=120)
            raise AssertionError("non-continuous server accepted max_new")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv2.shutdown()
