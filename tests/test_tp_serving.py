"""Tensor-parallel continuous serving (serve/continuous.py ``tp_engine=``):
the acceptance gate for the quantized, overlapped collective layer — the
continuous engine serves end-to-end on a forced-8-device CPU mesh with
``collective_mode="qpsum_overlap"`` and emits greedy tokens matching the
bf16-psum arm within the pinned agreement bound, with the collective wire
accounted in metrics and spans.
"""

import jax
import numpy as np
import pytest

from edgemesh.agents.orchestrator import build_agent
from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
from edgemesh.obs import Registry
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.tp_infer import TPInferenceEngine
from edgemesh.serve.continuous import ContinuousEngine
from edgemesh.utils.tracing import JsonlLogger

# Engines compile per mode; multi-minute territory — slow tier.
pytestmark = pytest.mark.slow

#: The 0.999 ship gate PERFORMANCE.md pins applies to the bench on
#: real-scale models, where top-2 logit gaps dwarf the int8 wire noise.
#: This tiny RANDOM model decodes through near-ties (top-2 gaps at the
#: quantization-noise scale), so single argmax flips are expected and
#: deterministic on the CPU backend — the pin here is set where it still
#: catches real breakage (a broken ring/scale lands near chance
#: agreement, ~1/260 per token) without failing on a near-tie flip.
TINY_MODEL_AGREEMENT_BOUND = 0.75


def _agent():
    return build_agent(AgentSpec(
        role="qa",
        model=ModelSpec(
            family="llama", vocab_size=260, num_layers=2, hidden_size=64,
            num_heads=8, num_kv_heads=8, intermediate_size=128,
            max_seq_len=128,
        ),
        sampling=SamplingParams(max_new_tokens=8, do_sample=False,
                                repetition_penalty=1.0),
    ))


def _serve(agent, mode, dtype, questions, span_log=None):
    tp_eng = TPInferenceEngine(
        agent.cfg, agent.params, build_mesh(dp=1, tp=8),
        attention_impl="xla", collective_mode=mode, comm_dtype=dtype,
    )
    reg = Registry()
    eng = ContinuousEngine(agent, slots=2, chunk=4, kv_backend="dense",
                           registry=reg, tp_engine=tp_eng, span_log=span_log)
    try:
        futs = [eng.submit(q) for q in questions]
        results = [f.result() for f in futs]
        stats = eng.stats()
    finally:
        eng.close()
    return results, reg, stats


def _agreement(a: str, b: str) -> float:
    if a == b:
        return 1.0
    n = max(len(a), len(b), 1)
    return sum(x == y for x, y in zip(a, b)) / n


def test_qpsum_overlap_serving_matches_bf16_psum_arm(devices, tmp_path):
    """The acceptance criterion: continuous serving over tp8 with
    qpsum_overlap produces the bf16-psum arm's greedy tokens within the
    pinned agreement bound (see TINY_MODEL_AGREEMENT_BOUND — the 0.999
    gate rides the bench on real models), requests joining mid-flight
    included."""
    agent = _agent()
    qs = [
        "what color is the sky on a clear day?",
        "name a fruit that is yellow.",
        "how many legs does a spider have?",
    ]
    base, _, _ = _serve(agent, "psum", "bf16", qs)
    log = tmp_path / "spans.jsonl"
    got, reg, stats = _serve(agent, "qpsum_overlap", "int8", qs,
                             span_log=str(log))
    for r_base, r_got in zip(base, got):
        assert r_got["generated"] == r_base["generated"] > 0
        assert _agreement(r_base["answer"], r_got["answer"]) >= \
            TINY_MODEL_AGREEMENT_BOUND

    # Engine surface: the tp knobs ride /stats.
    assert stats["tp"] == 8
    assert stats["collective_mode"] == "qpsum_overlap"
    assert stats["collective_dtype"] == "int8"

    # Wire accounting: the counter carries the quantized op/dtype and a
    # byte total consistent with the segment math (chunk+1 steps per
    # dispatched segment plus the admission prefills — all > 0).
    snap = reg.snapshot()
    samples = snap["edgemesh_collective_bytes_total"]["samples"]
    assert len(samples) == 1
    labels = samples[0]["labels"]
    assert labels["op"] == "qpsum" and labels["dtype"] == "int8"
    assert samples[0]["value"] > 0

    # Span records: prefill carries the per-layer accounting attrs, decode
    # spans carry their slice of the wire (critical_path rolls them up).
    recs = [r for r in JsonlLogger(log).read()
            if r.get("event") == "request_spans"]
    assert len(recs) == 3
    for rec in recs:
        assert rec["collective_op"] == "qpsum"
        assert rec["collective_dtype"] == "int8"
        assert rec["collective_per_layer_bytes"]["attn_o"] > 0
        decode_bytes = [
            s.get("collective_bytes") for s in rec["spans"]
            if s["name"] == "decode"
        ]
        assert sum(b or 0 for b in decode_bytes) > 0


def test_tp_serving_matches_plain_single_program_engine(devices):
    """The psum arm over tp8 must be token-identical to the unsharded
    single-program continuous engine — tensor parallelism is an execution
    detail, not a model change."""
    agent = _agent()
    q = "what color is the sky on a clear day?"
    plain = ContinuousEngine(agent, slots=2, chunk=4, kv_backend="dense",
                             registry=Registry())
    try:
        a = plain.answer(q)
    finally:
        plain.close()
    got, _, _ = _serve(agent, "psum", "bf16", [q])
    assert got[0]["answer"] == a["answer"]
    assert got[0]["generated"] == a["generated"] > 0


def test_tp_engine_requires_dense_backend_and_dp1(devices):
    agent = _agent()
    tp_eng = TPInferenceEngine(agent.cfg, agent.params, build_mesh(dp=1, tp=8),
                               attention_impl="xla")
    with pytest.raises(ValueError, match="dense"):
        ContinuousEngine(agent, slots=2, kv_backend="paged", tp_engine=tp_eng)
    dp_eng = TPInferenceEngine(agent.cfg, agent.params, build_mesh(dp=2, tp=4),
                               attention_impl="xla")
    with pytest.raises(ValueError, match="dp=1"):
        ContinuousEngine(agent, slots=2, kv_backend="dense", tp_engine=dp_eng)


def test_tp_generate_greedy_qpsum_modes_match_psum(devices):
    """Engine-level ablation shape: generate_greedy under qpsum/
    qpsum_overlap agrees with the psum arm within the pinned bound on a
    tp8 mesh (the bench's quality-delta column, minus the wall clock)."""
    from edgemesh.models import init_params
    from edgemesh.models.families import tiny_config

    cfg = tiny_config("llama", num_heads=8, num_kv_heads=8, num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, tp=8)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                cfg.vocab_size)
    lengths = np.asarray([5, 5])
    ref = None
    for mode in ("psum", "qpsum", "qpsum_overlap"):
        eng = TPInferenceEngine(cfg, params, mesh, attention_impl="xla",
                                collective_mode=mode)
        toks = np.asarray(eng.generate_greedy(
            tokens, jax.numpy.asarray(lengths), max_new=6))
        if ref is None:
            ref = toks
        else:
            assert float(np.mean(toks == ref)) >= TINY_MODEL_AGREEMENT_BOUND
