"""Ensemble-over-the-fleet fast tier: model descriptors + pool membership
in the registry (purged on deregister, reset on revive — beside the PR 14
stale-digest purge contract), pool-filtered routing, the EnsembleCoordinator
degradation ladder against a fake transport, the /ensemble wire contract,
and the loadgen --target URL rewrite. No model, no device; loopback sockets
only where the frontend HTTP layer itself is under test."""

import json
import random
import time
import urllib.request

import pytest

from edgemesh.agents.prompts import (
    DEFAULT_QA_TEMPLATE,
    PASSTHROUGH_TEMPLATE,
    REFINER_TEMPLATE,
    format_refiner_prompt,
)
from edgemesh.fleet import (
    EnsembleCoordinator,
    FleetRouter,
    ReplicaRegistry,
    make_balancer,
    serve_fleet,
)
from edgemesh.fleet.ensemble import OUTCOMES
from edgemesh.obs import Registry
from edgemesh.serve.httputil import ENSEMBLE_PATH, TRACE_HEADER, WIRE_CONTRACT
from edgemesh.utils.tracing import JsonlLogger


class FakeTransport:
    """Scripted transport: first registered URL substring that matches wins.
    Handlers return ``(status, body)``; every call is recorded."""

    def __init__(self):
        self.calls = []
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def _dispatch(self, method, url, payload, timeout_s, headers):
        self.calls.append((method, url, payload, timeout_s, dict(headers or {})))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return self._dispatch("GET", url, None, timeout_s, headers)

    def post_json(self, url, payload, timeout_s, headers=None):
        return self._dispatch("POST", url, payload, timeout_s, headers)


def _pool_registry():
    reg = ReplicaRegistry()
    reg.register("qa-a-0", "http://qa-a-0", model={"pool": "qa-a", "role": "qa"})
    reg.register("qa-b-0", "http://qa-b-0", model={"pool": "qa-b", "role": "qa"})
    reg.register("ref-0", "http://ref-0",
                 model={"pool": "refiner", "role": "refiner"})
    return reg


def _router(reg, transport, **kw):
    kw.setdefault("obs_registry", Registry())
    kw.setdefault("rng", random.Random(0))
    return FleetRouter(reg, transport=transport, **kw)


def _answer(text, confidence=0.5):
    return lambda u, p, h: (200, {"answer": text, "confidence": confidence})


# ---------------------------------------------------------------------------
# Registry: model descriptors, pool views, purge-on-deregister hygiene
# ---------------------------------------------------------------------------


def test_registry_model_descriptor_and_pools_view():
    reg = _pool_registry()
    reg.register("plain", "http://plain")  # descriptor-less: no named pool
    assert reg.get("qa-a-0").pool == "qa-a"
    assert reg.get("plain").pool is None
    pools = reg.pools()
    assert set(pools) == {"qa-a", "qa-b", "refiner"}
    assert pools["refiner"]["role"] == "refiner"
    assert pools["qa-a"]["replicas"] == ["qa-a-0"]
    assert pools["qa-a"]["routable"] == 1
    # Unroutable members stay listed but don't count as routable.
    reg.set_state("qa-b-0", "unhealthy")
    pools = reg.pools()
    assert pools["qa-b"]["replicas"] == ["qa-b-0"]
    assert pools["qa-b"]["routable"] == 0
    # The descriptor rides the snapshot → /fleetz.
    snap = {s["id"]: s for s in reg.snapshot()}
    assert snap["qa-a-0"]["model"] == {"pool": "qa-a", "role": "qa"}
    assert snap["qa-a-0"]["pool"] == "qa-a"
    assert "model" not in snap["plain"]


def test_registry_purges_model_on_remove_and_resets_on_revive():
    # Mirrors the stale-digest purge contract: pool membership dies with
    # the backend — a deregistered replica must vanish from pools() and a
    # revived one must NOT inherit the old descriptor (the re-registered
    # checkpoint may be a different model).
    reg = _pool_registry()
    reg.set_state("qa-b-0", "removed")
    assert "qa-b" not in reg.pools()
    assert reg.get("qa-b-0").model is None
    # Revive WITHOUT a descriptor: no pool (fresh registration decides).
    reg.register("qa-b-0", "http://qa-b-0")
    assert reg.get("qa-b-0").pool is None
    # Revive WITH a new descriptor: the new pool wins.
    reg.set_state("qa-b-0", "removed")
    reg.register("qa-b-0", "http://qa-b-0",
                 model={"pool": "qa-c", "role": "qa"})
    assert reg.get("qa-b-0").pool == "qa-c"
    # A live heartbeat re-register without a descriptor keeps the existing
    # one (idempotence — same contract as outstanding accounting).
    reg.register("qa-a-0", "http://qa-a-0")
    assert reg.get("qa-a-0").pool == "qa-a"
    # deregister purges outright.
    reg.deregister("ref-0")
    assert "refiner" not in reg.pools()


def test_router_forget_replica_purges_pool_tiers():
    reg = _pool_registry()
    router = _router(reg, FakeTransport(), tiered=True)
    tm = router._tiers_for("qa-a")
    assert tm is not router.tiers
    assert router._tiers_for("qa-a") is tm  # cached per pool
    assert router._tiers_for(None) is router.tiers
    tm._prefill_rids = frozenset({"qa-a-0"})
    router.forget_replica("qa-a-0")
    assert "qa-a-0" not in tm._prefill_rids
    assert reg.get("qa-a-0") is None


def test_available_and_acquire_filter_by_pool():
    reg = _pool_registry()
    assert {r.rid for r in reg.available()} == {"qa-a-0", "qa-b-0", "ref-0"}
    assert [r.rid for r in reg.available(pool="qa-a")] == ["qa-a-0"]
    bal = make_balancer("round_robin")
    for _ in range(3):  # never leaks outside the pool
        rep = reg.acquire(bal, pool="refiner")
        assert rep.rid == "ref-0"
        reg.release("ref-0", ok=True)
    assert reg.acquire(bal, pool="nope") is None


def test_per_pool_hedge_estimators_are_distinct():
    router = _router(_pool_registry(), FakeTransport())
    a = router._hedge_estimator_for("qa-a")
    b = router._hedge_estimator_for("qa-b")
    assert a is not b
    assert router._hedge_estimator_for("qa-a") is a
    assert router._hedge_estimator_for(None) is router._hedge_estimator


# ---------------------------------------------------------------------------
# EnsembleCoordinator: parallel fan-out + the degradation ladder
# ---------------------------------------------------------------------------


def test_ensemble_branches_overlap_and_share_one_trace(tmp_path):
    log = tmp_path / "router.jsonl"
    ft = FakeTransport()

    def slow_answer(url, payload, headers):
        time.sleep(0.3)
        return 200, {"answer": "draft", "confidence": 0.5}

    ft.on("qa-a-0/generate", slow_answer)
    ft.on("qa-b-0/generate", slow_answer)
    ft.on("ref-0/generate", _answer("refined", 0.9))
    router = _router(_pool_registry(), ft, span_log=log, trace_sample=1.0)

    t0 = time.monotonic()
    status, body, headers = router.ensemble.handle({"question": "q?"})
    elapsed = time.monotonic() - t0
    assert status == 200
    assert body["answer"] == "refined" and body["refined"] is True
    assert body["outcome"] == "ok"
    assert {c["pool"] for c in body["candidates"]} == {"qa-a", "qa-b"}
    # Two 0.3 s branches serially would be >= 0.6 s.
    assert elapsed < 0.55
    # ONE router record carries the whole fan-out tree; the branch spans
    # provably overlap (the property the e2e asserts cross-process).
    recs = JsonlLogger(log).read()
    assert len(recs) == 1
    spans = recs[0]["spans"]
    assert spans[0]["name"] == "ensemble"
    branch = [s for s in spans if s["name"] == "branch"]
    assert {s["pool"] for s in branch} == {"qa-a", "qa-b"}
    assert all(s["outcome"] == "ok" for s in branch)
    assert max(s["t0"] for s in branch) < min(s["t1"] for s in branch)
    refine = [s for s in spans if s["name"] == "refine"]
    assert len(refine) == 1 and refine[0]["pool"] == "refiner"
    # The response header joins the same trace the record carries.
    assert recs[0]["trace_id"] in headers[TRACE_HEADER]
    # The refiner saw the COMPOSED prompt (both drafts in the template),
    # not the raw question — composed fleet-side, passthrough on the wire.
    refiner_calls = [p for m, u, p, t, h in ft.calls if "ref-0" in u]
    assert refiner_calls[0]["question"] == format_refiner_prompt(
        "q?", ["draft", "draft"])


def test_ensemble_degradation_ladder(tmp_path):
    def run(handlers, refiner=True):
        reg = ReplicaRegistry()
        reg.register("qa-a-0", "http://qa-a-0",
                     model={"pool": "qa-a", "role": "qa"})
        reg.register("qa-b-0", "http://qa-b-0",
                     model={"pool": "qa-b", "role": "qa"})
        if refiner:
            reg.register("ref-0", "http://ref-0",
                         model={"pool": "refiner", "role": "refiner"})
        ft = FakeTransport()
        for substr, handler in handlers.items():
            ft.on(substr, handler)
        obs = Registry()
        router = _router(reg, ft, obs_registry=obs)
        status, body, _ = router.ensemble.handle({"question": "q?"},
                                                 deadline_s=5.0)
        return status, body, router.ensemble, obs

    no_answer = lambda u, p, h: (200, {"note": "no answer key"})

    # Rung 1: everything healthy → "ok", refiner's answer wins.
    status, body, ens, obs = run({
        "qa-a-0": _answer("a", 0.2), "qa-b-0": _answer("b", 0.8),
        "ref-0": _answer("merged", 0.9),
    })
    assert (status, body["outcome"], body["answer"]) == (200, "ok", "merged")
    assert ens.stats()["outcomes"] == {"ok": 1}

    # Rung 2: one QA branch dead → single-candidate refine, "degraded_qa".
    status, body, ens, obs = run({
        "qa-a-0": _answer("a", 0.2), "qa-b-0": no_answer,
        "ref-0": _answer("merged", 0.9),
    })
    assert (status, body["outcome"]) == (200, "degraded_qa")
    assert body["answer"] == "merged" and body["refined"] is True
    assert len(body["candidates"]) == 1
    fates = {b["pool"]: b["outcome"] for b in body["branches"]}
    assert fates == {"qa-a": "ok", "qa-b": "failed"}
    summary = obs.summary(prefix="edgemesh_ensemble_")
    assert summary['edgemesh_ensemble_total{outcome="degraded_qa"}'] == 1
    assert summary[
        'edgemesh_ensemble_branch_total{pool="qa-b",outcome="failed"}'] == 1

    # Rung 3: refiner dead → best-confidence QA candidate, still 200.
    status, body, ens, obs = run({
        "qa-a-0": _answer("a", 0.2), "qa-b-0": _answer("b", 0.8),
        "ref-0": no_answer,
    })
    assert (status, body["outcome"]) == (200, "refiner_fallback")
    assert body["answer"] == "b" and body["refined"] is False

    # Rung 4: no refiner pool registered at all.
    status, body, ens, obs = run(
        {"qa-a-0": _answer("a", 0.9), "qa-b-0": _answer("b", 0.1)},
        refiner=False,
    )
    assert (status, body["outcome"]) == (200, "no_refiner")
    assert body["answer"] == "a"

    # Rung 5 (the only client-visible failure): every branch dead.
    status, body, ens, obs = run({
        "qa-a-0": no_answer, "qa-b-0": no_answer,
        "ref-0": _answer("merged", 0.9),
    })
    assert status == 502
    assert body["kind"] == "ensemble_failed"
    assert all(b["outcome"] == "failed" for b in body["branches"])
    assert ens.stats()["outcomes"] == {"failed": 1}
    # Every ladder rung is a declared outcome.
    assert {"ok", "degraded_qa", "refiner_fallback", "no_refiner",
            "failed"} == set(OUTCOMES)


def test_ensemble_without_descriptors_degenerates_to_single_branch():
    reg = ReplicaRegistry([("r0", "http://r0")])
    ft = FakeTransport().on("r0/generate", _answer("plain", 0.4))
    router = _router(reg, ft)
    status, body, _ = router.ensemble.handle({"question": "q?"})
    assert (status, body["outcome"]) == (200, "no_refiner")
    assert body["answer"] == "plain"
    assert [b["pool"] for b in body["branches"]] == ["fleet"]


def test_ensemble_missing_question_is_400():
    router = _router(_pool_registry(), FakeTransport())
    for payload in ({}, {"question": ""}, {"question": 3}, None):
        status, body, _ = router.ensemble.handle(payload)
        assert status == 400 and body == {"error": "missing question"}


def test_pinned_topology_overrides_discovery():
    reg = _pool_registry()
    ens = EnsembleCoordinator(_router(reg, FakeTransport()),
                              qa_pools=["qa-b"], refiner_pool=None,
                              obs_registry=Registry())
    qa, refiner = ens.topology()
    assert qa == ["qa-b"]
    # Pinned QA pools + discovered refiner (refiner_pool stays live).
    assert refiner == "refiner"


def test_router_status_carries_pools_and_ensemble_stats():
    router = _router(_pool_registry(), FakeTransport())
    st = router.status()
    assert set(st["pools"]) == {"qa-a", "qa-b", "refiner"}
    assert st["ensemble"]["qa_pools"] == ["qa-a", "qa-b"]
    assert st["ensemble"]["refiner_pool"] == "refiner"
    assert st["ensemble"]["outcomes"] is None  # no traffic yet


def test_ensemble_spans_carry_quality_attrs(tmp_path):
    """Satellite (quality observatory): branch spans carry answer_len +
    confidence, the ensemble span and response body carry agreement +
    refiner_divergence, and the agreement EWMA rides stats()."""
    log = tmp_path / "spans.jsonl"
    ft = FakeTransport()
    ft.on("qa-a-0/generate", _answer("the sky is blue", 0.9))
    ft.on("qa-b-0/generate", _answer("the sky is blue today", 0.4))
    ft.on("ref-0/generate", _answer("the sky is blue", 0.8))
    obs = Registry()
    router = _router(_pool_registry(), ft, span_log=log, trace_sample=1.0,
                     obs_registry=obs)
    status, body, _ = router.ensemble.handle({"question": "sky?"})
    assert status == 200 and body["outcome"] == "ok"

    spans = JsonlLogger(log).read()[0]["spans"]
    branch = {s["pool"]: s for s in spans if s["name"] == "branch"}
    assert branch["qa-a"]["answer_len"] == len("the sky is blue")
    assert branch["qa-a"]["confidence"] == 0.9
    assert branch["qa-b"]["confidence"] == 0.4
    # 4/5 tokens shared both ways → F1 = 2*0.8*1.0/1.8 ≈ 0.8889.
    agreement = spans[0]["agreement"]
    assert agreement == pytest.approx(0.8889, abs=1e-3)
    assert body["agreement"] == agreement
    # Refiner echoed the best draft verbatim → zero divergence.
    assert spans[0]["refiner_divergence"] == 0.0
    assert body["refiner_divergence"] == 0.0
    # First observation seeds the EWMA directly.
    assert router.ensemble.stats()["agreement_ewma"] == pytest.approx(
        agreement, abs=1e-3)
    summary = obs.summary(prefix="edgemesh_ensemble_agreement")
    assert summary["edgemesh_ensemble_agreement"]["count"] == 1


def test_ensemble_low_agreement_counter_and_null_attrs():
    """Disagreeing branches trip the low-agreement counter per pool; a
    failed branch keeps its quality attrs at the pre-seeded nulls."""
    ft = FakeTransport()
    ft.on("qa-a-0/generate", _answer("alpha beta gamma", 0.9))
    ft.on("qa-b-0/generate", _answer("delta epsilon zeta", 0.4))
    ft.on("ref-0/generate", lambda u, p, h: (200, {"note": "no answer"}))
    obs = Registry()
    router = _router(_pool_registry(), ft, obs_registry=obs)
    status, body, _ = router.ensemble.handle({"question": "q?"})
    assert status == 200
    # Zero token overlap → agreement 0.0 < low_agreement default 0.3.
    assert body["agreement"] == 0.0
    summary = obs.summary(prefix="edgemesh_ensemble_low_agreement")
    assert summary[
        'edgemesh_ensemble_low_agreement_total{pool="qa-a"}'] == 1
    assert summary[
        'edgemesh_ensemble_low_agreement_total{pool="qa-b"}'] == 1
    # Refiner failed → fallback answer, divergence stays null.
    assert body["outcome"] == "refiner_fallback"
    assert body["refiner_divergence"] is None

    # Single surviving branch: agreement needs >= 2 answers → null, and the
    # dead branch's span keeps the pre-seeded null quality attrs.
    ft2 = FakeTransport()
    ft2.on("qa-a-0/generate", _answer("solo", 0.7))
    ft2.on("qa-b-0/generate", lambda u, p, h: (200, {"note": "dead"}))
    ft2.on("ref-0/generate", _answer("refined", 0.9))
    import tempfile, pathlib
    with tempfile.TemporaryDirectory() as td:
        log = pathlib.Path(td) / "spans.jsonl"
        router2 = _router(_pool_registry(), ft2, span_log=log,
                          trace_sample=1.0)
        status, body, _ = router2.ensemble.handle({"question": "q?"})
        assert status == 200 and body["agreement"] is None
        spans = JsonlLogger(log).read()[0]["spans"]
        dead = [s for s in spans
                if s["name"] == "branch" and s["pool"] == "qa-b"][0]
        assert dead["answer_len"] is None and dead["confidence"] is None


# ---------------------------------------------------------------------------
# Frontend: POST /ensemble route + model descriptors over /replicas/register
# ---------------------------------------------------------------------------


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}"), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), e.headers


def test_frontend_serves_ensemble_and_registers_model_descriptors():
    import urllib.error  # noqa: F401 — _post's except path

    ft = FakeTransport()
    ft.on("qa-a-0/generate", _answer("a", 0.3))
    ft.on("qa-b-0/generate", _answer("b", 0.6))
    ft.on("ref-0/generate", _answer("merged", 0.9))
    reg = ReplicaRegistry()
    reg.register("qa-a-0", "http://qa-a-0",
                 model={"pool": "qa-a", "role": "qa"})
    reg.register("ref-0", "http://ref-0",
                 model={"pool": "refiner", "role": "refiner"})
    router = _router(reg, ft)
    srv = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        # Runtime registration carries the model descriptor.
        status, body, _ = _post(base + "/replicas/register", {
            "id": "qa-b-0", "url": "http://qa-b-0",
            "model": {"pool": "qa-b", "role": "qa"},
        })
        assert status == 200
        assert reg.get("qa-b-0").pool == "qa-b"

        status, body, headers = _post(base + "/ensemble", {"question": "q?"})
        assert status == 200
        assert body["answer"] == "merged" and body["outcome"] == "ok"
        assert headers[TRACE_HEADER]

        # Deregister purges the pool; the next ensemble degrades, never 5xx.
        status, _, _ = _post(base + "/replicas/deregister", {"id": "qa-b-0"})
        assert status == 200
        assert "qa-b" not in reg.pools()
        status, body, _ = _post(base + "/ensemble", {"question": "q?"})
        assert status == 200 and body["outcome"] == "ok"
        assert {c["pool"] for c in body["candidates"]} == {"qa-a"}
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Wire contract + prompt templates + loadgen target rewrite
# ---------------------------------------------------------------------------


def test_wire_contract_declares_ensemble_and_model_keys():
    row = WIRE_CONTRACT[("POST", ENSEMBLE_PATH)]
    assert ENSEMBLE_PATH == "/ensemble"
    assert "frontend" in row["servers"]
    assert "question" in row["request_keys"]
    assert "ensemble_failed" in row["error_kinds"]
    assert "model" in WIRE_CONTRACT[("POST", "/replicas/register")]["request_keys"]
    from edgemesh.fleet.frontend import SERVED_ROUTES

    assert "/ensemble" in SERVED_ROUTES["POST"]


def test_refiner_prompt_is_the_shared_template():
    got = format_refiner_prompt("Q?", ["a1", "a2"])
    assert got == REFINER_TEMPLATE.format(
        question="Q?", candidates="Answer 1: a1\nAnswer 2: a2\n")
    assert PASSTHROUGH_TEMPLATE.format(question=got) == got
    assert "{question}" in DEFAULT_QA_TEMPLATE


def test_loadgen_resolve_target_url():
    from edgemesh.loadgen.cli import resolve_target_url

    assert resolve_target_url("http://h:1/generate", "ensemble") == \
        "http://h:1/ensemble"
    assert resolve_target_url("http://h:1", "ensemble") == "http://h:1/ensemble"
    assert resolve_target_url("http://h:1/", "generate") == "http://h:1/generate"
    assert resolve_target_url("http://h:1/ensemble", "generate") == \
        "http://h:1/generate"
    # Idempotent for the default flow.
    assert resolve_target_url("http://h:1/generate", "generate") == \
        "http://h:1/generate"
