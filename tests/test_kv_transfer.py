"""KV transfer + tiered routing (fast tier): the wire format round-trip
(bf16 and int8 pools, zero-length, page-boundary, corruption/version
refusal), dynamic tier assignment (TierManager), the router's tiered path
over a fake transport (export→import flow, shared prefix cache, graceful
fallback), and the non-hedgeable-transfer regression. Engine-level and
gateway-level round trips (real model) live in the slow tier at the bottom
of this file; the full subprocess A/B is tests/test_disagg_e2e.py."""

import random
import time

import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.fleet import FleetRouter, ReplicaRegistry, TransportError
from edgemesh.fleet.balancer import TierManager
from edgemesh.models.transformer import ModelConfig
from edgemesh.obs import Registry
from edgemesh.runtime import paged_kv as pk


# ---------------------------------------------------------------------------
# Wire format round trip (no model, no engine)
# ---------------------------------------------------------------------------


def _cfg(**kw):
    base = dict(num_layers=2, hidden_size=32, num_heads=4, num_kv_heads=2,
                intermediate_size=64, vocab_size=128, max_seq_len=256)
    base.update(kw)
    return ModelConfig(**base)


def _mark_pages(cache, pages, value):
    upd = dict(
        k=cache.k.at[:, pages].set(value),
        v=cache.v.at[:, pages].set(value + 1),
    )
    if hasattr(cache, "k_scale"):
        upd["k_scale"] = cache.k_scale.at[:, pages].set(0.5)
        upd["v_scale"] = cache.v_scale.at[:, pages].set(0.25)
    return cache._replace(**upd)


@pytest.mark.parametrize("quant", [False, True], ids=["bf16", "int8"])
@pytest.mark.parametrize("tokens", [13, 16, 1], ids=["partial", "boundary", "one"])
def test_wire_roundtrip_pools_and_lengths(quant, tokens):
    cfg = _cfg()
    init = pk.init_quant_paged_cache if quant else pk.init_paged_cache
    kw = {} if quant else {"dtype": jnp.bfloat16}
    src = init(cfg, 2, total_pages=9, page_size=8, **kw)
    n_pages = -(-tokens // 8)
    pages = list(range(3, 3 + n_pages))
    src = _mark_pages(src, pages, 7)
    ids = np.arange(100, 100 + tokens, dtype=np.int32)
    buf = pk.export_pages(src, pages, tokens, ids)
    payload = pk.decode_wire(buf)
    assert payload.tokens == tokens and payload.n_pages == n_pages
    assert (payload.ids == ids).all()
    assert np.asarray(payload.k, np.float32).min() == 7
    if quant:
        assert payload.k_scale is not None
        assert float(payload.k_scale.min()) == 0.5

    dst = init(cfg, 2, total_pages=9, page_size=8, **kw)
    dest_pages = list(range(6, 6 + n_pages))
    dst = pk.splice_imported(dst, payload, dest_pages)
    assert np.asarray(dst.k[:, dest_pages], np.float32).min() == 7
    assert np.asarray(dst.v[:, dest_pages], np.float32).min() == 8
    if quant:
        assert np.asarray(dst.k_scale[:, dest_pages]).min() == 0.5
        assert np.asarray(dst.v_scale[:, dest_pages]).min() == 0.25
    # Pages OUTSIDE the destination set stay untouched (the trash page
    # absorbs the pow2 padding writes harmlessly).
    others = [p for p in range(1, 9) if p not in dest_pages]
    assert np.asarray(dst.k[:, others], np.float32).max() == 0


def test_wire_zero_length_export_is_legal():
    src = pk.init_paged_cache(_cfg(), 2, total_pages=5, page_size=8)
    buf = pk.export_pages(src, [], 0, [])
    payload = pk.decode_wire(buf)
    assert payload.tokens == 0 and payload.n_pages == 0
    assert payload.ids.size == 0 and payload.k.size == 0
    # Importing nothing is a no-op, not an error.
    dst = pk.splice_imported(src, payload, [])
    assert dst.k.shape == src.k.shape


def test_wire_partial_import_uses_leading_pages_only():
    # An importer whose token match ends early takes FEWER pages than the
    # payload carries — the leading ones.
    cfg = _cfg()
    src = pk.init_paged_cache(cfg, 2, total_pages=9, page_size=8)
    src = src._replace(k=src.k.at[:, 3].set(7).at[:, 4].set(9))
    buf = pk.export_pages(src, [3, 4], 16, np.arange(16, dtype=np.int32))
    payload = pk.decode_wire(buf)
    dst = pk.init_paged_cache(cfg, 2, total_pages=9, page_size=8)
    dst = pk.splice_imported(dst, payload, [6])
    assert np.asarray(dst.k[:, 6], np.float32).min() == 7  # first page
    assert np.asarray(dst.k[:, 7], np.float32).max() == 0  # second not taken
    with pytest.raises(pk.KVWireError):
        pk.splice_imported(dst, payload, [5, 6, 7])  # more than it carries


def test_wire_corruption_and_version_mismatch_refused():
    src = pk.init_paged_cache(_cfg(), 2, total_pages=5, page_size=8)
    src = src._replace(k=src.k.at[:, 2].set(1.0))
    buf = pk.export_pages(src, [2], 5, np.arange(5, dtype=np.int32))
    with pytest.raises(pk.KVWireError, match="truncated or corrupt"):
        pk.decode_wire(buf[:-3])
    with pytest.raises(pk.KVWireError, match="too short"):
        pk.decode_wire(b"EM")
    bad_magic = b"NOPE" + buf[4:]
    with pytest.raises(pk.KVWireError, match="bad magic"):
        pk.decode_wire(bad_magic)
    bad_version = bytearray(buf)
    bad_version[4] = 99  # the version u16's low byte
    with pytest.raises(pk.KVWireError, match="version"):
        pk.decode_wire(bytes(bad_version))


def test_wire_geometry_mismatch_refused_on_import():
    src = pk.init_paged_cache(_cfg(), 2, total_pages=5, page_size=8)
    buf = pk.export_pages(src, [2], 5, np.arange(5, dtype=np.int32))
    payload = pk.decode_wire(buf)
    # Different kv-head count → refuse with the differing fields named.
    other = pk.init_paged_cache(_cfg(num_kv_heads=4, num_heads=4), 2,
                                total_pages=5, page_size=8)
    with pytest.raises(pk.KVWireError, match="kv_heads"):
        pk.check_wire_compat(payload, other)
    # Quant pool vs float payload → kind mismatch.
    quant = pk.init_quant_paged_cache(_cfg(), 2, total_pages=5, page_size=8)
    with pytest.raises(pk.KVWireError, match="kind"):
        pk.check_wire_compat(payload, quant)


def test_wire_ids_token_count_must_agree():
    src = pk.init_paged_cache(_cfg(), 2, total_pages=5, page_size=8)
    with pytest.raises(ValueError, match="ids carries"):
        pk.export_pages(src, [2], 5, np.arange(4, dtype=np.int32))
    with pytest.raises(ValueError, match="do not fit"):
        pk.export_pages(src, [2], 9, np.arange(9, dtype=np.int32))


# ---------------------------------------------------------------------------
# TierManager: dynamic, digest-EWMA-driven membership
# ---------------------------------------------------------------------------


def _registry(*rids):
    reg = ReplicaRegistry()
    for rid in rids:
        reg.register(rid, f"http://{rid}")
    return reg


def _load(reg, rid, prefill, decode):
    reg.update_load(rid, {"ewma_prefill_tokens": prefill,
                          "ewma_decode_tokens": decode})


def test_tiers_need_two_replicas():
    reg = _registry("r0")
    t = TierManager().assign(reg.replicas())
    assert t["prefill"] == [] and [r.rid for r in t["decode"]] == ["r0"]


def test_tiers_follow_digest_prefill_share():
    reg = _registry("r0", "r1", "r2")
    _load(reg, "r2", 500.0, 10.0)   # prefill-heavy
    _load(reg, "r0", 5.0, 100.0)
    _load(reg, "r1", 5.0, 100.0)
    tm = TierManager(refresh_s=0.0)
    t = tm.assign(reg.replicas())
    assert [r.rid for r in t["prefill"]] == ["r2"]
    assert [r.rid for r in t["decode"]] == ["r0", "r1"]
    # The workload mix flips → membership follows (dynamic).
    _load(reg, "r2", 1.0, 500.0)
    _load(reg, "r0", 400.0, 2.0)
    t = tm.assign(reg.replicas())
    assert [r.rid for r in t["prefill"]] == ["r0"]


def test_tiers_cold_fleet_is_deterministic_and_bounded():
    reg = _registry("r3", "r1", "r2", "r0")
    t = TierManager(prefill_fraction=0.5, refresh_s=0.0).assign(reg.replicas())
    # All scores neutral → rid order; fraction 0.5 of 4 → 2 prefill, and
    # the bounds hold (1 <= prefill <= n-1).
    assert [r.rid for r in t["prefill"]] == ["r0", "r1"]
    assert [r.rid for r in t["decode"]] == ["r2", "r3"]


def test_tiers_hysteresis_resists_flapping_and_unhealthy_excluded():
    reg = _registry("r0", "r1", "r2")
    tm = TierManager(refresh_s=0.0, hysteresis=0.2)
    _load(reg, "r0", 100.0, 100.0)  # share 0.5, incumbent after first call
    _load(reg, "r1", 90.0, 110.0)
    _load(reg, "r2", 90.0, 110.0)
    t = tm.assign(reg.replicas())
    assert [r.rid for r in t["prefill"]] == ["r0"]
    # r1 nudges slightly ahead — within the hysteresis margin, the
    # incumbent keeps the tier (no flap).
    _load(reg, "r1", 110.0, 100.0)
    t = tm.assign(reg.replicas())
    assert [r.rid for r in t["prefill"]] == ["r0"]
    # A decisive shift does move membership.
    _load(reg, "r1", 1000.0, 1.0)
    t = tm.assign(reg.replicas())
    assert [r.rid for r in t["prefill"]] == ["r1"]
    # Unhealthy replicas leave both tiers.
    reg.set_state("r1", "unhealthy")
    reg.set_state("r2", "unhealthy")
    t = tm.assign(reg.replicas())
    assert t["prefill"] == [] and [r.rid for r in t["decode"]] == ["r0"]


def test_tiers_assignment_caches_until_invalidated():
    reg = _registry("r0", "r1", "r2")
    clock = [0.0]
    tm = TierManager(refresh_s=10.0, now=lambda: clock[0])
    t1 = tm.assign(reg.replicas())
    _load(reg, "r2", 900.0, 1.0)
    # Within refresh_s and same membership: the cached split is served.
    assert tm.assign(reg.replicas()) is t1
    tm.invalidate()  # the prober's on_digest hook
    t2 = tm.assign(reg.replicas())
    assert [r.rid for r in t2["prefill"]] == ["r2"]


# ---------------------------------------------------------------------------
# Tiered routing over a fake transport
# ---------------------------------------------------------------------------


class FakeTransport:
    def __init__(self):
        self.calls = []
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def _dispatch(self, method, url, payload, timeout_s, headers):
        self.calls.append((method, url, payload, timeout_s, dict(headers or {})))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return self._dispatch("GET", url, None, timeout_s, headers)

    def post_json(self, url, payload, timeout_s, headers=None):
        return self._dispatch("POST", url, payload, timeout_s, headers)

    def urls(self, substr):
        return [c[1] for c in self.calls if substr in c[1]]


def _tiered_router(reg, transport, **kw):
    kw.setdefault("obs_registry", Registry())
    kw.setdefault("rng", random.Random(0))
    kw.setdefault("tiered", True)
    kw.setdefault("tier_manager", TierManager(refresh_s=0.0))
    kw.setdefault("prefill_threshold_chars", 40)
    return FleetRouter(reg, transport=transport, **kw)


def _skewed_registry():
    reg = _registry("r0", "r1", "r2")
    _load(reg, "r2", 500.0, 10.0)  # r2 is the prefill tier
    _load(reg, "r0", 5.0, 100.0)
    _load(reg, "r1", 5.0, 100.0)
    return reg


def _export_ok(url, payload, headers):
    # The lint contract, asserted live: every transfer hop carries the
    # trace AND deadline headers.
    assert "X-Edgemesh-Trace" in headers and "X-Edgemesh-Deadline-S" in headers
    return 200, {"kv": "QUJD", "tokens": 99, "bytes": 3, "cached": False}


def _import_ok(url, payload, headers):
    assert "X-Edgemesh-Trace" in headers and "X-Edgemesh-Deadline-S" in headers
    assert payload["kv"] == "QUJD"
    return 200, {"answer": "imported", "generated": 4}


def test_tiered_long_prompt_exports_from_prefill_tier_and_imports_to_decode():
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    router = _tiered_router(reg, tr)
    status, body, headers = router.handle_generate({"question": "x" * 100})
    assert status == 200 and body["answer"] == "imported"
    assert headers["X-Edgemesh-Tiered"] == "1"
    assert "X-Edgemesh-Replica" in headers
    exports, imports = tr.urls("/kv/export"), tr.urls("/kv/import")
    assert len(exports) == 1 and "r2" in exports[0]  # the prefill tier
    assert len(imports) == 1 and ("r0" in imports[0] or "r1" in imports[0])
    # Outstanding bookkeeping balanced out through both pinned attempts.
    assert all(r.outstanding == 0 for r in reg.replicas())
    s = router.obs.summary(prefix="edgemesh_fleet_")
    assert s['edgemesh_fleet_kv_transfer_bytes_total{direction="export"}'] == 3
    assert s['edgemesh_fleet_kv_transfer_bytes_total{direction="import"}'] == 3
    assert s['edgemesh_fleet_tiered_total{outcome="tiered"}'] == 1


def test_tiered_repeat_prompt_hits_router_prefix_cache():
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    router = _tiered_router(reg, tr)
    q = "y" * 120
    assert router.handle_generate({"question": q})[0] == 200
    assert router.handle_generate({"question": q})[0] == 200
    assert len(tr.urls("/kv/export")) == 1  # second request skipped the hop
    assert len(tr.urls("/kv/import")) == 2
    s = router.obs.summary(prefix="edgemesh_fleet_")
    assert s['edgemesh_fleet_tiered_total{outcome="cache_hit"}'] == 1


def test_tiered_transfer_failure_falls_back_homogeneous_no_client_error():
    reg = _skewed_registry()
    tr = FakeTransport()
    tr.on("/kv/export", lambda u, p, h: (_ for _ in ()).throw(
        TransportError("export down")))
    tr.on("/generate", lambda u, p, h: (200, {"answer": "homog"}))
    router = _tiered_router(reg, tr)
    status, body, headers = router.handle_generate({"question": "z" * 100})
    assert status == 200 and body["answer"] == "homog"
    assert "X-Edgemesh-Tiered" not in headers
    s = router.obs.summary(prefix="edgemesh_fleet_")
    assert s['edgemesh_fleet_tiered_total{outcome="fallback_export"}'] == 1
    # Import-side failure too: export succeeds, import 500s, still no
    # client-visible error.
    tr2 = FakeTransport().on("/kv/export", _export_ok)
    tr2.on("/kv/import", lambda u, p, h: (500, {"error": "boom"}))
    tr2.on("/generate", lambda u, p, h: (200, {"answer": "homog"}))
    router2 = _tiered_router(_skewed_registry(), tr2)
    status, body, _ = router2.handle_generate({"question": "z" * 100})
    assert status == 200 and body["answer"] == "homog"
    s2 = router2.obs.summary(prefix="edgemesh_fleet_")
    assert s2['edgemesh_fleet_tiered_total{outcome="fallback_import"}'] == 1


def test_tiered_long_prompt_fallback_is_fully_homogeneous():
    # Regression: after a failed transfer the long prompt must NOT stay
    # excluded from the prefill tier — with the decode tier down, the
    # prefill-tier replica is the only one left and it must answer.
    reg = _skewed_registry()
    tr = FakeTransport()
    tr.on("/kv/export", lambda u, p, h: (_ for _ in ()).throw(
        TransportError("export down")))

    def generate(url, payload, headers):
        if "r2" in url:  # the prefill-tier replica
            return 200, {"answer": "prefill-tier-answered"}
        raise TransportError("decode tier down")

    tr.on("/generate", generate)
    router = _tiered_router(reg, tr, max_attempts=3)
    status, body, _ = router.handle_generate({"question": "q" * 100})
    assert status == 200 and body["answer"] == "prefill-tier-answered"


def test_tiered_outcome_fates_are_disjoint():
    # Every tiered-path request lands in exactly ONE outcome bucket, so
    # fallback ratios over the family stay honest.
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    router = _tiered_router(reg, tr)
    q = "d" * 100
    for _ in range(3):
        assert router.handle_generate({"question": q})[0] == 200
    s = router.obs.summary(prefix="edgemesh_fleet_")
    outcomes = {k: v for k, v in s.items()
                if k.startswith("edgemesh_fleet_tiered_total")}
    assert outcomes == {
        'edgemesh_fleet_tiered_total{outcome="tiered"}': 1.0,
        'edgemesh_fleet_tiered_total{outcome="cache_hit"}': 2.0,
    }


def test_tiered_empty_tier_degrades_to_homogeneous():
    reg = _registry("r0", "r1")
    reg.set_state("r1", "unhealthy")  # 1 healthy → no prefill tier
    tr = FakeTransport().on("/generate", lambda u, p, h: (200, {"answer": "homog"}))
    router = _tiered_router(reg, tr)
    status, body, _ = router.handle_generate({"question": "w" * 100})
    assert status == 200 and body["answer"] == "homog"
    assert tr.urls("/kv/export") == []


def test_tiered_short_prompts_stay_on_decode_tier_until_prefix_is_hot():
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    tr.on("/generate", lambda u, p, h: (200, {"answer": "homog"}))
    router = _tiered_router(reg, tr, prefill_threshold_chars=1000,
                            prefix_hot_after=2)
    q = "short shared prefix question"
    s1, b1, _ = router.handle_generate({"question": q})
    assert b1["answer"] == "homog"
    # Chatty traffic never lands on the prefill tier (routing hint).
    assert all("r2" not in u for u in tr.urls("/generate"))
    # Second sighting: the prefix is hot → export once, import, answer.
    s2, b2, h2 = router.handle_generate({"question": q})
    assert b2["answer"] == "imported" and h2.get("X-Edgemesh-Tiered") == "1"
    assert len(tr.urls("/kv/export")) == 1


def test_tiered_status_surfaces_membership_and_cache():
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    router = _tiered_router(reg, tr)
    router.handle_generate({"question": "x" * 100})
    st = router.status()
    assert st["tiers"]["prefill"] == ["r2"]
    assert sorted(st["tiers"]["decode"]) == ["r0", "r1"]
    assert st["tiers"]["kv_cache"]["entries"] == 1
    # Untiered routers surface null — single-replica deployments see the
    # pre-tiering /fleetz shape plus one explicit "off" marker.
    plain = FleetRouter(_registry("r0"), transport=FakeTransport(),
                        obs_registry=Registry())
    assert plain.status()["tiers"] is None


def test_note_digest_invalidates_tier_cache():
    reg = _registry("r0", "r1", "r2")
    tm = TierManager(refresh_s=1e9)  # cache would never expire on its own
    tr = FakeTransport()
    router = _tiered_router(reg, tr, tier_manager=tm)
    assert [r.rid for r in tm.assign(reg.replicas())["prefill"]] == ["r0"]
    _load(reg, "r2", 900.0, 1.0)
    router.note_digest("r2", reg.get("r2").load)  # the prober's hook
    assert [r.rid for r in tm.assign(reg.replicas())["prefill"]] == ["r2"]


# ---------------------------------------------------------------------------
# Non-hedgeable transfer endpoints (regression: hedging a transfer can
# double-import pages)
# ---------------------------------------------------------------------------


def _slow_then_ok(delay_s):
    def handler(url, payload, headers):
        time.sleep(delay_s)
        return 200, {"answer": "slow-ok", "kv": "QUJD", "bytes": 3}
    return handler


def test_kv_transfer_paths_never_hedge():
    reg = _registry("r0", "r1", "r2")
    tr = FakeTransport().on("/kv/", _slow_then_ok(0.15))
    router = FleetRouter(reg, transport=tr, obs_registry=Registry(),
                         rng=random.Random(0), hedge_after_s=0.02)
    for path in ("/kv/import", "/kv/export"):
        status, _, _ = router.handle_generate(
            {"question": "q", "kv": "QUJD"}, path=path)
        assert status == 200
    s = router.obs.summary(prefix="edgemesh_fleet_")
    hedged = sum(v for k, v in s.items()
                 if k.startswith("edgemesh_fleet_hedged_total"))
    assert hedged == 0
    # Exactly one attempt per request — no raced twin ever dispatched.
    assert len(tr.urls("/kv/")) == 2


def test_generate_still_hedges_under_same_config():
    # Control for the regression above: the SAME router/latency profile
    # hedges /generate, so the transfer exemption is the path, not a
    # broken hedge arm.
    reg = _registry("r0", "r1", "r2")
    tr = FakeTransport().on("/generate", _slow_then_ok(0.15))
    router = FleetRouter(reg, transport=tr, obs_registry=Registry(),
                         rng=random.Random(0), hedge_after_s=0.02)
    status, _, _ = router.handle_generate({"question": "q"})
    assert status == 200
    s = router.obs.summary(prefix="edgemesh_fleet_")
    hedged = sum(v for k, v in s.items()
                 if k.startswith("edgemesh_fleet_hedged_total{"))
    assert hedged >= 1


def test_transfer_latency_stays_out_of_hedge_estimator():
    reg = _skewed_registry()
    tr = FakeTransport().on("/kv/export", _export_ok).on("/kv/import", _import_ok)
    router = _tiered_router(reg, tr, hedge_auto=True)
    before = router._hedge_estimator.weight()
    router.handle_generate({"question": "x" * 100})
    # Two transfer attempts completed; neither fed the estimator.
    assert router._hedge_estimator.weight() == before


# ---------------------------------------------------------------------------
# Digest schema: the prefill/decode token EWMA split
# ---------------------------------------------------------------------------


def test_span_tracker_digest_splits_prefill_and_decode_volume():
    from edgemesh.obs.spans import SpanTracker

    tr = SpanTracker(Registry(), engine="continuous")
    d0 = tr.load_digest()
    assert d0["ewma_prefill_tokens"] is None
    assert d0["ewma_decode_tokens"] is None
    t = tr.submit(0)
    tr.admit_start(t)
    tr.admitted(t, prompt_tokens=100, prefill_tokens=80)
    tr.tokens(t, 5)
    tr.retire(t, status="ok")
    d = tr.load_digest()
    # The COMPUTED prefill (80, not the 100-token prompt) feeds the split:
    # imported/warm admissions must not inflate a replica's prefill share.
    assert d["ewma_prefill_tokens"] == 80.0
    assert d["ewma_decode_tokens"] == 5.0


# ---------------------------------------------------------------------------
# Gateway capability gate (stub — no engine, fast)
# ---------------------------------------------------------------------------


def test_gateway_kv_endpoints_refuse_without_paged_engine():
    import json as _json
    import urllib.error
    import urllib.request

    from edgemesh.serve import serve_rest

    class _StubEnsemble:
        qa_agents = ()
        refiner = None

        def answer(self, question):
            return {"answer": "x"}

    srv = serve_rest(_StubEnsemble(), host="127.0.0.1", port=0, block=False,
                     registry=Registry())
    try:
        url = f"http://127.0.0.1:{srv.server_address[1]}/kv/export"
        req = urllib.request.Request(
            url, data=_json.dumps({"question": "q"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400
        body = _json.load(exc.value)
        assert body["kind"] == "kv_capability"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Slow tier: real engines — export/import parity, structured 400s, and the
# zero-prefill-recompute span contract
# ---------------------------------------------------------------------------


def _agent(max_new=12):
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

    return build_agent(AgentSpec(
        role="qa", model=ModelSpec(),
        sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                repetition_penalty=1.0),
    ))


@pytest.mark.slow
@pytest.mark.parametrize("kv_backend", ["paged", "paged_int8"])
@pytest.mark.parametrize("ragged", [True, False], ids=["ragged", "segmented"])
def test_engine_export_import_greedy_parity(kv_backend, ragged):
    """The whole-stack correctness pin: a request admitted from an imported
    KV payload emits EXACTLY the tokens the same engine produces cold —
    both pool precisions, both admission modes."""
    from edgemesh.serve.continuous import ContinuousEngine

    agent = _agent()
    q = "where is the eiffel tower located in the city of paris exactly?"
    src = ContinuousEngine(agent, slots=2, chunk=8, kv_backend=kv_backend,
                           page_size=8, registry=Registry(), ragged=ragged)
    dst = ContinuousEngine(agent, slots=2, chunk=8, kv_backend=kv_backend,
                           page_size=8, registry=Registry(), ragged=ragged)
    try:
        direct = src.answer(q)
        exp = src.submit_export(q).result(timeout=600)
        assert exp["tokens"] == exp["prompt_tokens"] - 1
        assert exp["cached"] is False
        # The export cache serves repeats without re-prefilling.
        assert src.submit_export(q).result(timeout=600)["cached"] is True
        got = dst.answer(q, kv_import=exp["kv_bytes"])
        assert got["answer"] == direct["answer"]
        st_src, st_dst = src.stats(), dst.stats()
        assert st_src["kv_exports"] == 2
        assert st_dst["kv_imports"] == 1
        assert st_dst["kv_imported_tokens"] == exp["tokens"]
        s = dst.obs.registry.summary(prefix="edgemesh_")
        assert s['edgemesh_prefix_remote_hits_total{engine="continuous"}'] == 1
        key = 'edgemesh_kv_transfer_bytes_total{engine="continuous",direction="import"}'
        assert s[key] == len(exp["kv_bytes"])
    finally:
        src.close()
        dst.close()


@pytest.mark.slow
def test_engine_import_span_shows_zero_prefill_recompute(tmp_path):
    """The disagg acceptance contract at engine level: the imported
    request's prefill span computes exactly ONE token (the suffix) and
    carries kv_import_tokens — the span phase split that proves no prefill
    recompute happened."""
    from edgemesh.serve.continuous import ContinuousEngine
    from edgemesh.utils.tracing import JsonlLogger

    agent = _agent()
    q = "what is the tallest mountain on the european continent called?"
    span_log = tmp_path / "spans.jsonl"
    src = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, registry=Registry())
    dst = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, registry=Registry(),
                           span_log=span_log)
    try:
        exp = src.submit_export(q).result(timeout=600)
        dst.answer(q, kv_import=exp["kv_bytes"])
    finally:
        src.close()
        dst.close()
    recs = [r for r in JsonlLogger(span_log).read()
            if r.get("event") == "request_spans"]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["kv_import_tokens"] == exp["tokens"]
    prefill = [s for s in rec["spans"] if s["name"] == "prefill"]
    assert prefill and prefill[0]["prefill_tokens"] == 1
    assert prefill[0].get("shared_prefix_hit") is False


@pytest.mark.slow
def test_engine_partial_match_import_still_correct():
    """A payload exported for a DIFFERENT question still imports safely:
    the token match stops at the divergence point and the rest prefills
    locally — wrong-token KV can never graft onto a prompt."""
    from edgemesh.serve.continuous import ContinuousEngine

    agent = _agent()
    q_a = "shared leading words then question number one please?"
    q_b = "shared leading words then a different question two?"
    src = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, registry=Registry())
    dst = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, registry=Registry())
    try:
        direct = dst.answer(q_b)
        exp = src.submit_export(q_a).result(timeout=600)
        got = dst.answer(q_b, kv_import=exp["kv_bytes"])
        assert got["answer"] == direct["answer"]
        st = dst.stats()
        # A real (partial) match was consumed — more than zero, fewer than
        # the full payload.
        assert 0 < st["kv_imported_tokens"] < exp["tokens"]
    finally:
        src.close()
        dst.close()


@pytest.mark.slow
def test_gateway_kv_transfer_roundtrip_and_structured_400(tmp_path):
    import json as _json
    import urllib.error
    import urllib.request

    from edgemesh.agents.orchestrator import Ensemble
    from edgemesh.serve import serve_rest

    def post(url, payload, headers=None):
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        try:
            with urllib.request.urlopen(req, timeout=600) as r:
                return r.status, _json.load(r)
        except urllib.error.HTTPError as e:
            return e.code, _json.load(e)

    agent = _agent(max_new=8)
    srvA = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                      block=False, continuous=True, batch=2,
                      kv_backend="paged", kv_page_size=8, registry=Registry())
    srvB = serve_rest(Ensemble(qa_agents=[agent]), host="127.0.0.1", port=0,
                      block=False, continuous=True, batch=2,
                      kv_backend="paged", kv_page_size=8, registry=Registry())
    try:
        a = f"http://127.0.0.1:{srvA.server_address[1]}"
        b = f"http://127.0.0.1:{srvB.server_address[1]}"
        q = "what is the capital of france and where is it located?"
        st, direct = post(f"{a}/generate", {"question": q})
        assert st == 200
        st, exp = post(f"{a}/kv/export", {"question": q})
        assert st == 200 and exp["tokens"] == exp["prompt_tokens"] - 1
        st, got = post(f"{b}/kv/import", {"question": q, "kv": exp["kv"]})
        assert st == 200 and got["answer"] == direct["answer"]
        # Corrupted payload → structured 400, never a 500.
        st, err = post(f"{b}/kv/import", {"question": q, "kv": exp["kv"][:-8]})
        assert st == 400 and err["kind"] == "kv_wire"
        # Malformed base64 → 400.
        st, err = post(f"{b}/kv/import", {"question": q, "kv": "!!nope!!"})
        assert st == 400 and err["kind"] == "kv_wire"
        # Version mismatch → 400 naming the version.
        import base64
        raw = bytearray(base64.b64decode(exp["kv"]))
        raw[4] = 99
        st, err = post(f"{b}/kv/import", {
            "question": q, "kv": base64.b64encode(bytes(raw)).decode()})
        assert st == 400 and "version" in err["error"]
        # Expired propagated deadline → 504 before any model work.
        st, _ = post(f"{a}/kv/export", {"question": q},
                     headers={"X-Edgemesh-Deadline-S": "-1"})
        assert st == 504
        # Missing question → 400.
        st, _ = post(f"{a}/kv/export", {})
        assert st == 400
    finally:
        for s in (srvA, srvB):
            s.shutdown()
            if s.batcher is not None:
                s.batcher.close()
