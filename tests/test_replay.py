"""Trace-driven replay: span records → Workload.from_spans →
the unmodified OpenLoopGenerator (obs replay / loadgen --replay).

The round-trip fidelity contract (ISSUE satellite): a seeded workload
driven through a stub engine that records real span records must
reconstruct into a workload whose inter-arrival deltas, tenant shares,
session grouping, and length distribution match the original spec within
tolerance."""

import http.server
import json
import threading

import pytest

from edgemesh.loadgen.arrivals import ConstantProcess, PoissonProcess
from edgemesh.loadgen.generator import OpenLoopGenerator
from edgemesh.loadgen.workload import (
    LengthMix,
    ReplayWorkload,
    TenantSpec,
    Workload,
)
from edgemesh.obs.metrics import Registry
from edgemesh.obs.spans import SpanTracker
from edgemesh.serve.httputil import SESSION_HEADER, TENANT_HEADER
from edgemesh.utils.tracing import JsonlLogger


def _records(n=6, gap=0.5, tenant="chat", session=None, chars=120, gen=8,
             t0=1000.0):
    out = []
    for i in range(n):
        out.append({
            "event": "request_spans", "rid": i, "engine": "continuous",
            "status": "ok", "tenant": tenant,
            "session": session, "ts_submit": t0 + i * gap,
            "generated": gen, "prompt_chars": chars, "prompt_tokens": 10,
            "latency_s": 0.1, "slo_result": "good", "spans": [],
        })
    return out


def test_from_spans_rebuilds_arrivals_tenants_and_budgets():
    recs = _records(n=5, gap=0.75, tenant="chat", session="chat-0")
    wl = Workload.from_spans(recs)
    sched = wl.build_schedule()
    assert len(sched) == 5
    assert [round(r.at_s, 3) for r in sched] == [0.0, 0.75, 1.5, 2.25, 3.0]
    assert all(r.tenant == "chat" for r in sched)
    # Recorded session id survives; prompts share the session prefix.
    assert all(r.session == "chat-0" for r in sched)
    prefixes = {r.prompt.split("]")[0] for r in sched}
    assert len(prefixes) == 1
    assert [r.turn for r in sched] == [1, 2, 3, 4, 5]
    # Prompt length tracks the recorded prompt_chars (word-pad overshoot;
    # the stable session prefix sets a ~70-char floor, same as the
    # original generator's own prompts).
    for r in sched:
        assert 120 <= len(r.prompt) <= 145
    # Budget: recorded generated count rides as max_new.
    assert all(r.max_new == 8 for r in sched)
    wl2 = Workload.from_spans(recs, include_max_new=False)
    assert all(r.max_new is None for r in wl2.build_schedule())


def test_from_spans_speed_scales_and_is_deterministic():
    recs = _records(n=4, gap=1.0)
    fast = Workload.from_spans(recs, speed=2.0)
    assert [round(r.at_s, 3) for r in fast.build_schedule()] == [
        0.0, 0.5, 1.0, 1.5]
    a = [r.prompt for r in Workload.from_spans(recs).build_schedule()]
    b = [r.prompt for r in Workload.from_spans(recs).build_schedule()]
    assert a == b  # seeded from the session id: byte-identical rebuilds


def test_from_spans_synthesizes_sessions_for_pre_session_logs():
    recs = _records(n=6, session=None)
    for r in recs:
        r.pop("session")
    wl = Workload.from_spans(recs, sessions_per_tenant=2)
    sessions = {r.session for r in wl.build_schedule()}
    assert sessions == {"chat-r0", "chat-r1"}


def test_from_spans_pre_prompt_chars_records_fall_back_to_tokens():
    recs = _records(n=2)
    for r in recs:
        r.pop("prompt_chars")
        r["prompt_tokens"] = 30
    wl = Workload.from_spans(recs)
    for r in wl.build_schedule():
        assert 120 <= len(r.prompt) <= 145  # 30 tokens x 4 chars


def test_from_spans_rejects_empty_and_bad_speed():
    with pytest.raises(ValueError, match="nothing to replay"):
        Workload.from_spans([{"event": "pool_reset"}])
    with pytest.raises(ValueError, match="speed"):
        Workload.from_spans(_records(), speed=0)


def test_replay_workload_doc_round_trip():
    wl = Workload.from_spans(_records(n=3))
    doc = wl.to_doc()
    assert doc["kind"] == "replay_workload"
    back = ReplayWorkload.from_doc(json.loads(json.dumps(doc)))
    assert [r.__dict__ for r in back.build_schedule()] == [
        r.__dict__ for r in wl.build_schedule()]
    with pytest.raises(ValueError, match="replay workload"):
        ReplayWorkload.from_doc({"kind": "load_report"})


# ---------------------------------------------------------------------------
# Round-trip fidelity: spec → generator → span log → from_spans → ~spec
# ---------------------------------------------------------------------------


def _stub_engine_target(tracker):
    """A generator target that behaves like the serving stack's span seam:
    every call produces one full span record with the propagated tenant +
    session identity and the real prompt length — no model, no sleep."""
    lock = threading.Lock()
    rid = [0]

    def call(payload, headers):
        with lock:
            rid[0] += 1
            my = rid[0]
        tr = tracker.submit(my, tenant=headers.get(TENANT_HEADER),
                            session=headers.get(SESSION_HEADER))
        tracker.admit_start(tr)
        tracker.admitted(tr, prompt_tokens=len(payload["question"]) // 4,
                         prompt_chars=len(payload["question"]))
        tracker.tokens(tr, payload.get("max_new") or 4)
        tracker.retire(tr, status="ok")
        return 200, {"answer": "ok"}

    return call


def test_round_trip_fidelity_through_stub_engine(tmp_path):
    spec = Workload([
        TenantSpec(name="chat", arrival=PoissonProcess(12.0, seed=7),
                   lane="interactive",
                   prompt_mix=LengthMix(median=60, sigma=0.4, lo=20, hi=200),
                   sessions=2, turns_mean=1e9, send_max_new=True),
        TenantSpec(name="bulk", arrival=ConstantProcess(4.0), lane="batch",
                   prompt_mix=LengthMix(median=120, sigma=0.0),
                   sessions=1, turns_mean=1e9, send_max_new=True),
    ], seed=3)
    schedule = spec.build_schedule(2.0)
    tracker = SpanTracker(Registry(), tmp_path / "spans.jsonl")
    gen = OpenLoopGenerator(_stub_engine_target(tracker), schedule,
                            slo_latency_s=1.0, duration_s=2.0)
    report = gen.run()
    assert report["ok"] == len(schedule)

    records = JsonlLogger(tmp_path / "spans.jsonl").read()
    wl = Workload.from_spans(records)
    replay = wl.build_schedule()
    assert len(replay) == len(schedule)

    # Tenant shares: exact — every scheduled request was recorded tagged.
    def shares(reqs):
        return {t: sum(1 for r in reqs if r.tenant == t)
                for t in ("chat", "bulk")}

    assert shares(replay) == shares(schedule)

    # Inter-arrival structure: the replay schedule tracks the original
    # offsets within the generator's own launch skew (plus sub-ms span
    # bookkeeping) — both schedules sorted, compared pointwise.
    orig = sorted(r.at_s for r in schedule)
    got = sorted(r.at_s for r in replay)
    skew = max(report["max_launch_skew_s"], 0.05)
    worst = max(abs(a - b) for a, b in zip(orig, got))
    assert worst <= skew + 0.25, (worst, skew)

    # Session grouping: the recorded session ids survive verbatim, so the
    # per-tenant session counts match the spec exactly.
    orig_sessions = {r.session for r in schedule}
    replay_sessions = {r.session for r in replay}
    assert replay_sessions == orig_sessions

    # Length distribution: prompt_chars was recorded exactly, and the
    # rebuilt prompts pad to it — means match within 10%.
    def mean_len(reqs, tenant):
        xs = [len(r.prompt) for r in reqs if r.tenant == tenant]
        return sum(xs) / len(xs)

    for tenant in ("chat", "bulk"):
        a, b = mean_len(schedule, tenant), mean_len(replay, tenant)
        assert abs(a - b) / a < 0.10, (tenant, a, b)

    # Output budgets: the recorded generated counts ride back as max_new.
    orig_budgets = sorted(r.max_new for r in schedule)
    got_budgets = sorted(r.max_new for r in replay)
    assert got_budgets == orig_budgets


# ---------------------------------------------------------------------------
# CLI: obs replay → workload.json → loadgen --replay
# ---------------------------------------------------------------------------


@pytest.fixture()
def stub_gateway():
    """Minimal /generate endpoint that answers 200 and counts requests."""
    seen = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            seen.append((body, dict(self.headers)))
            payload = json.dumps({"answer": "ok", "generated": 2}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}", seen
    finally:
        srv.shutdown()


def test_obs_replay_cli_then_loadgen_replay_drives_it(tmp_path, stub_gateway,
                                                      capsys):
    from edgemesh.loadgen.cli import main as loadgen_main
    from edgemesh.obs.cli import main as obs_main

    url, seen = stub_gateway
    log = JsonlLogger(tmp_path / "spans.jsonl")
    for rec in _records(n=4, gap=0.1, session="chat-0"):
        log.log(rec.pop("event"), **rec)
    out = tmp_path / "workload.json"
    # Directory acceptance + --speed ride the same invocation.
    rc = obs_main(["replay", str(tmp_path), "--out", str(out),
                   "--speed", "4.0", "--no-max-new"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["requests"] == 4 and summary["tenants"] == ["chat"]
    doc = json.loads(out.read_text())
    assert doc["kind"] == "replay_workload" and doc["speed"] == 4.0
    assert doc["requests"][-1]["at_s"] == pytest.approx(0.075)

    rc = loadgen_main(["--url", f"{url}/generate", "--replay", str(out)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["scheduled"] == 4 and report["ok"] == 4
    assert report["replayed_from"] == str(out)
    assert report["tenants"]["chat"]["ok"] == 4
    # The generator sent the reconstructed identity headers.
    _, headers = seen[0]
    assert headers.get(TENANT_HEADER) == "chat"
    assert headers.get(SESSION_HEADER) == "chat-0"


def test_obs_replay_cli_errors(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    out = tmp_path / "w.json"
    assert obs_main(["replay", str(tmp_path / "nope.jsonl"),
                     "--out", str(out)]) == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert obs_main(["replay", str(empty), "--out", str(out)]) == 1
    capsys.readouterr()


def test_loadgen_replay_missing_and_malformed_docs(tmp_path, capsys):
    from edgemesh.loadgen.cli import main as loadgen_main

    assert loadgen_main(["--url", "http://x/generate", "--replay",
                         str(tmp_path / "nope.json")]) == 2
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"kind": "something_else"}))
    assert loadgen_main(["--url", "http://x/generate", "--replay",
                         str(bad)]) == 2
    capsys.readouterr()
