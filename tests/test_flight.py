"""The incident observatory: flight recorder ring, anomaly triggers,
fleet incident propagation, and postmortem assembly (obs/flight.py,
obs/anomaly.py, fleet wiring). Pure host-side — no jax, no engines; the
live serving path rides the slow-tier incident e2e."""

import json
import threading
import time

import pytest

from edgemesh.obs.anomaly import (
    AnomalyMonitor,
    CompileStormDetector,
    ErrorSpikeDetector,
    QueueCollapseDetector,
    SloBurstDetector,
)
from edgemesh.obs.flight import (
    DUMP_EVENT,
    SNAPSHOT_EVENT,
    FlightRecorder,
    assemble_incident,
)
from edgemesh.obs.metrics import Registry
from edgemesh.obs.spans import SPAN_RECORD_EVENT, SpanTracker, replay_spans
from edgemesh.utils.tracing import JsonlLogger


# ---------------------------------------------------------------------------
# FlightRecorder: bounded ring + dump schema
# ---------------------------------------------------------------------------


def test_ring_is_bounded_and_dump_header_counts_drops(tmp_path):
    reg = Registry()
    rec = FlightRecorder(capacity=4, registry=reg, replica="r0")
    for i in range(10):
        rec.record(SPAN_RECORD_EVENT, {"rid": i})
    assert len(rec) == 4
    assert [r["rid"] for r in rec.peek()] == [6, 7, 8, 9]
    out = rec.dump(tmp_path, "inc-1", kind="manual")
    records = JsonlLogger(out).read()
    header = records[0]
    assert header["event"] == DUMP_EVENT
    assert header["incident_id"] == "inc-1"
    assert header["kind"] == "manual"
    assert header["replica"] == "r0"
    assert header["records"] == 4 and header["capacity"] == 4
    assert header["dropped"] == 6
    assert [r["rid"] for r in records[1:]] == [6, 7, 8, 9]
    # Metrics: appends counted by event, dumps by kind.
    s = reg.summary()
    assert s['edgemesh_flight_records_total{event="request_spans"}'] == 10
    assert s['edgemesh_flight_dumps_total{kind="manual"}'] == 1
    assert s["edgemesh_flight_ring_records"] == 4


def test_dump_preserves_original_timestamps_and_redump_replaces(tmp_path):
    rec = FlightRecorder(capacity=8, registry=Registry(), replica="r0")
    rec.record(SPAN_RECORD_EVENT, {"ts": 123.5, "rid": 0})
    out = rec.dump(tmp_path, "inc-1", kind="slo_burst")
    assert JsonlLogger(out).read()[1]["ts"] == 123.5
    # A re-trigger re-dumps the fuller ring over the same file: no dupes.
    rec.record(SPAN_RECORD_EVENT, {"ts": 124.0, "rid": 1})
    out2 = rec.dump(tmp_path, "inc-1", kind="slo_burst")
    assert out2 == out
    records = JsonlLogger(out).read()
    assert [r.get("rid") for r in records[1:]] == [0, 1]


def test_snapshot_rides_the_record_path_on_interval():
    digests = iter([{"queue_depth": 3}, {"queue_depth": 7}])
    rec = FlightRecorder(capacity=16, registry=Registry(), replica="r0",
                         snapshot_source=lambda: next(digests),
                         snapshot_interval_s=0.0)
    rec.record(SPAN_RECORD_EVENT, {"rid": 0})
    snaps = [r for r in rec.peek() if r["event"] == SNAPSHOT_EVENT]
    assert len(snaps) == 1 and snaps[0]["queue_depth"] == 3


def test_span_tracker_feeds_flight_even_when_sampled_out(tmp_path):
    """trace_sample=0 writes NO span JSONL — but the flight ring still gets
    every record at full fidelity, and a dump of the ring replays through
    the standard offline tooling."""
    reg = Registry()
    flight = FlightRecorder(capacity=16, registry=reg, replica="r0")
    tracker = SpanTracker(reg, tmp_path / "spans.jsonl",
                          trace_sample=0.0, flight=flight)
    for rid in range(3):
        tr = tracker.submit(rid, tenant="chat", session=f"chat-{rid % 2}")
        tracker.admit_start(tr)
        tracker.admitted(tr, prompt_tokens=8, prompt_chars=30)
        tracker.tokens(tr, 4)
        tracker.retire(tr, status="ok")
    assert not (tmp_path / "spans.jsonl").exists()  # sampled out
    ring = [r for r in flight.peek() if r["event"] == SPAN_RECORD_EVENT]
    assert len(ring) == 3
    assert ring[0]["tenant"] == "chat" and ring[0]["session"] == "chat-0"
    assert ring[0]["prompt_chars"] == 30
    # The dump is a standard span log: obs summary/replay machinery works.
    out = flight.dump(tmp_path, "inc-2", kind="manual")
    offline = replay_spans(JsonlLogger(out).read()).summary()
    assert offline['edgemesh_requests_submitted_total{engine="continuous"}'] == 3


# ---------------------------------------------------------------------------
# Detectors
# ---------------------------------------------------------------------------


def test_slo_burst_needs_a_healthy_baseline_before_firing():
    det = SloBurstDetector(window=8, min_misses=4, miss_ratio=0.5,
                           burst_factor=2.0, min_weight=4.0)
    # Uniform misses from cold start: slow, not degraded — never fires.
    assert not any(det.observe("ttft", 1.0) for _ in range(20))
    det2 = SloBurstDetector(window=8, min_misses=4, miss_ratio=0.5,
                            burst_factor=2.0, min_weight=4.0)
    # Healthy traffic arms the baseline...
    for _ in range(16):
        assert not det2.observe("good", 0.05)
    # ...then a burst of misses far outside it fires.
    fired = [det2.observe("ttft", 1.5) for _ in range(8)]
    assert any(fired)


def test_slo_burst_misses_without_latency_fire_once_armed():
    det = SloBurstDetector(window=8, min_misses=4, miss_ratio=0.5,
                           min_weight=4.0)
    for _ in range(16):
        det.observe("good", 0.05)
    assert any(det.observe("error", None) for _ in range(6))


def test_queue_collapse_fires_once_per_streak():
    det = QueueCollapseDetector(depth=4, consecutive=3)
    fires = [det.observe(d) for d in (5, 5, 5, 5, 5)]
    assert fires == [False, False, True, False, False]
    det.observe(0)  # streak reset
    assert [det.observe(9) for d in range(3)] == [False, False, True]


def test_error_spike_counts_within_window_only():
    det = ErrorSpikeDetector(count=3, window_s=10.0)
    assert not det.observe("error", now=0.0)
    assert not det.observe("ok", now=1.0)
    assert not det.observe("error", now=2.0)
    assert det.observe("error", now=3.0)
    # Old errors age out of the window.
    det2 = ErrorSpikeDetector(count=3, window_s=10.0)
    det2.observe("error", now=0.0)
    det2.observe("error", now=1.0)
    assert not det2.observe("error", now=20.0)


def test_compile_storm_exempts_warmup_then_fires():
    det = CompileStormDetector(count=2, window_s=60.0)
    assert not det.observe(now=0.0)  # warmup compile: free
    assert not det.observe(now=1.0)
    assert det.observe(now=2.0)


# ---------------------------------------------------------------------------
# AnomalyMonitor: trigger → dump, cooldown, propagation adoption
# ---------------------------------------------------------------------------


def test_trigger_dumps_counts_and_cooldown_dedupes(tmp_path):
    reg = Registry()
    flight = FlightRecorder(capacity=8, registry=reg, replica="r0")
    flight.record(SPAN_RECORD_EVENT, {"rid": 0})
    mon = AnomalyMonitor(flight, tmp_path, registry=reg, cooldown_s=60.0)
    rec = mon.trigger("slo_burst", detail={"queue_depth": 9})
    assert rec is not None and rec["kind"] == "slo_burst"
    dump = tmp_path / rec["id"] / "flight-r0.jsonl"
    assert dump.exists()
    header = JsonlLogger(dump).read()[0]
    assert header["kind"] == "slo_burst" and header["queue_depth"] == 9
    # Cooldown: a second trigger still counts but does not dump.
    assert mon.trigger("error_spike") is None
    s = reg.summary()
    assert s['edgemesh_anomaly_triggers_total{kind="slo_burst"}'] == 1
    assert s['edgemesh_anomaly_triggers_total{kind="error_spike"}'] == 1
    assert s['edgemesh_flight_dumps_total{kind="slo_burst"}'] == 1
    assert mon.last_incident()["id"] == rec["id"]


def test_note_incident_bypasses_cooldown_and_is_idempotent(tmp_path):
    reg = Registry()
    flight = FlightRecorder(capacity=8, registry=reg, replica="r1")
    flight.record(SPAN_RECORD_EVENT, {"rid": 0})
    mon = AnomalyMonitor(flight, tmp_path, registry=reg, cooldown_s=3600.0)
    assert mon.trigger("slo_burst") is not None
    # A sibling replica's incident arrives mid-cooldown: must still dump.
    rec = mon.note_incident("inc-remote-1",
                            detail={"origin_kind": "slo_burst",
                                    "source": "replica-0"})
    assert rec is not None
    assert (tmp_path / "inc-remote-1" / "flight-r1.jsonl").exists()
    # Idempotent per id: the router re-observes digests every probe tick.
    assert mon.note_incident("inc-remote-1") is None
    s = reg.summary()
    assert s['edgemesh_anomaly_triggers_total{kind="propagated"}'] == 1


def test_monitor_on_retire_wires_slo_burst_through_tracker(tmp_path):
    """The real seam: SpanTracker.retire → monitor.on_retire → dump."""
    reg = Registry()
    flight = FlightRecorder(capacity=64, registry=reg, replica="r0")
    mon = AnomalyMonitor(
        flight, tmp_path, registry=reg,
        slo_burst=SloBurstDetector(window=8, min_misses=4, miss_ratio=0.5,
                                   burst_factor=1.5, min_weight=4.0),
        cooldown_s=0.0)
    tracker = SpanTracker(reg, engine="continuous", flight=flight)
    tracker.anomaly = mon

    def run_one(rid, slow):
        tr = tracker.submit(rid)
        tracker.admit_start(tr)
        tracker.admitted(tr)
        # Fake the timings by editing the trace edges: healthy requests
        # retire instantly; degraded ones look seconds old at retire.
        if slow:
            tr.t_submit -= 30.0
            tr.t_first_token = None
        else:
            tr.t_first_token = tr.t_submit + 0.01
        tracker.tokens(tr, 2)
        tracker.retire(tr, status="ok")

    for rid in range(16):
        run_one(rid, slow=False)
    assert mon.incidents() == []
    for rid in range(16, 26):
        run_one(rid, slow=True)
    incs = mon.incidents()
    assert incs and incs[0]["kind"] == "slo_burst"
    assert (tmp_path / incs[0]["id"] / "flight-r0.jsonl").exists()


# ---------------------------------------------------------------------------
# Fleet propagation: router fan-out + prober callback
# ---------------------------------------------------------------------------


class _StubTransport:
    """Records post_json calls; answers get_json from a canned table."""

    def __init__(self, readyz_body=None):
        self.posts = []
        self.readyz_body = readyz_body or {"ready": True, "inflight": 0}
        self._lock = threading.Lock()

    def post_json(self, url, payload, timeout_s=None, headers=None):
        with self._lock:
            self.posts.append((url, payload, timeout_s))
        return 200, {"accepted": True}

    def get_json(self, url, timeout_s=None, headers=None):
        return 200, dict(self.readyz_body)


def _wait_for(predicate, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def test_router_observe_incident_broadcasts_dedupes_and_surfaces(tmp_path):
    from edgemesh.fleet import FleetRouter, ReplicaRegistry

    reg = ReplicaRegistry([("r0", "http://h0"), ("r1", "http://h1"),
                           ("r2", "http://h2")])
    transport = _StubTransport()
    obs = Registry()
    router = FleetRouter(reg, transport=transport, obs_registry=obs,
                         span_log=tmp_path / "router.jsonl")
    incident = {"id": "inc-abc", "kind": "slo_burst", "ts": 1234.0}
    assert router.observe_incident("r0", incident) is True
    # Fan-out (on its own thread) reaches every OTHER replica's /incident.
    assert _wait_for(lambda: len(transport.posts) == 2)
    urls = sorted(u for u, _, _ in transport.posts)
    assert urls == ["http://h1/incident", "http://h2/incident"]
    for _, payload, timeout_s in transport.posts:
        assert payload == {"id": "inc-abc", "kind": "slo_burst",
                           "source": "r0"}
        assert timeout_s is not None  # EM502 dial-timeout semantics, live
    # Dedupe: the prober re-observes the same digest every tick.
    assert router.observe_incident("r0", incident) is False
    assert len(transport.posts) == 2
    # Surfaced on /fleetz + counted + logged for the postmortem timeline.
    status = router.status()
    assert status["incidents"][0]["id"] == "inc-abc"
    assert status["incidents"][0]["source"] == "r0"
    assert obs.summary()[
        'edgemesh_fleet_incidents_total{kind="slo_burst"}'] == 1
    logged = JsonlLogger(tmp_path / "router.jsonl").read()
    assert any(r["event"] == "incident" and r["id"] == "inc-abc"
               for r in logged)


def test_prober_invokes_incident_callback_from_digest():
    from edgemesh.fleet import HealthProber, ReplicaRegistry

    incident = {"id": "inc-xyz", "kind": "queue_collapse", "ts": 1.0}
    transport = _StubTransport(readyz_body={
        "ready": True, "inflight": 0,
        "load": {"queue_depth": 40, "incident": incident},
    })
    reg = ReplicaRegistry([("r0", "http://h0")])
    seen = []
    prober = HealthProber(reg, transport=transport, obs_registry=Registry(),
                          on_incident=lambda rid, inc: seen.append((rid, inc)))
    prober.probe_once()
    assert seen == [("r0", incident)]
    # A digest without the field (pre-flight replicas) is simply quiet.
    transport.readyz_body = {"ready": True, "inflight": 0,
                             "load": {"queue_depth": 0}}
    prober.probe_once()
    assert len(seen) == 1


# ---------------------------------------------------------------------------
# Postmortem assembly
# ---------------------------------------------------------------------------


def _span_record(rid, replica, ts_submit, queue_s, decode_s, tenant="chat",
                 slo_result="good", trace_id=None):
    t0 = 100.0  # perf-counter anchor; ts_submit is the wall anchor
    prefill_s = 0.01
    spans = [
        {"name": "queued", "t0": t0, "t1": t0 + queue_s},
        {"name": "prefill", "t0": t0 + queue_s,
         "t1": t0 + queue_s + prefill_s},
        {"name": "decode", "t0": t0 + queue_s + prefill_s,
         "t1": t0 + queue_s + prefill_s + decode_s, "tokens": 4},
        {"name": "retire", "t0": t0 + queue_s + prefill_s + decode_s,
         "t1": t0 + queue_s + prefill_s + decode_s},
    ]
    return {
        "rid": rid, "engine": "continuous", "status": "ok",
        "tenant": tenant, "session": f"{tenant}-0",
        "trace_id": trace_id or f"{replica}-{rid:04d}",
        "ts_submit": ts_submit, "generated": 4, "segments": 1,
        "queue_s": queue_s, "prefill_s": prefill_s,
        "latency_s": queue_s + prefill_s + decode_s,
        "slo_result": slo_result, "spans": spans,
    }


def test_assemble_incident_marks_window_and_names_degraded_replica(tmp_path):
    trigger_wall = 1000.0
    reg = Registry()
    rings = {}
    for rid in ("fast-1", "fast-2", "slow"):
        rings[rid] = FlightRecorder(capacity=64, registry=reg, replica=rid)
    # Before the window: everyone healthy.
    for i, rid in enumerate(("fast-1", "fast-2", "slow")):
        rings[rid].record(SPAN_RECORD_EVENT, _span_record(
            i, rid, trigger_wall - 60.0, queue_s=0.01, decode_s=0.05))
    # During the window: the slow replica's requests drown in queue+decode.
    for i in range(4):
        rings["slow"].record(SPAN_RECORD_EVENT, _span_record(
            10 + i, "slow", trigger_wall - 2.0 + i * 0.5,
            queue_s=2.0, decode_s=3.0, slo_result="ttft"))
        rings["fast-1"].record(SPAN_RECORD_EVENT, _span_record(
            20 + i, "fast-1", trigger_wall - 2.0 + i * 0.5,
            queue_s=0.01, decode_s=0.05))
    # After: recovery.
    rings["fast-2"].record(SPAN_RECORD_EVENT, _span_record(
        30, "fast-2", trigger_wall + 30.0, queue_s=0.01, decode_s=0.05))
    # The slow replica fired locally; the others dumped via propagation.
    incident_id = "inc-test-1"
    rings["slow"].dump(tmp_path, incident_id, kind="slo_burst",
                       trigger_ts=trigger_wall)
    for rid in ("fast-1", "fast-2"):
        rings[rid].dump(tmp_path, incident_id, kind="propagated",
                        trigger_ts=trigger_wall + 1.0)

    paths = sorted((tmp_path / incident_id).glob("*.jsonl"))
    assert len(paths) == 3
    doc = assemble_incident(paths, window_s=10.0)
    assert doc["incident_id"] == incident_id
    # The LOCAL trigger anchors the window, not the propagated dumps.
    assert doc["trigger_ts"] == trigger_wall
    assert doc["replicas"] == ["fast-1", "fast-2", "slow"]
    assert set(doc["kinds"]) == {"slo_burst", "propagated"}
    # Phases: healthy before, degraded during, recovered after.
    assert doc["phases"]["before"]["goodput_ratio"] == 1.0
    assert doc["phases"]["during"]["goodput_ratio"] == 0.5
    assert doc["phases"]["after"]["goodput_ratio"] == 1.0
    assert doc["phases"]["during"]["tenants"]["chat"]["classified"] == 8
    # The trigger-window critical path names the degraded replica.
    cp = doc["critical_path"]
    assert cp["slowest_replica"] == "slow"
    assert cp["window"]["slow"]["queue_s"] > cp["window"]["fast-1"]["queue_s"]
    assert cp["window"]["slow"]["decode_s"] > 1.0
    assert doc["timeline"], "dump headers must land on the timeline"


def test_obs_incident_cli_and_directory_expansion(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    reg = Registry()
    ring = FlightRecorder(capacity=8, registry=reg, replica="r0")
    ring.record(SPAN_RECORD_EVENT, _span_record(
        0, "r0", 500.0, queue_s=0.5, decode_s=1.0, slo_result="ttft"))
    ring.dump(tmp_path / "incident", "inc-cli", kind="error_spike",
              trigger_ts=500.5)
    rc = obs_main(["incident", str(tmp_path / "incident" / "inc-cli")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["incident_id"] == "inc-cli"
    assert doc["critical_path"]["slowest_replica"] == "r0"
    # A directory with no dump header is a usage answer, exit 1.
    (tmp_path / "empty").mkdir()
    (tmp_path / "empty" / "x.jsonl").write_text("")
    assert obs_main(["incident", str(tmp_path / "empty")]) == 1
    capsys.readouterr()
    # Missing path: usage error.
    assert obs_main(["incident", str(tmp_path / "nope")]) == 2


def test_obs_summary_and_trace_accept_directories(tmp_path, capsys):
    """Satellite: a DIRECTORY of logs works wherever a span log did —
    incident dump dirs make explicit file lists untenable."""
    from edgemesh.obs.cli import main as obs_main

    d = tmp_path / "logs"
    d.mkdir()
    for i, name in enumerate(("a.jsonl", "b.jsonl")):
        log = JsonlLogger(d / name)
        rec = _span_record(i, "r0", 500.0 + i, queue_s=0.1, decode_s=0.2,
                           trace_id=f"{'ab'[i] * 32}")
        log.log(SPAN_RECORD_EVENT, **rec)
    assert obs_main(["summary", str(d)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["requests"] == 2
    assert obs_main(["tail", str(d)]) == 0
    capsys.readouterr()
    assert obs_main(["prom", str(d)]) == 0
    capsys.readouterr()
    # trace --logs with the directory: assembles from the expanded files.
    assert obs_main(["trace", "a" * 32, "--logs", str(d)]) == 0
    tree = json.loads(capsys.readouterr().out)
    assert tree["trace_id"] == "a" * 32 and tree["tree"] is not None
