"""utils/compat.py drift shims: exercise BOTH sides of every shim via
monkeypatched signatures, so the branch this jax doesn't take is still
tested (the pre-drift branches were untested before — a compat bug on the
other side of a drift would ship silently and resurface as the seed's
seven ring-attention failures).

No devices and no tracing: every fake captures its kwargs and returns a
sentinel; what's under test is the SHIM's dispatch — which spelling it
calls and how it maps the ``check_vma``/``check_rep`` kwarg."""

import inspect

import jax

from edgemesh.utils import compat


# ---------------------------------------------------------------------------
# shard_map: jax.shard_map (check_vma) / jax.shard_map (check_rep) /
# jax.experimental.shard_map (check_rep) — three drift states
# ---------------------------------------------------------------------------


def test_shard_map_modern_spelling_with_check_vma(monkeypatch):
    calls = {}

    def fake_sm(f, *, mesh, in_specs, out_specs, check_vma=True):
        calls.update(f=f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=check_vma)
        return "mapped"

    monkeypatch.setattr(compat.jax, "shard_map", fake_sm, raising=False)
    out = compat.shard_map(abs, mesh="m", in_specs=(1,), out_specs=(2,),
                           check_vma=False)
    assert out == "mapped"
    assert calls["check_vma"] is False and calls["mesh"] == "m"
    assert calls["f"] is abs


def test_shard_map_modern_spelling_with_check_rep_keying(monkeypatch):
    # The move to jax.shard_map and the kwarg rename were SEPARATE drift
    # events: a jax.shard_map whose signature still says check_rep must be
    # called with check_rep — keyed on the signature, not the location.
    calls = {}

    def fake_sm(f, *, mesh, in_specs, out_specs, check_rep=True):
        calls.update(check_rep=check_rep)
        return "mapped"

    assert "check_rep" in inspect.signature(fake_sm).parameters
    monkeypatch.setattr(compat.jax, "shard_map", fake_sm, raising=False)
    out = compat.shard_map(abs, mesh="m", in_specs=(), out_specs=(),
                           check_vma=False)
    assert out == "mapped" and calls["check_rep"] is False


def test_shard_map_experimental_fallback_maps_to_check_rep(monkeypatch):
    # Pre-drift jax: no jax.shard_map at all — the shim falls through to
    # the experimental module, mapping check_vma onto check_rep.
    import jax.experimental.shard_map as exp_mod

    calls = {}

    def fake_sm(f, *, mesh, in_specs, out_specs, check_rep=True):
        calls.update(check_rep=check_rep)
        return "exp-mapped"

    monkeypatch.setattr(compat.jax, "shard_map", None, raising=False)
    monkeypatch.setattr(exp_mod, "shard_map", fake_sm)
    out = compat.shard_map(abs, mesh="m", in_specs=(), out_specs=(),
                           check_vma=False)
    assert out == "exp-mapped" and calls["check_rep"] is False
    # Default check_vma=True flows through as check_rep=True.
    compat.shard_map(abs, mesh="m", in_specs=(), out_specs=())
    assert calls["check_rep"] is True


# ---------------------------------------------------------------------------
# axis_size: lax.axis_size / axis-env fallback
# ---------------------------------------------------------------------------


def test_axis_size_modern_spelling(monkeypatch):
    calls = {}

    def fake_axis_size(name):
        calls["name"] = name
        return 8

    monkeypatch.setattr(compat.lax, "axis_size", fake_axis_size,
                        raising=False)
    assert compat.axis_size("tp") == 8
    assert calls["name"] == "tp"


def test_axis_size_axis_env_fallback(monkeypatch):
    import jax._src.core as core

    class _Env:
        def axis_size(self, name):
            assert name == "sp"
            return 4

    monkeypatch.setattr(compat.lax, "axis_size", None, raising=False)
    monkeypatch.setattr(core, "get_axis_env", lambda: _Env(), raising=False)
    assert compat.axis_size("sp") == 4


# ---------------------------------------------------------------------------
# pcast: lax.pcast / pre-vma identity
# ---------------------------------------------------------------------------


def test_pcast_modern_spelling(monkeypatch):
    calls = {}

    def fake_pcast(x, axis_name, *, to):
        calls.update(axis_name=axis_name, to=to)
        return ("cast", x)

    monkeypatch.setattr(compat.lax, "pcast", fake_pcast, raising=False)
    out = compat.pcast(3, "sp", to="varying")
    assert out == ("cast", 3)
    assert calls == {"axis_name": "sp", "to": "varying"}


def test_pcast_pre_vma_identity(monkeypatch):
    # No vma type system → no cast exists; the identity must be EXACT
    # (the enclosing check_rep machinery tracks replication on its own).
    monkeypatch.setattr(compat.lax, "pcast", None, raising=False)
    sentinel = object()
    assert compat.pcast(sentinel, "sp") is sentinel


# ---------------------------------------------------------------------------
# register_compile_event_listener: present / kwarg-growing / absent
# ---------------------------------------------------------------------------


class _FakeMonitoring:
    def __init__(self):
        self.listener = None

    def register_event_duration_secs_listener(self, fn):
        self.listener = fn


def test_compile_listener_adapter_swallows_new_kwargs(monkeypatch):
    fake = _FakeMonitoring()
    monkeypatch.setattr(compat.jax, "monitoring", fake, raising=False)
    seen = []
    assert compat.register_compile_event_listener(
        lambda name, dur: seen.append((name, dur))
    ) is True
    # Newer jax passes extra keyword metadata — the adapter must drop it.
    fake.listener("/jax/core/compile/backend_compile", 1.5, extra="meta")
    assert seen == [("/jax/core/compile/backend_compile", 1.5)]


def test_compile_listener_degrades_without_monitoring(monkeypatch):
    monkeypatch.setattr(compat.jax, "monitoring", None, raising=False)
    assert compat.register_compile_event_listener(lambda n, d: None) is False


def test_compile_listener_degrades_without_register_hook(monkeypatch):
    class _NoHook:
        pass

    monkeypatch.setattr(compat.jax, "monitoring", _NoHook(), raising=False)
    assert compat.register_compile_event_listener(lambda n, d: None) is False


# ---------------------------------------------------------------------------
# The shims against the REAL installed jax (whichever side of each drift
# it is on): shard_map must build a runnable program end to end.
# ---------------------------------------------------------------------------


def test_shard_map_real_jax_traces():
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    mapped = compat.shard_map(
        lambda x: x * 2, mesh=mesh, in_specs=(P(),), out_specs=P()
    )
    out = jax.eval_shape(mapped, jax.ShapeDtypeStruct((4,), jnp.float32))
    assert out.shape == (4,)
