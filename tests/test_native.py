"""Native C++ runtime (CSV loader + BPE tokenizer) vs pure-Python oracles.

Skips cleanly when no C++ toolchain is available — the native layer is an
accelerator, never a hard dependency.
"""

import csv
import json
from pathlib import Path

import pytest

from edgemesh.runtime.native import load_native

pytestmark = pytest.mark.skipif(load_native() is None, reason="no native toolchain")

NQ_CSV = Path("/root/reference/Code/Dataset/natural_questions_1000.csv")


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------


def test_csv_matches_stdlib_on_tricky_file(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(
        'query,answer\n'
        '"hello, world","line1\nline2"\n'
        '\n'
        'plain,"embedded ""quotes"" here"\n'
        'trailing,empty\n'
        '\n'
        '"final, no newline","ok"',
        encoding="utf-8",
    )
    from edgemesh.runtime.native import NativeCSV

    table = NativeCSV(p)
    with open(p, newline="", encoding="utf-8") as f:
        rows = list(csv.reader(f))
    assert table.num_rows == len(rows)
    for r, row in enumerate(rows):
        assert table.num_cols(r) == len(row)
        for c, want in enumerate(row):
            assert table.cell(r, c) == want, (r, c)
    table.close()


@pytest.mark.skipif(not NQ_CSV.exists(), reason="reference dataset not mounted")
def test_csv_loader_parity_on_reference_dataset():
    from edgemesh.eval.data import _load_qa_csv_native, _load_qa_csv_py

    native = _load_qa_csv_native(NQ_CSV, None)
    python = _load_qa_csv_py(NQ_CSV, None)
    assert len(native) == len(python) == 1000
    for a, b in zip(native, python):
        assert (a.index, a.question, a.answer) == (b.index, b.question, b.answer)


# ---------------------------------------------------------------------------
# BPE tokenizer
# ---------------------------------------------------------------------------


def _tiny_gpt2_files(tmp_path: Path) -> Path:
    """Build a small but real GPT-2-format vocab: all 256 byte symbols plus
    merges learned for common English fragments."""
    # GPT-2 byte->unicode map (mirrors the C++ table).
    printable = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAD))
        + list(range(0xAE, 0x100))
    )
    b2u = {}
    k = 0
    for b in range(256):
        if b in printable:
            b2u[b] = chr(b)
        else:
            b2u[b] = chr(256 + k)
            k += 1
    vocab = {}
    for b in range(256):
        vocab[b2u[b]] = len(vocab)
    merges = []

    def add_merge(a, b):
        merges.append(f"{a} {b}")
        tok = a + b
        if tok not in vocab:
            vocab[tok] = len(vocab)

    sp = b2u[ord(" ")]  # 'Ġ'
    for pair in [
        ("t", "h"), ("th", "e"), (sp, "th"), (sp + "th", "e"),
        ("i", "n"), ("a", "n"), ("an", "d"), (sp, "an"), (sp + "an", "d"),
        ("e", "r"), ("o", "n"), (sp, "w"), (sp + "w", "h"),
        ("1", "9"), ("19", "9"), ("'", "s"),
    ]:
        add_merge(*pair)
    vocab["<|endoftext|>"] = len(vocab)
    (tmp_path / "vocab.json").write_text(json.dumps(vocab), encoding="utf-8")
    (tmp_path / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(merges) + "\n", encoding="utf-8"
    )
    return tmp_path


CASES = [
    "the cat sat on the mat",
    "What's the airspeed? I'll check — they've asked 1999 times!",
    "  leading and   multiple   spaces  ",
    "line one\nline two\n\n  indented",
    "don't stop, can't won't SHOULDN'T",
    "numbers 123 and 456,789.0 mixed2with3words",
    "tabs\there\tand trailing spaces   ",
    "punctuation!!! ... ??? ((nested))",
    "",
    "unicode café naïve — em—dash",
]


def test_bpe_matches_hf_tokenizers(tmp_path):
    transformers = pytest.importorskip("transformers")
    d = _tiny_gpt2_files(tmp_path)
    hf = transformers.GPT2TokenizerFast(
        vocab_file=str(d / "vocab.json"), merges_file=str(d / "merges.txt")
    )
    from edgemesh.runtime.native import NativeBPE

    tok = NativeBPE(d)
    assert tok.vocab_size == len(hf)
    for text in CASES:
        got = tok.encode(text)
        want = hf.encode(text)
        assert got == want, f"{text!r}: {got} != {want}"
        assert tok.decode(got) == hf.decode(want)
    tok.close()


def test_csv_blank_lines_skipped_like_dictreader(tmp_path):
    from edgemesh.eval.data import _load_qa_csv_native, _load_qa_csv_py

    p = tmp_path / "blank.csv"
    p.write_text("query,answer\nq1,a1\n\nq2,a2\n\n", encoding="utf-8")
    native = _load_qa_csv_native(p, None)
    python = _load_qa_csv_py(p, None)
    assert [(s.question, s.answer) for s in native] == \
        [(s.question, s.answer) for s in python] == [("q1", "a1"), ("q2", "a2")]


def test_bpe_decode_of_long_tokens_not_truncated(tmp_path):
    import json as _json
    d = _tiny_gpt2_files(tmp_path)
    vocab = _json.loads((d / "vocab.json").read_text())
    vocab["a" * 40] = len(vocab)  # longer than decode's initial 16-bytes/id guess
    (d / "vocab.json").write_text(_json.dumps(vocab), encoding="utf-8")
    from edgemesh.runtime.native import NativeBPE

    tok = NativeBPE(d)
    assert tok.decode([vocab["a" * 40]]) == "a" * 40
    tok.close()


def test_bpe_roundtrips_arbitrary_bytes(tmp_path):
    d = _tiny_gpt2_files(tmp_path)
    from edgemesh.runtime.native import NativeBPE

    tok = NativeBPE(d)
    for text in CASES + ["emoji 🎉 and ünïcödé ẽverywhere"]:
        assert tok.decode(tok.encode(text)) == text
    tok.close()


def test_bpe_eos_and_protocol(tmp_path):
    d = _tiny_gpt2_files(tmp_path)
    from edgemesh.runtime.native import NativeBPE

    tok = NativeBPE(d)
    assert tok.eos_id == tok.pad_id == tok.vocab_size - 1  # <|endoftext|> last
    assert tok.encode("the", max_len=1) == tok.encode("the")[:1]
    tok.close()


def test_csv_lone_cr_is_row_terminator(tmp_path):
    p = tmp_path / "mac.csv"
    p.write_bytes(b"query,answer\rq1,a1\rq2,a2")
    from edgemesh.runtime.native import NativeCSV

    table = NativeCSV(p)
    with open(p, newline="", encoding="utf-8") as f:
        rows = list(csv.reader(f))
    assert table.num_rows == len(rows) == 3
    for r, row in enumerate(rows):
        assert [table.cell(r, c) for c in range(table.num_cols(r))] == row
    table.close()


def test_corrupt_vocab_returns_error_not_crash(tmp_path):
    (tmp_path / "vocab.json").write_text('{"bad\\uZZ12": 1}', encoding="utf-8")
    (tmp_path / "merges.txt").write_text("#version: 0.2\n", encoding="utf-8")
    from edgemesh.runtime.native import NativeBPE

    with pytest.raises(FileNotFoundError):  # graceful: nullptr -> raise, no SIGABRT
        NativeBPE(tmp_path)
