"""Knee-tracking admission (fleet/autotune.py) against synthetic curves.

The tuner is driven with hand-built window patterns through an injected
clock, so every control decision is deterministic: underload grows the
limit to the ceiling, overload converges it near the knee, oscillating
arrivals hold it steady (hysteresis), and an incident freezes tuning.
"""

import threading

import pytest

from edgemesh.fleet.admission import AdmissionController, TenantPolicy
from edgemesh.fleet.autotune import TUNE_RECORD_EVENT, KneeTracker
from edgemesh.obs import Registry


class Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def make_tuner(adm=None, **kw):
    clock = Clock()
    adm = adm or AdmissionController(max_inflight=kw.pop("max_inflight", 8))
    kw.setdefault("floor", 2)
    kw.setdefault("ceiling", 64)
    kw.setdefault("window_s", 1.0)
    kw.setdefault("patience", 2)
    kw.setdefault("obs_registry", Registry())
    tuner = KneeTracker(adm, now=clock, **kw)
    return tuner, adm, clock


def drive_window(tuner, clock, requests=20, good_frac=1.0, shed=0):
    """One closed window: ``requests`` observations at ``good_frac``
    goodness, then the clock steps past the window span and one more
    observation closes it (counted into the NEXT window)."""
    good_n = round(requests * good_frac)
    for i in range(requests):
        tuner.observe(answered=i >= shed, good=i < good_n,
                      shed=i < shed)
    clock.tick(tuner.window_s + 0.01)
    tuner.observe(answered=True, good=True)


def test_underload_grows_limit_to_ceiling():
    tuner, adm, clock = make_tuner(max_inflight=4, ceiling=16, increase=4)
    for _ in range(12):
        drive_window(tuner, clock, good_frac=1.0)
    assert adm.max_inflight == 16  # ceiling, never beyond
    st = tuner.status()
    assert st["limit"] == 16 and st["ceiling"] == 16
    # Per-tenant rates scaled WITH the limit (16/4 = 4x).
    assert st["rate_scale"] == pytest.approx(4.0)


def test_overload_converges_near_the_knee():
    # Closed-loop synthetic service with a true knee at concurrency 8:
    # goodput ratio is 1.0 at/below the knee and degrades 8%/slot above
    # it. The tuner must cut multiplicatively into the neighborhood of
    # the knee and then HOLD (dead zone), not collapse to the floor.
    knee = 8
    tuner, adm, clock = make_tuner(max_inflight=32, floor=2, ceiling=64)
    for _ in range(40):
        ratio = min(1.0, max(0.0, 1.0 - 0.08 * (adm.max_inflight - knee)))
        drive_window(tuner, clock, good_frac=ratio)
    assert knee - 2 <= adm.max_inflight <= 2 * knee
    # Converged, not flapping: another 10 windows move it by at most 1.
    settled = adm.max_inflight
    for _ in range(10):
        ratio = min(1.0, max(0.0, 1.0 - 0.08 * (adm.max_inflight - knee)))
        drive_window(tuner, clock, good_frac=ratio)
    assert abs(adm.max_inflight - settled) <= 1


def test_decrease_is_multiplicative_and_floored():
    tuner, adm, clock = make_tuner(max_inflight=32, floor=4, decrease=0.5)
    for _ in range(20):
        drive_window(tuner, clock, good_frac=0.0)
    assert adm.max_inflight == 4  # floor holds under sustained overload
    assert tuner.status()["floor"] == 4


def test_oscillating_windows_hold_the_limit():
    # Alternating good/bad windows never build a patience=2 streak:
    # hysteresis means the limit does not flap.
    tuner, adm, clock = make_tuner(max_inflight=8)
    for i in range(16):
        drive_window(tuner, clock, good_frac=1.0 if i % 2 == 0 else 0.0)
    assert adm.max_inflight == 8
    # Dead-zone windows (between target and the bad band) also hold.
    for _ in range(8):
        drive_window(tuner, clock, good_frac=0.8)
    assert adm.max_inflight == 8


def test_incident_freeze_pauses_tuning_then_resumes():
    tuner, adm, clock = make_tuner(max_inflight=16, freeze_s=5.0)
    tuner.freeze(reason="incident:inc-1")
    assert tuner.status()["frozen"] is True
    for _ in range(4):
        drive_window(tuner, clock, good_frac=0.0)
    assert adm.max_inflight == 16  # bad windows measured, not acted on
    clock.tick(10.0)  # past freeze_s
    assert tuner.status()["frozen"] is False
    for _ in range(4):
        drive_window(tuner, clock, good_frac=0.0)
    assert adm.max_inflight < 16  # control resumed


def test_thin_windows_never_ratchet_the_limit():
    # A near-idle window says nothing about the knee: below
    # min_window_requests the tuner records nothing and holds.
    tuner, adm, clock = make_tuner(max_inflight=8, min_window_requests=8)
    for _ in range(10):
        drive_window(tuner, clock, requests=2, good_frac=1.0)
    assert adm.max_inflight == 8


def test_knee_estimate_tracks_the_observed_curve():
    # Feed two regimes: 20 req/window all good, then 40 req/window mostly
    # bad — find_knee must put the knee at the good regime's offered load.
    tuner, adm, clock = make_tuner(max_inflight=8)
    for _ in range(4):
        drive_window(tuner, clock, requests=20, good_frac=1.0)
    for _ in range(4):
        drive_window(tuner, clock, requests=40, good_frac=0.2)
    knee = tuner.status()["knee"]
    assert knee["knee_offered_rps"] == pytest.approx(20, rel=0.2)
    assert knee["collapsed"] is True


def test_tune_actions_land_in_the_span_log(tmp_path):
    from edgemesh.utils.tracing import JsonlLogger

    log_path = tmp_path / "router.jsonl"
    adm = AdmissionController(max_inflight=4)
    tuner, adm, clock = make_tuner(adm=adm, log=JsonlLogger(log_path))
    for _ in range(4):
        drive_window(tuner, clock, good_frac=1.0)
    records = JsonlLogger(log_path).read()
    tunes = [r for r in records if r.get("event") == TUNE_RECORD_EVENT]
    assert tunes and tunes[-1]["action"] == "increase"
    assert tunes[-1]["limit"] > 4
    assert "window" in tunes[-1] and "knee_offered_rps" in tunes[-1]


def test_validation():
    adm = AdmissionController(max_inflight=8)
    with pytest.raises(ValueError):
        KneeTracker(adm, floor=0, obs_registry=Registry())
    with pytest.raises(ValueError):
        KneeTracker(adm, floor=8, ceiling=4, obs_registry=Registry())
    with pytest.raises(ValueError):
        KneeTracker(adm, decrease=1.5, obs_registry=Registry())


# -- the admission seams the tuner drives -----------------------------------


def test_set_max_inflight_grows_grant_queued_waiters():
    adm = AdmissionController(max_inflight=1, queue_cap=4)
    assert adm.acquire("t", wait_s=0.0) == "ok"  # pool now full
    got = []

    def waiter():
        got.append(adm.acquire("t", wait_s=5.0))

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    # The waiter is queued; growing the pool must grant it immediately.
    import time

    for _ in range(100):
        if adm.stats()["waiting"]:
            break
        time.sleep(0.01)
    adm.set_max_inflight(2)
    th.join(timeout=5.0)
    assert got == ["ok"]
    adm.release()
    adm.release()


def test_set_max_inflight_shrink_never_revokes():
    adm = AdmissionController(max_inflight=4)
    for _ in range(4):
        assert adm.acquire("t") == "ok"
    adm.set_max_inflight(2)
    assert adm.stats()["inflight"] == 4  # in-flight work finishes
    assert adm.acquire("t") == "overload"  # but no new grants past the bound
    for _ in range(4):
        adm.release()
    assert adm.acquire("t") == "ok"


def test_set_rate_scale_rebuilds_tenant_buckets():
    t = [0.0]
    adm = AdmissionController(
        max_inflight=8,
        policies={"bulk": TenantPolicy(rate_per_s=2.0, burst=2.0)},
        now=lambda: t[0],
    )
    assert adm.acquire("bulk") == "ok"
    adm.release()
    assert adm.acquire("bulk") == "ok"
    adm.release()
    assert adm.acquire("bulk") == "ratelimited"  # burst of 2 spent
    # Halving the scale halves rate AND burst; a fresh bucket at 1 rps
    # refills one token per second.
    adm.set_rate_scale(0.5)
    t[0] += 1.0
    assert adm.acquire("bulk") == "ok"
    adm.release()
    assert adm.acquire("bulk") == "ratelimited"
    assert adm.stats()["rate_scale"] == 0.5
    # Unlimited tenants stay unlimited at any scale.
    for _ in range(10):
        assert adm.acquire("other") == "ok"
        adm.release()


def test_initial_limit_is_clamped_into_the_band():
    # A default max_inflight above the configured ceiling must not serve
    # out-of-band until the first decrease (found driving the fleet CLI).
    adm = AdmissionController(max_inflight=64)
    tuner, adm, clock = make_tuner(adm=adm, floor=2, ceiling=32)
    assert adm.max_inflight == 32
    assert tuner.status()["rate_scale"] == 1.0
    adm2 = AdmissionController(max_inflight=1)
    tuner2, adm2, _ = make_tuner(adm=adm2, floor=4, ceiling=32)
    assert adm2.max_inflight == 4


def test_set_rate_scale_never_refunds_a_burst():
    # The tuner retunes every window: rebuilding buckets would hand each
    # tenant a fresh burst per action, disabling its limit during a ramp.
    # Rescale must preserve the current token level.
    t = [0.0]
    adm = AdmissionController(
        max_inflight=8,
        policies={"bulk": TenantPolicy(rate_per_s=1.0, burst=10.0)},
        now=lambda: t[0],
    )
    for _ in range(10):  # spend the whole burst
        assert adm.acquire("bulk") == "ok"
        adm.release()
    assert adm.acquire("bulk") == "ratelimited"
    # A no-op-sized retune (scale 1.0 -> 1.01) must NOT refund tokens.
    adm.set_rate_scale(1.01)
    assert adm.acquire("bulk") == "ratelimited"
    # Refill still follows the (scaled) rate.
    t[0] += 1.0
    assert adm.acquire("bulk") == "ok"
    adm.release()
    assert adm.acquire("bulk") == "ratelimited"
