"""Int4 weight-only quantization (ops/int4.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.ops.int4 import (
    dequantize_weight_int4,
    int4_matmul,
    quantize_params_int4,
    quantize_weight_int4,
)
from edgemesh.runtime import generate



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def test_quantize_roundtrip_error_bounded():
    k = jax.random.normal(jax.random.PRNGKey(0), (128, 64)) * 0.3
    for gs in (0, 32, 64):
        q, scales = quantize_weight_int4(k, group_size=gs)
        assert q.dtype == jnp.int8 and q.shape == (64, 64)  # nibble-packed
        deq = dequantize_weight_int4(q, scales, jnp.float32)
        # max error <= half a quantization step per (group, column)
        groups = scales.shape[0]
        step = np.asarray(scales).reshape(groups, 1, -1)
        err = np.abs(np.asarray(deq - k)).reshape(groups, 128 // groups, -1)
        assert (err <= 0.5 * step + 1e-6).all()


def test_grouped_scales_beat_per_channel_on_outliers():
    # One giant outlier per column wrecks a per-channel scale; grouping
    # contains the damage to the outlier's group.
    k = jax.random.normal(jax.random.PRNGKey(1), (128, 16)) * 0.1
    k = k.at[0].set(8.0)  # outlier row
    qc, sc = quantize_weight_int4(k, group_size=0)
    qg, sg = quantize_weight_int4(k, group_size=32)
    err_c = float(jnp.mean(jnp.abs(dequantize_weight_int4(qc, sc, jnp.float32) - k)[32:]))
    err_g = float(jnp.mean(jnp.abs(dequantize_weight_int4(qg, sg, jnp.float32) - k)[32:]))
    assert err_g < err_c / 4


@pytest.mark.parametrize("gs", [0, 64])
def test_int4_matmul_matches_dequant_reference(gs):
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 128), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(3), (128, 32)) * 0.2
    q, scales = quantize_weight_int4(k, group_size=gs)
    ref = x @ dequantize_weight_int4(q, scales, jnp.float32)
    out = int4_matmul(x, q, scales)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_model_level_int4_generates_close_to_dequant_model():
    cfg = tiny_config("llama", vocab_size=128, max_seq_len=64, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    q_params = quantize_params_int4(params, group_size=32)
    # Greedy decode of the int4 model vs the explicitly dequantized model:
    # identical weights up to quantization, so identical greedy tokens.

    def dequant_walk(node):
        if isinstance(node, dict):
            if "kernel_q4" in node:
                out = {"kernel": None}
                q, s = node["kernel_q4"], node["scales"]
                if q.ndim == 3:
                    out["kernel"] = jax.vmap(
                        lambda qq, ss: dequantize_weight_int4(qq, ss, jnp.float32)
                    )(q, s)
                else:
                    out["kernel"] = dequantize_weight_int4(q, s, jnp.float32)
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            return {k: dequant_walk(v) for k, v in node.items()}
        return node

    deq = dequant_walk(q_params)
    tokens = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    lengths = jnp.asarray([4], jnp.int32)
    sampling = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    out_q = generate(cfg, q_params, tokens, lengths, sampling)
    out_d = generate(cfg, deq, tokens, lengths, sampling)
    np.testing.assert_array_equal(np.asarray(out_q.tokens), np.asarray(out_d.tokens))


def test_agent_precision_int4():
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec

    agent = build_agent(
        AgentSpec(
            role="qa", model=ModelSpec(precision="int4"),
            sampling=SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0),
        )
    )
    assert "kernel_q4" in agent.params["layers"]["up"]
    r = agent.answer("what is the capital of france")
    assert isinstance(r["answer"], str)


def test_int4_shards_on_tp_mesh():
    """Grouped int4 scales ([L, G, out]) must shard the OUT dim, never the
    group dim, and the sharded agent must still answer (regression: the
    int8-shaped scales pspec used to land on the G axis)."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec
    from edgemesh.parallel.mesh import build_mesh

    mesh = build_mesh(tp=2)
    agent = build_agent(
        AgentSpec(
            role="qa",
            model=ModelSpec(precision="int4", hidden_size=64, intermediate_size=128),
            sampling=SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0),
        ),
        mesh=mesh,
    )
    # Find grouped (3D) scales leaves and check their sharding axes: the out
    # dim follows the kernel's out sharding, and the G axis follows the
    # kernel's IN-dim sharding (G subdivides the contraction, so splitting it
    # with the packed rows keeps each shard's local group_size correct).
    layers = agent.params["layers"]
    grouped = [
        (k, v["scales"], v["kernel_q4"])
        for k, v in layers.items()
        if isinstance(v, dict) and "scales" in v and v["scales"].ndim == 3
    ]
    assert grouped, "expected at least one grouped int4 scales leaf"
    for name, scales, kernel in grouped:
        spec = scales.sharding.spec
        k_spec = kernel.sharding.spec
        assert spec[-1] == k_spec[-1], (name, spec, k_spec)  # out dim matches
        if scales.shape[-2] % 2 == 0:
            assert spec[-2] == k_spec[-2], (name, spec, k_spec)  # G follows in dim
        else:  # G=1 (effectively per-channel) cannot shard — stays replicated
            assert spec[-2] is None, (name, spec)
    r = agent.answer("where is the eiffel tower")
    assert isinstance(r["answer"], str)


def test_pallas_int4_matmul_matches_xla_path():
    """The fused kernel (one HBM pass over the packed nibbles) must equal
    the XLA two-matmul formulation bit-for-bit-ish on the same inputs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edgemesh.ops.int4 import (
        int4_matmul,
        pallas_int4_matmul,
        quantize_weight_int4,
    )

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (8, 256), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (256, 128), jnp.float32)
    packed, scales = quantize_weight_int4(w, group_size=0)
    ref = int4_matmul(x, packed, scales)
    got = pallas_int4_matmul(x, packed, scales[0], tile_m=8, tile_n=128,
                             tile_k2=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    # Multi-stripe K accumulation path too.
    got2 = pallas_int4_matmul(x, packed, scales[0], tile_m=8, tile_n=128,
                              tile_k2=64, interpret=True)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
