"""Load observatory end-to-end (slow tier): a REAL 3-replica subprocess
fleet driven open-loop by edgemesh.loadgen.

Two acceptance proofs (ISSUE 9 / ROADMAP "million-user load harness"):

1. **The curve**: sweeping offered load from under-capacity to heavy
   overload produces a monotone-then-collapsing goodput-vs-offered-load
   curve with the saturation knee identified — the schema the bench stage
   ``load_curve`` embeds in BENCH JSON.
2. **Isolation**: with an abusive batch tenant flooding the frontend,
   weighted-fair admission + priority lanes keep the compliant
   interactive tenant's SLO goodput within 10% of its solo-run value,
   while the unprotected (fairness-off) arm visibly starves it.

Multi-minute territory: each replica is a full ``edgemesh serve
--continuous`` subprocess compiling the tiny model on a 1-core CPU slice.
"""

import time
from pathlib import Path

import pytest

from edgemesh.fleet import FleetRouter, HttpTransport, ReplicaRegistry, serve_fleet
from edgemesh.fleet.admission import AdmissionController, TenantPolicy
from edgemesh.loadgen import (
    OpenLoopGenerator,
    PoissonProcess,
    TenantSpec,
    Workload,
    http_target,
    run_curve,
)
from edgemesh.loadgen.workload import LengthMix
from edgemesh.obs import Registry
from test_fleet_e2e import _free_port, _post, _spawn_replica, _wait_ready

pytestmark = pytest.mark.slow

# A deliberately SLOWER replica than test_fleet_e2e's (48-token budget,
# 2 layers): per-request service lands around hundreds of ms, so fleet
# capacity is a couple dozen rps — queueing delay, SLO misses, and
# starvation all scale well above the harness's absolute floors, and an
# overload point is a bounded number of client threads.
REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 2, hidden_size: 64, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 128}
    sampling: {max_new_tokens: 48, do_sample: false, repetition_penalty: 1.0}
"""

#: One prompt-length bucket: the e2e pins curve SHAPE and tenant
#: isolation, not compile-ladder behavior (long-tail mixes are fast-tier
#: unit-tested) — a constant length keeps replica latency regime-free.
_PROMPT_MIX = LengthMix(median=80, sigma=0.0, lo=80, hi=80)

#: Calibration prompt shaped like the workload's session prompts (word
#: tokens, not a repeated character — token count drives the compile
#: buckets, not character count).
_CAL_PROMPT = ("[session cal-0] context: mesh edge device tensor shard "
               "page. turn 1: decode stream route batch token cache?")


@pytest.fixture(scope="module")
def fleet():
    """3 warm continuous replica subprocesses + capacity/SLO estimates."""
    import tempfile

    tmp = Path(tempfile.mkdtemp(prefix="edgemesh-loadgen-e2e-"))
    cfg = tmp / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    ports = [_free_port() for _ in range(3)]
    procs = [_spawn_replica(cfg, p, extra=("--continuous", "--batch", "2"))
             for p in ports]
    transport = HttpTransport()
    try:
        _wait_ready(transport, ports)
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        for url in urls:
            status, _ = _post(f"{url}/generate", {"question": _CAL_PROMPT})
            assert status == 200
        fleet_state = {"transport": transport, "urls": urls}
        # Warm the compile ladder with WORKLOAD-SHAPED prompts: session
        # prompts tokenize differently from any synthetic constant, and a
        # fresh prompt-length bucket mid-measurement costs a multi-second
        # compile on this 1-core host. A short throwaway open-loop pass
        # over the same generator hits every bucket the arms will hit.
        front, _router, url = _front(fleet_state)
        warm_wl = Workload([
            TenantSpec(name="interactive", arrival=PoissonProcess(2.0, seed=91),
                       prompt_mix=_PROMPT_MIX, lane="interactive"),
            TenantSpec(name="batch", arrival=PoissonProcess(2.0, seed=93),
                       prompt_mix=_PROMPT_MIX, lane="batch"),
        ], seed=5)
        OpenLoopGenerator(http_target(url, timeout_s=300.0),
                          warm_wl.build_schedule(8.0), slo_latency_s=60.0,
                          duration_s=8.0).run()
        front.shutdown()
        _drain(fleet_state)
        # Self-calibrate: a short CLOSED-loop probe (6 workers hammering a
        # temp frontend) measures the fleet's true sustainable throughput
        # and its loaded latency on THIS machine — the open-loop sweep
        # points are placed relative to that, so the curve shape is
        # machine-independent.
        capacity_rps, p95_loaded = _closed_probe(fleet_state)
        fleet_state["capacity_rps"] = min(capacity_rps, 40.0)
        # 4x the loaded p95: comfortably above the fleet's healthy tail
        # (open-loop Poisson bursts + segment-boundary waits ride on top
        # of the closed-loop number), comfortably below the many-SLO
        # latencies of a saturated backlog.
        fleet_state["slo_s"] = max(4.0 * p95_loaded, 0.5)
        print(f"\nloadgen-e2e calibration: capacity={capacity_rps:.1f} rps "
              f"(using {fleet_state['capacity_rps']:.1f}), "
              f"p95_loaded={p95_loaded * 1e3:.0f}ms, "
              f"slo={fleet_state['slo_s']:.2f}s")
        yield fleet_state
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()


def _closed_probe(fleet, workers: int = 6, seconds: float = 3.0):
    """Closed-loop calibration: achieved rps + loaded p95 latency."""
    import threading

    front, _router, url = _front(fleet)
    target = http_target(url, timeout_s=60.0)
    lats = []
    lock = threading.Lock()
    stop = time.monotonic() + seconds
    try:
        def worker():
            while time.monotonic() < stop:
                t0 = time.monotonic()
                status, _ = target({"question": _CAL_PROMPT}, {})
                if status == 200:
                    with lock:
                        lats.append(time.monotonic() - t0)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
    finally:
        front.shutdown()
    assert len(lats) >= workers, "calibration probe produced no throughput"
    lats.sort()
    return len(lats) / seconds, lats[int(0.95 * (len(lats) - 1))]


def _drain(fleet):
    """Wait until every replica is idle (backlog from a previous arm must
    not bleed into the next measurement)."""
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        busy = False
        for url in fleet["urls"]:
            status, body = fleet["transport"].get_json(
                f"{url}/loadz", timeout_s=10.0)
            assert status == 200
            if (body.get("inflight") or 0) > 0 or (body.get("queue_depth") or 0) > 0:
                busy = True
        if not busy:
            return
        time.sleep(0.25)
    raise AssertionError("replicas never drained between arms")


def _front(fleet, admission=None, max_inflight=64, wait_s=10.0):
    registry = ReplicaRegistry(
        (f"replica-{i}", url) for i, url in enumerate(fleet["urls"])
    )
    router = FleetRouter(
        registry, balancer="least_outstanding",
        transport=fleet["transport"], obs_registry=Registry(),
        max_attempts=1, attempt_timeout_s=300.0, default_deadline_s=600.0,
        max_inflight=max_inflight, admission=admission,
        admission_wait_s=wait_s,
    )
    front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    url = f"http://127.0.0.1:{front.server_address[1]}/generate"
    return front, router, url


def test_open_loop_curve_is_monotone_then_collapses(fleet):
    c = fleet["capacity_rps"]
    # 8x capacity for the overload point: the collapse has to be
    # unambiguous — backlog delay must blow through the SLO within the
    # first second of the window, not just at its tail.
    rates = [round(0.3 * c, 2), round(0.7 * c, 2), round(8.0 * c, 2)]
    front, router, url = _front(fleet)
    target = http_target(url, timeout_s=60.0)
    try:
        def make_run(rate):
            _drain(fleet)
            # An overloaded system serves ~capacity*slo GOOD requests as a
            # one-off transient while its queues fill, no matter how long
            # the window is — so the overload window must be several SLOs
            # long for goodput-RPS to show the collapse, not the transient.
            duration = 12.0 if rate > 2.0 * c else 4.0
            wl = Workload([
                TenantSpec(name="interactive",
                           arrival=PoissonProcess(max(0.2, rate * 2 / 3),
                                                  seed=21),
                           prompt_mix=_PROMPT_MIX, lane="interactive"),
                TenantSpec(name="batch",
                           arrival=PoissonProcess(max(0.2, rate / 3),
                                                  seed=23),
                           prompt_mix=_PROMPT_MIX, lane="batch"),
            ], seed=9)
            gen = OpenLoopGenerator(target, wl.build_schedule(duration),
                                    slo_latency_s=fleet["slo_s"],
                                    duration_s=duration)
            return gen.run()

        curve = run_curve(make_run, rates)
    finally:
        front.shutdown()
    pts = curve["points"]
    assert len(pts) >= 3
    gp = [p["goodput_rps"] for p in pts]
    # Monotone below saturation: more offered load, more goodput...
    assert gp[1] > gp[0], curve
    # ...then COLLAPSE under heavy overload: queueing delay blows the SLO
    # and sheds take over — the region closed-loop drivers cannot see.
    assert gp[2] < 0.7 * gp[1], curve
    # The knee is identified, in-sweep, and the collapse is flagged.
    assert curve["knee_offered_rps"] == pts[1]["offered_rps"], curve
    assert curve["collapsed"] is True
    # The overload point visibly shed or missed (not silently absorbed).
    assert pts[2]["shed"] + pts[2]["errors"] > 0 or \
        pts[2]["goodput_ratio"] < 0.5
    # Per-tenant splits ride every point.
    assert {"interactive", "batch"} <= set(pts[0]["tenants"])


def _interactive_workload(rate):
    return TenantSpec(name="interactive",
                      arrival=PoissonProcess(rate, seed=31),
                      prompt_mix=_PROMPT_MIX, lane="interactive")


def _flood_workload(rate):
    return TenantSpec(name="batch",
                      arrival=PoissonProcess(rate, seed=37),
                      prompt_mix=_PROMPT_MIX, lane="batch")


def test_fair_admission_isolates_interactive_from_batch_flood(fleet):
    c = fleet["capacity_rps"]
    inter_rate = max(0.5, 0.25 * c)
    flood_rate = 3.0 * c
    # Several SLOs long: an overloaded fleet serves ~capacity*slo good
    # requests as a queue-filling transient regardless of window length,
    # so a short window would hide the starvation the arm exists to show.
    duration = 12.0
    slo = fleet["slo_s"]

    def run_arm(admission, tenants, max_inflight=64, wait_s=10.0):
        _drain(fleet)
        front, router, url = _front(fleet, admission=admission,
                                    max_inflight=max_inflight,
                                    wait_s=wait_s)
        try:
            wl = Workload(tenants, seed=3)
            gen = OpenLoopGenerator(http_target(url, timeout_s=60.0),
                                    wl.build_schedule(duration),
                                    slo_latency_s=slo, duration_s=duration)
            return gen.run(), router
        finally:
            front.shutdown()

    # Arm 0 — solo baseline: the compliant interactive tenant alone.
    solo, _ = run_arm(None, [_interactive_workload(inter_rate)])
    solo_ratio = solo["tenants"]["interactive"]["goodput_ratio"]
    assert solo_ratio > 0.8, solo  # sanity: alone, the tenant is healthy

    # Arm 1 — UNPROTECTED: fairness off (legacy immediate-shed admission),
    # abusive batch tenant floods the frontend at 3x fleet capacity.
    unprot, _ = run_arm(
        None,
        [_interactive_workload(inter_rate), _flood_workload(flood_rate)],
    )
    unprot_ratio = unprot["tenants"]["interactive"]["goodput_ratio"]

    # Arm 2 — PROTECTED: weighted-fair queueing + priority lanes + a
    # token-bucket rate limit on the abuser. Slot pool sized to the
    # fleet (queueing happens at the ROUTER, where policy applies —
    # not in the replicas' FIFO engine queues where it cannot). The
    # bucket is tight (0.25x capacity) and the queue small with short
    # waits: flood requests past budget answer 429/503 IMMEDIATELY
    # instead of parking hundreds of handler threads — protecting the
    # fleet also means protecting the frontend itself.
    admission = AdmissionController(
        max_inflight=9, queue_cap=16,
        policies={
            "interactive": TenantPolicy(lane="interactive", weight=8.0),
            "batch": TenantPolicy(lane="batch", weight=1.0,
                                  rate_per_s=max(1.0, 0.25 * c),
                                  burst=2.0),
        },
    )
    prot, prot_router = run_arm(
        admission,
        [_interactive_workload(inter_rate), _flood_workload(flood_rate)],
        wait_s=2.0,
    )
    prot_ratio = prot["tenants"]["interactive"]["goodput_ratio"]

    # THE acceptance bar: fairness keeps the compliant tenant within 10%
    # of its solo goodput under the flood; the unprotected arm visibly
    # starves it.
    assert prot_ratio >= 0.9 * solo_ratio, (solo, prot)
    assert unprot_ratio < 0.6 * prot_ratio, (unprot, prot)
    # The mechanism is visible in the telemetry: the abuser was rate
    # limited and/or queued, and /fleetz attributes it per tenant.
    st = prot_router.status()
    assert st["tenants"]["batch"]["shed"] > 0
    hits = st["admission"]["ratelimit_hits"]
    timeouts = st["admission"]["queue_timeouts"]
    assert hits.get("batch", 0) + timeouts.get("batch", 0) > 0
    assert st["tenants"]["interactive"]["goodput_ratio"] is not None


def test_load_curve_benchmark_smoke():
    """The bench stage end-to-end at smoke scale: real in-process
    replicas, real open-loop sweep, the BENCH JSON schema keys."""
    from edgemesh.benchmarks import load_curve_benchmark

    r = load_curve_benchmark(n_replicas=1, duration_s=1.5,
                             point_factors=(0.4, 3.0))
    assert r["metric"] == "load_curve_knee_rps"
    assert r["unit"] == "req/s"
    assert len(r["points"]) == 2
    assert r["value"] in {p["offered_rps"] for p in r["points"]}
    assert r["slo_latency_s"] > 0 and r["estimated_capacity_rps"] > 0
    for p in r["points"]:
        assert {"interactive", "batch"} <= set(p["tenants"])
        assert p["goodput_ratio"] is not None
