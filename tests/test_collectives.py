"""Quantized/overlapped collectives (parallel/collectives.py + the chunked
projection joins in parallel/tp_infer.py).

Numerics contract: qpsum must track exact ``lax.psum`` within a PINNED
per-dtype bound on adversarial inputs — outlier channels, near-zero chunks —
at every world size the serving stack registers (2/4/8, on the suite's
forced-8-device CPU platform), and the chunked-overlap decomposition must
reassemble the monolithic matmul+psum for every chunk count. A broken scale
or ring index blows these bounds by orders of magnitude; normal quantization
noise sits well inside them.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from edgemesh.parallel.collectives import (
    COMM_DTYPES,
    collective_wire_bytes,
    qpsum,
    validate_collective_mode,
)
from edgemesh.parallel.mesh import build_mesh
from edgemesh.utils.compat import shard_map

#: Pinned per-dtype error coefficients: for every per-row group,
#: |qpsum - psum| <= C * world * absmax(inputs in that row's group). The
#: error scales with the magnitudes QUANTIZED (the running partials, up to
#: world x the input absmax — outliers can cancel in the exact sum, so the
#: result magnitude is the wrong yardstick). Measured worst cases across
#: seeds sit at 0.0457*amax (int8, w2) and 0.234*amax (fp8, w8) — these
#: pins carry >=2.7x margin while a broken scale or ring index lands at
#: ~1x amax and beyond.
_BOUND_COEFF = {"int8": 1 / 16.0, "fp8": 1 / 8.0}


def _qpsum_sharded(x, world, dtype, devices):
    mesh = build_mesh(tp=world, devices=devices[:world])
    f = shard_map(
        lambda xs: qpsum(xs, "tp", dtype=dtype),
        mesh=mesh,
        in_specs=(P("tp", None, None),),
        out_specs=P("tp", None, None),
        check_vma=False,
    )
    return np.asarray(jax.jit(f)(x), np.float32)


def _psum_ref(x, world):
    xs = np.asarray(x, np.float32).reshape(world, -1, *x.shape[1:])
    total = xs.sum(axis=0)  # one shard's worth, summed over the axis
    return np.tile(total, (world,) + (1,) * (total.ndim - 1))


def _adversarial(world, rows=2, h=48, seed=0):
    """Outlier channels + near-zero chunks + ordinary noise, stacked so each
    shard's rows carry all three regimes."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(world * rows, 3, h)).astype(np.float32)
    x[:, 0, 0] = 1e4 * rng.choice([-1.0, 1.0], size=world * rows)  # outlier
    x[:, 1, :] = 1e-7 * rng.normal(size=(world * rows, h))  # near-zero chunk
    return jnp.asarray(x)


@pytest.mark.parametrize("world", [2, 4, 8])
@pytest.mark.parametrize("dtype", ["int8", "fp8"])
def test_qpsum_error_bound_adversarial(devices, world, dtype):
    x = _adversarial(world)
    got = _qpsum_sharded(x, world, dtype, devices)
    ref = _psum_ref(x, world)
    # Per-(row, regime) bound: scales are per leading row, so one outlier
    # row must not get judged against — or hide behind — the quiet rows.
    xs = np.asarray(x, np.float32).reshape(world, -1, 3, x.shape[-1])
    err = np.abs(got - ref).reshape(world, -1, 3, x.shape[-1]).max(axis=(0, 3))
    amax = np.abs(xs).max(axis=(0, 3))
    bound = _BOUND_COEFF[dtype] * world * np.maximum(amax, 1e-6)
    assert np.all(err <= bound), (err, bound)
    # All-zero slices must dequantize to EXACT zeros (clamped scale, not
    # 0/0 garbage).
    zero = jnp.zeros((world * 2, 3, 48), jnp.float32)
    assert np.all(_qpsum_sharded(zero, world, dtype, devices) == 0.0)


def test_qpsum_bf16_mode_and_world1_are_plain_psum(devices):
    x = _adversarial(4)
    got = _qpsum_sharded(x, 4, "bf16", devices)
    np.testing.assert_allclose(got, _psum_ref(x, 4), rtol=0, atol=0)
    # world 1: identity-sum (nothing on the wire).
    mesh = build_mesh(tp=1, devices=devices[:1])
    f = shard_map(
        lambda xs: qpsum(xs, "tp", dtype="int8"),
        mesh=mesh, in_specs=(P(None, None),), out_specs=P(None, None),
        check_vma=False,
    )
    y = jnp.asarray(np.random.default_rng(1).normal(size=(4, 48)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(f(y)), np.asarray(y))


def test_qpsum_indivisible_trailing_dim_falls_back_exact(devices):
    # h=9 does not chunk over tp=4: the plain-psum fallback must be exact.
    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(8, 3, 9)), jnp.float32
    )
    got = _qpsum_sharded(x, 4, "int8", devices)
    np.testing.assert_allclose(got, _psum_ref(x, 4), rtol=1e-6, atol=1e-6)


def test_qpsum_replicated_across_shards(devices):
    """Every shard must hold bit-identical results (the all-gather
    re-quantizes the local chunk too) — out_specs replication is a real
    claim, not a vibe."""
    x = _adversarial(4, seed=3)
    got = _qpsum_sharded(x, 4, "int8", devices).reshape(4, -1, 3, 48)
    for i in range(1, 4):
        np.testing.assert_array_equal(got[i], got[0])


@pytest.mark.parametrize("n_chunks", [1, 2, 3, 4, 8])
def test_chunked_overlap_decomposition_matches_monolithic(devices, n_chunks):
    """The qpsum_overlap projection split (tp_infer._collective_dense):
    disjoint OUTPUT-dim slices joined per-chunk must reassemble the
    monolithic matmul + psum for EVERY chunk count — bf16 wire makes it
    exact, and the (tp-pre-divided) bias slices with the columns so the
    concatenation carries it exactly once."""
    from edgemesh.parallel.tp_infer import _collective_dense

    world, in_dim, out_dim = 4, 24, 10
    rng = np.random.default_rng(4)
    kernel = rng.normal(size=(in_dim, out_dim)).astype(np.float32)
    bias = rng.normal(size=(out_dim,)).astype(np.float32)
    x = rng.normal(size=(2, 3, in_dim)).astype(np.float32)
    mesh = build_mesh(tp=world, devices=devices[:world])

    def body(k_shard, x_shard):
        # The tp_infer convention: row-sharded kernel, replicated bias
        # pre-divided by tp (each shard's dense adds bias/tp; the join
        # reassembles the full bias).
        p = {"kernel": k_shard, "bias": jnp.asarray(bias / world)}
        return _collective_dense(
            p, x_shard, "qpsum_overlap", "bf16", n_chunks, "w8a16"
        )

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P("tp", None), P(None, None, "tp")),
        out_specs=P(None, None, None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(f)(jnp.asarray(kernel), jnp.asarray(x)))
    ref = x @ kernel + bias
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_chunked_overlap_int8_wire_stays_in_bound(devices):
    from edgemesh.parallel.tp_infer import _collective_dense

    world, in_dim, out_dim = 4, 24, 16
    rng = np.random.default_rng(5)
    kernel = rng.normal(size=(in_dim, out_dim)).astype(np.float32)
    x = rng.normal(size=(2, 3, in_dim)).astype(np.float32)
    mesh = build_mesh(tp=world, devices=devices[:world])
    f = shard_map(
        lambda k, xs: _collective_dense(
            {"kernel": k}, xs, "qpsum_overlap", "int8", 4, "w8a16"
        ),
        mesh=mesh,
        in_specs=(P("tp", None), P(None, None, "tp")),
        out_specs=P(None, None, None),
        check_vma=False,
    )
    got = np.asarray(jax.jit(f)(jnp.asarray(kernel), jnp.asarray(x)))
    ref = x @ kernel
    bound = _BOUND_COEFF["int8"] * world * np.max(np.abs(ref))
    assert np.max(np.abs(got - ref)) <= bound


def test_wire_accounting_and_mode_validation():
    shape = (1, 1, 2048)
    psum = collective_wire_bytes(shape, 8, "psum")
    q = collective_wire_bytes(shape, 8, "qpsum", "int8")
    assert psum > 0 and q > 0
    # Quantization must at least approach halving the wire; the float32
    # per-row scales are the only overhead.
    assert q < 0.6 * psum
    assert collective_wire_bytes(shape, 1, "qpsum", "int8") == 0
    # Non-divisible trailing dims fall back to the full-precision wire.
    assert collective_wire_bytes((1, 1, 9), 8, "qpsum", "int8") == \
        collective_wire_bytes((1, 1, 9), 8, "psum")
    for dtype in COMM_DTYPES:
        if dtype == "fp8" and getattr(jnp, "float8_e4m3fn", None) is None:
            continue
        validate_collective_mode("qpsum", dtype)
    with pytest.raises(ValueError, match="collective_mode"):
        validate_collective_mode("ring", "int8")
    with pytest.raises(ValueError, match="dtype"):
        validate_collective_mode("qpsum", "int3")


def test_tp_engine_collective_accounting():
    """The engine-side accounting (what the serving counter and span attrs
    consume) mirrors collective_wire_bytes: two joins per layer, quantized
    ops report the narrow wire."""
    from edgemesh.models import init_params
    from edgemesh.models.families import tiny_config

    from edgemesh.parallel.tp_infer import TPInferenceEngine

    cfg = tiny_config("llama", num_heads=8, num_kv_heads=8, num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, tp=8)
    eng_q = TPInferenceEngine(cfg, params, mesh, attention_impl="xla",
                              collective_mode="qpsum_overlap")
    eng_p = TPInferenceEngine(cfg, params, mesh, attention_impl="xla")
    aq, ap = eng_q.collective_accounting(batch=4), eng_p.collective_accounting(batch=4)
    assert aq["op"] == "qpsum" and aq["dtype"] == "int8"
    assert ap["op"] == "psum" and ap["dtype"] == "bf16"
    # Output-dim chunking: k disjoint [b, 1, h/k] joins per projection —
    # the payloads sum to the monolithic join plus k x the per-row scale
    # vectors, NEVER a multiple of the full payload (the contraction-split
    # wire-blowup regression would read k x mono here). At this tiny
    # hidden the scale vectors dominate, so the meaningful pin is the
    # blowup bound, not chunked < psum (test_wire_accounting covers the
    # halving at a production-sized hidden).
    k = eng_q.overlap_chunks
    per = k * collective_wire_bytes(
        (4, 1, cfg.hidden_size // k), 8, "qpsum", "int8"
    )
    assert aq["per_layer"] == {"attn_o": per, "mlp_down": per}
    assert aq["bytes_per_step"] == cfg.num_layers * 2 * per
    mono = collective_wire_bytes((4, 1, cfg.hidden_size), 8, "qpsum", "int8")
    # Exact decomposition: chunking adds (k-1) extra per-row float32 scale
    # vectors per hop and NOTHING else; the contraction-split regression
    # would read k * mono (every chunk all-reducing the full output).
    rows, hops = 4, 2 * (8 - 1)
    assert per == mono + (k - 1) * rows * 4 * hops
    assert per < k * mono
