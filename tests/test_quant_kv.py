"""Int8-quantized KV cache backend (runtime/quant_kv.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params
from edgemesh.runtime import generate
from edgemesh.runtime.quant_kv import (
    forward_prefill_quant,
    generate_quant_kv,
    init_quant_kv_cache,
    quantize_kv,
)


def test_quantize_kv_roundtrip_bounded():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 4, 16))
    q, scale = quantize_kv(x)
    assert q.dtype == jnp.int8 and scale.shape == (2, 8, 4)
    deq = q.astype(jnp.float32) * scale[..., None]
    err = np.abs(np.asarray(deq - x))
    # Symmetric absmax quantization: error <= half a step per row.
    assert (err <= 0.5 * np.asarray(scale)[..., None] + 1e-6).all()


def test_prefill_logits_close_to_dense():
    cfg = tiny_config("llama", vocab_size=128, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[5, 9, 2, 7, 11, 3]], jnp.int32)
    lengths = jnp.asarray([6], jnp.int32)

    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 1, 32))
    got, cache = forward_prefill_quant(
        cfg, params, tokens, lengths, init_quant_kv_cache(cfg, 1, 32)
    )
    assert int(cache.lengths[0]) == 6
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=3e-2, atol=3e-2)


def test_generate_matches_dense_greedy():
    """Greedy decode over the int8 cache reproduces the bf16-cache tokens on
    the tiny model (deterministic; per-element cache error ~0.4% is far under
    the typical top-1/top-2 logit gap)."""
    cfg = tiny_config("llama", vocab_size=128, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[5, 9, 2, 7], [3, 1, 0, 0]], jnp.int32)
    lengths = jnp.asarray([4, 2], jnp.int32)
    sampling = SamplingParams(max_new_tokens=10, do_sample=False, repetition_penalty=1.0)

    ref = generate(cfg, params, tokens, lengths, sampling)
    got = generate_quant_kv(cfg, params, tokens, lengths, sampling)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(ref.tokens))
    assert got.decode_tok_s > 0


def test_cache_capacity_check():
    cfg = tiny_config("llama", vocab_size=128, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    small = init_quant_kv_cache(cfg, 1, 8)
    try:
        generate_quant_kv(
            cfg, params, jnp.zeros((1, 6), jnp.int32), jnp.asarray([6], jnp.int32),
            SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0),
            cache=small,
        )
        raise AssertionError("expected capacity ValueError")
    except ValueError as e:
        assert "capacity" in str(e)


def test_kv_bytes_halved():
    """The int8 cache (with fp32 scales) stores ~9/16 of the bf16 cache's
    bytes per slot at head_dim 16 — the point of the backend."""
    cfg = tiny_config("llama")
    dense_c = init_kv_cache(cfg, 2, 64)
    quant_c = init_quant_kv_cache(cfg, 2, 64)
    dense_bytes = dense_c.k.nbytes + dense_c.v.nbytes
    quant_bytes = (
        quant_c.k.nbytes + quant_c.v.nbytes
        + quant_c.k_scale.nbytes + quant_c.v_scale.nbytes
    )
    assert quant_bytes < 0.65 * dense_bytes, (quant_bytes, dense_bytes)


def test_gemma2_windowless_matches_dense_exactly():
    """Gemma-2 (post-norms, soft caps, fixed query scale) through the int8
    cache, no windows: greedy tokens match the dense path exactly — the
    quantization error is below every greedy margin here."""
    cfg = tiny_config("gemma2", vocab_size=128, max_seq_len=64, dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 128, jnp.int32)
    lengths = jnp.asarray([20, 14], jnp.int32)
    sampling = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    ref = generate(cfg, params, tokens, lengths, sampling)
    got = generate_quant_kv(cfg, params, tokens, lengths, sampling)
    np.testing.assert_array_equal(np.asarray(got.tokens), np.asarray(ref.tokens))


def test_gemma2_alternating_window_assignment():
    """The int8 cache's pair-wise scan assigns the window to the SAME layers
    as the dense scan. Token equality is too strict (Gemma-2's logit soft cap
    compresses greedy margins below int8-KV rounding), so the pin is on
    prefill logits: correct assignment agrees within quantization tolerance,
    while a deliberately misassigned window (negative control: window on ALL
    layers) diverges by an order of magnitude more."""
    cfg = tiny_config("gemma2", vocab_size=128, max_seq_len=64,
                      dtype="float32").replace(sliding_window=6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 128, jnp.int32)
    lengths = jnp.asarray([20, 14], jnp.int32)

    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 2, 32))
    got, _ = forward_prefill_quant(
        cfg, params, tokens, lengths, init_quant_kv_cache(cfg, 2, 32)
    )

    def rel(a, b):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        return float(np.linalg.norm(a - b) / np.linalg.norm(b))

    good = rel(got, ref)
    assert good < 0.05, good  # quantization-rounding scale

    # Negative control: window on EVERY layer (alt off) vs the alternating
    # dense reference must look clearly wrong, proving the check has teeth.
    bad_cfg = cfg.replace(alt_sliding_window=False)
    bad, _ = forward_prefill_quant(
        bad_cfg, params, tokens, lengths, init_quant_kv_cache(bad_cfg, 2, 32)
    )
    assert rel(bad, ref) > 5 * good, (rel(bad, ref), good)


def test_remat_quant_path_traces():
    """cfg.remat=True must work on the quant scan (regression: checkpoint's
    static_argnums once pointed past the passed args and crashed at trace)."""
    cfg = tiny_config("llama", vocab_size=128, dtype="float32").replace(remat=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    out = generate_quant_kv(
        cfg, params, jnp.asarray([[5, 9, 2, 7]], jnp.int32), jnp.asarray([4], jnp.int32),
        SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0),
    )
    assert int(out.num_generated[0]) == 4
