"""Compute-observatory e2e: a real speculative serving run writes launch
and spec-round records that ``edgemesh obs compute`` attributes.

The acceptance pin for the observatory: over a slow-tier engine run's
span log, the CLI names the speculative verify round as a DISTINCT
boundary (``spec_rounds``, not folded into ``decode_loop``) and reports
round-level attribution — rounds, acceptance, per-round seconds, and the
labeled analytic draft/verify split.
"""

import json

import pytest

from edgemesh.agents.orchestrator import build_agent
from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow


def _spec_agent(max_new=8, gamma=2):
    return build_agent(AgentSpec(
        role="qa",
        model=ModelSpec(family="llama", vocab_size=260, num_layers=2,
                        hidden_size=64, num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_seq_len=128),
        draft=ModelSpec(family="llama", vocab_size=260, num_layers=1,
                        hidden_size=64, num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_seq_len=128),
        spec_gamma=gamma,
        sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                repetition_penalty=1.0),
    ))


def test_obs_compute_names_spec_round_boundary_e2e(tmp_path, monkeypatch,
                                                   capsys):
    from edgemesh.serve.continuous import SpeculativeContinuousEngine

    # Fence every post-compile launch: the run is short, and the e2e pin
    # needs measured records, not a sampling lottery.
    monkeypatch.setenv("EDGEMESH_COMPUTE_SAMPLE", "1")
    span_log = tmp_path / "spans.jsonl"
    eng = SpeculativeContinuousEngine(
        _spec_agent(), slots=4, chunk=6, kv_backend="paged", page_size=16,
        span_log=span_log)
    try:
        qs = [f"question number {i}: where is the eiffel tower?"
              for i in range(4)]
        results = [f.result() for f in [eng.submit(q) for q in qs]]
        assert all(r["generated"] > 0 for r in results)
        live = eng.compute.rollup()
    finally:
        eng.close()

    # The engine's own rollup names the round boundary distinctly.
    assert "spec_rounds" in live
    assert live["spec_rounds"]["launches"] > 0

    # The CLI over the span log agrees — and attributes rounds.
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["compute", str(span_log), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert "spec_rounds" in report["boundaries"]
    b = report["boundaries"]["spec_rounds"]
    assert b["measured"] > 0 and b["device_s"] > 0

    rounds = report["spec_rounds"]
    assert rounds is not None
    assert rounds["rounds"] > 0 and rounds["proposed"] > 0
    assert 0 <= rounds["accept_rate"] <= 1
    assert rounds["round_s"] > 0
    # The draft/verify partition is present and labeled as modeled.
    assert rounds["split"] == "analytic-flops"
    assert rounds["draft_s"] > 0 and rounds["verify_s"] > 0

    # Human rendering names the boundary and the split too.
    assert obs_main(["compute", str(span_log)]) == 0
    out = capsys.readouterr().out
    assert "spec_rounds" in out and "analytic-flops" in out
