"""edgemesh.fleet fast tier: balancer choice, backoff schedule, deadline
propagation, retries/hedging/admission, the drain state machine, and the
replica gateway's healthz/readyz/drain/hardening endpoints — all against a
fake transport (no model, no device, loopback sockets only where the HTTP
layer itself is under test)."""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from edgemesh.fleet import (
    FleetRouter,
    HealthProber,
    ReplicaRegistry,
    TransportError,
    make_balancer,
    serve_fleet,
)
from edgemesh.fleet.registry import Replica
from edgemesh.obs import Registry


# ---------------------------------------------------------------------------
# Fake transport
# ---------------------------------------------------------------------------


class FakeTransport:
    """Scripted transport: first registered URL substring that matches wins.
    Handlers return ``(status, body)`` or raise; every call is recorded."""

    def __init__(self):
        self.calls = []  # (method, url, payload, timeout_s, headers)
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def _dispatch(self, method, url, payload, timeout_s, headers):
        self.calls.append((method, url, payload, timeout_s, dict(headers or {})))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return self._dispatch("GET", url, None, timeout_s, headers)

    def post_json(self, url, payload, timeout_s, headers=None):
        return self._dispatch("POST", url, payload, timeout_s, headers)


def _registry(*rids):
    reg = ReplicaRegistry()
    for rid in rids:
        reg.register(rid, f"http://{rid}")
    return reg


def _router(reg, transport, **kw):
    kw.setdefault("obs_registry", Registry())
    kw.setdefault("rng", random.Random(0))
    return FleetRouter(reg, transport=transport, **kw)


def _refuse(url, payload, headers):
    raise TransportError(f"{url}: connection refused")


# ---------------------------------------------------------------------------
# Registry + balancers
# ---------------------------------------------------------------------------


def test_registry_membership_and_states():
    reg = _registry("r1", "r2")
    assert {r.rid for r in reg.available()} == {"r1", "r2"}
    reg.set_state("r1", "draining")
    assert {r.rid for r in reg.available()} == {"r2"}
    assert reg.deregister("r2") and not reg.deregister("r2")
    assert reg.available() == []
    # Re-register revives a removed replica, fail-open (routable at once).
    reg.set_state("r1", "removed")
    reg.register("r1", "http://r1")
    assert [r.rid for r in reg.available()] == ["r1"]
    with pytest.raises(ValueError):
        reg.set_state("r1", "sideways")


def test_registry_release_demotes_after_consecutive_failures():
    reg = _registry("r1")
    bal = make_balancer("round_robin")
    for i in range(2):
        rep = reg.acquire(bal)
        assert rep.rid == "r1" and rep.outstanding == 1
        reg.release("r1", ok=False, demote_after=2, error=f"boom {i}")
    rep = reg.get("r1")
    assert rep.state == "unhealthy" and rep.outstanding == 0
    assert rep.total_failures == 2 and "boom 1" in rep.last_error
    assert reg.acquire(bal) is None  # unhealthy replicas leave rotation


def test_register_same_url_is_idempotent_and_preserves_outstanding():
    # A duplicate register (operator retry) must NOT replace the live
    # object: outstanding accounting has to survive or a drain could
    # declare the replica safe while requests still run on it.
    reg = _registry("r1")
    bal = make_balancer("round_robin")
    rep = reg.acquire(bal)
    assert rep.outstanding == 1
    reg.set_state("r1", "unhealthy")
    revived = reg.register("r1", "http://r1")
    assert revived is rep and revived.outstanding == 1
    assert revived.state == "healthy"
    # A changed URL is a new backend: fresh object.
    replaced = reg.register("r1", "http://elsewhere")
    assert replaced is not rep and replaced.outstanding == 0


def test_round_robin_cycles_registration_order():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    bal = make_balancer("round_robin")
    picks = [bal.pick(reps).rid for _ in range(6)]
    assert picks == ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_least_outstanding_prefers_idle():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    reps[0].outstanding = 3
    reps[1].outstanding = 1
    bal = make_balancer("least_outstanding")
    assert bal.pick(reps).rid == "r2"
    reps[2].outstanding = 5
    assert bal.pick(reps).rid == "r1"


def test_prefix_affinity_is_sticky_and_stable_under_replica_death():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(4)]
    bal = make_balancer("prefix_affinity", prefix_chars=16)
    prompts = [f"shared template: question {i}?" for i in range(40)]
    # Same prefix → same replica, deterministically.
    owner = {p: bal.pick(reps, p).rid for p in prompts}
    assert owner == {p: bal.pick(reps, p).rid for p in prompts}
    # The 16-char prefix is shared here, so ALL land on one replica.
    assert len(set(owner.values())) == 1
    # Distinct prefixes spread across replicas.
    spread = {bal.pick(reps, f"prompt-{i:02d} asks something").rid
              for i in range(40)}
    assert len(spread) >= 2
    # Rendezvous property: killing one replica remaps ONLY its own keys.
    full = {i: bal.pick(reps, f"prompt-{i:02d} asks something").rid for i in range(40)}
    dead = reps[1]
    survivors = [r for r in reps if r is not dead]
    for i, rid in full.items():
        if rid != dead.rid:
            assert bal.pick(survivors, f"prompt-{i:02d} asks something").rid == rid


def test_prefix_affinity_spills_when_affine_replica_is_swamped():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    bal = make_balancer("prefix_affinity", spill_margin=2)
    affine = bal.pick(reps, "hot prompt")
    affine.outstanding = 5  # others idle: margin 5 > 2 → spill
    spilled = bal.pick(reps, "hot prompt")
    assert spilled.rid != affine.rid and spilled.outstanding == 0


# ---------------------------------------------------------------------------
# Router: retries, backoff, deadlines, admission, hedging
# ---------------------------------------------------------------------------


def test_router_retries_onto_surviving_replica_and_counts():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft, balancer="round_robin", demote_after=1)
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and body == {"answer": "ok"}
    assert headers["X-Edgemesh-Replica"] == "r2"
    assert headers["X-Edgemesh-Attempts"] == "2"
    assert reg.get("r1").state == "unhealthy"  # passive demotion
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_routed_total{replica="r2"}'] == 1
    assert m['edgemesh_fleet_retried_total{replica="r1",reason="connect"}'] == 1
    assert m["edgemesh_fleet_router_seconds"]["count"] == 1


def test_router_retries_5xx_but_returns_4xx_immediately():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", lambda u, p, h: (500, {"error": "engine died"}))
    router = _router(reg, ft, balancer="round_robin")
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"

    ft2 = FakeTransport().on("r1", lambda u, p, h: (400, {"error": "bad body"}))
    router2 = _router(_registry("r1", "r2"), ft2, balancer="round_robin")
    status, body, _ = router2.handle_generate({"question": "q?"})
    assert status == 400 and body["error"] == "bad body"  # the client's 400
    assert len(ft2.calls) == 1  # no retry on client errors


def test_router_exhausts_attempts_with_502():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("http://", _refuse)
    router = _router(reg, ft, max_attempts=3, backoff_base_s=0.001)
    status, body, _ = router.handle_generate({"question": "q?"})
    assert status == 502 and body["attempts"] == 3
    assert "refused" in body["last_error"]
    assert router.obs.summary()["edgemesh_fleet_exhausted_total"] == 1


def test_router_shed_when_no_replica():
    router = _router(ReplicaRegistry(), FakeTransport())
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 503 and headers["Retry-After"] == "1"
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="no_replica"}'] == 1


def test_backoff_schedule_is_jittered_exponential_and_capped():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("http://", _refuse)
    router = _router(reg, ft, max_attempts=4, backoff_base_s=0.1,
                     backoff_cap_s=0.3, backoff_jitter=0.5,
                     rng=random.Random(42))
    sleeps = []
    router._sleep = sleeps.append
    status, _, _ = router.handle_generate({"question": "q?"})
    assert status == 502
    assert len(sleeps) == 3  # one per retried attempt, none after the last
    for k, s in enumerate(sleeps):
        base = min(0.3, 0.1 * (2 ** k))
        assert base <= s <= base * 1.5, (k, s)


def test_deadline_propagates_and_shrinks_across_attempts():
    reg = _registry("r1", "r2")

    def slow_refuse(url, payload, headers):
        time.sleep(0.05)
        raise TransportError(f"{url}: reset")

    ft = FakeTransport().on("r1", slow_refuse)
    router = _router(reg, ft, balancer="round_robin", attempt_timeout_s=100.0,
                     backoff_base_s=0.01)
    status, _, _ = router.handle_generate({"question": "q?"}, deadline_s=5.0)
    assert status == 200
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert len(posts) == 2
    d1 = float(posts[0][4]["X-Edgemesh-Deadline-S"])
    d2 = float(posts[1][4]["X-Edgemesh-Deadline-S"])
    assert d1 <= 5.0 and d2 < d1  # the budget the replica sees shrinks
    # Per-attempt transport timeout is capped by the remaining budget
    # (the header is the same remaining value, rounded to 1 ms).
    assert posts[0][3] <= 5.0 and posts[1][3] <= d2 + 1e-3


def test_deadline_exhaustion_returns_504():
    reg = _registry("r1")

    def eat_budget(url, payload, headers):
        time.sleep(0.08)
        raise TransportError(f"{url}: reset")

    ft = FakeTransport().on("r1", eat_budget)
    router = _router(reg, ft, max_attempts=5, backoff_base_s=0.0)
    status, body, _ = router.handle_generate({"question": "q?"}, deadline_s=0.05)
    assert status == 504 and "deadline" in body["error"]
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="deadline"}'] == 1


def test_router_admission_sheds_past_max_inflight():
    reg = _registry("r1")
    release = threading.Event()

    def block(url, payload, headers):
        release.wait(5.0)
        return 200, {"answer": "slow"}

    ft = FakeTransport().on("r1", block)
    router = _router(reg, ft, max_inflight=1)
    results = []
    t = threading.Thread(
        target=lambda: results.append(router.handle_generate({"question": "a"}))
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while reg.get("r1").outstanding == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    status, body, headers = router.handle_generate({"question": "b"})
    assert status == 503 and headers["Retry-After"] == "1"
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="overload"}'] == 1
    release.set()
    t.join(timeout=5.0)
    assert results and results[0][0] == 200


def test_hedged_request_wins_on_stalled_primary():
    reg = _registry("r1", "r2")
    stall = threading.Event()

    def stalled(url, payload, headers):
        stall.wait(5.0)
        return 200, {"answer": "late"}

    ft = FakeTransport().on("r1", stalled)
    router = _router(reg, ft, balancer="round_robin", hedge_after_s=0.05)
    t0 = time.monotonic()
    status, body, _ = router.handle_generate({"question": "q?"})
    elapsed = time.monotonic() - t0
    stall.set()
    assert status == 200 and body == {"answer": "ok"}
    assert elapsed < 2.0  # did not wait out the stalled primary
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_hedged_total{replica="r2"}'] == 1
    assert m['edgemesh_fleet_hedged_won_total{replica="r2"}'] == 1


def test_fast_failure_inside_hedge_window_takes_retry_path_not_hedge():
    # A primary that fails in ~1ms is not a tail-latency event: it must go
    # through the backoff/retried path, leaving the hedge counters meaning
    # exactly "the primary was slow".
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft, balancer="round_robin", hedge_after_s=0.2)
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"
    assert headers["X-Edgemesh-Attempts"] == "2"  # retry, not hedge
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_retried_total{replica="r1",reason="connect"}'] == 1
    assert not any("hedged" in k for k in m)


def test_drain_transient_poll_failure_does_not_complete_drain():
    # One failed /readyz poll is indistinguishable from a GC pause — only
    # a streak may conclude the replica is gone.
    reg = _registry("r1")
    polls = iter([
        "refuse",                      # transient blip
        {"inflight": 1},               # still draining in-flight work
        {"inflight": 0},               # now actually drained
    ])

    def readyz(url, payload, headers):
        step = next(polls, {"inflight": 0})
        if step == "refuse":
            raise TransportError(f"{url}: reset")
        return 503, {"ready": False, "draining": True, **step}

    ft = FakeTransport().on("r1/drain", lambda u, p, h: (200, {"draining": True}))
    ft.on("r1/readyz", readyz)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=5.0)
    assert out["drained"] is True
    # The transient failure cost one extra poll, not a premature removal.
    assert len([c for c in ft.calls if c[1].endswith("/readyz")]) == 3


def test_adaptive_hedge_delay_needs_a_window():
    router = _router(_registry("r1"), FakeTransport(), hedge_percentile=0.95)
    assert router._hedge_delay() is None  # no samples yet: no hedging
    for _ in range(32):
        router._lat_window.append(0.01)
    router._lat_window.append(5.0)
    delay = router._hedge_delay()
    assert delay is not None and 0.01 <= delay <= 5.0


# ---------------------------------------------------------------------------
# Drain state machine
# ---------------------------------------------------------------------------


def test_drain_state_machine_zero_inflight_then_removed():
    reg = _registry("r1", "r2")
    inflight = {"n": 2}

    def readyz(url, payload, headers):
        n, inflight["n"] = inflight["n"], max(0, inflight["n"] - 1)
        return 503, {"ready": False, "draining": True, "inflight": n}

    ft = FakeTransport().on("r1/drain", lambda u, p, h: (200, {"draining": True}))
    ft.on("r1/readyz", readyz)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=5.0)
    assert out == {"replica": "r1", "drained": True, "inflight": 0}
    assert reg.get("r1").state == "removed"
    # The drain hook fired before the readyz poll loop.
    urls = [c[1] for c in ft.calls]
    assert urls[0].endswith("/drain") and urls[1].endswith("/readyz")
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_drain_total{replica="r1",event="started"}'] == 1
    assert m['edgemesh_fleet_drain_total{replica="r1",event="completed"}'] == 1
    # Traffic keeps flowing — to the survivor only.
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"


def test_drain_unknown_replica_and_dead_replica():
    reg = _registry("r1")
    assert "error" in FleetRouter(
        reg, transport=FakeTransport(), obs_registry=Registry()
    ).drain_replica("nope")
    # A replica that died before the drain: unreachable readyz counts as
    # drained (nothing left in flight to wait for).
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=1.0)
    assert out["drained"] is True and reg.get("r1").state == "removed"


# ---------------------------------------------------------------------------
# Health prober
# ---------------------------------------------------------------------------


def test_prober_demotes_and_repromotes():
    reg = _registry("r1")
    healthy = {"ok": False}

    def readyz(url, payload, headers):
        if healthy["ok"]:
            return 200, {"ready": True, "inflight": 0}
        raise TransportError(f"{url}: refused")

    ft = FakeTransport().on("r1/readyz", readyz)
    prober = HealthProber(reg, transport=ft, unhealthy_after=2,
                          healthy_after=2, obs_registry=Registry())
    assert prober.probe_once() == {"r1": "healthy"}  # 1 failure < threshold
    assert prober.probe_once() == {"r1": "unhealthy"}
    healthy["ok"] = True
    assert prober.probe_once() == {"r1": "unhealthy"}  # 1 success < threshold
    assert prober.probe_once() == {"r1": "healthy"}


def test_prober_never_unrains_a_draining_replica():
    reg = _registry("r1")
    reg.set_state("r1", "draining")
    ft = FakeTransport().on("r1/readyz", lambda u, p, h: (200, {"ready": True}))
    prober = HealthProber(reg, transport=ft, obs_registry=Registry())
    assert prober.probe_once() == {"r1": "draining"}


def test_prober_background_loop_runs_and_stops():
    reg = _registry("r1")
    ft = FakeTransport().on("r1/readyz", lambda u, p, h: (200, {"ready": True}))
    prober = HealthProber(reg, transport=ft, interval_s=0.01,
                          obs_registry=Registry()).start()
    deadline = time.monotonic() + 5.0
    while not ft.calls and time.monotonic() < deadline:
        time.sleep(0.01)
    prober.stop()
    assert ft.calls and reg.get("r1").last_probe_ts is not None


# ---------------------------------------------------------------------------
# Fleet HTTP frontend (real loopback sockets, fake replicas)
# ---------------------------------------------------------------------------


@pytest.fixture()
def frontend():
    reg = _registry("r1")
    ft = FakeTransport()
    router = _router(reg, ft)
    srv = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    yield srv, router, ft
    srv.shutdown()


def _http(srv, path, data=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.server_address[1]}{path}", data=data,
        headers=dict(headers or {}),
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def test_frontend_routes_and_exposes_fleet_state(frontend):
    srv, router, ft = frontend
    status, body, headers = _http(
        srv, "/generate", data=json.dumps({"question": "q?"}).encode()
    )
    assert status == 200 and body == {"answer": "ok"}
    assert headers["X-Edgemesh-Replica"] == "r1"
    # Client deadline header caps the routed budget.
    _http(srv, "/generate", data=json.dumps({"question": "q?"}).encode(),
          headers={"X-Edgemesh-Deadline-S": "7"})
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert float(posts[-1][4]["X-Edgemesh-Deadline-S"]) <= 7.0

    status, body, _ = _http(srv, "/fleetz")
    assert status == 200 and body["replicas"][0]["id"] == "r1"
    assert body["metrics"]['edgemesh_fleet_routed_total{replica="r1"}'] == 2

    status, body, _ = _http(srv, "/healthz")
    assert status == 200
    status, body, _ = _http(srv, "/readyz")
    assert status == 200 and body["available"] == 1

    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.server_address[1]}/metrics", timeout=30
    ) as r:
        text = r.read().decode()
    assert 'edgemesh_fleet_routed_total{replica="r1"} 2' in text
    assert "edgemesh_fleet_router_seconds_bucket" in text


def test_frontend_bad_bodies_and_membership(frontend):
    srv, router, ft = frontend
    status, body, _ = _http(srv, "/generate", data=b"not json")
    assert status == 400 and "JSON" in body["error"]
    status, body, _ = _http(srv, "/generate", data=b"[1, 2]")
    assert status == 400 and "object" in body["error"]
    status, body, _ = _http(
        srv, "/generate", data=json.dumps({"question": "q"}).encode(),
        headers={"X-Edgemesh-Deadline-S": "soon"},
    )
    assert status == 400
    status, _, _ = _http(srv, "/nope", data=b"{}")
    assert status == 404

    # Runtime membership: register / deregister via the API.
    status, body, _ = _http(
        srv, "/replicas/register",
        data=json.dumps({"id": "r9", "url": "http://r9"}).encode(),
    )
    assert status == 200 and body["registered"] == "r9"
    assert {r.rid for r in router.registry.replicas()} == {"r1", "r9"}
    status, body, _ = _http(
        srv, "/replicas/deregister", data=json.dumps({"id": "r9"}).encode()
    )
    assert status == 200 and body["deregistered"] is True
    status, body, _ = _http(srv, "/replicas/drain", data=b"{}")
    assert status == 400  # missing id

    status, _, _ = _http(srv, "/readyz")
    assert status == 200


def test_router_status_shape():
    router = _router(_registry("r1"), FakeTransport(), balancer="prefix_affinity")
    st = router.status()
    assert st["balancer"] == "prefix_affinity"
    assert st["replicas"][0]["state"] == "healthy"
    assert isinstance(st["metrics"], dict)


def test_make_balancer_rejects_unknown():
    with pytest.raises(ValueError, match="unknown balancer"):
        make_balancer("fastest_first")


# ---------------------------------------------------------------------------
# Replica gateway (serve/rest.py): healthz/readyz/drain + hardening.
# A stub ensemble keeps this fast — the HTTP lifecycle is under test, not
# the model.
# ---------------------------------------------------------------------------


class _StubEnsemble:
    qa_agents = ()
    refiner = None

    def __init__(self, answer_fn=None):
        self._answer = answer_fn

    def answer(self, question):
        if self._answer is not None:
            return self._answer(question)
        return {"answer": f"echo:{question}"}


def _serve_stub(**kw):
    from edgemesh.serve import serve_rest

    kw.setdefault("registry", Registry())
    return serve_rest(_StubEnsemble(kw.pop("answer_fn", None)),
                      host="127.0.0.1", port=0, block=False, **kw)


def test_gateway_healthz_readyz_and_drain_state_machine():
    srv = _serve_stub()
    try:
        status, body, _ = _http(srv, "/healthz")
        assert status == 200 and body == {"status": "ok"}
        status, body, _ = _http(srv, "/readyz")
        assert status == 200
        assert body == {"ready": True, "draining": False, "inflight": 0}

        status, body, _ = _http(srv, "/drain", data=b"{}")
        assert status == 200 and body["draining"] is True

        # Drain-aware readiness: alive (healthz 200) but NOT ready.
        status, _, _ = _http(srv, "/healthz")
        assert status == 200
        status, body, _ = _http(srv, "/readyz")
        assert status == 503 and body["draining"] is True

        # New work is refused with 503 + Retry-After.
        status, body, headers = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode()
        )
        assert status == 503 and "draining" in body["error"]
        assert headers["Retry-After"] == "1"
    finally:
        srv.shutdown()


def test_gateway_drain_waits_for_inflight_requests():
    gate = threading.Event()
    started = threading.Event()

    def slow_answer(question):
        started.set()
        gate.wait(10.0)
        return {"answer": "done"}

    srv = _serve_stub(answer_fn=slow_answer)
    try:
        results = []
        t = threading.Thread(target=lambda: results.append(
            _http(srv, "/generate", data=json.dumps({"question": "q"}).encode())
        ))
        t.start()
        assert started.wait(5.0)
        # Drain with a request in flight: draining flips immediately...
        out = srv.drain(wait=True, timeout_s=0.05)
        assert out["draining"] is True and out["drained"] is False
        assert out["inflight"] == 1
        # ... and the in-flight request still completes (zero dropped).
        gate.set()
        t.join(timeout=10.0)
        assert results and results[0][0] == 200
        assert results[0][1]["answer"] == "done"
        out = srv.drain(wait=True, timeout_s=5.0)
        assert out["drained"] is True and out["inflight"] == 0
    finally:
        srv.shutdown()


def test_gateway_sheds_past_max_inflight():
    gate = threading.Event()
    started = threading.Event()

    def slow_answer(question):
        started.set()
        gate.wait(10.0)
        return {"answer": "done"}

    srv = _serve_stub(answer_fn=slow_answer, max_inflight=1)
    try:
        results = []
        t = threading.Thread(target=lambda: results.append(
            _http(srv, "/generate", data=json.dumps({"question": "a"}).encode())
        ))
        t.start()
        assert started.wait(5.0)
        status, body, headers = _http(
            srv, "/generate", data=json.dumps({"question": "b"}).encode()
        )
        assert status == 503 and body["error"] == "overloaded"
        assert headers["Retry-After"] == "1"
        gate.set()
        t.join(timeout=10.0)
        assert results and results[0][0] == 200
    finally:
        srv.shutdown()


def test_gateway_admission_check_and_increment_is_atomic():
    # A burst of N+1 concurrent requests against max_inflight=N must shed
    # exactly one — a split check/increment would shed all of them.
    srv = _serve_stub(max_inflight=2)
    try:
        assert [srv.begin_request() for _ in range(3)] == \
            ["ok", "ok", "overloaded"]
        srv.end_request()
        assert srv.begin_request() == "ok"  # freed capacity readmits
        srv.end_request()
        srv.end_request()
        assert srv.inflight() == 0
    finally:
        srv.shutdown()


def test_gateway_malformed_inputs_are_structured_400s():
    srv = _serve_stub()
    try:
        status, body, _ = _http(srv, "/generate", data=b"not json")
        assert status == 400 and body["error"] == "invalid JSON body"
        status, body, _ = _http(srv, "/generate", data=b"[1, 2]")
        assert status == 400 and "object" in body["error"]
        status, body, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "soon"},
        )
        assert status == 400 and "X-Edgemesh-Deadline-S" in body["error"]

        # A garbage Content-Length header (hand-rolled request) is a 400,
        # not an unhandled int() ValueError → 500.
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=30
        )
        try:
            conn.putrequest("POST", "/generate")
            conn.putheader("Content-Length", "nope")
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 400
            assert "Content-Length" in json.load(resp)["error"]
        finally:
            conn.close()
    finally:
        srv.shutdown()


def test_gateway_refuses_expired_propagated_deadline():
    calls = []
    srv = _serve_stub(answer_fn=lambda q: calls.append(q) or {"answer": "x"})
    try:
        status, body, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "0"},
        )
        assert status == 504 and "deadline" in body["error"]
        assert calls == []  # refused BEFORE any model work
        status, _, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "30"},
        )
        assert status == 200 and calls == ["q"]
    finally:
        srv.shutdown()


def test_gateway_socket_timeout_is_applied_per_connection():
    srv = _serve_stub(request_timeout_s=0.2)
    try:
        import socket

        # A client that opens a connection, sends half a request, and
        # stalls: the handler thread must be reclaimed by the socket
        # timeout instead of pinned forever.
        s = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]), timeout=5.0
        )
        try:
            s.sendall(b"POST /generate HTTP/1.1\r\nContent-Length: 999\r\n\r\n{")
            t0 = time.monotonic()
            # Server must close the connection (empty read) in bounded time.
            s.settimeout(5.0)
            data = s.recv(1024)
            assert time.monotonic() - t0 < 5.0
            assert data == b""  # dropped, no half-baked 500
        finally:
            s.close()
        # The gateway still serves afterwards.
        status, _, _ = _http(srv, "/healthz")
        assert status == 200
    finally:
        srv.shutdown()
