"""edgemesh.fleet fast tier: balancer choice, backoff schedule, deadline
propagation, retries/hedging/admission, the drain state machine, and the
replica gateway's healthz/readyz/drain/hardening endpoints — all against a
fake transport (no model, no device, loopback sockets only where the HTTP
layer itself is under test)."""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from edgemesh.fleet import (
    FleetRouter,
    HealthProber,
    ReplicaRegistry,
    TransportError,
    make_balancer,
    serve_fleet,
)
from edgemesh.fleet.registry import Replica
from edgemesh.obs import Registry


# ---------------------------------------------------------------------------
# Fake transport
# ---------------------------------------------------------------------------


class FakeTransport:
    """Scripted transport: first registered URL substring that matches wins.
    Handlers return ``(status, body)`` or raise; every call is recorded."""

    def __init__(self):
        self.calls = []  # (method, url, payload, timeout_s, headers)
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def _dispatch(self, method, url, payload, timeout_s, headers):
        self.calls.append((method, url, payload, timeout_s, dict(headers or {})))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return self._dispatch("GET", url, None, timeout_s, headers)

    def post_json(self, url, payload, timeout_s, headers=None):
        return self._dispatch("POST", url, payload, timeout_s, headers)


def _registry(*rids):
    reg = ReplicaRegistry()
    for rid in rids:
        reg.register(rid, f"http://{rid}")
    return reg


def _router(reg, transport, **kw):
    kw.setdefault("obs_registry", Registry())
    kw.setdefault("rng", random.Random(0))
    return FleetRouter(reg, transport=transport, **kw)


def _refuse(url, payload, headers):
    raise TransportError(f"{url}: connection refused")


# ---------------------------------------------------------------------------
# Registry + balancers
# ---------------------------------------------------------------------------


def test_registry_membership_and_states():
    reg = _registry("r1", "r2")
    assert {r.rid for r in reg.available()} == {"r1", "r2"}
    reg.set_state("r1", "draining")
    assert {r.rid for r in reg.available()} == {"r2"}
    assert reg.deregister("r2") and not reg.deregister("r2")
    assert reg.available() == []
    # Re-register revives a removed replica, fail-open (routable at once).
    reg.set_state("r1", "removed")
    reg.register("r1", "http://r1")
    assert [r.rid for r in reg.available()] == ["r1"]
    with pytest.raises(ValueError):
        reg.set_state("r1", "sideways")


def test_registry_release_demotes_after_consecutive_failures():
    reg = _registry("r1")
    bal = make_balancer("round_robin")
    for i in range(2):
        rep = reg.acquire(bal)
        assert rep.rid == "r1" and rep.outstanding == 1
        reg.release("r1", ok=False, demote_after=2, error=f"boom {i}")
    rep = reg.get("r1")
    assert rep.state == "unhealthy" and rep.outstanding == 0
    assert rep.total_failures == 2 and "boom 1" in rep.last_error
    assert reg.acquire(bal) is None  # unhealthy replicas leave rotation


def test_register_same_url_is_idempotent_and_preserves_outstanding():
    # A duplicate register (operator retry) must NOT replace the live
    # object: outstanding accounting has to survive or a drain could
    # declare the replica safe while requests still run on it.
    reg = _registry("r1")
    bal = make_balancer("round_robin")
    rep = reg.acquire(bal)
    assert rep.outstanding == 1
    reg.set_state("r1", "unhealthy")
    revived = reg.register("r1", "http://r1")
    assert revived is rep and revived.outstanding == 1
    assert revived.state == "healthy"
    # A changed URL is a new backend: fresh object.
    replaced = reg.register("r1", "http://elsewhere")
    assert replaced is not rep and replaced.outstanding == 0


def test_round_robin_cycles_registration_order():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    bal = make_balancer("round_robin")
    picks = [bal.pick(reps).rid for _ in range(6)]
    assert picks == ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_least_outstanding_prefers_idle():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    reps[0].outstanding = 3
    reps[1].outstanding = 1
    bal = make_balancer("least_outstanding")
    assert bal.pick(reps).rid == "r2"
    reps[2].outstanding = 5
    assert bal.pick(reps).rid == "r1"


def test_prefix_affinity_is_sticky_and_stable_under_replica_death():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(4)]
    bal = make_balancer("prefix_affinity", prefix_chars=16)
    prompts = [f"shared template: question {i}?" for i in range(40)]
    # Same prefix → same replica, deterministically.
    owner = {p: bal.pick(reps, p).rid for p in prompts}
    assert owner == {p: bal.pick(reps, p).rid for p in prompts}
    # The 16-char prefix is shared here, so ALL land on one replica.
    assert len(set(owner.values())) == 1
    # Distinct prefixes spread across replicas.
    spread = {bal.pick(reps, f"prompt-{i:02d} asks something").rid
              for i in range(40)}
    assert len(spread) >= 2
    # Rendezvous property: killing one replica remaps ONLY its own keys.
    full = {i: bal.pick(reps, f"prompt-{i:02d} asks something").rid for i in range(40)}
    dead = reps[1]
    survivors = [r for r in reps if r is not dead]
    for i, rid in full.items():
        if rid != dead.rid:
            assert bal.pick(survivors, f"prompt-{i:02d} asks something").rid == rid


def test_prefix_affinity_spills_when_affine_replica_is_swamped():
    reps = [Replica(rid=f"r{i}", base_url="http://x") for i in range(3)]
    bal = make_balancer("prefix_affinity", spill_margin=2)
    affine = bal.pick(reps, "hot prompt")
    affine.outstanding = 5  # others idle: margin 5 > 2 → spill
    spilled = bal.pick(reps, "hot prompt")
    assert spilled.rid != affine.rid and spilled.outstanding == 0


# ---------------------------------------------------------------------------
# Router: retries, backoff, deadlines, admission, hedging
# ---------------------------------------------------------------------------


def test_router_retries_onto_surviving_replica_and_counts():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft, balancer="round_robin", demote_after=1)
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and body == {"answer": "ok"}
    assert headers["X-Edgemesh-Replica"] == "r2"
    assert headers["X-Edgemesh-Attempts"] == "2"
    assert reg.get("r1").state == "unhealthy"  # passive demotion
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_routed_total{replica="r2"}'] == 1
    assert m['edgemesh_fleet_retried_total{replica="r1",reason="connect"}'] == 1
    assert m["edgemesh_fleet_router_seconds"]["count"] == 1


def test_router_retries_5xx_but_returns_4xx_immediately():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", lambda u, p, h: (500, {"error": "engine died"}))
    router = _router(reg, ft, balancer="round_robin")
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"

    ft2 = FakeTransport().on("r1", lambda u, p, h: (400, {"error": "bad body"}))
    router2 = _router(_registry("r1", "r2"), ft2, balancer="round_robin")
    status, body, _ = router2.handle_generate({"question": "q?"})
    assert status == 400 and body["error"] == "bad body"  # the client's 400
    assert len(ft2.calls) == 1  # no retry on client errors


def test_router_exhausts_attempts_with_502():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("http://", _refuse)
    router = _router(reg, ft, max_attempts=3, backoff_base_s=0.001)
    status, body, _ = router.handle_generate({"question": "q?"})
    assert status == 502 and body["attempts"] == 3
    assert "refused" in body["last_error"]
    assert router.obs.summary()["edgemesh_fleet_exhausted_total"] == 1


def test_router_shed_when_no_replica():
    router = _router(ReplicaRegistry(), FakeTransport())
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 503 and headers["Retry-After"] == "1"
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="no_replica"}'] == 1


def test_backoff_schedule_is_jittered_exponential_and_capped():
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("http://", _refuse)
    router = _router(reg, ft, max_attempts=4, backoff_base_s=0.1,
                     backoff_cap_s=0.3, backoff_jitter=0.5,
                     rng=random.Random(42))
    sleeps = []
    router._sleep = sleeps.append
    status, _, _ = router.handle_generate({"question": "q?"})
    assert status == 502
    assert len(sleeps) == 3  # one per retried attempt, none after the last
    for k, s in enumerate(sleeps):
        base = min(0.3, 0.1 * (2 ** k))
        assert base <= s <= base * 1.5, (k, s)


def test_deadline_propagates_and_shrinks_across_attempts():
    reg = _registry("r1", "r2")

    def slow_refuse(url, payload, headers):
        time.sleep(0.05)
        raise TransportError(f"{url}: reset")

    ft = FakeTransport().on("r1", slow_refuse)
    router = _router(reg, ft, balancer="round_robin", attempt_timeout_s=100.0,
                     backoff_base_s=0.01)
    status, _, _ = router.handle_generate({"question": "q?"}, deadline_s=5.0)
    assert status == 200
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert len(posts) == 2
    d1 = float(posts[0][4]["X-Edgemesh-Deadline-S"])
    d2 = float(posts[1][4]["X-Edgemesh-Deadline-S"])
    assert d1 <= 5.0 and d2 < d1  # the budget the replica sees shrinks
    # Per-attempt transport timeout is capped by the remaining budget
    # (the header is the same remaining value, rounded to 1 ms).
    assert posts[0][3] <= 5.0 and posts[1][3] <= d2 + 1e-3


def test_deadline_exhaustion_returns_504():
    reg = _registry("r1")

    def eat_budget(url, payload, headers):
        time.sleep(0.08)
        raise TransportError(f"{url}: reset")

    ft = FakeTransport().on("r1", eat_budget)
    router = _router(reg, ft, max_attempts=5, backoff_base_s=0.0)
    status, body, _ = router.handle_generate({"question": "q?"}, deadline_s=0.05)
    assert status == 504 and "deadline" in body["error"]
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="deadline"}'] == 1


def test_router_admission_sheds_past_max_inflight():
    reg = _registry("r1")
    release = threading.Event()

    def block(url, payload, headers):
        release.wait(5.0)
        return 200, {"answer": "slow"}

    ft = FakeTransport().on("r1", block)
    router = _router(reg, ft, max_inflight=1)
    results = []
    t = threading.Thread(
        target=lambda: results.append(router.handle_generate({"question": "a"}))
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while reg.get("r1").outstanding == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    status, body, headers = router.handle_generate({"question": "b"})
    assert status == 503 and headers["Retry-After"] == "1"
    assert router.obs.summary()['edgemesh_fleet_shed_total{reason="overload"}'] == 1
    release.set()
    t.join(timeout=5.0)
    assert results and results[0][0] == 200


def test_hedged_request_wins_on_stalled_primary():
    reg = _registry("r1", "r2")
    stall = threading.Event()

    def stalled(url, payload, headers):
        stall.wait(5.0)
        return 200, {"answer": "late"}

    ft = FakeTransport().on("r1", stalled)
    router = _router(reg, ft, balancer="round_robin", hedge_after_s=0.05)
    t0 = time.monotonic()
    status, body, _ = router.handle_generate({"question": "q?"})
    elapsed = time.monotonic() - t0
    stall.set()
    assert status == 200 and body == {"answer": "ok"}
    assert elapsed < 2.0  # did not wait out the stalled primary
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_hedged_total{replica="r2"}'] == 1
    assert m['edgemesh_fleet_hedged_won_total{replica="r2"}'] == 1


def test_fast_failure_inside_hedge_window_takes_retry_path_not_hedge():
    # A primary that fails in ~1ms is not a tail-latency event: it must go
    # through the backoff/retried path, leaving the hedge counters meaning
    # exactly "the primary was slow".
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft, balancer="round_robin", hedge_after_s=0.2)
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"
    assert headers["X-Edgemesh-Attempts"] == "2"  # retry, not hedge
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_retried_total{replica="r1",reason="connect"}'] == 1
    assert not any("hedged" in k for k in m)


def test_drain_transient_poll_failure_does_not_complete_drain():
    # One failed /readyz poll is indistinguishable from a GC pause — only
    # a streak may conclude the replica is gone.
    reg = _registry("r1")
    polls = iter([
        "refuse",                      # transient blip
        {"inflight": 1},               # still draining in-flight work
        {"inflight": 0},               # now actually drained
    ])

    def readyz(url, payload, headers):
        step = next(polls, {"inflight": 0})
        if step == "refuse":
            raise TransportError(f"{url}: reset")
        return 503, {"ready": False, "draining": True, **step}

    ft = FakeTransport().on("r1/drain", lambda u, p, h: (200, {"draining": True}))
    ft.on("r1/readyz", readyz)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=5.0)
    assert out["drained"] is True
    # The transient failure cost one extra poll, not a premature removal.
    assert len([c for c in ft.calls if c[1].endswith("/readyz")]) == 3


def test_adaptive_hedge_delay_needs_a_window():
    router = _router(_registry("r1"), FakeTransport(), hedge_percentile=0.95)
    assert router._hedge_delay() is None  # no samples yet: no hedging
    for _ in range(32):
        router._lat_window.append(0.01)
    router._lat_window.append(5.0)
    delay = router._hedge_delay()
    assert delay is not None and 0.01 <= delay <= 5.0


def test_auto_hedge_delay_tracks_decayed_p95_with_a_floor():
    router = _router(_registry("r1"), FakeTransport(), hedge_auto=True)
    assert router._hedge_delay() is None  # estimator empty: no hedging yet
    for _ in range(40):
        router._hedge_estimator.observe(0.010)
    # Healthy sub-floor latencies: the floor stops hedge storms.
    assert router._hedge_delay() == router.hedge_floor_s
    for _ in range(40):
        router._hedge_estimator.observe(1.0)
    delay = router._hedge_delay()
    assert router.hedge_floor_s < delay <= 1.5  # tracked the new regime


def test_auto_hedge_wins_on_stalled_primary_with_zero_config():
    # The tentpole contract: no hedge_after_s, no percentile — the delay
    # auto-tunes from observed latencies, and a stalled primary still gets
    # hedged around within the request budget.
    reg = _registry("r1", "r2")
    stall = threading.Event()

    def stalled(url, payload, headers):
        stall.wait(5.0)
        return 200, {"answer": "late"}

    ft = FakeTransport().on("r1", stalled)
    router = _router(reg, ft, balancer="round_robin", hedge_auto=True)
    for _ in range(40):  # the live window a warm router would have
        router._hedge_estimator.observe(0.01)
    t0 = time.monotonic()
    status, body, _ = router.handle_generate({"question": "q?"})
    elapsed = time.monotonic() - t0
    stall.set()
    assert status == 200 and body == {"answer": "ok"}
    assert elapsed < 2.0
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_hedged_won_total{replica="r2"}'] == 1


def test_latency_window_is_bounded_and_exposed_in_status():
    reg = _registry("r1")
    router = _router(reg, FakeTransport(), latency_window=8)
    for _ in range(20):
        router.handle_generate({"question": "q?"})
    st = router.status()
    # Explicit ring: 20 successes, only the configured bound retained.
    assert st["latency_window"] == {"size": 8, "len": 8}
    assert st["hedge"]["mode"] == "off" and st["hedge"]["delay_s"] is None
    assert st["hedge"]["estimator_weight"] > 0


def test_router_latency_histogram_labeled_by_outcome():
    # ok / retried / shed each land in the labeled histogram; the unlabeled
    # total keeps its successful-requests-only semantics.
    reg = _registry("r1", "r2")
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft, balancer="round_robin", backoff_base_s=0.001)
    router.handle_generate({"question": "q?"})  # r1 fails → retried onto r2
    router.handle_generate({"question": "q?"})  # round-robin lands on r1 again
    m = router.obs.summary(prefix="edgemesh_fleet_")
    by_outcome = {
        k: v["count"] for k, v in m.items()
        if k.startswith("edgemesh_fleet_router_outcome_seconds")
        and isinstance(v, dict)
    }
    assert by_outcome.get(
        'edgemesh_fleet_router_outcome_seconds{outcome="retried"}') >= 1
    total_labeled = sum(by_outcome.values())
    assert total_labeled == 2
    # Empty fleet → shed lands in the distribution too.
    router2 = _router(ReplicaRegistry(), FakeTransport())
    router2.handle_generate({"question": "q?"})
    m2 = router2.obs.summary(prefix="edgemesh_fleet_")
    assert m2['edgemesh_fleet_router_outcome_seconds{outcome="shed"}']["count"] == 1
    # The unlabeled family saw no successful request.
    assert "edgemesh_fleet_router_seconds" not in m2


# ---------------------------------------------------------------------------
# Drain state machine
# ---------------------------------------------------------------------------


def test_drain_state_machine_zero_inflight_then_removed():
    reg = _registry("r1", "r2")
    inflight = {"n": 2}

    def readyz(url, payload, headers):
        n, inflight["n"] = inflight["n"], max(0, inflight["n"] - 1)
        return 503, {"ready": False, "draining": True, "inflight": n}

    ft = FakeTransport().on("r1/drain", lambda u, p, h: (200, {"draining": True}))
    ft.on("r1/readyz", readyz)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=5.0)
    assert out == {"replica": "r1", "drained": True, "inflight": 0}
    assert reg.get("r1").state == "removed"
    # The drain hook fired before the readyz poll loop.
    urls = [c[1] for c in ft.calls]
    assert urls[0].endswith("/drain") and urls[1].endswith("/readyz")
    m = router.obs.summary(prefix="edgemesh_fleet_")
    assert m['edgemesh_fleet_drain_total{replica="r1",event="started"}'] == 1
    assert m['edgemesh_fleet_drain_total{replica="r1",event="completed"}'] == 1
    # Traffic keeps flowing — to the survivor only.
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200 and headers["X-Edgemesh-Replica"] == "r2"


def test_drain_unknown_replica_and_dead_replica():
    reg = _registry("r1")
    assert "error" in FleetRouter(
        reg, transport=FakeTransport(), obs_registry=Registry()
    ).drain_replica("nope")
    # A replica that died before the drain: unreachable readyz counts as
    # drained (nothing left in flight to wait for).
    ft = FakeTransport().on("r1", _refuse)
    router = _router(reg, ft)
    router._sleep = lambda s: None
    out = router.drain_replica("r1", timeout_s=1.0)
    assert out["drained"] is True and reg.get("r1").state == "removed"


# ---------------------------------------------------------------------------
# Health prober
# ---------------------------------------------------------------------------


def test_prober_demotes_and_repromotes():
    reg = _registry("r1")
    healthy = {"ok": False}

    def readyz(url, payload, headers):
        if healthy["ok"]:
            return 200, {"ready": True, "inflight": 0}
        raise TransportError(f"{url}: refused")

    ft = FakeTransport().on("r1/readyz", readyz)
    prober = HealthProber(reg, transport=ft, unhealthy_after=2,
                          healthy_after=2, obs_registry=Registry())
    assert prober.probe_once() == {"r1": "healthy"}  # 1 failure < threshold
    assert prober.probe_once() == {"r1": "unhealthy"}
    healthy["ok"] = True
    assert prober.probe_once() == {"r1": "unhealthy"}  # 1 success < threshold
    assert prober.probe_once() == {"r1": "healthy"}


def test_prober_never_unrains_a_draining_replica():
    reg = _registry("r1")
    reg.set_state("r1", "draining")
    ft = FakeTransport().on("r1/readyz", lambda u, p, h: (200, {"ready": True}))
    prober = HealthProber(reg, transport=ft, obs_registry=Registry())
    assert prober.probe_once() == {"r1": "draining"}


def test_prober_background_loop_runs_and_stops():
    reg = _registry("r1")
    ft = FakeTransport().on("r1/readyz", lambda u, p, h: (200, {"ready": True}))
    prober = HealthProber(reg, transport=ft, interval_s=0.01,
                          obs_registry=Registry()).start()
    deadline = time.monotonic() + 5.0
    while not ft.calls and time.monotonic() < deadline:
        time.sleep(0.01)
    prober.stop()
    assert ft.calls and reg.get("r1").last_probe_ts is not None


# ---------------------------------------------------------------------------
# Fleet HTTP frontend (real loopback sockets, fake replicas)
# ---------------------------------------------------------------------------


@pytest.fixture()
def frontend():
    reg = _registry("r1")
    ft = FakeTransport()
    router = _router(reg, ft)
    srv = serve_fleet(router, host="127.0.0.1", port=0, block=False)
    yield srv, router, ft
    srv.shutdown()


def _http(srv, path, data=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.server_address[1]}{path}", data=data,
        headers=dict(headers or {}),
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e), dict(e.headers)


def test_frontend_routes_and_exposes_fleet_state(frontend):
    srv, router, ft = frontend
    status, body, headers = _http(
        srv, "/generate", data=json.dumps({"question": "q?"}).encode()
    )
    assert status == 200 and body == {"answer": "ok"}
    assert headers["X-Edgemesh-Replica"] == "r1"
    # Client deadline header caps the routed budget.
    _http(srv, "/generate", data=json.dumps({"question": "q?"}).encode(),
          headers={"X-Edgemesh-Deadline-S": "7"})
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert float(posts[-1][4]["X-Edgemesh-Deadline-S"]) <= 7.0

    status, body, _ = _http(srv, "/fleetz")
    assert status == 200 and body["replicas"][0]["id"] == "r1"
    assert body["metrics"]['edgemesh_fleet_routed_total{replica="r1"}'] == 2

    status, body, _ = _http(srv, "/healthz")
    assert status == 200
    status, body, _ = _http(srv, "/readyz")
    assert status == 200 and body["available"] == 1

    with urllib.request.urlopen(
        f"http://127.0.0.1:{srv.server_address[1]}/metrics", timeout=30
    ) as r:
        text = r.read().decode()
    assert 'edgemesh_fleet_routed_total{replica="r1"} 2' in text
    assert "edgemesh_fleet_router_seconds_bucket" in text


def test_frontend_bad_bodies_and_membership(frontend):
    srv, router, ft = frontend
    status, body, _ = _http(srv, "/generate", data=b"not json")
    assert status == 400 and "JSON" in body["error"]
    status, body, _ = _http(srv, "/generate", data=b"[1, 2]")
    assert status == 400 and "object" in body["error"]
    status, body, _ = _http(
        srv, "/generate", data=json.dumps({"question": "q"}).encode(),
        headers={"X-Edgemesh-Deadline-S": "soon"},
    )
    assert status == 400
    status, _, _ = _http(srv, "/nope", data=b"{}")
    assert status == 404

    # Runtime membership: register / deregister via the API.
    status, body, _ = _http(
        srv, "/replicas/register",
        data=json.dumps({"id": "r9", "url": "http://r9"}).encode(),
    )
    assert status == 200 and body["registered"] == "r9"
    assert {r.rid for r in router.registry.replicas()} == {"r1", "r9"}
    status, body, _ = _http(
        srv, "/replicas/deregister", data=json.dumps({"id": "r9"}).encode()
    )
    assert status == 200 and body["deregistered"] is True
    status, body, _ = _http(srv, "/replicas/drain", data=b"{}")
    assert status == 400  # missing id

    status, _, _ = _http(srv, "/readyz")
    assert status == 200


def test_router_status_shape():
    router = _router(_registry("r1"), FakeTransport(), balancer="prefix_affinity")
    st = router.status()
    assert st["balancer"] == "prefix_affinity"
    assert st["replicas"][0]["state"] == "healthy"
    assert isinstance(st["metrics"], dict)


def test_make_balancer_rejects_unknown():
    with pytest.raises(ValueError, match="unknown balancer"):
        make_balancer("fastest_first")


def test_make_balancer_bad_kwargs_is_a_valueerror_naming_the_policy():
    # Constructor kwarg typos surface as a ValueError naming the policy,
    # not a bare TypeError from deep inside a constructor.
    with pytest.raises(ValueError, match="telemetry"):
        make_balancer("telemetry", staleness=5.0)
    with pytest.raises(ValueError, match="round_robin"):
        make_balancer("round_robin", prefix_chars=8)
    with pytest.raises(ValueError, match="stale_after_s"):
        make_balancer("telemetry", stale_after_s=0.0)


# ---------------------------------------------------------------------------
# Telemetry balancer: digest-weighted picks, staleness decay, cold replicas
# ---------------------------------------------------------------------------


def _loaded_replica(rid, queue_s, prefill_s, service_s, now,
                    outstanding=0, age=0.0, recent_compile=False):
    rep = Replica(rid=rid, base_url="http://x")
    rep.outstanding = outstanding
    rep.load = {
        "ewma_queue_s": queue_s, "ewma_prefill_s": prefill_s,
        "ewma_service_s": service_s, "recent_compile": recent_compile,
    }
    rep.load_ts = now - age
    return rep


def test_telemetry_balancer_prefers_observed_fast_replica_even_when_idle():
    # Both idle (outstanding 0): least_outstanding would tie-break to the
    # FIRST (slow) replica; telemetry reads the digests and avoids it.
    now = 1000.0
    slow = _loaded_replica("slow", 0.05, 0.4, 2.0, now)
    fast = _loaded_replica("fast", 0.001, 0.01, 0.05, now)
    bal = make_balancer("telemetry", now=lambda: now)
    assert bal.pick([slow, fast]).rid == "fast"
    # A recent compile on the otherwise-fast replica tips the pick away.
    warming = _loaded_replica("warming", 0.001, 0.01, 0.05, now,
                              recent_compile=True)
    steady = _loaded_replica("steady", 0.002, 0.02, 0.08, now)
    assert bal.pick([warming, steady]).rid == "steady"


def test_telemetry_balancer_backpressure_self_limits_between_probes():
    # Outstanding is read LIVE from the registry, so picks spread once the
    # fast replica queues up — no herding at the currently-fastest replica.
    now = 1000.0
    a = _loaded_replica("a", 0.001, 0.01, 0.5, now, outstanding=6)
    b = _loaded_replica("b", 0.002, 0.05, 0.6, now, outstanding=0)
    bal = make_balancer("telemetry", now=lambda: now)
    assert bal.pick([a, b]).rid == "b"


def test_telemetry_balancer_stale_digests_degrade_to_least_outstanding():
    # Past stale_after_s the digest's weight decays to zero: a glowing but
    # STALE digest must never outvote live queue depth. With every digest
    # stale the pick IS least-outstanding (ties by registration order) —
    # and it never throws.
    now = 1000.0
    bal = make_balancer("telemetry", stale_after_s=10.0, now=lambda: now)
    fast_stale = _loaded_replica("fast_stale", 0.001, 0.01, 0.05, now,
                                 outstanding=3, age=60.0)
    slow_fresh_idle = _loaded_replica("busy_looking", 0.05, 0.4, 2.0, now,
                                      outstanding=0, age=60.0)
    assert bal.pick([fast_stale, slow_fresh_idle]).rid == "busy_looking"
    # All stale + equal outstanding: registration order, like LO.
    r1 = _loaded_replica("r1", 0.9, 0.9, 9.0, now, age=99.0)
    r2 = _loaded_replica("r2", 0.001, 0.001, 0.01, now, age=99.0)
    assert bal.pick([r1, r2]).rid == "r1"


def test_telemetry_balancer_null_ewma_digest_scores_like_no_digest():
    # A fresh digest whose EWMA fields are all null (non-continuous
    # gateway, or a continuous replica before its first request) carries
    # no telemetry: it must score on live outstanding like a cold replica,
    # not as zero cost — or the least-instrumented replica would win
    # every pick regardless of its queue.
    now = 1000.0
    empty = Replica(rid="empty", base_url="http://x")
    empty.outstanding = 5
    empty.load = {"ewma_queue_s": None, "ewma_prefill_s": None,
                  "ewma_service_s": None, "recent_compile": False}
    empty.load_ts = now
    fast = _loaded_replica("fast", 0.001, 0.01, 0.05, now)
    bal = make_balancer("telemetry", now=lambda: now)
    assert bal.pick([empty, fast]).rid == "fast"


def test_telemetry_balancer_cold_replica_is_not_starved():
    # A just-registered replica has NO digest: it competes on live queue
    # depth (freshness 0) instead of being frozen out by replicas with
    # attractive telemetry.
    now = 1000.0
    veteran = _loaded_replica("veteran", 0.001, 0.01, 0.05, now, outstanding=2)
    cold = Replica(rid="cold", base_url="http://x")
    bal = make_balancer("telemetry", now=lambda: now)
    assert bal.pick([veteran, cold]).rid == "cold"


def test_prober_refreshes_load_digest_from_readyz_body():
    reg = _registry("r1")
    digest = {"inflight": 2, "queue_depth": 1, "ewma_queue_s": 0.003,
              "ewma_prefill_s": 0.02, "ewma_decode_s": 0.004,
              "ewma_service_s": 0.11, "recent_compile": False,
              "slo_goodput_ratio": 0.97}
    ft = FakeTransport().on(
        "r1/readyz",
        lambda u, p, h: (200, {"ready": True, "inflight": 2, "load": digest}),
    )
    prober = HealthProber(reg, transport=ft, obs_registry=Registry())
    assert prober.probe_once() == {"r1": "healthy"}
    rep = reg.get("r1")
    assert rep.load == digest and rep.load_ts is not None
    assert rep.load_age_s() >= 0.0
    # The digest rides the registry snapshot → /fleetz.
    snap = reg.snapshot()[0]
    assert snap["load"]["ewma_prefill_s"] == 0.02
    assert snap["load_age_s"] >= 0.0
    # A pre-digest replica (no "load" key) still probes fine.
    ft2 = FakeTransport().on("r1/readyz",
                             lambda u, p, h: (200, {"ready": True}))
    reg2 = _registry("r1")
    HealthProber(reg2, transport=ft2, obs_registry=Registry()).probe_once()
    assert reg2.get("r1").load is None


# ---------------------------------------------------------------------------
# Replica gateway (serve/rest.py): healthz/readyz/drain + hardening.
# A stub ensemble keeps this fast — the HTTP lifecycle is under test, not
# the model.
# ---------------------------------------------------------------------------


class _StubEnsemble:
    qa_agents = ()
    refiner = None

    def __init__(self, answer_fn=None):
        self._answer = answer_fn

    def answer(self, question):
        if self._answer is not None:
            return self._answer(question)
        return {"answer": f"echo:{question}"}


def _serve_stub(**kw):
    from edgemesh.serve import serve_rest

    kw.setdefault("registry", Registry())
    return serve_rest(_StubEnsemble(kw.pop("answer_fn", None)),
                      host="127.0.0.1", port=0, block=False, **kw)


def test_gateway_healthz_readyz_and_drain_state_machine():
    srv = _serve_stub()
    try:
        status, body, _ = _http(srv, "/healthz")
        assert status == 200 and body == {"status": "ok"}
        status, body, _ = _http(srv, "/readyz")
        assert status == 200
        assert body["ready"] is True and body["draining"] is False
        assert body["inflight"] == 0
        # The load digest piggybacks on readiness (the prober refreshes the
        # telemetry balancer's signal for free — docs/FLEET.md).
        assert "load" in body and body["load"]["inflight"] == 0

        status, body, _ = _http(srv, "/drain", data=b"{}")
        assert status == 200 and body["draining"] is True

        # Drain-aware readiness: alive (healthz 200) but NOT ready.
        status, _, _ = _http(srv, "/healthz")
        assert status == 200
        status, body, _ = _http(srv, "/readyz")
        assert status == 503 and body["draining"] is True

        # New work is refused with 503 + Retry-After.
        status, body, headers = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode()
        )
        assert status == 503 and "draining" in body["error"]
        assert headers["Retry-After"] == "1"
    finally:
        srv.shutdown()


def test_gateway_drain_waits_for_inflight_requests():
    gate = threading.Event()
    started = threading.Event()

    def slow_answer(question):
        started.set()
        gate.wait(10.0)
        return {"answer": "done"}

    srv = _serve_stub(answer_fn=slow_answer)
    try:
        results = []
        t = threading.Thread(target=lambda: results.append(
            _http(srv, "/generate", data=json.dumps({"question": "q"}).encode())
        ))
        t.start()
        assert started.wait(5.0)
        # Drain with a request in flight: draining flips immediately...
        out = srv.drain(wait=True, timeout_s=0.05)
        assert out["draining"] is True and out["drained"] is False
        assert out["inflight"] == 1
        # ... and the in-flight request still completes (zero dropped).
        gate.set()
        t.join(timeout=10.0)
        assert results and results[0][0] == 200
        assert results[0][1]["answer"] == "done"
        out = srv.drain(wait=True, timeout_s=5.0)
        assert out["drained"] is True and out["inflight"] == 0
    finally:
        srv.shutdown()


def test_gateway_sheds_past_max_inflight():
    gate = threading.Event()
    started = threading.Event()

    def slow_answer(question):
        started.set()
        gate.wait(10.0)
        return {"answer": "done"}

    srv = _serve_stub(answer_fn=slow_answer, max_inflight=1)
    try:
        results = []
        t = threading.Thread(target=lambda: results.append(
            _http(srv, "/generate", data=json.dumps({"question": "a"}).encode())
        ))
        t.start()
        assert started.wait(5.0)
        status, body, headers = _http(
            srv, "/generate", data=json.dumps({"question": "b"}).encode()
        )
        assert status == 503 and body["error"] == "overloaded"
        assert headers["Retry-After"] == "1"
        gate.set()
        t.join(timeout=10.0)
        assert results and results[0][0] == 200
    finally:
        srv.shutdown()


def test_gateway_admission_check_and_increment_is_atomic():
    # A burst of N+1 concurrent requests against max_inflight=N must shed
    # exactly one — a split check/increment would shed all of them.
    srv = _serve_stub(max_inflight=2)
    try:
        assert [srv.begin_request() for _ in range(3)] == \
            ["ok", "ok", "overloaded"]
        srv.end_request()
        assert srv.begin_request() == "ok"  # freed capacity readmits
        srv.end_request()
        srv.end_request()
        assert srv.inflight() == 0
    finally:
        srv.shutdown()


def test_gateway_loadz_digest_degrades_without_an_engine():
    # A non-continuous gateway has no span tracker: the digest keeps its
    # schema (the balancer parses one shape) with null telemetry and the
    # live in-flight count.
    srv = _serve_stub()
    try:
        status, body, _ = _http(srv, "/loadz")
        assert status == 200
        assert body["inflight"] == 0 and body["queue_depth"] is None
        for key in ("ewma_queue_s", "ewma_prefill_s", "ewma_decode_s",
                    "ewma_service_s", "slo_goodput_ratio"):
            assert key in body and body[key] is None
        assert isinstance(body["recent_compile"], bool)
    finally:
        srv.shutdown()


def test_gateway_malformed_inputs_are_structured_400s():
    srv = _serve_stub()
    try:
        status, body, _ = _http(srv, "/generate", data=b"not json")
        assert status == 400 and body["error"] == "invalid JSON body"
        status, body, _ = _http(srv, "/generate", data=b"[1, 2]")
        assert status == 400 and "object" in body["error"]
        status, body, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "soon"},
        )
        assert status == 400 and "X-Edgemesh-Deadline-S" in body["error"]

        # A garbage Content-Length header (hand-rolled request) is a 400,
        # not an unhandled int() ValueError → 500.
        import http.client

        conn = http.client.HTTPConnection(
            "127.0.0.1", srv.server_address[1], timeout=30
        )
        try:
            conn.putrequest("POST", "/generate")
            conn.putheader("Content-Length", "nope")
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 400
            assert "Content-Length" in json.load(resp)["error"]
        finally:
            conn.close()
    finally:
        srv.shutdown()


def test_gateway_refuses_expired_propagated_deadline():
    calls = []
    srv = _serve_stub(answer_fn=lambda q: calls.append(q) or {"answer": "x"})
    try:
        status, body, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "0"},
        )
        assert status == 504 and "deadline" in body["error"]
        assert calls == []  # refused BEFORE any model work
        status, _, _ = _http(
            srv, "/generate", data=json.dumps({"question": "q"}).encode(),
            headers={"X-Edgemesh-Deadline-S": "30"},
        )
        assert status == 200 and calls == ["q"]
    finally:
        srv.shutdown()


def test_gateway_socket_timeout_is_applied_per_connection():
    srv = _serve_stub(request_timeout_s=0.2)
    try:
        import socket

        # A client that opens a connection, sends half a request, and
        # stalls: the handler thread must be reclaimed by the socket
        # timeout instead of pinned forever.
        s = socket.create_connection(
            ("127.0.0.1", srv.server_address[1]), timeout=5.0
        )
        try:
            s.sendall(b"POST /generate HTTP/1.1\r\nContent-Length: 999\r\n\r\n{")
            t0 = time.monotonic()
            # Server must close the connection (empty read) in bounded time.
            s.settimeout(5.0)
            data = s.recv(1024)
            assert time.monotonic() - t0 < 5.0
            assert data == b""  # dropped, no half-baked 500
        finally:
            s.close()
        # The gateway still serves afterwards.
        status, _, _ = _http(srv, "/healthz")
        assert status == 200
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Multi-tenant admission: token buckets, weighted fairness, priority lanes
# (fleet/admission.py), and the tenant context through the router
# ---------------------------------------------------------------------------


from edgemesh.fleet.admission import (  # noqa: E402
    AdmissionController,
    TenantPolicy,
    TokenBucket,
)
from edgemesh.serve.httputil import TENANT_HEADER  # noqa: E402


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_token_bucket_refill_math():
    clk = _Clock()
    b = TokenBucket(rate_per_s=2.0, burst=4.0, now=clk)
    assert all(b.try_take() for _ in range(4))  # the full burst
    assert not b.try_take()
    clk.t += 0.5  # refills 1 token
    assert b.try_take() and not b.try_take()
    clk.t += 10.0  # refill caps at burst, not rate*dt
    assert b.tokens() == pytest.approx(4.0)
    with pytest.raises(ValueError):
        TokenBucket(rate_per_s=0.0)


def test_tenant_policy_parse_and_validation():
    name, p = TenantPolicy.parse("bulk=batch:1:5:10")
    assert name == "bulk" and p.lane == "batch" and p.weight == 1.0
    assert p.rate_per_s == 5.0 and p.burst == 10.0
    name, p = TenantPolicy.parse("chat=interactive:4")
    assert p.lane == "interactive" and p.weight == 4.0 and p.rate_per_s == 0.0
    with pytest.raises(ValueError):
        TenantPolicy.parse("nonsense")
    with pytest.raises(ValueError):
        TenantPolicy(lane="sideways")
    with pytest.raises(ValueError):
        TenantPolicy(weight=0.0)


def test_admission_default_matches_legacy_semaphore():
    ac = AdmissionController(max_inflight=2)
    assert ac.acquire("a") == "ok" and ac.acquire("b") == "ok"
    assert ac.acquire("c") == "overload"  # no queue budget: immediate shed
    ac.release()
    assert ac.acquire("c") == "ok"
    st = ac.stats()
    assert st["inflight"] == 2 and st["queue_cap"] == 0


def test_admission_rate_limit_spends_no_slot():
    clk = _Clock()
    ac = AdmissionController(
        max_inflight=8,
        policies={"bulk": TenantPolicy(rate_per_s=1.0, burst=1.0)},
        now=clk,
    )
    assert ac.acquire("bulk") == "ok"
    assert ac.acquire("bulk") == "ratelimited"
    assert ac.stats()["ratelimit_hits"] == {"bulk": 1}
    # Refused requests consumed zero capacity; other tenants unaffected.
    assert ac.stats()["inflight"] == 1
    assert ac.acquire("other") == "ok"
    clk.t += 1.0
    assert ac.acquire("bulk") == "ok"


def test_admission_weighted_fair_grants_follow_weights():
    """4 freed slots against backlogs of tenant a (weight 3) and b
    (weight 1): start-time fair queueing grants 3:1."""
    ac = AdmissionController(
        max_inflight=4, queue_cap=100,
        policies={"a": TenantPolicy(weight=3.0), "b": TenantPolicy(weight=1.0)},
    )
    for _ in range(4):  # fill every slot so new arrivals queue
        assert ac.acquire("warm") == "ok"
    granted = {"a": 0, "b": 0}
    done = []

    def waiter(tenant):
        if ac.acquire(tenant, wait_s=30.0) == "ok":
            with lock:
                granted[tenant] += 1
                done.append(tenant)

    lock = threading.Lock()
    threads = [threading.Thread(target=waiter, args=(t,), daemon=True)
               for t in ("a",) * 6 + ("b",) * 6]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 10.0
    while sum(ac.stats()["waiting"].values()) < 12:
        assert time.monotonic() < deadline, ac.stats()
        time.sleep(0.01)
    for _ in range(4):  # free 4 slots; grants land on the waiters
        ac.release()
    deadline = time.monotonic() + 10.0
    while len(done) < 4:
        assert time.monotonic() < deadline, (done, ac.stats())
        time.sleep(0.01)
    assert granted == {"a": 3, "b": 1}


def test_admission_interactive_preempts_batch_in_queue():
    ac = AdmissionController(
        max_inflight=1, queue_cap=10,
        policies={"bulk": TenantPolicy(lane="batch"),
                  "chat": TenantPolicy(lane="interactive")},
    )
    assert ac.acquire("chat-warm") == "ok"
    order = []
    lock = threading.Lock()

    def waiter(tenant):
        if ac.acquire(tenant, wait_s=30.0) == "ok":
            with lock:
                order.append(tenant)

    t_batch = threading.Thread(target=waiter, args=("bulk",), daemon=True)
    t_batch.start()  # batch queues FIRST
    deadline = time.monotonic() + 10.0
    while sum(ac.stats()["waiting"].values()) < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    t_inter = threading.Thread(target=waiter, args=("chat",), daemon=True)
    t_inter.start()  # interactive arrives LATER
    while sum(ac.stats()["waiting"].values()) < 2:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    ac.release()  # one slot frees: the interactive request jumps the queue
    t_inter.join(timeout=10.0)
    assert order == ["chat"]
    ac.release()  # now the batch request gets its turn
    t_batch.join(timeout=10.0)
    assert order == ["chat", "bulk"]


def test_admission_queue_timeout_sheds():
    ac = AdmissionController(max_inflight=1, queue_cap=4)
    assert ac.acquire("a") == "ok"
    t0 = time.monotonic()
    assert ac.acquire("b", wait_s=0.1) == "queue_timeout"
    assert 0.05 < time.monotonic() - t0 < 5.0
    assert ac.stats()["queue_timeouts"] == {"b": 1}
    # The abandoned waiter must not absorb a later grant.
    ac.release()
    assert ac.acquire("c") == "ok"


def test_router_tenant_rate_limit_answers_429_with_counters():
    reg = _registry("r1")
    ft = FakeTransport()
    obs = Registry()
    admission = AdmissionController(
        max_inflight=8,
        policies={"bulk": TenantPolicy(rate_per_s=0.001, burst=1.0)},
    )
    router = _router(reg, ft, obs_registry=obs, admission=admission)
    status, body, headers = router.handle_generate(
        {"question": "q?"}, tenant="bulk")
    assert status == 200
    status, body, headers = router.handle_generate(
        {"question": "q?"}, tenant="bulk")
    assert status == 429 and headers["Retry-After"] == "1"
    assert body["tenant"] == "bulk"
    s = obs.summary()
    assert s['edgemesh_fleet_tenant_ratelimited_total{tenant="bulk"}'] == 1
    assert s['edgemesh_fleet_tenant_shed_total{tenant="bulk",reason="ratelimit"}'] == 1
    assert s['edgemesh_fleet_shed_total{reason="ratelimit"}'] == 1
    assert s['edgemesh_fleet_tenant_requests_total{tenant="bulk",outcome="ok"}'] == 1
    assert s['edgemesh_fleet_tenant_requests_total{tenant="bulk",outcome="shed"}'] == 1
    # Other tenants are not rate limited.
    status, _, _ = router.handle_generate({"question": "q?"}, tenant="chat")
    assert status == 200


def test_router_propagates_tenant_header_and_stamps_spans(tmp_path):
    reg = _registry("r1")
    ft = FakeTransport()
    router = _router(reg, ft, span_log=tmp_path / "router.jsonl")
    status, _, _ = router.handle_generate({"question": "q?"}, tenant="acme")
    assert status == 200
    posts = [c for c in ft.calls if c[0] == "POST"]
    # The attempt carried the tenant alongside trace + deadline.
    assert posts[-1][4][TENANT_HEADER] == "acme"
    assert "X-Edgemesh-Trace" in posts[-1][4]
    # Untagged traffic carries NO tenant header (single-tenant unchanged).
    router.handle_generate({"question": "q?"})
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert TENANT_HEADER not in posts[-1][4]
    # The router span record is tenant-stamped (null for untagged).
    from edgemesh.utils.tracing import JsonlLogger

    recs = JsonlLogger(tmp_path / "router.jsonl").read()
    assert [r.get("tenant") for r in recs] == ["acme", None]


def test_router_status_surfaces_tenants_and_admission():
    reg = _registry("r1")
    ft = FakeTransport()
    admission = AdmissionController(
        max_inflight=4, queue_cap=8,
        policies={"bulk": TenantPolicy(lane="batch", weight=1.0,
                                       rate_per_s=0.001, burst=2.0)},
    )
    router = _router(reg, ft, admission=admission)
    for _ in range(2):
        router.handle_generate({"question": "q?"}, tenant="chat")
    for _ in range(3):  # third one trips the bucket
        router.handle_generate({"question": "q?"}, tenant="bulk")
    st = router.status()
    assert st["admission"]["queue_cap"] == 8
    assert st["admission"]["policies"]["bulk"]["lane"] == "batch"
    assert st["admission"]["ratelimit_hits"] == {"bulk": 1}
    chat, bulk = st["tenants"]["chat"], st["tenants"]["bulk"]
    assert chat["requests"] == 2 and chat["answered"] == 2
    assert chat["goodput_ratio"] == 1.0  # fake transport answers instantly
    assert bulk["shed"] == 1 and bulk["ratelimited"] == 1
    # max_inflight reflects the controller's truth.
    assert st["max_inflight"] == 4


def test_frontend_forwards_tenant_header_to_router(frontend):
    srv, router, ft = frontend
    status, _, _ = _http(
        srv, "/generate", data=json.dumps({"question": "q?"}).encode(),
        headers={TENANT_HEADER: "acme"},
    )
    assert status == 200
    posts = [c for c in ft.calls if c[0] == "POST"]
    assert posts[-1][4][TENANT_HEADER] == "acme"
    status, body, _ = _http(srv, "/fleetz")
    assert status == 200
    assert body["tenants"]["acme"]["answered"] == 1
    assert "admission" in body


def test_configured_policy_survives_label_namespace_flood():
    """A tenant configured at construction must keep its policy even
    after abusive clients mint enough fresh tenant ids to fill the
    bounded-label namespace — construction pre-seeds the policy names,
    so they can never collapse into 'other' and silently lose their
    rate limit / lane."""
    reg = _registry("r1")
    ft = FakeTransport()
    admission = AdmissionController(
        max_inflight=64,
        policies={"bulk": TenantPolicy(rate_per_s=0.001, burst=1.0,
                                       lane="batch")},
    )
    router = _router(reg, ft, admission=admission)
    # An abuser floods with fresh tenant ids until the namespace caps out.
    for i in range(40):
        assert router.handle_generate({"question": "q?"},
                                      tenant=f"minted-{i}")[0] == 200
    # The configured tenant still resolves to ITS policy: second request
    # trips the 1-token bucket with a 429 (the default policy would not).
    assert router.handle_generate({"question": "q?"}, tenant="bulk")[0] == 200
    assert router.handle_generate({"question": "q?"}, tenant="bulk")[0] == 429
