"""Device-backend guard rails (utils/platform.py).

The axon tunnel can wedge at the very first dispatch; every CLI entry now
front-loads ``ensure_device_ready`` so a dead backend fails in bounded time
with a pin-CPU hint instead of hanging forever (round-2 judge observed a
>600s silent hang on `edgemesh eval`).
"""

import time

import pytest

from edgemesh.utils.platform import ensure_device_ready


def test_ready_backend_passes_quickly():
    t0 = time.monotonic()
    ensure_device_ready(timeout_s=60)  # CPU backend: answers immediately
    assert time.monotonic() - t0 < 30


def test_wedged_backend_exits_with_actionable_message():
    with pytest.raises(SystemExit) as e:
        ensure_device_ready(timeout_s=0.2, _probe=lambda: time.sleep(30))
    msg = str(e.value)
    assert "JAX_PLATFORMS=cpu" in msg
    assert "EDGEMESH_DEVICE_INIT_TIMEOUT" in msg


def test_probe_errors_propagate():
    with pytest.raises(RuntimeError, match="boom"):
        ensure_device_ready(timeout_s=5, _probe=lambda: (_ for _ in ()).throw(RuntimeError("boom")))


def test_zero_timeout_disables(monkeypatch):
    monkeypatch.setenv("EDGEMESH_DEVICE_INIT_TIMEOUT", "0")
    ensure_device_ready(_probe=lambda: time.sleep(30))  # returns without probing
