"""Fleet fault injection end-to-end (slow tier): REAL replica subprocesses
behind the real router — one replica SIGKILLed and another SIGSTOPped
mid-load with zero client-visible failures, graceful drain with zero
dropped in-flight requests, and the /metrics contract of the acceptance
criteria. Multi-minute territory: each replica is a full `edgemesh serve`
process that compiles the tiny model on its own 1-core CPU slice."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path: Path, port: int,
                   extra: tuple = ()) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port), *extra],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0
                )
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on ports {sorted(pending)} never became ready"


def _post(url: str, payload: dict, timeout_s: float = 300.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_fleet_survives_kill_stall_and_drains_cleanly(tmp_path):
    from edgemesh.fleet import FleetRouter, HealthProber, HttpTransport, \
        ReplicaRegistry, serve_fleet
    from edgemesh.obs import Registry

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    ports = [_free_port() for _ in range(3)]
    procs = [_spawn_replica(cfg, p) for p in ports]
    transport = HttpTransport()
    prober = None
    front = None
    stopped_pid = None
    try:
        _wait_ready(transport, ports)
        # Warm each replica's decode compile OUTSIDE the measured fault
        # window (first answer costs a jit compile on this 1-core host).
        for p in ports:
            status, _ = _post(f"http://127.0.0.1:{p}/generate",
                              {"question": "warmup?"})
            assert status == 200

        obs = Registry()
        registry = ReplicaRegistry(
            (f"replica-{i}", f"http://127.0.0.1:{p}")
            for i, p in enumerate(ports)
        )
        router = FleetRouter(
            registry, balancer="least_outstanding", transport=transport,
            obs_registry=obs, max_attempts=5, attempt_timeout_s=15.0,
            default_deadline_s=240.0, backoff_base_s=0.05, demote_after=1,
        )
        prober = HealthProber(registry, transport=transport, interval_s=0.5,
                              timeout_s=2.0, unhealthy_after=1,
                              obs_registry=obs).start()
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
        url = f"http://127.0.0.1:{front.server_address[1]}"
        n_ok = 0

        # ---- Phase A: concurrent load, SIGKILL one replica mid-run. The
        # acceptance bar: ZERO client-visible failures — retries absorb it.
        results, errors = [], []

        def client(i):
            try:
                results.append(_post(f"{url}/generate", {"question": f"q {i}?"}))
            except Exception as e:  # a transport-level failure IS a failure
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for i, t in enumerate(threads):
            t.start()
            if i == 4:
                procs[0].kill()  # SIGKILL mid-load: connections now refused
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=240.0)
        assert not errors, errors
        assert len(results) == 12
        assert all(status == 200 for status, _ in results), results
        assert all("answer" in body for _, body in results)
        n_ok += 12

        # ---- Phase B: deterministic retry evidence. Resurrect the dead
        # replica's registry entry: the next pick dials it, gets connection
        # refused, retries onto a live replica — still 200.
        registry.register("replica-0", f"http://127.0.0.1:{ports[0]}")
        status, body = _post(f"{url}/generate", {"question": "retry probe?"})
        assert status == 200 and "answer" in body
        n_ok += 1
        m = obs.summary(prefix="edgemesh_fleet_")
        retried = sum(v for k, v in m.items()
                      if k.startswith("edgemesh_fleet_retried_total"))
        assert retried >= 1, m

        # ---- Phase C: stall a replica's accept loop (SIGSTOP — the
        # kernel still completes TCP handshakes, reads just hang) and hedge
        # around it. The prober is stopped so the stall stays "healthy"
        # at pick time; least-outstanding tie-break then picks the stalled
        # replica first and the hedge must win well under the 15 s attempt
        # timeout.
        prober.stop()
        procs[1].send_signal(signal.SIGSTOP)
        stopped_pid = procs[1].pid
        registry.set_state("replica-0", "unhealthy")
        registry.set_state("replica-1", "healthy")
        registry.set_state("replica-2", "healthy")
        router.hedge_after_s = 2.0
        t0 = time.monotonic()
        status, body = _post(f"{url}/generate", {"question": "hedge probe?"})
        elapsed = time.monotonic() - t0
        assert status == 200 and "answer" in body
        assert elapsed < 15.0, f"hedge did not cut the stall tail: {elapsed:.1f}s"
        n_ok += 1
        m = obs.summary(prefix="edgemesh_fleet_")
        assert m.get('edgemesh_fleet_hedged_total{replica="replica-2"}', 0) >= 1
        assert m.get('edgemesh_fleet_hedged_won_total{replica="replica-2"}', 0) >= 1
        router.hedge_after_s = 0.0

        # ---- Phase D: graceful drain with requests in flight — zero
        # dropped. Un-stall replica-1 first so the fleet keeps capacity.
        procs[1].send_signal(signal.SIGCONT)
        stopped_pid = None
        registry.set_state("replica-1", "healthy")
        d_results = []

        def d_client(i):
            d_results.append(_post(f"{url}/generate", {"question": f"drain {i}?"}))

        d_threads = [threading.Thread(target=d_client, args=(i,)) for i in range(4)]
        for t in d_threads:
            t.start()
        out = router.drain_replica("replica-2", timeout_s=60.0)
        for t in d_threads:
            t.join(timeout=240.0)
        assert out["drained"] is True, out
        assert registry.get("replica-2").state == "removed"
        assert len(d_results) == 4
        assert all(status == 200 for status, _ in d_results), d_results
        n_ok += 4
        # The drained replica answered /readyz 503 on its way out but the
        # fleet still answers — via replica-1 only now.
        status, body = _post(f"{url}/generate", {"question": "post drain?"})
        assert status == 200
        n_ok += 1

        # ---- Phase E: drain the last replica → an honest 503 shed, not a
        # hang (and the shed counter lands in the exposition below).
        router.drain_replica("replica-1", timeout_s=60.0)
        status, body = _post(f"{url}/generate", {"question": "empty fleet?"})
        assert status == 503 and "no available replica" in body["error"]

        # ---- /metrics on the router: the acceptance-criteria exposition.
        with urllib.request.urlopen(f"{url}/metrics", timeout=30) as r:
            text = r.read().decode()
        for needle in (
            'edgemesh_fleet_routed_total{replica="replica-1"}',
            'edgemesh_fleet_routed_total{replica="replica-2"}',
            "edgemesh_fleet_retried_total{",
            'edgemesh_fleet_hedged_won_total{replica="replica-2"}',
            'edgemesh_fleet_shed_total{reason="no_replica"}',
            'edgemesh_fleet_drain_total{replica="replica-2",event="completed"}',
            "edgemesh_fleet_router_seconds_bucket{",
            "edgemesh_fleet_router_seconds_count",
        ):
            assert needle in text, f"missing {needle!r} in /metrics"
        # Every successful client request was routed exactly once.
        m = obs.summary(prefix="edgemesh_fleet_")
        routed = sum(v for k, v in m.items()
                     if k.startswith("edgemesh_fleet_routed_total"))
        assert routed == n_ok
        assert m["edgemesh_fleet_router_seconds"]["count"] == n_ok
    finally:
        if prober is not None:
            prober.stop()
        if front is not None:
            front.shutdown()
        if stopped_pid is not None:
            try:
                os.kill(stopped_pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_fleet_cli_serve_and_status_json(tmp_path):
    """`edgemesh fleet serve` spawns its own replica and fronts it;
    `edgemesh fleet status --json` is machine-readable; SIGINT drains."""
    from edgemesh.fleet.transport import HttpTransport, TransportError

    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    port = _free_port()
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "fleet", "serve",
         "--config", str(cfg), "--replicas", "1", "--host", "127.0.0.1",
         "--port", str(port), "--probe-interval-s", "0.5"],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )
    transport = HttpTransport()
    url = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 300.0
        ready = False
        while time.monotonic() < deadline:
            assert proc.poll() is None, "fleet CLI died during boot"
            try:
                status, _ = transport.get_json(f"{url}/readyz", timeout_s=2.0)
                if status == 200:
                    ready = True
                    break
            except TransportError:
                pass
            time.sleep(0.5)
        assert ready, "fleet never became ready"

        status, body = _post(f"{url}/generate", {"question": "via fleet?"})
        assert status == 200 and "answer" in body

        # status --json, in-process (what scripts call).
        out = subprocess.run(
            [sys.executable, "-m", "edgemesh.cli", "fleet", "status",
             "--url", url, "--json"],
            capture_output=True, text=True, timeout=60, env=env,
            cwd=Path(__file__).resolve().parent.parent,
        )
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["balancer"] == "least_outstanding"
        assert doc["replicas"][0]["state"] == "healthy"
        assert doc["metrics"]['edgemesh_fleet_routed_total{replica="replica-0"}'] >= 1

        # Human table mode exits 0 too.
        out = subprocess.run(
            [sys.executable, "-m", "edgemesh.cli", "fleet", "status",
             "--url", url],
            capture_output=True, text=True, timeout=60, env=env,
            cwd=Path(__file__).resolve().parent.parent,
        )
        assert out.returncode == 0 and "replica-0" in out.stdout
    finally:
        proc.send_signal(signal.SIGINT)  # graceful: drains the replica
        try:
            proc.wait(timeout=90)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=15)


SLOW_REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 6, hidden_size: 64, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 128, max_seq_len: 512}
    sampling: {max_new_tokens: 32, do_sample: false, repetition_penalty: 1.0}
"""


def test_adaptive_router_beats_least_outstanding_on_skewed_fleet(tmp_path):
    """The telemetry-loop acceptance bar: a 3-replica fleet with one
    artificially degraded replica (6x the layers, 8x the token budget —
    genuinely slower prefill and decode), REAL subprocess replicas serving
    --continuous so their /readyz bodies ship live load digests. The
    adaptive router (TelemetryBalancer + auto-tuned hedging, ZERO hedge or
    threshold config) must beat least_outstanding on p99 latency and SLO
    goodput over the identical concurrent workload."""
    from edgemesh.fleet import FleetRouter, HealthProber, HttpTransport, \
        ReplicaRegistry, serve_fleet
    from edgemesh.obs import Registry

    fast_cfg = tmp_path / "fast.yaml"
    fast_cfg.write_text(REPLICA_YAML)
    slow_cfg = tmp_path / "slow.yaml"
    slow_cfg.write_text(SLOW_REPLICA_YAML)
    ports = [_free_port() for _ in range(3)]
    # The degraded replica is registered FIRST so least_outstanding's
    # registration-order tie-break prefers it — the worst case the
    # telemetry balancer must route around.
    procs = [
        _spawn_replica(slow_cfg, ports[0], extra=("--continuous",)),
        _spawn_replica(fast_cfg, ports[1], extra=("--continuous",)),
        _spawn_replica(fast_cfg, ports[2], extra=("--continuous",)),
    ]
    rids = ["slow", "fast-1", "fast-2"]
    urls = {rid: f"http://127.0.0.1:{p}" for rid, p in zip(rids, ports)}
    transport = HttpTransport()
    n_requests, concurrency = 18, 6
    try:
        _wait_ready(transport, ports)
        # Warm every replica (decode compiles + digest EWMAs) and measure
        # the fast replicas' steady-state latency for the SLO target.
        fast_lats = []
        for rid, url in urls.items():
            for _ in range(2):
                t0 = time.monotonic()
                status, _ = _post(f"{url}/generate", {"question": "warm?"})
                assert status == 200
                lat = time.monotonic() - t0
            if rid != "slow":
                fast_lats.append(lat)
        slow_t0 = time.monotonic()
        _post(f"{urls['slow']}/generate", {"question": "warm again?"})
        slow_lat = time.monotonic() - slow_t0
        slo_target_s = max(4.0 * max(fast_lats), 0.5)
        # The skew must be real, or the comparison means nothing.
        assert slow_lat > slo_target_s, (slow_lat, slo_target_s)

        # The replica side exposes the SLO instrumentation end to end.
        import urllib.request

        with urllib.request.urlopen(f"{urls['slow']}/metrics",
                                    timeout=30) as r:
            text = r.read().decode()
        assert "edgemesh_slo_goodput_ratio" in text
        assert "edgemesh_slo_requests_total" in text
        with urllib.request.urlopen(f"{urls['slow']}/loadz", timeout=30) as r:
            digest = json.load(r)
        assert digest["ewma_service_s"] is not None
        assert digest["queue_depth"] == 0

        def run_arm(balancer: str, hedge_auto: bool):
            obs = Registry()
            registry = ReplicaRegistry(list(urls.items()))
            prober = HealthProber(registry, transport=transport,
                                  interval_s=0.3, timeout_s=5.0,
                                  obs_registry=obs).start()
            prober.probe_once()  # digests fresh before the first pick
            router = FleetRouter(
                registry, balancer=balancer, transport=transport,
                obs_registry=obs, hedge_auto=hedge_auto,
                attempt_timeout_s=120.0, default_deadline_s=240.0,
            )
            front = serve_fleet(router, host="127.0.0.1", port=0, block=False)
            url = f"http://127.0.0.1:{front.server_address[1]}/generate"
            lats, errors = [], []
            lock = threading.Lock()
            remaining = list(range(n_requests))

            def worker():
                while True:
                    with lock:
                        if not remaining:
                            return
                        i = remaining.pop()
                    t0 = time.monotonic()
                    status, body = _post(url, {"question": f"q {i}?"})
                    lat = time.monotonic() - t0
                    with lock:
                        if status != 200:
                            errors.append((i, status, body))
                        else:
                            lats.append(lat)

            threads = [threading.Thread(target=worker)
                       for _ in range(concurrency)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=240.0)
            prober.stop()
            front.shutdown()
            assert not errors, errors
            assert len(lats) == n_requests
            routed_slow = obs.summary().get(
                'edgemesh_fleet_routed_total{replica="slow"}', 0)
            return lats, routed_slow

        lo_lats, lo_slow = run_arm("least_outstanding", hedge_auto=False)
        ad_lats, ad_slow = run_arm("telemetry", hedge_auto=True)

        def p99(xs):
            return sorted(xs)[min(len(xs) - 1, int(0.99 * len(xs)))]

        def goodput(xs):
            return sum(1 for x in xs if x <= slo_target_s) / len(xs)

        # The baseline actually exercised the degraded replica (its
        # registration-order tie-break guarantees at least the first pick)
        # and paid for it in the tail; the adaptive arm routed around it.
        assert lo_slow >= 1, lo_slow
        assert ad_slow < lo_slow, (ad_slow, lo_slow)
        assert goodput(lo_lats) < 1.0
        assert p99(ad_lats) < p99(lo_lats), (p99(ad_lats), p99(lo_lats))
        assert goodput(ad_lats) > goodput(lo_lats), (
            goodput(ad_lats), goodput(lo_lats))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_adaptive_router_benchmark_smoke():
    """Bench CI smoke: the BENCH JSON schema of the adaptive-router stage
    (the full-size comparison rides the driver bench)."""
    from edgemesh.benchmarks import adaptive_router_benchmark

    r = adaptive_router_benchmark(n_requests=6, concurrency=2, max_new=4,
                                  slow_layers=4, slow_hidden=64,
                                  slow_max_new=16)
    assert r["metric"] == "adaptive_over_least_outstanding_p99"
    assert r["value"] > 0
    for key in ("least_outstanding_p99_s", "adaptive_p99_s",
                "least_outstanding_goodput", "adaptive_goodput",
                "least_outstanding_routed_to_slow", "adaptive_routed_to_slow",
                "slo_target_s"):
        assert key in r, key
    assert r["n_requests"] == 6


def test_router_overhead_benchmark_smoke():
    """The bench CI smoke: direct vs routed vs traced percentiles with the
    obs summary and a real assembled sample trace attached (full-size runs
    ride the TPU driver, not CI)."""
    from edgemesh.benchmarks import router_overhead_benchmark

    r = router_overhead_benchmark(n_requests=5, max_new=4)
    assert r["metric"] == "router_overhead_p50_s"
    assert r["direct_p50_s"] > 0 and r["routed_p50_s"] > 0
    assert r["traced_p50_s"] > 0
    assert "tracing_overhead_p50_s" in r and "tracing_overhead_p99_s" in r
    # The flight-recorder arm: absolute percentiles, the delta vs the
    # recorder-off routed arm, and proof the ring actually recorded.
    assert r["recorder_p50_s"] > 0
    assert "recorder_overhead_p50_s" in r and "recorder_overhead_p99_s" in r
    assert r["recorder_ring_records"] >= 5
    assert r["n_requests"] == 5
    # Three routed arms (tracing off, tracing on, recorder on), each
    # 5 requests + 1 warmup, all through one replica.
    assert r["obs"]['edgemesh_fleet_routed_total{replica="r0"}'] == 18
    assert r["obs"]["edgemesh_fleet_router_seconds"]["count"] == 18
    # The sample trace is a real cross-process assembly: router record +
    # the replica's engine record under the winning attempt.
    st = r["sample_trace"]
    assert st is not None and st["processes"] >= 2, st
    tree = st["tree"]
    attempts = [c for c in tree["children"] if c["name"] == "attempt"]
    assert attempts and attempts[-1]["outcome"] == "ok"
    servers = [c for c in attempts[-1]["children"] if c["name"] == "server"]
    assert servers, "replica spans did not attach under the attempt"
    names = [s["name"] for s in servers[0]["children"]]
    assert "queued" in names and "prefill" in names and "decode" in names
    cp = st["critical_path"]
    parts = (cp["retry_wasted_s"] + cp["wire_s"] + cp["queue_s"]
             + cp["prefill_s"] + cp["decode_s"] + cp["other_s"])
    assert cp["total_s"] == pytest.approx(parts, abs=1e-6)
