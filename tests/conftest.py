"""Test harness bootstrap: force an 8-device virtual CPU platform BEFORE jax loads.

The reference repo has no test suite at all (SURVEY.md §4); its stand-in was a
1,000-sample golden-metric sweep on real hardware. Here every distributed code
path (DP/TP/PP/SP collectives over a Mesh) runs in CI on emulated devices, per
the strategy in SURVEY.md §4/§7.8.
"""

import os

# Must happen before the first jax BACKEND INIT anywhere in the test process.
# The session image's sitecustomize registers the axon (remote-TPU-tunnel) PJRT
# plugin and force-updates jax_platforms to "axon,cpu" — overriding the
# JAX_PLATFORMS env var — so the env alone is not enough: any jax op would
# dial the TPU pool and block. Reset the config to cpu AFTER import (backends
# initialize lazily, so this wins as long as it runs before the first op).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _fresh_bounded_labels():
    """Isolate the process-wide bounded-label seen-sets (obs/metrics.py):
    tenant names minted by one test must not push a later test's tenants
    into the 'other' overflow bucket."""
    from edgemesh.obs.metrics import reset_bounded_labels

    reset_bounded_labels()
    yield


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    from edgemesh.parallel.mesh import build_mesh

    return build_mesh(dp=2, tp=4)


@pytest.fixture(scope="session")
def rng():
    return jax.random.PRNGKey(0)
