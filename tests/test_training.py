"""Training step: loss decreases, sharded step runs, dryrun entry works."""

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.models import init_params
from edgemesh.models.families import tiny_config
from edgemesh.training import (
    causal_lm_loss,
    init_train_state,
    make_optimizer,
    make_train_step,
)


import pytest

# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def test_loss_decreases_on_fixed_batch():
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    optimizer = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, params, optimizer)
    step = make_train_step(cfg, optimizer)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    lengths = jnp.array([16, 12])
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5


def test_padding_excluded_from_loss():
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
    full = causal_lm_loss(cfg, params, toks, jnp.array([8]))
    # same tokens with padding garbage after position 4
    padded = toks.at[:, 4:].set(0)
    l1 = causal_lm_loss(cfg, params, padded, jnp.array([4]))
    l2 = causal_lm_loss(cfg, params, padded.at[:, 4:].set(7), jnp.array([4]))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    assert float(full) != float(l1)


def test_dryrun_multichip_8(devices):
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)  # raises/asserts on failure
