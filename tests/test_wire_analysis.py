"""The wire protocol-contract pass (analysis/wire.py, EM501-EM506): one
known-bad fixture per rule (each demonstrably fires), the negative twins,
helper-descent and constant-resolution cases, the Layer-2 dryrun (green on
the shipped tree; a broken contract names the route), the `obs routes`
renderer, and the shipped-tree zero-unbaselined-EM5xx gate. Fast tier —
pure AST + stdlib imports, no sockets, no accelerator."""

import json
import subprocess
import sys
from pathlib import Path

from edgemesh.analysis.edgelint import lint_source
from edgemesh.analysis.findings import Baseline, default_baseline_path
from edgemesh.analysis.wire import analyze_source, run_wire_contracts
from edgemesh.serve import httputil

REPO = Path(__file__).resolve().parent.parent


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# EM501 unknown-route
# ---------------------------------------------------------------------------


def test_em501_fires_on_typoed_route():
    src = (
        "def call(t, url):\n"
        "    return t.post_json(url + '/generaet', {'question': 'q'},\n"
        "                       timeout_s=1.0)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM501"}
    assert "/generaet" in findings[0].message
    assert "WIRE_CONTRACT" in findings[0].message


def test_em501_fires_on_wrong_method_and_names_the_right_one():
    src = (
        "def call(t, url):\n"
        "    return t.get_json(f'{url}/drain', timeout_s=1.0)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM501"}
    assert "POST" in findings[0].message and "not GET" in findings[0].message


def test_em501_resolution_forms_and_opaque_urls():
    # f-string, concatenation, one-level local provenance, and the
    # httputil path constant all resolve; an opaque parameter does not.
    for url in ("f'{base}/loadz'", "base + '/loadz'", "'http://h:1/loadz'"):
        src = (
            "def probe(t, base):\n"
            f"    return t.get_json({url}, timeout_s=1.0)\n"
        )
        assert analyze_source(src, path="edgemesh/fleet/health.py") == [], url
    local = (
        "def probe(t, base):\n"
        "    u = f'{base}/laodz'\n"
        "    return t.get_json(u, timeout_s=1.0)\n"
    )
    assert rules_of(analyze_source(local, path="edgemesh/fleet/health.py")) \
        == {"EM501"}
    opaque = (
        "def probe(t, url):\n"
        "    return t.get_json(url, timeout_s=1.0)\n"
    )
    assert analyze_source(opaque, path="edgemesh/fleet/health.py") == []


def test_em501_resolves_httputil_path_constants():
    src = (
        "from edgemesh.serve.httputil import KV_EXPORT_PATH\n"
        "def xfer(t, rep, h):\n"
        "    return t.post_json(rep.url(KV_EXPORT_PATH), {'question': 'q'},\n"
        "                       timeout_s=1.0, headers=h)\n"
    )
    # The constant resolves to a declared route: no EM501. (The opaque
    # headers parameter is trusted — not a dict literal the pass can see.)
    assert analyze_source(src, path="edgemesh/fleet/router.py") == []


def test_em501_rides_lint_source_and_honors_disable():
    src = (
        "def call(t, url):\n"
        "    return t.post_json(url + '/generaet', {'question': 'q'},\n"
        "                       timeout_s=1.0)\n"
    )
    assert "EM501" in rules_of(lint_source(src, path="edgemesh/fleet/x.py"))
    quiet = src.replace(
        "def call(t, url):",
        "def call(t, url):  # edgelint: disable=EM501",
    )
    assert analyze_source(quiet, path="edgemesh/fleet/x.py") == []


# ---------------------------------------------------------------------------
# EM502 header-contract
# ---------------------------------------------------------------------------


def test_em502_client_fires_when_headers_lack_required_trace():
    src = (
        "def call(t, url):\n"
        "    headers = {'X-Edgemesh-Tenant': 'a'}\n"
        "    return t.post_json(f'{url}/generate', {'question': 'q'},\n"
        "                       timeout_s=1.0, headers=headers)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM502"}
    assert "X-Edgemesh-Trace" in findings[0].message
    # Outside the fleet the client header obligation does not apply.
    assert analyze_source(src, path="edgemesh/loadgen/driver.py") == []


def test_em502_satisfied_by_literal_constant_or_expansion():
    base = (
        "from edgemesh.serve.httputil import TRACE_HEADER\n"
        "def call(t, url, h):\n"
        "    return t.post_json(f'{url}/generate', {'question': 'q'},\n"
        "                       timeout_s=1.0, headers=HEADERS)\n"
    )
    for headers in ("{'X-Edgemesh-Trace': h}", "{TRACE_HEADER: h}",
                    "{httputil.TRACE_HEADER: h}", "{**h}"):
        src = base.replace("HEADERS", headers)
        assert analyze_source(src, path="edgemesh/fleet/router.py") == [], \
            headers


def test_em502_strict_route_flags_call_with_no_headers_at_all():
    src = (
        "from edgemesh.serve.httputil import KV_EXPORT_PATH\n"
        "def xfer(t, rep):\n"
        "    return t.post_json(rep.url(KV_EXPORT_PATH), {'question': 'q'},\n"
        "                       timeout_s=1.0)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM502"}
    assert "strict" in findings[0].message
    # /generate is NOT strict: no headers at all stays out of scope (probes
    # and admin calls have no obligation to build a headers dict).
    probe = (
        "def call(t, url):\n"
        "    return t.post_json(f'{url}/generate', {'question': 'q'},\n"
        "                       timeout_s=1.0)\n"
    )
    assert analyze_source(probe, path="edgemesh/fleet/router.py") == []


def test_em502_strict_route_satisfied_with_both_headers():
    src = (
        "from edgemesh.serve.httputil import (DEADLINE_HEADER, TRACE_HEADER,\n"
        "                                     KV_EXPORT_PATH)\n"
        "def xfer(t, rep, ctx):\n"
        "    return t.post_json(rep.url(KV_EXPORT_PATH), {'question': 'q'},\n"
        "                       timeout_s=1.0,\n"
        "                       headers={TRACE_HEADER: ctx,\n"
        "                                DEADLINE_HEADER: '1.0'})\n"
    )
    assert analyze_source(src, path="edgemesh/fleet/router.py") == []
    # Dropping the deadline from a KV hop flags — the retired EM109's
    # transfer contract, now a WIRE_CONTRACT row.
    broken = src.replace("DEADLINE_HEADER: '1.0'", "'X-Other': '1'")
    findings = analyze_source(broken, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM502"}
    assert "X-Edgemesh-Deadline-S" in findings[0].message


def test_em502_bare_dial_without_timeout_fleet_only():
    src = (
        "import urllib.request\n"
        "def probe(url):\n"
        "    return urllib.request.urlopen(url)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM502"}
    assert "timeout" in findings[0].message
    assert analyze_source(src, path="edgemesh/obs/cli.py") == []
    kwarg = src.replace("urlopen(url)", "urlopen(url, timeout=2.0)")
    assert analyze_source(kwarg, path="edgemesh/fleet/router.py") == []
    # Third positional IS urlopen's timeout; aliased imports still resolve.
    pos = src.replace("urlopen(url)", "urlopen(url, None, 2.0)")
    assert analyze_source(pos, path="edgemesh/fleet/router.py") == []
    aliased = (
        "from urllib.request import urlopen as uo\n"
        "def probe(url):\n"
        "    return uo(url)\n"
    )
    assert rules_of(analyze_source(aliased, path="edgemesh/fleet/x.py")) \
        == {"EM502"}


def test_em502_handler_missing_read_helper_fires():
    src = (
        "from edgemesh.serve import httputil\n"
        "class H:\n"
        "    def do_POST(self):\n"
        "        if self.path == '/generate':\n"
        "            payload = self._read_json()\n"
        "            q = payload.get('question')\n"
        "            httputil.read_deadline_header(self)\n"
        "            httputil.read_tenant_header(self)\n"
        "            httputil.read_session_header(self)\n"
    )
    findings = analyze_source(src, path="edgemesh/serve/rest.py")
    assert rules_of(findings) == {"EM502"}
    assert "read_trace_header" in findings[0].message


def test_em502_handler_helper_descent_through_self_calls():
    # The header read lives two self-calls below the dispatch branch: the
    # closure descent must find it (the shipped gateway's real shape).
    src = (
        "from edgemesh.serve import httputil\n"
        "class H:\n"
        "    def do_POST(self):\n"
        "        if self.path == '/generate':\n"
        "            self._generate()\n"
        "    def _generate(self):\n"
        "        payload = self._read_json()\n"
        "        q = payload.get('question')\n"
        "        self._common_headers()\n"
        "    def _common_headers(self):\n"
        "        httputil.read_trace_header(self)\n"
        "        httputil.read_deadline_header(self)\n"
        "        httputil.read_tenant_header(self)\n"
        "        httputil.read_session_header(self)\n"
    )
    assert analyze_source(src, path="edgemesh/serve/rest.py") == []


# ---------------------------------------------------------------------------
# EM503 payload-key-drift
# ---------------------------------------------------------------------------


def test_em503_client_fires_on_typoed_payload_key():
    src = (
        "def call(t, url):\n"
        "    return t.post_json(f'{url}/generate', {'qestion': 'q'},\n"
        "                       timeout_s=1.0)\n"
    )
    findings = analyze_source(src, path="edgemesh/loadgen/driver.py")
    assert rules_of(findings) == {"EM503"}
    assert "'qestion'" in findings[0].message
    ok = src.replace("qestion", "question")
    assert analyze_source(ok, path="edgemesh/loadgen/driver.py") == []


def test_em503_client_follows_local_payload_variable():
    src = (
        "def call(t, url):\n"
        "    payload = {'question': 'q', 'max_mew': 8}\n"
        "    return t.post_json(f'{url}/generate', payload, timeout_s=1.0)\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/router.py")
    assert rules_of(findings) == {"EM503"}
    assert "'max_mew'" in findings[0].message


def test_em503_handler_fires_on_undeclared_body_read():
    src = (
        "class H:\n"
        "    def do_POST(self):\n"
        "        if self.path == '/generate':\n"
        "            payload = self._read_json()\n"
        "            return payload.get('qestion')\n"
    )
    findings = analyze_source(src, path="edgemesh/serve/rest.py")
    # The fixture handler also reads no headers (EM502); the EM503 finding
    # is the one under test here.
    em503 = [f for f in findings if f.rule == "EM503"]
    assert len(em503) == 1 and "'qestion'" in em503[0].message
    # A declared key (any route of this server) is quiet — dispatch
    # helpers are shared, so the union is the contract.
    ok = src.replace("qestion", "question")
    assert [f for f in analyze_source(ok, path="edgemesh/serve/rest.py")
            if f.rule == "EM503"] == []


# ---------------------------------------------------------------------------
# EM504 schema-drift
# ---------------------------------------------------------------------------


def test_em504_fires_on_typoed_digest_key_in_balancer():
    src = (
        "def _cost(self, load):\n"
        "    return load.get('ewma_queu_s') or 0.0\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/balancer.py")
    assert rules_of(findings) == {"EM504"}
    assert "'ewma_queu_s'" in findings[0].message
    assert "load_digest" in findings[0].message
    ok = src.replace("ewma_queu_s", "ewma_queue_s")
    assert analyze_source(ok, path="edgemesh/fleet/balancer.py") == []


def test_em504_registered_schema_against_tmp_producer_tree(tmp_path,
                                                           monkeypatch):
    from edgemesh.analysis import wire

    (tmp_path / "prod.py").write_text(
        "def make():\n"
        "    out = {'alpha': 1}\n"
        "    out['beta'] = 2\n"
        "    out.setdefault('gamma', 3)\n"
        "    return dict(delta=4), out\n"
    )
    monkeypatch.setattr(wire, "_REPO_ROOT", tmp_path)
    monkeypatch.setattr(wire, "WIRE_SCHEMAS", {
        "toy": {
            "doc": "test schema",
            "producers": (("prod.py", "make"),),
            "consumers": (("cons.py", "use", ("doc",)),),
        },
    })
    wire._SCHEMA_CACHE.clear()
    # Derivation flows through `or {}`, rebinding, and loop targets.
    src = (
        "def use(doc):\n"
        "    d = doc or {}\n"
        "    for k in (d.get('alpha'), d['beta'], d.get('gamma'),\n"
        "              d.get('delta')):\n"
        "        pass\n"
        "    return d.get('epsilon')\n"
    )
    findings = wire.analyze_source(src, path="cons.py")
    assert rules_of(findings) == {"EM504"}
    assert "'epsilon'" in findings[0].message
    # An unrelated local dict is NOT the schema document: quiet.
    other = (
        "def use(doc):\n"
        "    mine = {'epsilon': 1}\n"
        "    return mine.get('epsilon'), doc.get('alpha')\n"
    )
    assert wire.analyze_source(other, path="cons.py") == []
    # No producer file readable → the check stays silent, not wrong.
    monkeypatch.setattr(wire, "_REPO_ROOT", tmp_path / "nope")
    wire._SCHEMA_CACHE.clear()
    assert wire.analyze_source(src, path="cons.py") == []
    wire._SCHEMA_CACHE.clear()


# ---------------------------------------------------------------------------
# EM505 response-discipline
# ---------------------------------------------------------------------------


def test_em505_fires_on_bare_500_and_send_json_form():
    src = (
        "class H:\n"
        "    def _handle(self, exc):\n"
        "        self._send(500, {'error': str(exc)})\n"
    )
    findings = analyze_source(src, path="edgemesh/serve/rest.py")
    assert rules_of(findings) == {"EM505"}
    assert findings[0].severity == "warning"
    assert '"kind"' in findings[0].message
    direct = (
        "from edgemesh.serve import httputil\n"
        "def answer(h, exc):\n"
        "    httputil.send_json(h, 500, {'error': str(exc)})\n"
    )
    assert rules_of(analyze_source(direct, path="edgemesh/fleet/frontend.py")) \
        == {"EM505"}
    # The structured vocabulary satisfies; non-5xx dicts are out of scope.
    ok = src.replace("{'error': str(exc)}",
                     "{'error': str(exc), 'kind': 'internal'}")
    assert analyze_source(ok, path="edgemesh/serve/rest.py") == []
    notfound = src.replace("500", "404")
    assert analyze_source(notfound, path="edgemesh/serve/rest.py") == []


def test_em505_fires_on_503_branch_without_retry_after():
    src = (
        "def call(t, url):\n"
        "    status, body = t.get_json(f'{url}/readyz', timeout_s=1.0)\n"
        "    if status == 503:\n"
        "        return None\n"
        "    return body\n"
    )
    findings = analyze_source(src, path="edgemesh/fleet/health.py")
    assert rules_of(findings) == {"EM505"}
    assert "Retry-After" in findings[0].message
    ok = src.replace(
        "        return None\n",
        "        backoff(headers.get(httputil.RETRY_AFTER_HEADER))\n"
        "        return None\n",
    )
    assert analyze_source(ok, path="edgemesh/fleet/health.py") == []


# ---------------------------------------------------------------------------
# Layer 2: the wire dryrun (EM506)
# ---------------------------------------------------------------------------


def test_wire_dryrun_green_on_shipped_tree():
    assert run_wire_contracts() == []


def test_wire_dryrun_names_declared_but_unserved_route(monkeypatch):
    monkeypatch.setitem(httputil.WIRE_CONTRACT, ("POST", "/ghost"),
                        {"servers": ("gateway",)})
    findings = run_wire_contracts()
    assert rules_of(findings) == {"EM506"}
    assert len(findings) == 1
    assert "POST /ghost" in findings[0].message
    assert "never serves it" in findings[0].message
    assert findings[0].context == "gateway"
    assert findings[0].path == "edgemesh/serve/rest.py"


def test_wire_dryrun_names_served_but_undeclared_route(monkeypatch):
    monkeypatch.delitem(httputil.WIRE_CONTRACT, ("POST", "/drain"))
    findings = run_wire_contracts()
    assert rules_of(findings) == {"EM506"}
    assert "POST /drain" in findings[0].message
    assert "undeclared" in findings[0].message


def test_wire_dryrun_reports_method_mismatch_once(monkeypatch):
    row = httputil.WIRE_CONTRACT[("POST", "/drain")]
    monkeypatch.delitem(httputil.WIRE_CONTRACT, ("POST", "/drain"))
    monkeypatch.setitem(httputil.WIRE_CONTRACT, ("GET", "/drain"), row)
    findings = run_wire_contracts()
    assert len(findings) == 1, [f.message for f in findings]
    assert "method mismatch" in findings[0].message
    assert "GET" in findings[0].message


def test_wire_dryrun_unimportable_module_is_the_finding():
    findings = run_wire_contracts([{
        "server": "ghost",
        "module": "edgemesh.no_such_module",
        "table": "SERVED_ROUTES",
        "path": "edgemesh/ghost.py",
    }])
    assert rules_of(findings) == {"EM506"}
    assert "unimportable" in findings[0].message


# ---------------------------------------------------------------------------
# `edgemesh obs routes` renders the live contract
# ---------------------------------------------------------------------------


def test_obs_routes_json_matches_contract_rows(capsys):
    from edgemesh.obs import cli as obs_cli

    assert obs_cli.main(["routes", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["routes"] == httputil.contract_rows()
    assert len(doc["routes"]) == len(httputil.WIRE_CONTRACT)


def test_obs_routes_human_table_lists_every_route(capsys):
    from edgemesh.obs import cli as obs_cli

    assert obs_cli.main(["routes"]) == 0
    out = capsys.readouterr().out
    for (_method, path) in httputil.WIRE_CONTRACT:
        assert path in out
    assert "X-Edgemesh-Trace" in out
    assert "EM5xx" in out  # the enforcement cross-reference


# ---------------------------------------------------------------------------
# Retired-id aliases and the shipped-tree gate
# ---------------------------------------------------------------------------


def test_select_em109_aliases_to_em502_with_deprecation_note():
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis",
         str(REPO / "edgemesh" / "fleet" / "transport.py"),
         "--select", "EM109", "--no-contracts"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "retired" in proc.stderr
    assert "EM502" in proc.stderr


def test_trace_header_constant_agrees_across_layers():
    # obs/trace.py keeps its own TRACE_HEADER definition (obs imports
    # nothing from serve/); the wire contract is the tie-breaker if the
    # two ever drift.
    from edgemesh.obs.trace import TRACE_HEADER

    assert TRACE_HEADER == httputil.TRACE_HEADER


def test_shipped_tree_has_zero_unbaselined_em5xx():
    # The acceptance gate: the whole package is wire-clean with an EMPTY
    # baseline — every real finding was fixed in-tree, never grandfathered.
    findings = []
    for py in sorted((REPO / "edgemesh").rglob("*.py")):
        findings.extend(analyze_source(py.read_text(), path=str(py)))
    assert [f"{f.path}:{f.line} {f.rule} {f.message}" for f in findings] == []
    assert run_wire_contracts() == []
    base = Baseline.load(default_baseline_path())
    assert [e for e in base.entries
            if e.get("rule", "").startswith("EM5")] == []
