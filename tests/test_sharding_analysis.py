"""edgemesh.analysis.sharding: the EM401-EM404 AST rules (positive AND
negative fixtures per rule — seeded bugs must flag, correct code must not),
the SHARDING_CONTRACTS AbstractMesh dryrun (EM405), the shipped tree's
EM4xx-cleanliness, and the --select/--ignore CLI filtering. Fast tier — the
dryrun is eval_shape-only (no device programs compiled)."""

import json
import subprocess
import sys
from pathlib import Path

from edgemesh.analysis.edgelint import lint_source

_PKG = Path(__file__).resolve().parent.parent / "edgemesh"


def em4(findings):
    return [f for f in findings if f.rule.startswith("EM4")]


# ---------------------------------------------------------------------------
# EM401 unbound-collective-axis
# ---------------------------------------------------------------------------

_EM401_SRC = (
    "from jax import lax\n"
    "from jax.sharding import Mesh, PartitionSpec as P\n"
    "from edgemesh.utils.compat import shard_map\n"
    "def wrap(x, devices):\n"
    "    mesh = Mesh(devices, ('sp',))\n"
    "    def body(xb):\n"
    "        return lax.psum(xb, 'tp')\n"
    "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
    "                     out_specs=P())(x)\n"
)


def test_em401_fires_on_unbound_axis_with_visible_mesh():
    findings = em4(lint_source(_EM401_SRC, path="edgemesh/parallel/x.py"))
    assert [f.rule for f in findings] == ["EM401"]
    assert findings[0].severity == "error"
    assert "'tp'" in findings[0].message and "sp" in findings[0].message
    # The message points back at the shard_map call site.
    assert "line 8" in findings[0].message


def test_em401_quiet_when_axis_bound():
    ok = _EM401_SRC.replace("lax.psum(xb, 'tp')", "lax.psum(xb, 'sp')")
    assert em4(lint_source(ok, path="edgemesh/parallel/x.py")) == []


def test_em401_spec_derived_env_and_helper_descent():
    # Mesh opaque (a parameter) but every spec literal: the spec axes stand
    # in for the environment. The collective hides inside a helper whose
    # axis parameter DEFAULTS to the wrong name — the descent binds it.
    src = (
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def helper(xb, axis='tp'):\n"
        "    return lax.all_gather(xb, axis, axis=0, tiled=True)\n"
        "def wrap(x, mesh):\n"
        "    def body(xb):\n"
        "        return helper(xb)\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
        "                     out_specs=P('sp'))(x)\n"
    )
    findings = em4(lint_source(src, path="edgemesh/parallel/x.py"))
    assert [f.rule for f in findings] == ["EM401"]
    # A constant-string call argument rebinding the axis to a bound name
    # silences it (ring_attend_block(..., axis='sp') style).
    ok = src.replace("return helper(xb)", "return helper(xb, axis='sp')")
    assert em4(lint_source(ok, path="edgemesh/parallel/x.py")) == []


def test_em401_factory_body_and_scan_nested_collectives():
    # The pipeline shape: shard_map's body comes from a factory, and the
    # collective sits inside a def nested in it (a lax.scan body).
    src = (
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def _make_stage(n):\n"
        "    def fn(xb):\n"
        "        def step(c, t):\n"
        "            return lax.ppermute(c, 'pp', [(0, 1)]), None\n"
        "        out, _ = lax.scan(step, xb, None, length=n)\n"
        "        return lax.psum(out, 'ep')\n"
        "    return fn\n"
        "def wrap(x, mesh, n):\n"
        "    fn = _make_stage(n)\n"
        "    mapped = shard_map(fn, mesh=mesh, in_specs=(P('pp'),),\n"
        "                       out_specs=P())\n"
        "    return mapped(x)\n"
    )
    findings = em4(lint_source(src, path="edgemesh/parallel/x.py"))
    # ppermute over 'pp' is bound (spec-derived env); psum over 'ep' is not.
    assert [f.rule for f in findings] == ["EM401"]
    assert "'ep'" in findings[0].message


def test_em401_open_environment_is_not_judged():
    # Mesh opaque AND a spec opaque (built by a call): the pass cannot
    # prove unboundness, so it stays silent — tp_infer/spmd's shape.
    src = (
        "from jax import lax\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def wrap(x, mesh, make_specs):\n"
        "    def body(xb):\n"
        "        return lax.psum(xb, 'tp')\n"
        "    return shard_map(body, mesh=mesh, in_specs=(make_specs(),),\n"
        "                     out_specs=None)(x)\n"
    )
    assert em4(lint_source(src, path="edgemesh/parallel/x.py")) == []


def test_em401_disable_comment_suppresses():
    quiet = _EM401_SRC.replace(
        "        return lax.psum(xb, 'tp')",
        "        return lax.psum(xb, 'tp')  # edgelint: disable=EM401",
    )
    assert em4(lint_source(quiet, path="edgemesh/parallel/x.py")) == []


# ---------------------------------------------------------------------------
# EM402 shard-spec-mismatch
# ---------------------------------------------------------------------------

_EM402_SRC = (
    "from jax.sharding import Mesh, PartitionSpec as P\n"
    "from edgemesh.utils.compat import shard_map\n"
    "def wrap(x, y, devices):\n"
    "    mesh = Mesh(devices, ('tp',))\n"
    "    def body(xb, yb):\n"
    "        return xb\n"
    "    return shard_map(body, mesh=mesh, in_specs=(P('tp'),),\n"
    "                     out_specs=P('sp'))(x, y)\n"
)


def test_em402_fires_on_arity_and_mesh_axis_mismatches():
    findings = em4(lint_source(_EM402_SRC, path="edgemesh/parallel/x.py"))
    assert {f.rule for f in findings} == {"EM402"}
    msgs = "\n".join(f.message for f in findings)
    # All three divergences: spec axis absent from the mesh, body arity,
    # and call-site arity.
    assert "'sp' is not an axis" in msgs
    assert "2 positional parameter(s)" in msgs
    assert "called with 2 argument(s)" in msgs


def test_em402_defaulted_body_params_are_optional():
    # A body parameter with a default is legally uncovered by in_specs
    # (shard_map fills it from the default) — must not flag.
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def wrap(x, mesh):\n"
        "    def body(xb, eps=1e-6):\n"
        "        return xb\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('tp'),),\n"
        "                     out_specs=P('tp'))(x)\n"
    )
    assert em4(lint_source(src, path="edgemesh/parallel/x.py")) == []
    # Fewer specs than even the REQUIRED params still flags.
    short = src.replace("def body(xb, eps=1e-6):", "def body(xb, yb, eps=1e-6):")
    findings = em4(lint_source(short, path="edgemesh/parallel/x.py"))
    assert any("2 to 3 positional" in f.message for f in findings)


def test_em402_quiet_when_specs_body_and_call_agree():
    ok = (
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def wrap(x, y, devices):\n"
        "    mesh = Mesh(devices, ('tp',))\n"
        "    def body(xb, yb):\n"
        "        return xb, yb\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('tp'), P('tp')),\n"
        "                     out_specs=(P('tp'), P('tp')))(x, y)\n"
    )
    assert em4(lint_source(ok, path="edgemesh/parallel/x.py")) == []


def test_em402_out_specs_tuple_vs_returned_tuple():
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def wrap(x, mesh):\n"
        "    def body(xb):\n"
        "        return xb, xb, xb\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('tp'),),\n"
        "                     out_specs=(P('tp'), P('tp')))(x)\n"
    )
    findings = em4(lint_source(src, path="edgemesh/parallel/x.py"))
    assert any("returns 3 value(s)" in f.message for f in findings)
    # A single (non-tuple) out spec is a pytree PREFIX — never an arity
    # finding, whatever the body returns.
    prefix = src.replace("out_specs=(P('tp'), P('tp'))", "out_specs=P('tp')")
    assert em4(lint_source(prefix, path="edgemesh/parallel/x.py")) == []


# ---------------------------------------------------------------------------
# EM403 unreduced-sharded-contraction
# ---------------------------------------------------------------------------

_EM403_SRC = (
    "import jax.numpy as jnp\n"
    "from jax import lax\n"
    "from jax.sharding import PartitionSpec as P\n"
    "from edgemesh.utils.compat import shard_map\n"
    "def row_dense(x, w, mesh):\n"
    "    def body(xb, wb):\n"
    "        y = xb @ wb\n"
    "        return y\n"
    "    return shard_map(body, mesh=mesh,\n"
    "                     in_specs=(P(None, 'tp'), P('tp', None)),\n"
    "                     out_specs=P(), check_vma=False)(x, w)\n"
)


def test_em403_fires_on_unreduced_contraction_and_names_vma_masking():
    findings = em4(lint_source(_EM403_SRC, path="edgemesh/parallel/x.py"))
    assert [f.rule for f in findings] == ["EM403"]
    assert findings[0].severity == "error"
    assert "psum" in findings[0].message and "'tp'" in findings[0].message
    # check_vma=False at the call site would mask the runtime checker too —
    # the message says so.
    assert "check_vma=False" in findings[0].message


def test_em403_quiet_with_psum_on_the_path():
    ok = _EM403_SRC.replace("y = xb @ wb", "y = lax.psum(xb @ wb, 'tp')")
    assert em4(lint_source(ok, path="edgemesh/parallel/x.py")) == []


def test_em403_quiet_when_out_specs_claims_the_axis():
    # out_specs sharding the axis is a DIFFERENT claim (not replication) —
    # out of this rule's scope.
    sharded = _EM403_SRC.replace("out_specs=P()", "out_specs=P('tp')")
    assert em4(lint_source(sharded, path="edgemesh/parallel/x.py")) == []


def test_em403_sees_einsum_contractions():
    src = (
        "import jax.numpy as jnp\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def wrap(x, w, mesh):\n"
        "    def body(xb, wb):\n"
        "        return jnp.einsum('th,hf->tf', xb, wb)\n"
        "    return shard_map(body, mesh=mesh,\n"
        "                     in_specs=(P(None, 'tp'), P('tp', None)),\n"
        "                     out_specs=P())(x, w)\n"
    )
    findings = em4(lint_source(src, path="edgemesh/parallel/x.py"))
    assert [f.rule for f in findings] == ["EM403"]
    # Contraction over an UNSHARDED dim is fine (the 'tf->f' reduction
    # below never crosses devices).
    ok = src.replace(
        "in_specs=(P(None, 'tp'), P('tp', None))",
        "in_specs=(P('tp', None), P(None, None))",
    )
    assert em4(lint_source(ok, path="edgemesh/parallel/x.py")) == []


# ---------------------------------------------------------------------------
# EM404 retrace-hazard
# ---------------------------------------------------------------------------

_EM404_SRC = (
    "from edgemesh.runtime.paged_generate import forward_prefill_paged\n"
    "def admit(cfg, params, req, cache):\n"
    "    s_cap = len(req.ids)\n"
    "    return forward_prefill_paged(cfg, params, req.toks, s_cap, cache)\n"
)


def test_em404_fires_on_raw_len_into_jitted_call_in_serving_only():
    findings = em4(lint_source(_EM404_SRC, path="edgemesh/serve/continuous.py"))
    assert [f.rule for f in findings] == ["EM404"]
    assert findings[0].severity == "warning"
    assert "bucket_pow2" in findings[0].message
    # Outside serve//runtime/ the rule is silent (bench code keys compiles
    # deliberately).
    assert em4(lint_source(_EM404_SRC, path="edgemesh/benchmarks.py")) == []


def test_em404_blessed_bucketing_sanitizes():
    ok = _EM404_SRC.replace(
        "    s_cap = len(req.ids)\n",
        "    from edgemesh.utils.bucketing import bucket_pow2\n"
        "    s_cap = bucket_pow2(len(req.ids), floor=16)\n",
    )
    assert em4(lint_source(ok, path="edgemesh/serve/continuous.py")) == []


def test_em404_sees_shape_arithmetic_and_jit_attr_calls():
    src = (
        "class Engine:\n"
        "    def step(self, tokens, cache):\n"
        "        pad = tokens.shape[1] + 7\n"
        "        return self._prefill_jit(tokens, pad, cache)\n"
    )
    findings = em4(lint_source(src, path="edgemesh/runtime/generate.py"))
    assert [f.rule for f in findings] == ["EM404"]


def test_em404_disable_comment_suppresses():
    quiet = _EM404_SRC.replace(
        "    return forward_prefill_paged(cfg, params, req.toks, s_cap, cache)",
        "    return forward_prefill_paged(cfg, params, req.toks, s_cap, cache)"
        "  # edgelint: disable=EM404",
    )
    assert em4(lint_source(quiet, path="edgemesh/serve/continuous.py")) == []


# ---------------------------------------------------------------------------
# The shipped tree is the negative fixture: zero EM4xx findings, zero
# baseline entries grandfathering any.
# ---------------------------------------------------------------------------


def test_shipped_tree_is_em4xx_clean_with_no_baseline_entries():
    from edgemesh.analysis.edgelint import lint_paths
    from edgemesh.analysis.findings import default_baseline_path

    findings = em4(lint_paths([_PKG]))
    assert findings == [], [f.render() for f in findings]
    entries = json.loads(default_baseline_path().read_text())["findings"]
    assert [e for e in entries if e["rule"].startswith("EM4")] == []


# ---------------------------------------------------------------------------
# Layer 2: the AbstractMesh dryrun (EM405)
# ---------------------------------------------------------------------------


def test_sharding_dryrun_is_green():
    from edgemesh.analysis.sharding import run_sharding_contracts

    findings = run_sharding_contracts()
    assert findings == [], [f.render() for f in findings]


def test_dryrun_covers_the_required_layouts():
    # tp2 / tp8 / dp2×tp4 / pp2 are the acceptance layouts: they must stay
    # registered (and tp8 proves multichip-tracing without any devices).
    from edgemesh.analysis.sharding import LAYOUTS, SHARDING_CONTRACTS

    covered = {
        layout for c in SHARDING_CONTRACTS for layout in c["layouts"]
    }
    for required in ("tp2", "tp8", "dp2xtp4", "pp2"):
        assert required in LAYOUTS and required in covered, required
    assert dict(LAYOUTS["tp8"])["tp"] == 8
    wrappers = {c["wrapper"] for c in SHARDING_CONTRACTS}
    assert wrappers >= {"tp_infer", "ring_attention", "ulysses", "pipeline",
                        "spmd"}
    # The quantized-collective layer (PR 11): qpsum itself plus both
    # non-psum tp programs trace under every tp layout — "does tp8 trace
    # with quantized, overlapped collectives" is a fast-tier fact.
    for wrapper in ("collectives", "tp_infer_qpsum", "tp_infer_qpsum_overlap"):
        assert wrapper in wrappers, wrapper
        entry = next(c for c in SHARDING_CONTRACTS if c["wrapper"] == wrapper)
        assert set(entry["layouts"]) >= {"tp2", "tp8", "dp2xtp4"}


def test_em401_and_em403_know_qpsum():
    """qpsum is a registered collective: an unbound axis is EM401, and a
    qpsum on the contraction axis CLEARS the EM403 partial-sum taint just
    like lax.psum."""
    from edgemesh.analysis.sharding import analyze_source

    unbound = analyze_source(
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "from edgemesh.parallel.collectives import qpsum\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def f(mesh_devs):\n"
        "    mesh = Mesh(mesh_devs, ('sp',))\n"
        "    def body(x):\n"
        "        return qpsum(x, 'tp', dtype='int8')\n"
        "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
        "                     out_specs=P('sp'))\n"
    )
    assert [f.rule for f in unbound] == ["EM401"]
    assert "'tp'" in unbound[0].message

    reduced = analyze_source(
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "from edgemesh.parallel.collectives import qpsum\n"
        "from edgemesh.utils.compat import shard_map\n"
        "def f(mesh_devs):\n"
        "    mesh = Mesh(mesh_devs, ('tp',))\n"
        "    def body(x, w):\n"
        "        y = x @ w\n"
        "        return qpsum(y, 'tp', dtype='int8')\n"
        "    return shard_map(body, mesh=mesh,\n"
        "                     in_specs=(P(None, 'tp'), P('tp', None)),\n"
        "                     out_specs=P(None, None))\n"
    )
    assert [f.rule for f in reduced] == []


def test_dryrun_broken_spec_names_wrapper_and_layout(monkeypatch):
    # A deliberately broken out_spec (axis the mesh does not bind) must
    # fail the dryrun with an error naming the wrapper AND the layout.
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from edgemesh.analysis import sharding
    from edgemesh.utils.compat import shard_map

    def broken_runner(mesh):
        mapped = shard_map(
            lambda x: x, mesh=mesh,
            in_specs=(P("tp"),), out_specs=P("nonexistent_axis"),
        )
        jax.eval_shape(
            mapped, jax.ShapeDtypeStruct((mesh.shape["tp"], 4), jnp.float32)
        )
        return []

    monkeypatch.setattr(sharding, "SHARDING_CONTRACTS", [{
        "wrapper": "broken_fixture_wrapper",
        "path": "edgemesh/parallel/broken.py",
        "layouts": ("tp2",),
        "runner": broken_runner,
    }])
    findings = sharding.run_sharding_contracts()
    assert [f.rule for f in findings] == ["EM405"]
    assert findings[0].severity == "error"
    assert "broken_fixture_wrapper" in findings[0].message
    assert "tp2" in findings[0].message


def test_dryrun_shape_problem_reported_not_just_exceptions(monkeypatch):
    from edgemesh.analysis import sharding

    monkeypatch.setattr(sharding, "SHARDING_CONTRACTS", [{
        "wrapper": "odd_shapes",
        "path": "edgemesh/parallel/odd.py",
        "layouts": ("tp2",),
        "runner": lambda mesh: ["logits came out transposed"],
    }])
    findings = sharding.run_sharding_contracts()
    assert [f.rule for f in findings] == ["EM405"]
    assert "odd_shapes" in findings[0].message
    assert "transposed" in findings[0].message


# ---------------------------------------------------------------------------
# --select / --ignore rule filtering (prefix-aware), all formats
# ---------------------------------------------------------------------------

_MIXED_SRC = (
    "import jax\n"
    "from functools import partial\n"
    "from jax.sharding import Mesh, PartitionSpec as P\n"
    "from edgemesh.utils.compat import shard_map\n"
    "@partial(jax.jit, static_argnums=(2,))\n"
    "def decode(tokens, cache, len_cap):\n"
    "    return tokens + cache\n"
    "def wrap(x, devices):\n"
    "    mesh = Mesh(devices, ('sp',))\n"
    "    def body(xb, yb):\n"
    "        return xb\n"
    "    return shard_map(body, mesh=mesh, in_specs=(P('sp'),),\n"
    "                     out_specs=P())(x)\n"
)


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", *args],
        capture_output=True, text=True, timeout=120,
    )


def test_cli_select_is_prefix_aware(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    # Unfiltered: one EM104 (dead jit param) + EM402s (arity).
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--format", "json")
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert "EM104" in rules and "EM402" in rules
    # --select EM4xx: the EM1xx finding disappears.
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--format", "json", "--select", "EM4xx")
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert rules and all(r.startswith("EM4") for r in rules)
    # Exact ids and comma lists work too.
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--format", "json", "--select", "EM104,EM3xx")
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert rules == {"EM104"}


def test_cli_ignore_drops_rules_and_exit_code_follows(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--ignore", "EM4xx", "--format", "json")
    assert proc.returncode == 1  # EM104 remains
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert rules == {"EM104"}
    # Ignoring everything present → clean, exit 0.
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--ignore", "EM1xx,EM4xx")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_select_honored_by_github_and_pretty_formats(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--format", "github", "--select", "EM104")
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("::")]
    assert lines and all("EM104" in ln for ln in lines)
    proc = _run_cli(str(bad), "--no-contracts", "--no-baseline",
                    "--select", "EM104")
    assert "EM402" not in proc.stdout


def test_cli_select_does_not_condemn_filtered_baseline_entries(tmp_path):
    # A baselined EM104 finding is invisible to a --select EM4xx run: the
    # filtered run must not report it stale (or prune it).
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    bl = tmp_path / "bl.json"
    _run_cli(str(bad), "--no-contracts", "--baseline", str(bl),
             "--write-baseline")
    proc = _run_cli(str(bad), "--no-contracts", "--baseline", str(bl),
                    "--select", "EM4xx")
    assert "stale baseline entry" not in proc.stderr
    assert proc.returncode == 0, proc.stdout + proc.stderr  # all baselined


def test_cli_write_baseline_under_select_keeps_other_rules(tmp_path):
    # A filtered --write-baseline only saw the selected rules: it must
    # rewrite THEIR entries and keep everything else — not silently
    # destroy the other rules' grandfathered debt.
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    bl = tmp_path / "bl.json"
    _run_cli(str(bad), "--no-contracts", "--baseline", str(bl),
             "--write-baseline")
    rules_before = {e["rule"] for e in
                    json.loads(bl.read_text())["findings"]}
    assert "EM104" in rules_before and "EM402" in rules_before
    # Rewrite only the EM4xx entries (code unchanged → same set back).
    proc = _run_cli(str(bad), "--no-contracts", "--baseline", str(bl),
                    "--select", "EM4xx", "--write-baseline")
    assert proc.returncode == 0
    rules_after = {e["rule"] for e in json.loads(bl.read_text())["findings"]}
    assert rules_after == rules_before  # EM104 entry survived
    # And the unfiltered run is still fully baselined.
    proc = _run_cli(str(bad), "--no-contracts", "--baseline", str(bl))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_lint_subcommand_forwards_filters(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_MIXED_SRC)
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.cli", "lint", str(bad),
         "--no-contracts", "--no-baseline", "--format", "json",
         "--select", "EM4xx"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert rules and all(r.startswith("EM4") for r in rules)
