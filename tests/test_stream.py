"""Streaming generation (runtime/stream.py, Agent/Ensemble.answer_stream, SSE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.agents.orchestrator import build_agent, build_ensemble
from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.runtime import generate
from edgemesh.runtime.stream import generate_stream

GREEDY = SamplingParams(max_new_tokens=24, do_sample=False, repetition_penalty=1.0)



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _model(vocab=64):
    cfg = tiny_config("llama", vocab_size=vocab, max_seq_len=128)
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _collect(cfg, params, tokens, lengths, sampling, chunk, eos_id=-1):
    toks = [[] for _ in range(tokens.shape[0])]
    n_chunks = 0
    for seg in generate_stream(cfg, params, tokens, lengths, sampling,
                               chunk=chunk, eos_id=eos_id):
        n_chunks += 1
        for b in range(tokens.shape[0]):
            toks[b].extend(int(t) for t in seg.tokens[b][: int(seg.counts[b])])
    return toks, n_chunks


@pytest.mark.parametrize("chunk", [5, 8, 24])
def test_greedy_stream_matches_dense(chunk):
    cfg, params = _model()
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size, jnp.int32)
    lengths = jnp.full((2,), 8, jnp.int32)
    ref = generate(cfg, params, tokens, lengths, GREEDY)
    toks, n_chunks = _collect(cfg, params, tokens, lengths, GREEDY, chunk)
    assert n_chunks == -(-GREEDY.max_new_tokens // chunk)
    for b in range(2):
        n = int(ref.num_generated[b])
        assert toks[b] == [int(t) for t in ref.tokens[b][:n]]


def test_stream_stops_at_eos():
    cfg, params = _model()
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, cfg.vocab_size, jnp.int32)
    lengths = jnp.full((2,), 8, jnp.int32)
    eos = 5
    ref = generate(cfg, params, tokens, lengths, GREEDY, eos_id=eos)
    toks, n_chunks = _collect(cfg, params, tokens, lengths, GREEDY, chunk=4, eos_id=eos)
    for b in range(2):
        n = int(ref.num_generated[b])
        assert toks[b] == [int(t) for t in ref.tokens[b][:n]]
    # If every row finished early, fewer chunks than the full budget's worth.
    if all(int(ref.num_generated[b]) < GREEDY.max_new_tokens for b in range(2)):
        assert n_chunks <= -(-max(int(x) for x in ref.num_generated) // 4) + 1


def test_stream_feeds_ttft_tpot_and_slo_metrics():
    # The raw streaming path records serving quality through the same
    # obs families the engines use (engine="stream"): TTFT at the first
    # token-bearing chunk, per-chunk weighted TPOT, one SLO verdict on
    # normal completion.
    from edgemesh.obs import Registry, SloTarget, StreamMeter

    cfg, params = _model()
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                cfg.vocab_size, jnp.int32)
    lengths = jnp.full((2,), 8, jnp.int32)
    reg = Registry()
    meter = StreamMeter(reg, target=SloTarget(ttft_s=600.0, tpot_s=600.0))
    for _ in generate_stream(cfg, params, tokens, lengths, GREEDY, chunk=8,
                             meter=meter):
        pass
    s = reg.summary()
    assert s['edgemesh_ttft_seconds{engine="stream"}']["count"] == 1
    # 24-token budget in 8-token chunks: the two post-first chunks credit
    # per-token latency weighted by their token counts.
    assert s['edgemesh_inter_token_seconds{engine="stream"}']["count"] > 0
    assert s['edgemesh_slo_goodput_ratio{engine="stream"}'] == 1.0
    assert s['edgemesh_slo_requests_total{engine="stream",result="good"}'] == 1


def test_agent_stream_deltas_concatenate_to_answer():
    agent = build_agent(AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY))
    q = "where is the eiffel tower"
    items = list(agent.answer_stream(q, chunk=6))
    assert items[-1]["done"] is True
    deltas = "".join(i["delta"] for i in items[:-1])
    assert deltas.strip() == items[-1]["answer"]
    assert items[-1]["answer"] == agent.answer(q)["answer"]
    assert len(items) >= 3  # actually streamed, not one blob


def test_stream_deltas_hold_multibyte_chars_at_chunk_boundary(monkeypatch):
    """A UTF-8 char split across segments must not stream a U+FFFD half; the
    delta is held back until the remaining bytes arrive."""
    from types import SimpleNamespace

    import edgemesh.runtime.stream as stream_mod

    agent = build_agent(AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY))
    ids = agent.tokenizer.encode("a€b")  # '€' is 3 bytes (+ a BOS id)
    ids = [i for i in ids if i < 256]  # keep raw byte ids only
    assert len(ids) == 5, ids
    split = [ids[:2], ids[2:]]  # cut mid-'€'

    def fake_stream(cfg, params, tokens, lengths, sampling, eos_id=-1, rng=None, chunk=16):
        for part in split:
            yield SimpleNamespace(
                tokens=jnp.asarray([part + [0] * (8 - len(part))], jnp.int32),
                counts=jnp.asarray([len(part)], jnp.int32),
                finished=jnp.asarray([False]),
                elapsed_s=0.0,
            )

    monkeypatch.setattr(stream_mod, "generate_stream", fake_stream)
    items = list(agent.answer_stream("q"))
    deltas = [i["delta"] for i in items if "delta" in i]
    assert all("�" not in d for d in deltas), deltas
    assert "".join(deltas) == "a€b"
    assert items[-1]["answer"] == "a€b"


def test_ensemble_stream_through_refiner():
    cfg = EdgeMeshConfig(
        agents=[
            AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY),
            AgentSpec(role="refiner", model=ModelSpec(), sampling=GREEDY),
        ]
    )
    ens = build_ensemble(cfg, use_submeshes=False)
    items = list(ens.answer_stream("who wrote hamlet", chunk=8))
    final = items[-1]
    assert final["done"] and "drafts" in final and len(final["drafts"]) == 1
    assert final["answer"] == ens.answer("who wrote hamlet")["answer"]


def test_ensemble_stream_multi_qa_no_refiner_matches_answer():
    # Max-confidence selection can't stream; the result must still MATCH
    # the non-streamed endpoint (one done event, same answer + drafts).
    cfg = EdgeMeshConfig(
        agents=[
            AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY),
            AgentSpec(role="qa2", model=ModelSpec(family="neox"), sampling=GREEDY),
        ]
    )
    ens = build_ensemble(cfg, use_submeshes=False)
    items = list(ens.answer_stream("who wrote hamlet"))
    assert len(items) == 1 and items[0]["done"]
    ref = ens.answer("who wrote hamlet")
    assert items[0]["answer"] == ref["answer"]
    assert len(items[0]["drafts"]) == 2


def test_stream_failure_counts_against_supervisor():
    from edgemesh.serve.supervisor import Supervisor

    sup = Supervisor(lambda: object(), lambda b, r: r, max_consecutive_failures=2)

    def boom():
        raise RuntimeError("generation exploded")

    with pytest.raises(RuntimeError):
        sup.track(boom)
    h = sup.health()
    assert h["total_failures"] == 1 and h["consecutive_failures"] == 1
    assert sup.track(lambda: "ok") == "ok"
    assert sup.health()["consecutive_failures"] == 0


def test_rest_sse_endpoint_streams():
    import json
    import urllib.request

    from edgemesh.serve.rest import serve_rest

    cfg = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=ModelSpec(), sampling=GREEDY)])
    ens = build_ensemble(cfg, use_submeshes=False)
    server = serve_rest(ens, host="127.0.0.1", port=0, block=False)
    port = server.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate_stream",
            data=json.dumps({"question": "where is the eiffel tower"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            events = [
                json.loads(line[len("data: "):])
                for line in resp.read().decode().splitlines()
                if line.startswith("data: ")
            ]
        assert events[-1]["done"] is True
        assert "".join(e.get("delta", "") for e in events[:-1]).strip() == events[-1]["answer"]
    finally:
        server.shutdown()


def test_rest_sse_endpoint_streams_with_draft():
    """SSE over a DRAFT-configured agent rides the segmented speculative
    loop end-to-end: deltas reassemble to the final answer, and the answer
    equals the non-streamed /generate answer (greedy)."""
    import json
    import urllib.request

    from edgemesh.serve.rest import serve_rest

    cfg = EdgeMeshConfig(agents=[AgentSpec(
        role="qa",
        model=ModelSpec(num_layers=2, hidden_size=64, max_seq_len=256),
        draft=ModelSpec(num_layers=1, hidden_size=64, max_seq_len=256),
        spec_gamma=3,
        sampling=GREEDY,
    )])
    ens = build_ensemble(cfg, use_submeshes=False)
    assert ens.qa_agents[0].draft_cfg is not None
    server = serve_rest(ens, host="127.0.0.1", port=0, block=False)
    port = server.server_address[1]
    try:
        def post(path):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps({"question": "where is the eiffel tower"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=300)

        with post("/generate_stream") as resp:
            events = [
                json.loads(line[len("data: "):])
                for line in resp.read().decode().splitlines()
                if line.startswith("data: ")
            ]
        assert events[-1]["done"] is True
        assert "".join(e.get("delta", "") for e in events[:-1]).strip() == events[-1]["answer"]
        with post("/generate") as resp:
            plain = json.loads(resp.read())
        assert plain["answer"] == events[-1]["answer"]
    finally:
        server.shutdown()
