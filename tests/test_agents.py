"""Multi-agent ensemble: concurrent QA agents + refiner merge."""

import jax.numpy as jnp
import pytest

from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.agents import build_agent, build_ensemble



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _tiny_spec(role="qa", **model_kw):
    model_kw.setdefault("num_layers", 2)
    model_kw.setdefault("hidden_size", 32)
    model_kw.setdefault("num_heads", 4)
    model_kw.setdefault("num_kv_heads", 4)
    model_kw.setdefault("intermediate_size", 64)
    return AgentSpec(
        role=role,
        model=ModelSpec(family="llama", **model_kw),
        sampling=SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0),
    )


def test_single_agent_answer():
    agent = build_agent(_tiny_spec())
    out = agent.answer("What color is the sky?")
    assert set(out) >= {"answer", "tps", "confidence", "ttft_s", "role"}
    assert isinstance(out["answer"], str)
    assert out["tps"] > 0


def test_ensemble_with_refiner(devices):
    cfg = EdgeMeshConfig(
        agents=[_tiny_spec("qa"), _tiny_spec("qa2"), _tiny_spec("refiner")]
    )
    ens = build_ensemble(cfg)
    assert len(ens.qa_agents) == 2
    assert ens.refiner is not None
    # QA agents landed on disjoint submeshes
    m0, m1 = ens.qa_agents[0].mesh, ens.qa_agents[1].mesh
    assert m0 is not None and m1 is not None
    ids0 = {d.id for d in m0.devices.flat}
    ids1 = {d.id for d in m1.devices.flat}
    assert ids0.isdisjoint(ids1)

    out = ens.answer("What is the capital of France?")
    assert "answer" in out and len(out["drafts"]) == 2
    assert {d["role"] for d in out["drafts"]} == {"qa", "qa2"}
    # refiner prompt template wired in
    assert "Merge" in ens.refiner.prompt_template


def test_ensemble_without_refiner_picks_most_confident():
    cfg = EdgeMeshConfig(agents=[_tiny_spec("qa"), _tiny_spec("qa2")])
    ens = build_ensemble(cfg, use_submeshes=False)
    out = ens.answer("test?")
    confidences = [d["confidence"] for d in out["drafts"]]
    assert out["confidence"] == max(confidences)


def test_int8_agent():
    spec = _tiny_spec()
    spec.model.precision = "int8"
    agent = build_agent(spec)
    from edgemesh.ops.int8 import is_quantized

    assert is_quantized(agent.params)
    out = agent.answer("quantized?")
    assert isinstance(out["answer"], str)


def test_ensemble_threadpool_overlaps_agents():
    """The orchestrator's concurrency machinery: two slow agents answered
    through Ensemble.answer must overlap in wall time (< 0.8x the serial
    sum) — the measured fix over the reference's sequential agent calls
    (combiner_fp.py:436-439). Fake agents isolate the thread-pool path from
    this host's single CPU core."""
    import time as _time

    class SlowAgent:
        def __init__(self, delay):
            self.delay = delay

        def answer(self, question, prompt=None):
            t0 = _time.perf_counter()
            _time.sleep(self.delay)
            return {"answer": "x", "role": "qa", "confidence": 0.5, "tps": 1.0,
                    "ttft_s": 0.0, "t_start": t0, "t_end": _time.perf_counter()}

        def answer_batch(self, questions, prompts=None):
            return [self.answer(q) for q in questions]

    from edgemesh.agents.orchestrator import Ensemble

    delay = 0.15
    ens = Ensemble(qa_agents=[SlowAgent(delay), SlowAgent(delay)])
    t0 = _time.perf_counter()
    out = ens.answer("q?")
    wall = _time.perf_counter() - t0
    serial = 2 * delay
    assert wall < 0.8 * serial, (wall, serial)
    starts = [d["t_start"] for d in out["drafts"]]
    ends = [d["t_end"] for d in out["drafts"]]
    assert max(starts) < min(ends), "agent intervals must share a common instant"


def test_real_agent_intervals_overlap_on_submeshes(devices):
    """Real tiny agents on disjoint submeshes: async dispatch must put both
    agents in flight simultaneously (interval overlap). Wall-clock speedup
    is asserted only off this 1-core host (benchmarks.ensemble_overlap_benchmark
    reports the ratio on real hardware)."""
    from edgemesh.benchmarks import ensemble_overlap_benchmark

    r = ensemble_overlap_benchmark(n_agents=2, questions=2)
    assert r["intervals_overlapped"] >= 1, r
    assert r["serial_s"] > 0 and r["concurrent_s"] > 0


def test_agent_with_draft_runs_speculative():
    """An AgentSpec with a draft model answers through speculative decoding;
    greedy output must equal the same agent without a draft (exactness)."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

    sampling = SamplingParams(max_new_tokens=12, do_sample=False, repetition_penalty=1.0)
    plain = build_agent(AgentSpec(role="qa", model=ModelSpec(), sampling=sampling))
    spec = build_agent(
        AgentSpec(
            role="qa", model=ModelSpec(), sampling=sampling,
            draft=ModelSpec(num_layers=1, hidden_size=32), spec_gamma=3,
        )
    )
    q = "where is the eiffel tower located"
    assert spec.draft_cfg is not None
    assert spec.answer(q)["answer"] == plain.answer(q)["answer"]


def test_agent_draft_vocab_mismatch_rejected():
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec

    import pytest

    # 300 clears the tokenizer-range guard (>= 259) but differs from the
    # main model's 260 — the speculative contract needs identical vocabs.
    with pytest.raises(ValueError, match="shared tokenizer"):
        build_agent(
            AgentSpec(role="qa", model=ModelSpec(), draft=ModelSpec(vocab_size=300))
        )
