"""Multi-agent ensemble: concurrent QA agents + refiner merge."""

import jax.numpy as jnp
import pytest

from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.agents import build_agent, build_ensemble


def _tiny_spec(role="qa", **model_kw):
    model_kw.setdefault("num_layers", 2)
    model_kw.setdefault("hidden_size", 32)
    model_kw.setdefault("num_heads", 4)
    model_kw.setdefault("num_kv_heads", 4)
    model_kw.setdefault("intermediate_size", 64)
    return AgentSpec(
        role=role,
        model=ModelSpec(family="llama", **model_kw),
        sampling=SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0),
    )


def test_single_agent_answer():
    agent = build_agent(_tiny_spec())
    out = agent.answer("What color is the sky?")
    assert set(out) >= {"answer", "tps", "confidence", "ttft_s", "role"}
    assert isinstance(out["answer"], str)
    assert out["tps"] > 0


def test_ensemble_with_refiner(devices):
    cfg = EdgeMeshConfig(
        agents=[_tiny_spec("qa"), _tiny_spec("qa2"), _tiny_spec("refiner")]
    )
    ens = build_ensemble(cfg)
    assert len(ens.qa_agents) == 2
    assert ens.refiner is not None
    # QA agents landed on disjoint submeshes
    m0, m1 = ens.qa_agents[0].mesh, ens.qa_agents[1].mesh
    assert m0 is not None and m1 is not None
    ids0 = {d.id for d in m0.devices.flat}
    ids1 = {d.id for d in m1.devices.flat}
    assert ids0.isdisjoint(ids1)

    out = ens.answer("What is the capital of France?")
    assert "answer" in out and len(out["drafts"]) == 2
    assert {d["role"] for d in out["drafts"]} == {"qa", "qa2"}
    # refiner prompt template wired in
    assert "Merge" in ens.refiner.prompt_template


def test_ensemble_without_refiner_picks_most_confident():
    cfg = EdgeMeshConfig(agents=[_tiny_spec("qa"), _tiny_spec("qa2")])
    ens = build_ensemble(cfg, use_submeshes=False)
    out = ens.answer("test?")
    confidences = [d["confidence"] for d in out["drafts"]]
    assert out["confidence"] == max(confidences)


def test_int8_agent():
    spec = _tiny_spec()
    spec.model.precision = "int8"
    agent = build_agent(spec)
    from edgemesh.ops.int8 import is_quantized

    assert is_quantized(agent.params)
    out = agent.answer("quantized?")
    assert isinstance(out["answer"], str)
