"""edgemesh.obs: metrics registry + Prometheus exposition, request-lifecycle
spans and their JSONL replay, the `edgemesh obs` CLI, supervisor event
counters, and the REST /metrics|/stats|/statusz surfaces.

Fast tier except the live-engine end-to-end tests at the bottom (marked
slow like the rest of the serving e2e suite)."""

import json
import math
import re
import threading

import pytest

from edgemesh.obs import Registry, SpanTracker, replay_spans
from edgemesh.obs.spans import SPAN_RECORD_EVENT
from edgemesh.utils.tracing import JsonlLogger

# ---------------------------------------------------------------------------
# Registry: counters / gauges / histograms / labels
# ---------------------------------------------------------------------------


def test_counter_gauge_label_mechanics():
    reg = Registry()
    c = reg.counter("req_total", "requests", ("engine", "status"))
    c.labels(engine="a", status="ok").inc()
    c.labels(engine="a", status="ok").inc(2)
    c.labels(engine="a", status="err").inc()
    g = reg.gauge("pages", "free pages")
    g.set(7)
    g.inc(3)
    g.dec()
    s = reg.summary()
    assert s['req_total{engine="a",status="ok"}'] == 3
    assert s['req_total{engine="a",status="err"}'] == 1
    assert s["pages"] == 9
    with pytest.raises(ValueError):
        c.labels(engine="a").inc()  # missing label
    with pytest.raises(ValueError):
        reg.gauge("req_total", "type clash")  # re-register as other type
    with pytest.raises(ValueError):
        c.labels(engine="a", status="ok").inc(-1)  # counters go up


def test_histogram_buckets_and_weighted_observe():
    reg = Registry()
    h = reg.histogram("lat", "latency", buckets=(0.01, 0.1, 1.0))
    h.observe(0.005)
    h.observe(0.05, count=3)
    h.observe(5.0)  # overflow → +Inf only
    child = h.labels()
    assert child.count == 5
    assert child.sum == pytest.approx(0.005 + 3 * 0.05 + 5.0)
    assert child.cumulative() == [1, 4, 4, 5]  # cumulative, +Inf == count


def test_registry_is_thread_safe_under_contention():
    reg = Registry()
    c = reg.counter("n_total", "")
    h = reg.histogram("h", "", buckets=(0.5,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.labels().value == 8000
    assert h.labels().count == 8000


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$"
)


def _parse_prom(text: str):
    """Minimal exposition-format parser: every non-comment line must match
    ``name{labels} value``; returns ({name: type}, {(name, labels): value})."""
    types: dict[str, str] = {}
    samples: dict[tuple[str, str], float] = {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            assert mtype in ("counter", "gauge", "histogram")
            types[name] = mtype
        elif line.startswith("#"):
            assert line.startswith("# HELP "), line
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable exposition line: {line!r}"
            v = m.group(3)
            samples[(m.group(1), m.group(2) or "")] = (
                math.inf if v == "+Inf" else float(v)
            )
    return types, samples


def test_exposition_format_is_parseable_and_complete():
    reg = Registry()
    reg.counter("edge_req_total", "total requests", ("engine",)).labels(
        engine="spec").inc(4)
    reg.gauge("edge_pages", "pool pages", ("state",)).labels(
        state="free").set(12)
    h = reg.histogram("edge_ttft_seconds", "ttft", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5, count=2)
    types, samples = _parse_prom(reg.render())
    assert types["edge_req_total"] == "counter"
    assert types["edge_pages"] == "gauge"
    assert types["edge_ttft_seconds"] == "histogram"
    assert samples[("edge_req_total", '{engine="spec"}')] == 4
    assert samples[("edge_pages", '{state="free"}')] == 12
    # Histogram: cumulative buckets, +Inf == _count, _sum present.
    assert samples[("edge_ttft_seconds_bucket", '{le="0.1"}')] == 1
    assert samples[("edge_ttft_seconds_bucket", '{le="1"}')] == 3
    assert samples[("edge_ttft_seconds_bucket", '{le="+Inf"}')] == 3
    assert samples[("edge_ttft_seconds_count", "")] == 3
    assert samples[("edge_ttft_seconds_sum", "")] == pytest.approx(1.05)


def test_exposition_escapes_label_values():
    reg = Registry()
    reg.counter("c_total", "", ("path",)).labels(path='a"b\\c\nd').inc()
    text = reg.render()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    # Still one sample line per record (the newline was escaped, not emitted).
    assert sum(1 for l in text.splitlines() if l.startswith("c_total")) == 1


def test_collectors_run_at_scrape_and_broken_collector_is_isolated():
    reg = Registry()
    calls = []

    def good(r):
        calls.append(1)
        r.gauge("sampled", "").set(42)

    def broken(r):
        raise RuntimeError("collector exploded")

    reg.add_collector(good)
    reg.add_collector(broken)
    reg.add_collector(good)  # dedupe by identity
    text = reg.render()
    assert "sampled 42" in text
    assert calls == [1]
    reg.snapshot()
    assert calls == [1, 1]


# ---------------------------------------------------------------------------
# JsonlLogger torn-write tolerance (satellite fix)
# ---------------------------------------------------------------------------


def test_jsonl_read_skips_truncated_last_line_and_counts_it(tmp_path):
    lg = JsonlLogger(tmp_path / "log.jsonl")
    lg.log("a", x=1)
    lg.log("b", x=2)
    # Torn write: the process died mid-record; no trailing newline either.
    with open(lg.path, "a") as f:
        f.write('{"ts": 123.0, "event": "c", "x"')
    records = lg.read()
    assert [r["event"] for r in records] == ["a", "b"]
    assert lg.malformed == 1
    # A clean re-read of an intact file reports zero malformed lines.
    lg2 = JsonlLogger(lg.path)
    lg2.path.write_text('{"event": "solo", "ts": 1.0}\n')
    assert [r["event"] for r in lg2.read()] == ["solo"]
    assert lg2.malformed == 0


# ---------------------------------------------------------------------------
# Span tracker lifecycle + replay
# ---------------------------------------------------------------------------


def _drive_tracker(tracker, rid, tokens_per_seg=(3, 2), status="ok"):
    tr = tracker.submit(rid)
    tracker.admit_start(tr)
    tracker.admitted(tr, prompt_tokens=5)
    for n in tokens_per_seg:
        tracker.tokens(tr, n)
    tracker.retire(tr, status=status)
    return tr


def test_span_lifecycle_monotonic_and_aggregated(tmp_path):
    reg = Registry()
    tracker = SpanTracker(reg, tmp_path / "spans.jsonl", engine="unit")
    _drive_tracker(tracker, 0)
    _drive_tracker(tracker, 1, tokens_per_seg=(4,))
    records = JsonlLogger(tmp_path / "spans.jsonl").read()
    assert len(records) == 2
    for rec in records:
        assert rec["event"] == SPAN_RECORD_EVENT
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "queued" and names[1] == "prefill"
        assert names[-1] == "retire" and "decode" in names
        # Monotonic, properly nested timestamps.
        for s in rec["spans"]:
            assert s["t1"] >= s["t0"]
        edges = [s["t0"] for s in rec["spans"]]
        assert edges == sorted(edges)
        assert rec["queue_s"] >= 0 and rec["ttft_s"] >= rec["queue_s"]
        assert rec["latency_s"] >= rec["ttft_s"]
    s = reg.summary()
    assert s['edgemesh_requests_submitted_total{engine="unit"}'] == 2
    assert s['edgemesh_requests_completed_total{engine="unit",status="ok"}'] == 2
    assert s['edgemesh_tokens_generated_total{engine="unit"}'] == 9
    assert s['edgemesh_ttft_seconds{engine="unit"}']["count"] == 2
    # Inter-token latency observes once per post-first token: (5-1)+(4-1).
    assert s['edgemesh_inter_token_seconds{engine="unit"}']["count"] == 7


def test_replay_rebuilds_the_same_request_aggregates(tmp_path):
    reg = Registry()
    tracker = SpanTracker(reg, tmp_path / "spans.jsonl", engine="unit")
    _drive_tracker(tracker, 0)
    _drive_tracker(tracker, 1, status="error")
    tracker.pool_reset("test reset")
    replayed = replay_spans(tmp_path / "spans.jsonl")
    live, offline = reg.summary(), replayed.summary()
    # Every request-level family replays to identical aggregates.
    for key, val in offline.items():
        if isinstance(val, dict):
            assert val["count"] == live[key]["count"], key
            assert val["sum"] == pytest.approx(live[key]["sum"]), key
        else:
            assert val == live[key], key
    assert offline['edgemesh_pool_resets_total{engine="unit"}'] == 1
    assert offline[
        'edgemesh_requests_completed_total{engine="unit",status="error"}'] == 1


# ---------------------------------------------------------------------------
# `edgemesh obs` CLI
# ---------------------------------------------------------------------------


@pytest.fixture()
def span_log(tmp_path):
    tracker = SpanTracker(Registry(), tmp_path / "spans.jsonl", engine="cli")
    for rid in range(3):
        _drive_tracker(tracker, rid)
    # A torn trailing line must not break any subcommand.
    with open(tmp_path / "spans.jsonl", "a") as f:
        f.write('{"event": "request_spans", "rid"')
    return tmp_path / "spans.jsonl"


def test_obs_cli_tail_summary_prom(span_log, capsys):
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["tail", str(span_log), "-n", "2"]) == 0
    out = capsys.readouterr().out
    assert out.count("rid=") == 2 and "spans=queued>prefill" in out

    assert obs_main(["summary", str(span_log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 3
    assert report["latency_s_p50"] > 0 and report["ttft_s_p95"] > 0
    assert report["metrics"][
        'edgemesh_tokens_generated_total{engine="cli"}'] == 15

    assert obs_main(["prom", str(span_log)]) == 0
    types, samples = _parse_prom(capsys.readouterr().out)
    assert types["edgemesh_ttft_seconds"] == "histogram"
    assert samples[
        ("edgemesh_requests_completed_total", '{engine="cli",status="ok"}')
    ] == 3


def test_obs_cli_missing_file_is_usage_error(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["summary", str(tmp_path / "nope.jsonl")]) == 2
    assert "no such span log" in capsys.readouterr().err


def test_summary_capacity_rows_null_on_pre_capacity_logs(span_log, capsys):
    # Forward-compat pin (like the pre-SLO/pre-tenant fields): a log from
    # before the capacity model reports explicit nulls and exits 0.
    from edgemesh.obs.cli import main as obs_main

    assert obs_main(["summary", str(span_log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["capacity"] is None
    assert report["pool"] is None
    assert report["knee"] is None


def test_summary_reports_capacity_pool_and_knee_rows(tmp_path, capsys):
    # A directory mixing a flight dump (digest snapshots carry the
    # capacity/pool blocks) and a router log (admission_tune records
    # carry the tuner's knee) — summary reports the newest of each.
    from edgemesh.obs.cli import main as obs_main

    logdir = tmp_path / "logs"
    logdir.mkdir()
    flight = JsonlLogger(logdir / "flight-r0.jsonl")
    flight.log("flight_snapshot", replica="r0",
               capacity={"slots": 8, "est_tok_s": 100.0, "est_req_s": 5.0},
               pool={"pages_total": 50, "pages_free": 10,
                     "occupancy_ratio": 0.8, "fragmentation_ratio": 0.1,
                     "free_page_headroom": 1})
    flight.log("flight_snapshot", replica="r0",
               capacity={"slots": 8, "est_tok_s": 120.0, "est_req_s": 6.0},
               pool={"pages_total": 50, "pages_free": 30,
                     "occupancy_ratio": 0.4, "fragmentation_ratio": 0.0,
                     "free_page_headroom": 3})
    router_log = JsonlLogger(logdir / "router.jsonl")
    router_log.log("admission_tune", action="increase", limit=12,
                   rate_scale=1.5, knee_offered_rps=9.5,
                   knee_goodput_rps=9.1, collapsed=False)
    assert obs_main(["summary", str(logdir)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["capacity"]["est_tok_s"] == 120.0  # newest snapshot wins
    assert report["pool"]["occupancy_ratio"] == 0.4
    assert report["knee"] == {
        "action": "increase", "limit": 12, "rate_scale": 1.5,
        "knee_offered_rps": 9.5, "knee_goodput_rps": 9.1,
        "collapsed": False,
    }


def test_loadreport_json_mode(tmp_path, capsys):
    # --json prints the machine-readable document; a curve assembled from
    # raw points (no knee fields) gains them via the same find_knee math.
    from edgemesh.obs.cli import main as obs_main

    doc = {"points": [
        {"offered_rps": 2.0, "goodput_rps": 2.0},
        {"offered_rps": 4.0, "goodput_rps": 3.8},
        {"offered_rps": 8.0, "goodput_rps": 1.0},
    ], "slo_latency_s": 0.5}
    path = tmp_path / "curve.json"
    path.write_text(json.dumps(doc))
    assert obs_main(["loadreport", str(path), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["knee_offered_rps"] == 4.0
    assert out["collapsed"] is True
    assert len(out["points"]) == 3
    # Single-run reports round-trip verbatim.
    run = {"scheduled": 10, "goodput_rps": 3.0, "tenants": None}
    path2 = tmp_path / "run.json"
    path2.write_text(json.dumps(run))
    assert obs_main(["loadreport", str(path2), "--json"]) == 0
    assert json.loads(capsys.readouterr().out) == run


def test_cli_routes_obs_subcommand(span_log, capsys):
    from edgemesh.cli import main as cli_main

    assert cli_main(["obs", "tail", str(span_log), "-n", "1"]) == 0
    assert "rid=" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Supervisor restart events as labeled counters
# ---------------------------------------------------------------------------


class _Flaky:
    built = 0

    def __init__(self, fail_first):
        type(self).built += 1
        self.remaining = fail_first

    def answer(self, q):
        if self.remaining > 0:
            self.remaining -= 1
            raise RuntimeError("boom")
        return {"answer": f"ok:{q}"}


def test_supervisor_restart_events_become_counters():
    from edgemesh.serve.supervisor import Supervisor

    reg = Registry()
    _Flaky.built = 0
    sup = Supervisor(
        factory=lambda: _Flaky(2 if _Flaky.built == 0 else 0),
        handler=lambda b, q: b.answer(q),
        max_consecutive_failures=2,
        registry=reg,
    )
    for _ in range(2):
        with pytest.raises(RuntimeError):
            sup.call("q")
    assert sup.call("q2")["answer"] == "ok:q2"
    s = reg.summary()
    assert s['edgemesh_supervisor_events_total{kind="start"}'] == 1
    assert s['edgemesh_supervisor_events_total{kind="request_failed"}'] == 2
    assert s['edgemesh_supervisor_events_total{kind="restart"}'] == 1
    assert s['edgemesh_supervisor_events_total{kind="restart_ok"}'] == 1
    assert s["edgemesh_supervisor_request_seconds"]["count"] == 1  # success


# ---------------------------------------------------------------------------
# REST surfaces (no model: FakeEnsemble + supervisor)
# ---------------------------------------------------------------------------


def test_rest_metrics_stats_statusz_surfaces():
    import urllib.request

    from edgemesh.serve.rest import serve_rest
    from edgemesh.serve.supervisor import Supervisor

    class FakeEnsemble:
        qa_agents = []
        refiner = None

    reg = Registry()
    sup = Supervisor(factory=lambda: _Flaky(0),
                     handler=lambda b, q: b.answer(q), registry=reg)
    server = serve_rest(FakeEnsemble(), host="127.0.0.1", port=0, block=False,
                        supervisor=sup, registry=reg)
    port = server.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"question": "hi"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert json.load(resp)["answer"] == "ok:hi"
        # /metrics: Prometheus text exposition, not JSON.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            types, samples = _parse_prom(resp.read().decode())
        assert types["edgemesh_supervisor_events_total"] == "counter"
        assert samples[
            ("edgemesh_supervisor_events_total", '{kind="start"}')] == 1
        # The device collector ran at scrape time (CPU backend still
        # reports the device count even without memory_stats).
        assert ("edgemesh_devices", "") in samples
        # /stats: the legacy JSON blob.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as resp:
            stats = json.load(resp)
        assert stats["supervisor"]["total_requests"] == 1
        assert "phases" in stats
        # /statusz: human text.
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=10
        ) as resp:
            page = resp.read().decode()
        assert "edgemesh statusz" in page and "supervisor: healthy" in page
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# Live-engine end-to-end (slow tier, like the rest of the serving e2e)
# ---------------------------------------------------------------------------


def _tiny_agent(max_new=12):
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.config import AgentSpec, ModelSpec, SamplingParams

    return build_agent(AgentSpec(
        role="qa", model=ModelSpec(),
        sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                repetition_penalty=1.0),
    ))


@pytest.mark.slow
def test_engine_emits_spans_and_matching_metrics(tmp_path):
    """Acceptance: a live ContinuousEngine serving concurrent requests emits
    admit/prefill/decode/retire spans with monotonic timestamps; /metrics-
    style exposition carries TTFT + inter-token histograms and KV page
    gauges whose counts match the actual traffic; the span JSONL replays
    into the same request aggregates."""
    from edgemesh.serve.continuous import ContinuousEngine

    reg = Registry()
    agent = _tiny_agent()
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, span_log=tmp_path / "spans.jsonl",
                           registry=reg)
    try:
        futs = [eng.submit(f"question number {i}?") for i in range(4)]
        results = [f.result(timeout=600) for f in futs]
        assert all(r["generated"] > 0 for r in results)
    finally:
        eng.close()

    # Span records: one per request, full lifecycle, monotonic timestamps.
    records = JsonlLogger(tmp_path / "spans.jsonl").read()
    span_recs = [r for r in records if r["event"] == SPAN_RECORD_EVENT]
    assert len(span_recs) == 4
    for rec in span_recs:
        names = [s["name"] for s in rec["spans"]]
        assert names[0] == "queued" and names[1] == "prefill"
        assert "decode" in names and names[-1] == "retire"
        for s in rec["spans"]:
            assert s["t1"] >= s["t0"]
        edges = [s["t0"] for s in rec["spans"]]
        assert edges == sorted(edges)
        assert rec["status"] == "ok" and rec["generated"] > 0

    # Registry aggregates match the engine's actual traffic.
    generated = sum(r["generated"] for r in results)
    s = reg.summary()
    assert s['edgemesh_requests_submitted_total{engine="continuous"}'] == 4
    assert s[
        'edgemesh_requests_completed_total{engine="continuous",status="ok"}'
    ] == 4
    assert s['edgemesh_tokens_generated_total{engine="continuous"}'] == generated
    assert s['edgemesh_segments_total{engine="continuous"}'] == eng.segments
    assert s['edgemesh_ttft_seconds{engine="continuous"}']["count"] == 4

    # Exposition: parseable, with the acceptance families present.
    types, samples = _parse_prom(reg.render())
    assert types["edgemesh_ttft_seconds"] == "histogram"
    assert types["edgemesh_inter_token_seconds"] == "histogram"
    assert types["edgemesh_kv_pages"] == "gauge"
    assert samples[
        ("edgemesh_requests_completed_total",
         '{engine="continuous",status="ok"}')
    ] == 4
    # All requests retired: reserved drained to 0, free + template = total.
    free = samples[("edgemesh_kv_pages", '{engine="continuous",state="free"}')]
    total = samples[("edgemesh_kv_pages", '{engine="continuous",state="total"}')]
    tpl = samples[
        ("edgemesh_kv_pages", '{engine="continuous",state="template"}')]
    assert samples[
        ("edgemesh_kv_pages", '{engine="continuous",state="reserved"}')] == 0
    assert free + tpl == total - 1  # -1: page 0 is the trash page

    # Replay: the span log alone rebuilds the same request aggregates.
    # (Segments are pool-wide engine state — documented as non-replayable.)
    offline = replay_spans(tmp_path / "spans.jsonl").summary()
    for key, val in offline.items():
        if key.startswith("edgemesh_segments_total"):
            continue
        if isinstance(val, dict):
            assert val["count"] == s[key]["count"], key
            assert val["sum"] == pytest.approx(s[key]["sum"]), key
        else:
            assert val == s[key], key


@pytest.mark.slow
def test_rest_continuous_metrics_scrape_end_to_end(tmp_path):
    """The full serving stack: REST --continuous with a span log; /generate
    traffic shows up in a valid Prometheus /metrics scrape and replays via
    the obs CLI."""
    import urllib.request

    from edgemesh.agents.orchestrator import Ensemble
    from edgemesh.obs.cli import main as obs_main
    from edgemesh.serve.rest import serve_rest

    reg = Registry()
    srv = serve_rest(Ensemble(qa_agents=[_tiny_agent(max_new=6)]),
                     host="127.0.0.1", port=0, block=False, continuous=True,
                     kv_backend="paged", kv_page_size=8, batch=2,
                     span_log=tmp_path / "spans.jsonl", registry=reg)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        for i in range(2):
            req = urllib.request.Request(
                f"{url}/generate",
                data=json.dumps({"question": f"question {i}?"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=300) as r:
                assert json.load(r)["generated"] > 0
        with urllib.request.urlopen(f"{url}/metrics", timeout=60) as r:
            types, samples = _parse_prom(r.read().decode())
        assert samples[
            ("edgemesh_requests_completed_total",
             '{engine="continuous",status="ok"}')
        ] == 2
        assert types["edgemesh_inter_token_seconds"] == "histogram"
        assert ("edgemesh_kv_pages", '{engine="continuous",state="free"}') in samples
    finally:
        srv.shutdown()
        if srv.batcher is not None:
            srv.batcher.close()
    assert obs_main(["summary", str(tmp_path / "spans.jsonl")]) == 0


# ---------------------------------------------------------------------------
# bounded_label: the tenant-cardinality guard (obs/metrics.py, EM112)
# ---------------------------------------------------------------------------


def test_bounded_label_defaults_sanitize_and_overflow():
    from edgemesh.obs.metrics import OTHER_LABEL, bounded_label

    assert bounded_label(None) == "default"
    assert bounded_label("") == "default"
    assert bounded_label(123) == "default"  # non-strings never pass through
    assert bounded_label("acme-prod") == "acme-prod"
    # Sanitized: exposition syntax and exotic bytes cannot ride a label.
    assert bounded_label('x"y{z}\n') == "x_y_z__"
    assert len(bounded_label("q" * 500, namespace="long")) == 64
    # First-come cap per namespace, overflow collapses into OTHER_LABEL.
    for i in range(32):
        assert bounded_label(f"t{i}", namespace="cap") == f"t{i}"
    assert bounded_label("t-straggler", namespace="cap") == OTHER_LABEL
    assert bounded_label("t5", namespace="cap") == "t5"  # seen values stay


def test_bounded_label_allowlist_never_grows_state():
    from edgemesh.obs.metrics import OTHER_LABEL, bounded_label

    allow = ("gold", "silver")
    assert bounded_label("gold", namespace="al", allow=allow) == "gold"
    for i in range(100):
        assert bounded_label(f"mint-{i}", namespace="al",
                             allow=allow) == OTHER_LABEL
    # The allowlisted namespace banked nothing: unlisted still passes cap.
    assert bounded_label("silver", namespace="al", allow=allow) == "silver"


# ---------------------------------------------------------------------------
# Per-tenant SLO + span/replay tenant plumbing (forward-compat satellite)
# ---------------------------------------------------------------------------


def test_slo_tracker_tenant_families_ride_alongside_aggregate():
    from edgemesh.obs.slo import SloTarget, SloTracker

    reg = Registry()
    slo = SloTracker(reg, engine="unit", target=SloTarget(ttft_s=1.0,
                                                          tpot_s=0.1))
    slo.record("ok", 0.5, 0.05, tenant="acme")
    slo.record("ok", 5.0, 0.05, tenant="acme")   # ttft miss
    slo.record("ok", 0.5, 0.05)                  # untagged: aggregate only
    s = reg.summary()
    assert s['edgemesh_slo_requests_total{engine="unit",result="good"}'] == 2
    assert s['edgemesh_slo_tenant_requests_total'
             '{engine="unit",tenant="acme",result="good"}'] == 1
    assert s['edgemesh_slo_tenant_requests_total'
             '{engine="unit",tenant="acme",result="ttft"}'] == 1
    assert s['edgemesh_slo_tenant_goodput_ratio'
             '{engine="unit",tenant="acme"}'] == 0.5
    assert slo.goodput_ratio() == pytest.approx(2 / 3)
    assert slo.tenant_goodput() == {
        "acme": {"classified": 2, "good": 1, "goodput_ratio": 0.5}}


def test_span_records_carry_tenant_and_replay_per_tenant(tmp_path):
    reg = Registry()
    tracker = SpanTracker(reg, tmp_path / "spans.jsonl", engine="unit")
    tr = tracker.submit(0, tenant="acme")
    tracker.admit_start(tr)
    tracker.admitted(tr)
    tracker.tokens(tr, 3)
    tracker.retire(tr)
    _drive_tracker(tracker, 1)  # untagged request
    recs = JsonlLogger(tmp_path / "spans.jsonl").read()
    assert [r.get("tenant") for r in recs] == ["acme", None]
    offline = replay_spans(tmp_path / "spans.jsonl").summary()
    live = reg.summary()
    for key, val in live.items():
        if key.startswith("edgemesh_slo_tenant"):
            assert offline[key] == val, key
    assert offline[
        'edgemesh_slo_tenant_requests_total'
        '{engine="unit",tenant="acme",result="good"}'] == 1


def test_replay_and_summary_stay_rc0_on_pre_tenant_logs(tmp_path, capsys):
    """Forward-compat direction 1: a log written BEFORE the tenant field
    (and before slo_result) replays cleanly — per-tenant fields null,
    exit 0."""
    from edgemesh.obs.cli import main as obs_main

    log = tmp_path / "old.jsonl"
    old_records = [
        # Pre-SLO, pre-tenant era record: no slo_result, no tenant key.
        {"ts": 1.0, "event": SPAN_RECORD_EVENT, "rid": 0, "engine": "e",
         "status": "ok", "generated": 3, "queue_s": 0.01, "prefill_s": 0.02,
         "ttft_s": 0.05, "itl_s": 0.01, "latency_s": 0.2, "spans": []},
        # SLO-era but pre-tenant record.
        {"ts": 2.0, "event": SPAN_RECORD_EVENT, "rid": 1, "engine": "e",
         "status": "ok", "generated": 2, "latency_s": 0.1,
         "slo_result": "good", "spans": []},
    ]
    with open(log, "w") as f:
        for r in old_records:
            f.write(json.dumps(r) + "\n")
    reg = replay_spans(log)
    s = reg.summary()
    assert s['edgemesh_requests_submitted_total{engine="e"}'] == 2
    assert s['edgemesh_slo_requests_total{engine="e",result="good"}'] == 1
    # No per-tenant family was minted from tenant-less records.
    assert not any(k.startswith("edgemesh_slo_tenant") for k in s)
    assert obs_main(["summary", str(log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 2
    assert report["tenants"] is None  # null, not an error


def test_replay_ignores_unknown_keys_in_future_records(tmp_path, capsys):
    """Forward-compat direction 2: records written by a FUTURE version
    (unknown keys, unknown slo_result values) replay without error and
    the known fields still aggregate."""
    from edgemesh.obs.cli import main as obs_main

    log = tmp_path / "future.jsonl"
    future_records = [
        {"ts": 1.0, "event": SPAN_RECORD_EVENT, "rid": 0, "engine": "e",
         "status": "ok", "generated": 4, "latency_s": 0.2, "ttft_s": 0.05,
         "slo_result": "good", "tenant": "acme",
         # Unknown future keys must be ignored, not fatal.
         "tenant_shard": "eu-west", "qos_class": 3,
         "spans": [], "future_blob": {"nested": [1, 2, 3]}},
        {"ts": 2.0, "event": SPAN_RECORD_EVENT, "rid": 1, "engine": "e",
         "status": "ok", "generated": 1, "latency_s": 0.1,
         # An slo_result value this version does not know: skipped, the
         # rest of the record still counts.
         "slo_result": "good_with_asterisk", "spans": []},
        {"ts": 3.0, "event": "future_event_kind", "engine": "e",
         "payload": "???"},
    ]
    with open(log, "w") as f:
        for r in future_records:
            f.write(json.dumps(r) + "\n")
    s = replay_spans(log).summary()
    assert s['edgemesh_requests_submitted_total{engine="e"}'] == 2
    assert s['edgemesh_slo_requests_total{engine="e",result="good"}'] == 1
    assert s['edgemesh_slo_tenant_requests_total'
             '{engine="e",tenant="acme",result="good"}'] == 1
    assert obs_main(["summary", str(log)]) == 0
    report = json.loads(capsys.readouterr().out)
    # Three records, two of them request spans; the unknown event kind is
    # carried but not misread as a request.
    assert report["records"] == 3 and report["requests"] == 2
    assert report["tenants"]["acme"]["classified"] == 1


def test_quality_block_rides_span_records_both_compat_directions(
        tmp_path, capsys):
    """Quality-observatory schema compat, both directions: a record WITH
    a quality block replays + summarizes cleanly (the block aggregates
    into the summary's quality view), and a pre-quality log answers null
    quality at rc 0 — never an error."""
    from edgemesh.obs.cli import main as obs_main

    new_log = tmp_path / "quality.jsonl"
    records = [
        {"ts": 1.0, "event": SPAN_RECORD_EVENT, "rid": 0, "engine": "e",
         "status": "ok", "generated": 4, "latency_s": 0.2,
         "slo_result": "good", "tenant": "acme", "spans": [],
         "quality": {"confidence_mean": 0.91, "confidence_min": 0.4,
                     "entropy_mean": 1.1, "tokens": 4,
                     # A future build's extra key must be ignored.
                     "calibration_temp": 0.7}},
        {"ts": 2.0, "event": SPAN_RECORD_EVENT, "rid": 1, "engine": "e",
         "status": "ok", "generated": 2, "latency_s": 0.1,
         "slo_result": "good", "spans": []},  # quality-less sibling: fine
    ]
    with open(new_log, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    # Metric replay is quality-agnostic (the block rides as data, not
    # state) — the known families still aggregate both records.
    s = replay_spans(new_log).summary()
    assert s['edgemesh_requests_submitted_total{engine="e"}'] == 2
    assert obs_main(["summary", str(new_log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["quality"]["quality_records"] == 1
    assert report["quality"]["confidence"]["engines"]["e"]["mean"] == 0.91
    assert report["quality"]["confidence"]["tenants"]["acme"]["n"] == 1
    assert obs_main(["quality", str(new_log), "--json"]) == 0
    view = json.loads(capsys.readouterr().out)
    assert view["quality_records"] == 1

    # Backward direction: a pre-quality log (no quality key anywhere).
    old_log = tmp_path / "old.jsonl"
    with open(old_log, "w") as f:
        f.write(json.dumps({
            "ts": 1.0, "event": SPAN_RECORD_EVENT, "rid": 0, "engine": "e",
            "status": "ok", "generated": 3, "latency_s": 0.2,
            "slo_result": "good", "spans": []}) + "\n")
    assert obs_main(["summary", str(old_log)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["quality"] is None  # null, not an error
    assert obs_main(["quality", str(old_log), "--json"]) == 0
    assert json.loads(capsys.readouterr().out) is None


# ---------------------------------------------------------------------------
# DecayingQuantile under bursty open-loop arrival (satellite)
# ---------------------------------------------------------------------------


def _decaying(**kw):
    from edgemesh.obs.slo import DecayingQuantile

    clock = {"t": 0.0}
    dq = DecayingQuantile(now=lambda: clock["t"], **kw)
    return dq, clock


def test_decaying_quantile_decays_across_idle_gaps():
    dq, clk = _decaying(half_life_s=60.0)
    for _ in range(100):
        dq.observe(1.0)
    assert dq.weight() == pytest.approx(100.0)
    clk["t"] += 120.0  # two half-lives of silence
    assert dq.weight() == pytest.approx(25.0, rel=1e-6)
    # The surviving mass still answers quantiles at the old regime.
    assert dq.quantile(0.5) == pytest.approx(1.0, rel=0.4)


def test_decaying_quantile_min_weight_gate_rearms_after_quiet_period():
    dq, clk = _decaying(half_life_s=10.0, min_weight=16.0)
    assert dq.quantile(0.95) is None  # empty: must not arm
    for _ in range(20):
        dq.observe(0.1)
    assert dq.quantile(0.95) is not None  # armed
    clk["t"] += 10.0  # 20 -> 10: below the gate again
    assert dq.weight() < 16.0
    assert dq.quantile(0.95) is None  # DISARMED: stale evidence stands down
    # A fresh burst re-arms it (bursty open-loop traffic pattern).
    for _ in range(12):
        dq.observe(0.1)
    assert dq.quantile(0.95) is not None


def test_decaying_quantile_stable_across_interleaved_tenant_regimes():
    """Two tenants in disjoint latency regimes (1 ms vs 1 s) interleaving
    their observations: low quantiles answer from the fast regime, high
    quantiles from the slow one, and the answers do not drift with the
    interleaving order or repeated reads."""
    dq, _ = _decaying(half_life_s=3600.0)  # no decay inside the test
    for _ in range(100):
        dq.observe(0.001)  # interactive tenant
        dq.observe(1.0)    # batch tenant
    p25 = dq.quantile(0.25)
    p95 = dq.quantile(0.95)
    assert p25 < 0.01           # firmly in the fast regime
    assert 0.5 < p95 < 2.0      # firmly in the slow regime (bucket-coarse)
    # Repeated reads are stable (no internal mutation from reading).
    assert dq.quantile(0.25) == p25 and dq.quantile(0.95) == p95
    # Order independence: the reversed interleave lands in the same buckets.
    dq2, _ = _decaying(half_life_s=3600.0)
    for _ in range(100):
        dq2.observe(1.0)
        dq2.observe(0.001)
    assert dq2.quantile(0.25) == pytest.approx(p25, rel=1e-9)
    assert dq2.quantile(0.95) == pytest.approx(p95, rel=1e-9)
