"""edgemesh.analysis concurrency pass (EM301-EM304): one known-bad fixture
per rule plus the negative (quiet) twin, the annotation vocabulary
(``# guarded by:`` / ``# not shared``), inline disables, inheritance
merging, and the shipped-tree-clean gate. Fast tier — pure AST, no jax."""

from pathlib import Path

from edgemesh.analysis.concurrency import RULES, analyze_source
from edgemesh.analysis.edgelint import lint_paths, lint_source


def rules_of(findings):
    return {f.rule for f in findings}


def em3(findings):
    return [f for f in findings if f.rule.startswith("EM3")]


# ---------------------------------------------------------------------------
# EM301 unguarded-shared-state
# ---------------------------------------------------------------------------

_EM301_SRC = """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.segments = 0

    def stats(self):
        with self._lock:
            return {"segments": self.segments}

    def bump(self):
        self.segments += 1
"""


def test_em301_fires_on_unlocked_mutation_of_inferred_guarded_field():
    findings = analyze_source(_EM301_SRC, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM301"}
    f = findings[0]
    assert f.severity == "error"
    assert "segments" in f.message and "_lock" in f.message
    assert f.context == "Engine.bump"


def test_em301_quiet_when_mutation_is_under_the_lock():
    src = _EM301_SRC.replace(
        "        self.segments += 1",
        "        with self._lock:\n            self.segments += 1",
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em301_init_is_exempt_and_reads_do_not_fire():
    # __init__ mutations are construction; unlocked READS are not flagged
    # (the rule is about mutations racing locked readers).
    src = _EM301_SRC.replace(
        "        self.segments += 1", "        return self.segments"
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em301_catches_mutator_method_calls():
    src = """
import threading

class Q:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []

    def drain(self):
        with self._cond:
            return list(self._queue)

    def push(self, item):
        self._queue.append(item)
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM301"}
    assert "_queue" in findings[0].message


def test_em301_not_shared_annotation_exempts_worker_owned_fields():
    src = _EM301_SRC.replace(
        "        self.segments = 0",
        "        self.segments = 0  # not shared: worker-owned",
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em301_guarded_by_on_def_line_marks_method_as_locked():
    # The helper-called-with-the-lock-held pattern: assert the guard on the
    # def line instead of re-acquiring (an RLock would mask the mistake).
    src = _EM301_SRC.replace(
        "    def bump(self):",
        "    def bump(self):  # guarded by: _lock",
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em301_guarded_by_declaration_fires_without_inference():
    # No method ever touches the field under the lock — inference alone
    # would stay silent — but the declared guard makes the contract checked.
    src = """
import threading

class Counters:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0  # guarded by: _lock

    def bump(self):
        self.total += 1
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM301"}
    fixed = src.replace(
        "        self.total += 1",
        "        with self._lock:\n            self.total += 1",
    )
    assert analyze_source(fixed, path="edgemesh/serve/x.py") == []


def test_em301_sees_through_same_module_inheritance():
    # The speculative-engine shape: the base constructs the lock and reads
    # the counter under it; the SUBCLASS mutates it unlocked.
    src = _EM301_SRC + """

class SpecEngine(Engine):
    def dispatch(self):
        self.segments += 1
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert len(findings) == 2  # base bump + subclass dispatch
    assert {f.context for f in findings} == {"Engine.bump", "SpecEngine.dispatch"}
    assert any("SpecEngine.segments" in f.message for f in findings)


def test_em301_dataclass_field_lock_is_discovered():
    src = """
import threading
from dataclasses import dataclass, field
from typing import Any

@dataclass
class Agent:
    _prefix_lock: Any = field(default_factory=threading.Lock)
    _prefix: Any = None

    def warm(self):
        with self._prefix_lock:
            return self._prefix

    def clobber(self):
        self._prefix = None
"""
    findings = analyze_source(src, path="edgemesh/agents/x.py")
    assert rules_of(findings) == {"EM301"}


def test_em301_tracks_linear_acquire_release():
    # A with-block is not the only correct way to hold the lock.
    src = _EM301_SRC.replace(
        "        self.segments += 1",
        "        self._lock.acquire()\n"
        "        self.segments += 1\n"
        "        self._lock.release()",
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em301_inference_sees_acquire_release_readers():
    # The READER uses the try/finally acquire idiom; the bare writer must
    # still be caught — inference tracks linear regions too.
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def read(self):
        self._lock.acquire()
        try:
            return self.count
        finally:
            self._lock.release()

    def bump(self):
        self.count += 1
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM301"}
    assert findings[0].context == "C.bump"


def test_em301_honors_inline_disable():
    src = _EM301_SRC.replace(
        "        self.segments += 1",
        "        self.segments += 1  # edgelint: disable=EM301",
    )
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


# ---------------------------------------------------------------------------
# EM302 lock-order-inversion
# ---------------------------------------------------------------------------

_EM302_SRC = """
import threading

class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""


def test_em302_fires_on_opposite_acquisition_orders():
    findings = analyze_source(_EM302_SRC, path="edgemesh/fleet/x.py")
    assert rules_of(findings) == {"EM302"}
    f = findings[0]
    assert f.severity == "error"
    assert "_a_lock" in f.message and "_b_lock" in f.message


def test_em302_quiet_on_consistent_order():
    src = _EM302_SRC.replace(
        "        with self._b_lock:\n            with self._a_lock:",
        "        with self._a_lock:\n            with self._b_lock:",
    )
    assert analyze_source(src, path="edgemesh/fleet/x.py") == []


def test_em302_sees_inversion_through_self_calls():
    src = """
import threading

class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            self._helper()

    def _helper(self):
        with self._b_lock:
            pass

    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""
    findings = analyze_source(src, path="edgemesh/fleet/x.py")
    assert rules_of(findings) == {"EM302"}


def test_em302_sees_linear_acquire_inversions():
    # The try/finally acquire() idiom deadlocks just as well as with-blocks.
    src = """
import threading

class Pair:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        self._a_lock.acquire()
        try:
            with self._b_lock:
                pass
        finally:
            self._a_lock.release()

    def two(self):
        with self._b_lock:
            with self._a_lock:
                pass
"""
    findings = analyze_source(src, path="edgemesh/fleet/x.py")
    assert rules_of(findings) == {"EM302"}


def test_em302_single_lock_class_is_quiet():
    assert analyze_source(_EM301_SRC.replace(
        "        self.segments += 1",
        "        with self._lock:\n            self.segments += 1",
    ), path="edgemesh/x.py") == []


# ---------------------------------------------------------------------------
# EM303 blocking-under-lock
# ---------------------------------------------------------------------------

_EM303_SRC = """
import threading
import time

class Prober:
    def __init__(self):
        self._lock = threading.Lock()

    def probe(self, transport, url):
        with self._lock:
            status, body = transport.get_json(url, timeout_s=1.0)
            time.sleep(0.1)
        return status
"""


def test_em303_fires_on_transport_and_sleep_under_lock():
    findings = analyze_source(_EM303_SRC, path="edgemesh/fleet/x.py")
    assert [f.rule for f in findings] == ["EM303", "EM303"]
    assert all(f.severity == "warning" for f in findings)
    assert any(".get_json()" in f.message for f in findings)
    assert any("time.sleep()" in f.message for f in findings)


def test_em303_quiet_outside_the_lock():
    src = """
import threading
import time

class Prober:
    def __init__(self):
        self._lock = threading.Lock()

    def probe(self, transport, url):
        status, body = transport.get_json(url, timeout_s=1.0)
        with self._lock:
            self.last = status
        time.sleep(0.1)
        return status
"""
    assert analyze_source(src, path="edgemesh/fleet/x.py") == []


def test_em303_condition_wait_is_not_blocking_under_lock():
    src = """
import threading

class W:
    def __init__(self):
        self._cond = threading.Condition()

    def wait_for_work(self):
        with self._cond:
            self._cond.wait()
            self._cond.wait_for(lambda: True, timeout=1.0)
"""
    assert analyze_source(src, path="edgemesh/serve/x.py") == []


def test_em303_queue_get_and_future_result_without_timeout():
    src = """
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()

    def drain(self, q, fut):
        with self._lock:
            a = q.get()
            b = fut.result()
            c = q.get(timeout=1.0)
            d = fut.result(1.0)
        return a, b, c, d
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert [f.rule for f in findings] == ["EM303", "EM303"]  # a and b only


def test_em303_tracks_linear_acquire_release():
    src = """
import threading
import time

_lock = threading.Lock()

def capture(seconds):
    if not _lock.acquire(blocking=False):
        return False
    try:
        time.sleep(seconds)
    finally:
        _lock.release()
    return True
"""
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM303"}


def test_em303_semaphores_are_admission_tokens_not_locks():
    # Sleeping while holding an in-flight SLOT is the router's design;
    # only Lock/RLock/Condition (and lockish names) count.
    src = """
import threading
import time

class Router:
    def __init__(self):
        self._slots = threading.BoundedSemaphore(8)

    def handle(self):
        self._slots.acquire(blocking=False)
        try:
            time.sleep(0.01)
        finally:
            self._slots.release()
"""
    assert analyze_source(src, path="edgemesh/fleet/x.py") == []


def test_em303_descends_self_calls_and_anchors_at_call_site():
    src = """
import threading
import urllib.request

class D:
    def __init__(self):
        self._lock = threading.Lock()

    def refresh(self):
        with self._lock:
            self._dial()

    def _dial(self):
        return urllib.request.urlopen("http://x", timeout=1.0)
"""
    findings = analyze_source(src, path="edgemesh/fleet/x.py")
    assert rules_of(findings) == {"EM303"}
    f = findings[0]
    assert "via self._dial()" in f.message
    assert f.context == "D.refresh"  # anchored at the locked call site


def test_em303_honors_inline_disable():
    src = _EM303_SRC.replace(
        "            time.sleep(0.1)",
        "            time.sleep(0.1)  # edgelint: disable=EM303",
    ).replace(
        "            status, body = transport.get_json(url, timeout_s=1.0)",
        "            status, body = transport.get_json(url, timeout_s=1.0)  # edgelint: disable=EM303",
    )
    assert analyze_source(src, path="edgemesh/fleet/x.py") == []


# ---------------------------------------------------------------------------
# EM304 thread-hygiene
# ---------------------------------------------------------------------------


def test_em304_thread_without_daemon_or_join():
    src = (
        "import threading\n"
        "def start(fn):\n"
        "    threading.Thread(target=fn).start()\n"
    )
    findings = analyze_source(src, path="edgemesh/serve/x.py")
    assert rules_of(findings) == {"EM304"}
    assert findings[0].severity == "warning"
    assert "shutdown path" in findings[0].message


def test_em304_daemon_or_joined_threads_are_quiet():
    daemon = (
        "import threading\n"
        "def start(fn):\n"
        "    threading.Thread(target=fn, daemon=True).start()\n"
    )
    assert analyze_source(daemon, path="edgemesh/serve/x.py") == []
    joined = (
        "import threading\n"
        "def run(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
        "    t.join(timeout=5)\n"
    )
    assert analyze_source(joined, path="edgemesh/serve/x.py") == []
    annotated = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self, fn):\n"
        "        self._t: threading.Thread = threading.Thread(target=fn)\n"
        "    def close(self):\n"
        "        self._t.join(timeout=5)\n"
    )
    assert analyze_source(annotated, path="edgemesh/serve/x.py") == []


def test_em304_swallowing_worker_loop():
    src = """
import threading

def _loop():
    while True:
        try:
            work()
        except Exception:
            pass

def start():
    threading.Thread(target=_loop, daemon=True).start()
"""
    findings = analyze_source(src, path="edgemesh/fleet/x.py")
    assert rules_of(findings) == {"EM304"}
    assert "swallows" in findings[0].message


def test_em304_logging_handler_is_quiet():
    src = """
import logging
import threading

log = logging.getLogger(__name__)

def _loop():
    while True:
        try:
            work()
        except Exception:
            log.exception("pass failed")

def start():
    threading.Thread(target=_loop, daemon=True).start()
"""
    assert analyze_source(src, path="edgemesh/fleet/x.py") == []


# ---------------------------------------------------------------------------
# Integration: the shared lint entry points + the shipped tree
# ---------------------------------------------------------------------------


def test_lint_source_includes_concurrency_findings():
    # The EM3xx pass rides every edgelint entry point (CLI, repo gate).
    findings = lint_source(_EM301_SRC, path="edgemesh/serve/x.py")
    assert "EM301" in rules_of(findings)


def test_em3xx_findings_fingerprint_and_baseline_like_any_other():
    from edgemesh.analysis.findings import Baseline

    findings = analyze_source(_EM301_SRC, path="edgemesh/serve/x.py")
    baseline = Baseline.from_findings(findings)
    shifted = analyze_source("\n\n\n" + _EM301_SRC, path="edgemesh/serve/x.py")
    assert baseline.filter(shifted) == []


def test_shipped_tree_has_zero_unbaselined_em3xx():
    """The serving stack must stay concurrency-clean: zero unbaselined
    EM301-EM304 findings across edgemesh/ (this PR fixed the real ones
    rather than baselining them — fleet/serve hold the reference
    discipline)."""
    from edgemesh.analysis.findings import Baseline, default_baseline_path

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    fresh = Baseline.load(default_baseline_path()).filter(lint_paths([pkg]))
    bad = em3(fresh)
    assert bad == [], [f.render() for f in bad]


def test_every_concurrency_rule_has_metadata():
    for rule, meta in RULES.items():
        assert rule.startswith("EM3"), rule
        assert meta["severity"] in ("error", "warning"), rule
        assert meta["name"] and meta["summary"], rule
