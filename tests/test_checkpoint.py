"""Checkpoint/resume: pytree save/restore, sharded round-trips across mesh
layouts, rotating train checkpoints, and serving snapshots."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import init_params
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.sharding import param_pspecs
from edgemesh.runtime.checkpoint import (
    TrainCheckpointManager,
    restore_for_serving,
    restore_pytree,
    save_pytree,
    snapshot_for_serving,
)
from edgemesh.training import init_train_state, make_optimizer, make_train_step


def _cfg():
    return tiny_config("llama", num_heads=4, num_kv_heads=2, hidden_size=32,
                       intermediate_size=64, num_layers=2, vocab_size=64,
                       max_seq_len=32).replace(dtype="float32")


def _trees_equal(a, b):
    flat_a, _ = jax.tree.flatten(a)
    flat_b, _ = jax.tree.flatten(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_params_roundtrip(tmp_path):
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_pytree(tmp_path / "p", params)
    back = restore_pytree(tmp_path / "p")
    _trees_equal(params, back)


def test_sharded_save_restores_onto_new_mesh_layout(tmp_path):
    """Save under tp=4, restore under tp=2 — the chip-count migration case."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))

    mesh_a = build_mesh(dp=2, tp=4)
    specs_a = param_pspecs(cfg, mesh_a)
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh_a, s)),
        params, specs_a, is_leaf=lambda x: isinstance(x, P),
    )
    save_pytree(tmp_path / "s", sharded)

    mesh_b = build_mesh(dp=4, tp=2)
    specs_b = param_pspecs(cfg, mesh_b)
    template = jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=NamedSharding(mesh_b, s)
        ),
        params, specs_b, is_leaf=lambda x: isinstance(x, P),
    )
    back = restore_pytree(tmp_path / "s", template=template)
    _trees_equal(params, back)
    leaf = jax.tree.leaves(back)[0]
    assert leaf.sharding.mesh.shape["dp"] == 4 and leaf.sharding.mesh.shape["tp"] == 2


def test_train_manager_rotates_and_resumes(tmp_path):
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = make_optimizer()
    state = init_train_state(cfg, params, opt)
    step_fn = make_train_step(cfg, opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64, jnp.int32)
    lengths = jnp.full((2,), 16, jnp.int32)

    mgr = TrainCheckpointManager(tmp_path / "run", max_to_keep=2)
    assert mgr.restore_latest(state) is None  # fresh directory
    losses = []
    for step in range(3):
        state, loss = step_fn(state, tokens, lengths)
        losses.append(float(loss))
        mgr.save(step, state)
    assert mgr.latest_step() == 2
    restored, step = mgr.restore_latest(state)
    assert step == 2
    _trees_equal(state.params, restored.params)

    # Resumed training continues identically from the restored state.
    s_a, loss_a = step_fn(state, tokens, lengths)
    s_b, loss_b = step_fn(restored, tokens, lengths)
    assert float(loss_a) == float(loss_b)
    mgr.close()


def test_serving_snapshot_roundtrip(tmp_path):
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    snapshot_for_serving(tmp_path / "serve", cfg, params)
    cfg2, params2 = restore_for_serving(tmp_path / "serve")
    assert cfg2 == cfg
    _trees_equal(params, params2)

    mesh = build_mesh(dp=2, tp=4)
    cfg3, params3 = restore_for_serving(tmp_path / "serve", mesh=mesh)
    _trees_equal(params, params3)
    leaf = jax.tree.leaves(params3)[0]
    assert leaf.sharding.mesh.shape["dp"] == 2 and leaf.sharding.mesh.shape["tp"] == 4


def test_missing_snapshot_raises(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError, match="no serving snapshot"):
        restore_for_serving(tmp_path / "nothing")


def test_quantized_params_roundtrip(tmp_path):
    """Serving restarts restore quantized trees byte-exactly — int8, the
    nibble-packed int4 layout, and the int8 embedding all survive the orbax
    roundtrip."""
    import numpy as np

    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.ops.int4 import quantize_params_int4
    from edgemesh.ops.int8 import quantize_embedding, quantize_params
    from edgemesh.runtime.checkpoint import restore_pytree, save_pytree

    cfg = tiny_config("llama", vocab_size=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    for name, q in (
        ("int8", quantize_embedding(quantize_params(params))),
        ("int4", quantize_params_int4(params, group_size=32)),
    ):
        path = tmp_path / name
        save_pytree(path, q)
        r = restore_pytree(path, template=q)
        for a, b in zip(jax.tree.leaves(q), jax.tree.leaves(r)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32)
            )
