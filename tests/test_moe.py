"""MoE layer: routing math, capacity drops, end-to-end forward/training,
expert-parallel sharding parity on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_prefill, init_kv_cache, init_params
from edgemesh.ops.moe import expert_capacity, moe_mlp
from edgemesh.training import causal_lm_loss, init_train_state, make_optimizer, make_train_step


import pytest

# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _cfg(**kw):
    base = dict(num_heads=4, num_kv_heads=2, hidden_size=32, intermediate_size=64,
                num_layers=2, vocab_size=64, max_seq_len=64,
                num_experts=4, experts_per_token=2)
    base.update(kw)
    return tiny_config("llama", **base).replace(dtype="float32")


def test_single_expert_equals_dense_ffn():
    """E=1, k=1, ample capacity: routing is the identity, so the MoE layer
    must equal a plain dense FFN with the same weights."""
    cfg = _cfg(num_experts=1, experts_per_token=1, expert_capacity_factor=2.0)
    params = init_params(cfg, jax.random.PRNGKey(0))
    moe = jax.tree.map(lambda x: x, params["layers"]["moe"])
    layer0 = {k: v[0] for k, v in moe.items() if k != "router"}
    layer0["router"] = {"kernel": moe["router"]["kernel"][0]}
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.hidden_size))
    y, aux = moe_mlp(cfg, layer0, x)
    # Dense equivalent with expert 0's weights.
    gate_w, up_w, down_w = layer0["gate"][0], layer0["up"][0], layer0["down"][0]
    want = (jax.nn.silu(x @ gate_w) * (x @ up_w)) @ down_w
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), atol=1e-5, rtol=1e-5)
    assert float(aux) == 1.0  # single expert: frac=1, meanprob=1, E*1*1


def test_gates_sum_to_one_and_capacity_bounds():
    cfg = _cfg(expert_capacity_factor=1.0)
    assert expert_capacity(cfg, 64) == 64 // 4 * 2
    cfg2 = _cfg(expert_capacity_factor=0.01)
    assert expert_capacity(cfg2, 64) == 1  # floor at 1 slot


def test_capacity_overflow_drops_tokens_not_crashes():
    """Tiny capacity: most tokens lose expert slots; output stays finite and
    the dropped tokens contribute zero (residual passthrough upstream)."""
    cfg = _cfg(expert_capacity_factor=0.05)
    params = init_params(cfg, jax.random.PRNGKey(0))
    layer0 = {k: (v[0] if k != "router" else {"kernel": v["kernel"][0]})
              for k, v in params["layers"]["moe"].items()}
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.hidden_size))
    y, aux = moe_mlp(cfg, layer0, x)
    assert np.isfinite(np.asarray(y)).all() and np.isfinite(float(aux))
    # With C=1 per expert, at most E*C*k combine entries are nonzero → most
    # rows are exactly zero.
    zero_rows = np.mean(np.all(np.asarray(y) == 0, axis=-1))
    assert zero_rows > 0.4


def test_moe_model_forward_and_generate():
    from edgemesh.config import SamplingParams
    from edgemesh.runtime.generate import generate

    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 9, 11, 42, 7], [17, 3, 50, 8, 33]], jnp.int32)
    lengths = jnp.array([5, 5], jnp.int32)
    cache = init_kv_cache(cfg, 2)
    logits, _ = forward_prefill(cfg, params, tokens, lengths, cache)
    assert np.isfinite(np.asarray(logits)).all()
    r = generate(cfg, params, tokens, lengths,
                 SamplingParams(max_new_tokens=6, temperature=0.0))
    assert np.isfinite(np.asarray(r.confidence)).all()


def test_moe_training_step_moves_loss_and_router():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = make_optimizer(lr=1e-2)
    state = init_train_state(cfg, params, opt)
    step = make_train_step(cfg, opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64, jnp.int32)
    lengths = jnp.full((4,), 16, jnp.int32)
    r0 = np.asarray(params["layers"]["moe"]["router"]["kernel"]).copy()
    losses = []
    for _ in range(5):
        state, loss = step(state, tokens, lengths)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses  # learning happens through routing
    r1 = np.asarray(state.params["layers"]["moe"]["router"]["kernel"])
    assert np.max(np.abs(r1 - r0)) > 0  # router received gradients


def test_aux_loss_included_only_for_moe():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64, jnp.int32)
    lengths = jnp.full((2,), 12, jnp.int32)
    with_aux = float(causal_lm_loss(cfg, params, tokens, lengths, moe_aux_weight=0.5))
    without = float(causal_lm_loss(cfg, params, tokens, lengths, moe_aux_weight=0.0))
    assert with_aux > without  # aux term is strictly positive


def test_expert_parallel_sharding_parity():
    """Experts sharded over ep=4 produce the same logits as unsharded."""
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.sharding import param_pspecs

    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 9, 11, 42, 7, 3, 2, 1]], jnp.int32)
    lengths = jnp.array([8], jnp.int32)
    want, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 1))

    mesh = build_mesh(dp=2, ep=4)
    specs = param_pspecs(cfg, mesh)
    assert specs["layers"]["moe"]["up"][1] == "ep"  # expert dim on the ep axis
    sharded = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P),
    )
    got, _ = forward_prefill(cfg, sharded, tokens, lengths, init_kv_cache(cfg, 1))
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=1e-5, rtol=1e-5)


def test_moe_int8_quantization_experts_quantized_router_fp32():
    """quantize_params on an MoE model must quantize the EXPERT weights
    (they are ~96% of a Mixtral's parameters — leaving them float would
    void the int8 memory story) while keeping the router fp32 (routing
    softmax islands; moe_mlp reads router.kernel directly). The quantized
    model's logits must stay close to float and it must generate."""
    from edgemesh.config import SamplingParams
    from edgemesh.ops.int8 import quantize_params
    from edgemesh.runtime.generate import generate

    cfg = _cfg(quant_mode="w8a16")
    params = init_params(cfg, jax.random.PRNGKey(0))
    q = quantize_params(params)
    moe = q["layers"]["moe"]
    assert "kernel" in moe["router"] and moe["router"]["kernel"].dtype == jnp.float32
    assert "kernel_q" not in moe["router"]
    for name in ("gate", "up", "down"):
        assert name not in moe, f"float {name} left behind"
        assert moe[f"{name}_q"].dtype == jnp.int8
        # scales: [L, E, out] — kernel shape minus the contraction dim
        assert moe[f"{name}_scales"].shape == (
            params["layers"]["moe"][name].shape[0],
            params["layers"]["moe"][name].shape[1],
            params["layers"]["moe"][name].shape[3],
        )
    # Attention projections quantize as before.
    assert "kernel_q" in q["layers"]["q"]
    # Quantized logits stay close to float logits (w8a16 epilogue dequant).
    tokens = jnp.array([[5, 9, 11, 42, 7]], jnp.int32)
    lengths = jnp.array([5], jnp.int32)
    ref, _ = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 1))
    got, _ = forward_prefill(cfg, q, tokens, lengths, init_kv_cache(cfg, 1))
    rel = np.linalg.norm(np.asarray(got) - np.asarray(ref)) / np.linalg.norm(np.asarray(ref))
    assert rel < 0.05, rel
    r = generate(cfg, q, tokens, lengths,
                 SamplingParams(max_new_tokens=4, temperature=0.0))
    assert np.isfinite(np.asarray(r.confidence)).all()

    from edgemesh.ops.int4 import quantize_params_int4

    q4 = quantize_params_int4(params)
    assert "kernel" in q4["layers"]["moe"]["router"]
    assert "kernel_q4" not in q4["layers"]["moe"]["router"]
    # int4 keeps experts float (int8 is the MoE quant path).
    assert "up" in q4["layers"]["moe"]


def test_moe_int8_sharded_placement():
    """shard_params on a quantized MoE tree: expert int8 kernels keep the
    ep/tp expert sharding, scales drop the contraction axis, router stays
    replicated."""
    from edgemesh.ops.int8 import quantize_params
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.sharding import shard_params

    cfg = _cfg()
    params = quantize_params(init_params(cfg, jax.random.PRNGKey(0)))
    mesh = build_mesh(dp=1, tp=2, ep=2)
    sharded = shard_params(params, cfg, mesh)
    moe = sharded["layers"]["moe"]
    up_spec = moe["up_q"].sharding.spec
    assert up_spec[1] == "ep", up_spec  # expert axis sharded
    assert moe["up_scales"].sharding.spec[1] == "ep"


def test_mixtral_tiny_generates_dense_and_paged():
    """The mixtral family preset end-to-end: dense decode and the paged
    backend produce finite outputs from the same MoE config."""
    from edgemesh.config import SamplingParams
    from edgemesh.runtime.generate import generate
    from edgemesh.runtime.paged_generate import generate_paged

    cfg = tiny_config(
        "mixtral", num_heads=4, num_kv_heads=2, hidden_size=32,
        intermediate_size=64, num_layers=2, vocab_size=64, max_seq_len=64,
        num_experts=4, experts_per_token=2,
    ).replace(dtype="float32")
    assert cfg.gated and cfg.num_experts == 4
    params = init_params(cfg, jax.random.PRNGKey(1))
    tokens = jnp.array([[5, 9, 11, 42]], jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    sp = SamplingParams(max_new_tokens=5, temperature=0.0)
    r_dense = generate(cfg, params, tokens, lengths, sp)
    r_paged = generate_paged(cfg, params, tokens, lengths, sp, page_size=8)
    assert np.isfinite(np.asarray(r_dense.confidence)).all()
    np.testing.assert_array_equal(
        np.asarray(r_dense.tokens), np.asarray(r_paged.tokens)
    )
