"""Chunk-granular continuous batching (serve/continuous.py)."""

import time

import jax

import pytest

from edgemesh.agents.orchestrator import build_agent
from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
from edgemesh.serve.continuous import ContinuousEngine



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

def _agent(max_new=24):
    return build_agent(
        AgentSpec(
            role="qa",
            model=ModelSpec(),
            sampling=SamplingParams(
                max_new_tokens=max_new, do_sample=False, repetition_penalty=1.0
            ),
        )
    )


def test_single_request_matches_direct_answer():
    agent = _agent()
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        got = eng.answer("where is the eiffel tower?")
        direct = agent.answer("where is the eiffel tower?")
        assert got["answer"] == direct["answer"]
        assert got["role"] == "qa"
    finally:
        eng.close()


def test_concurrent_requests_complete_and_share_segments():
    agent = _agent()
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        qs = [f"question number {i}?" for i in range(4)]
        futs = [eng.submit(q) for q in qs]
        results = [f.result(timeout=600) for f in futs]
        directs = [agent.answer(q) for q in qs]
        for r, d in zip(results, directs):
            assert r["answer"] == d["answer"]
        st = eng.stats()
        assert st["requests"] == 4
        assert st["max_concurrent"] >= 2  # they actually shared the loop
    finally:
        eng.close()


def test_late_arrival_joins_mid_flight():
    """A request submitted while another decodes is admitted at a segment
    boundary, not after the first finishes — the point of the engine."""
    agent = _agent(max_new=48)  # long enough to span several 8-token segments
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        f1 = eng.submit("first question, a long answer please?")
        # Wait until the first request is actually decoding.
        deadline = time.time() + 300
        while eng.segments < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.segments >= 1
        f2 = eng.submit("second question arriving late?")
        r1, r2 = f1.result(timeout=600), f2.result(timeout=600)
        assert r1["answer"] is not None and r2["answer"] is not None
        assert eng.stats()["admitted_mid_flight"] >= 1
        # The late answer still matches its solo decode.
        assert r2["answer"] == agent.answer("second question arriving late?")["answer"]
    finally:
        eng.close()


def test_more_requests_than_slots_all_complete():
    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8)
    try:
        futs = [eng.submit(f"q {i}?") for i in range(5)]
        results = [f.result(timeout=600) for f in futs]
        assert len(results) == 5
        assert all(isinstance(r["answer"], str) for r in results)
    finally:
        eng.close()


def test_closed_engine_rejects():
    agent = _agent(max_new=4)
    eng = ContinuousEngine(agent, slots=2, chunk=4)
    eng.close()
    with pytest.raises(RuntimeError):
        eng.submit("too late")


def _wait_drained(eng, timeout=30.0):
    """reserved_pages drains moments AFTER the last future resolves (set_result
    precedes the reclaim inside _retire) — poll instead of racing the worker."""
    deadline = time.time() + timeout
    while eng.stats()["reserved_pages"] != 0 and time.time() < deadline:
        time.sleep(0.02)
    return eng.stats()["reserved_pages"]


def test_paged_single_request_matches_direct_answer():
    """Paged pool (bf16 pages): same greedy tokens as the solo decode path —
    zero-copy admission and the page-table kernel change nothing numeric."""
    agent = _agent()
    eng = ContinuousEngine(agent, slots=4, chunk=8, kv_backend="paged", page_size=8)
    try:
        got = eng.answer("where is the eiffel tower?")
        direct = agent.answer("where is the eiffel tower?")
        assert got["answer"] == direct["answer"]
        assert eng.stats()["kv_backend"] == "paged"
    finally:
        eng.close()


@pytest.mark.parametrize("backend", ["paged", "paged_int8"])
def test_paged_engine_overcommit_reclaims_pages(backend):
    """More requests than slots: retirements push pages back onto the free
    stack, queued requests admit at later boundaries, reservations drain to
    zero when the stream ends."""
    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend=backend, page_size=8)
    try:
        futs = [eng.submit(f"q {i}?") for i in range(5)]
        results = [f.result(timeout=600) for f in futs]
        assert len(results) == 5
        assert all(isinstance(r["answer"], str) for r in results)
        assert _wait_drained(eng) == 0
        assert eng.stats()["requests"] == 5
    finally:
        eng.close()


def test_paged_capacity_queues_requests_instead_of_crashing():
    """A pool sized below the all-slots worst case serializes admissions via
    the reservation check — every request still completes."""
    agent = _agent(max_new=12)
    eng = ContinuousEngine(
        agent, slots=2, chunk=8, kv_backend="paged", page_size=8, total_pages=16
    )
    try:
        futs = [eng.submit(f"question {i}?") for i in range(3)]
        results = [f.result(timeout=600) for f in futs]
        assert all(isinstance(r["answer"], str) for r in results)
        assert _wait_drained(eng) == 0
    finally:
        eng.close()


def test_paged_request_too_big_for_pool_fails_cleanly():
    agent = _agent(max_new=64)
    eng = ContinuousEngine(
        agent, slots=2, chunk=8, kv_backend="paged", page_size=8, total_pages=4
    )
    try:
        with pytest.raises(ValueError, match="pool holds"):
            eng.answer("this request cannot ever fit?")
    finally:
        eng.close()


def test_serving_benchmark_reports_throughput():
    """The bench's serving stage end-to-end on the tiny preset: aggregate
    tok/s, req/s, and latency percentiles from real engine futures."""
    from edgemesh.benchmarks import serving_benchmark

    r = serving_benchmark("tiny", "bf16", slots=2, chunk=8, n_requests=3,
                          max_new=8)
    assert r["value"] > 0 and r["generated"] >= 3 * 1
    assert r["latency_s_p95"] >= r["latency_s_p50"] > 0
    assert r["stats"]["kv_backend"] == "paged"


def test_paged_prefix_sharing_maps_template_pages():
    """Admitted rows' tables map the SAME physical pages for the template
    prefix (stored once in the pool), answers still match the solo path,
    and the shared pages survive retire/rebuild cycles."""
    import numpy as np

    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged", page_size=8)
    try:
        got = eng.answer("where is the eiffel tower?")
        assert got["answer"] == agent.answer("where is the eiffel tower?")["answer"]
        st = eng.stats()
        assert st["template_pages"] >= 1
        assert st["shared_prefix_hits"] >= 1
        tpl = list(eng._template_pages)

        # Two concurrent admissions share the template's physical pages.
        futs = [eng.submit(f"question {i}?") for i in range(2)]
        # Sample the tables while rows are in flight.
        import time as _t
        deadline = _t.time() + 120
        shared_seen = False
        nfull = (int(eng._template_ids.size) // 8)
        while _t.time() < deadline and not shared_seen:
            try:
                # The worker donates the cache into _decode_loop; a poll can
                # land on a deleted buffer — retry, don't fail the test.
                table = np.asarray(eng._cache.page_table)
            except RuntimeError:
                _t.sleep(0.005)
                continue
            rows = [r for r in table if (r[:nfull] > 0).all()]
            if len(rows) >= 2:
                shared_seen = all(
                    list(r[:nfull]) == tpl[:nfull] for r in rows[:2]
                )
            _t.sleep(0.005)
        [f.result(timeout=300) for f in futs]
        assert shared_seen, "no two in-flight rows observed sharing the template pages"

        # Many retire cycles: rebuild never frees template pages.
        for i in range(3):
            eng.answer(f"another question {i}?")
        assert list(eng._template_pages) == tpl
        assert _wait_drained(eng) == 0
    finally:
        eng.close()


def test_ragged_engine_matches_segmented_and_direct():
    """The ragged boundary launch (admission prefill + resident decode in
    ONE forward_ragged_paged program) is the paged engine's default and
    must be token-identical to both the segmented engine and the solo
    decode path — the wave structure changed, the math did not."""
    agent = _agent(max_new=12)
    qs = [
        "where is the eiffel tower?",
        "hm?",
        "name a large african animal",
        "what color is the sky above?",
        "another question to overcommit the slots?",
    ]
    direct = [agent.answer(q)["answer"] for q in qs]
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        assert eng._ragged  # paged default
        got = [f.result(timeout=600) for f in [eng.submit(q) for q in qs]]
        for g, d in zip(got, direct):
            assert g["answer"] == d, (g["answer"], d)
        st = eng.stats()
        assert st["ragged"] is True
        assert st["ragged_boundaries"] > 0
        assert st["ragged_prefill_tokens"] > 0
        assert _wait_drained(eng) == 0
    finally:
        eng.close()
    seg = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, ragged=False)
    try:
        assert not seg._ragged
        got = [f.result(timeout=600) for f in [seg.submit(q) for q in qs]]
        for g, d in zip(got, direct):
            assert g["answer"] == d, (g["answer"], d)
        assert seg.stats()["ragged"] is False
        assert "ragged_boundaries" not in seg.stats()
    finally:
        seg.close()


def test_ragged_obs_split_keeps_prefill_and_decode_separate(tmp_path):
    """The shared-launch observability contract: even with admission
    prefill and decode riding one kernel, the span tree still carries a
    distinct prefill span (tagged with the launch's prefill-token count)
    and decode spans, and the engine's phase counters split the boundary
    tokens — `edgemesh obs trace`'s critical path stays honest."""
    from edgemesh.obs import Registry
    from edgemesh.utils.tracing import JsonlLogger

    log = tmp_path / "spans.jsonl"
    reg = Registry()
    agent = _agent(max_new=10)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8, span_log=log, registry=reg)
    try:
        futs = [eng.submit(f"question number {i}?") for i in range(3)]
        [f.result(timeout=600) for f in futs]
        st = eng.stats()
        assert st["ragged_prefill_tokens"] > 0
        assert st["ragged_decode_tokens"] > 0
    finally:
        eng.close()
    # Registry: the per-phase token split through the shared launch.
    snap = reg.snapshot()
    phases = {
        s["labels"]["phase"]: s["value"]
        for s in snap["edgemesh_ragged_tokens_total"]["samples"]
    }
    assert phases["prefill"] > 0 and phases["decode"] > 0
    # Span records: per-request prefill span survives the shared launch,
    # tagged with its slice of the ragged boundary.
    recs = [r for r in JsonlLogger(log).read() if r.get("event") == "request_spans"]
    assert len(recs) == 3
    for rec in recs:
        names = [s["name"] for s in rec["spans"]]
        assert "prefill" in names and "decode" in names
        assert rec["ragged"] is True
        assert rec["prefill_tokens"] > 0
        assert rec["prefill_s"] is not None and rec["prefill_s"] >= 0


def test_engine_over_tp_sharded_params_matches_single_device():
    """The continuous engine over TP-sharded params: the jitted segment and
    admission programs ride GSPMD transparently (params carry
    NamedShardings; XLA inserts the collectives), and greedy tokens match
    the unsharded engine exactly."""
    from edgemesh.parallel.mesh import build_mesh

    spec = AgentSpec(
        role="qa",
        model=ModelSpec(
            family="llama", vocab_size=260, num_layers=2, hidden_size=64,
            num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=128,
        ),
        sampling=SamplingParams(max_new_tokens=8, do_sample=False,
                                repetition_penalty=1.0),
    )
    plain = build_agent(spec)
    mesh = build_mesh(dp=1, tp=2)
    sharded = build_agent(spec, mesh=mesh)
    assert any(
        getattr(leaf, "sharding", None) is not None
        and getattr(leaf.sharding, "spec", None) is not None
        for leaf in jax.tree.leaves(sharded.params)
    )
    q = "what color is the sky on a clear day?"
    eng_a = ContinuousEngine(plain, slots=2, chunk=4, kv_backend="dense")
    eng_b = ContinuousEngine(sharded, slots=2, chunk=4, kv_backend="dense")
    try:
        a = eng_a.answer(q)
        b = eng_b.answer(q)
        assert a["answer"] == b["answer"]
        assert a["generated"] == b["generated"] > 0
    finally:
        eng_a.close()
        eng_b.close()


def _spec_agent(max_new=8, gamma=2):
    return build_agent(AgentSpec(
        role="qa",
        model=ModelSpec(family="llama", vocab_size=260, num_layers=2,
                        hidden_size=64, num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_seq_len=128),
        draft=ModelSpec(family="llama", vocab_size=260, num_layers=1,
                        hidden_size=64, num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_seq_len=128),
        spec_gamma=gamma,
        sampling=SamplingParams(max_new_tokens=max_new, do_sample=False,
                                repetition_penalty=1.0),
    ))


def test_speculative_engine_greedy_matches_plain_engine():
    """Speculative continuous batching emits the target's distribution
    exactly: under greedy decoding the spec engine's answers are
    token-identical to the plain paged engine's, including concurrent
    requests joining mid-flight."""
    from edgemesh.serve.continuous import (
        ContinuousEngine,
        SpeculativeContinuousEngine,
    )

    agent = _spec_agent()
    plain = ContinuousEngine(agent, slots=4, chunk=4, kv_backend="paged",
                             page_size=16)
    spec = SpeculativeContinuousEngine(agent, slots=4, chunk=6,
                                       kv_backend="paged", page_size=16)
    qs = [f"question number {i}: where is the eiffel tower?" for i in range(6)]
    try:
        ref = [f.result() for f in [plain.submit(q) for q in qs]]
        got = [f.result() for f in [spec.submit(q) for q in qs]]
        for r, g in zip(ref, got):
            assert g["answer"] == r["answer"], (g["answer"], r["answer"])
            assert g["generated"] == r["generated"]
        st = spec.stats()
        assert st["spec_rounds"] > 0 and st["spec_proposed"] > 0
        assert st["gamma"] == 2 and st["kv_backend"] == "paged"
    finally:
        plain.close()
        spec.close()


def test_speculative_engine_guards_and_factory():
    from edgemesh.serve.continuous import (
        ContinuousEngine,
        SpeculativeContinuousEngine,
        make_engine,
    )

    agent = _spec_agent()
    with pytest.raises(ValueError, match="kv_backend='paged'"):
        SpeculativeContinuousEngine(agent, kv_backend="dense")
    plain_agent = build_agent(AgentSpec(
        role="qa",
        model=ModelSpec(family="llama", vocab_size=260, num_layers=2,
                        hidden_size=64, num_heads=4, num_kv_heads=2,
                        intermediate_size=128, max_seq_len=128),
        sampling=SamplingParams(max_new_tokens=8, do_sample=False,
                                repetition_penalty=1.0),
    ))
    with pytest.raises(ValueError, match="draft"):
        SpeculativeContinuousEngine(plain_agent)
    eng = make_engine(agent, kv_backend="paged", slots=2, chunk=4, page_size=16)
    try:
        assert isinstance(eng, SpeculativeContinuousEngine)
    finally:
        eng.close()
    eng2 = make_engine(plain_agent, kv_backend="paged", slots=2, chunk=4,
                       page_size=16)
    try:
        assert type(eng2) is ContinuousEngine
    finally:
        eng2.close()


def test_batched_admission_mixed_widths_matches_sequential():
    """One admission wave with prompts in different length buckets: the
    batched path groups by width (one padded prefill per group) and must
    produce the same answers as the dense engine's sequential admissions."""
    agent = _agent(max_new=6)
    qs = [
        "hi?",
        "a much longer question padded out well beyond the small bucket "
        "so it lands in a different prompt-width group entirely?",
        "mid-size question that is moderately long?",
        "hm?",
        "another long one that should share the second width bucket with "
        "the earlier long question in this very admission wave, yes?",
    ]
    ref_eng = ContinuousEngine(agent, slots=4, chunk=8, kv_backend="dense")
    try:
        ref = [f.result(timeout=600) for f in [ref_eng.submit(q) for q in qs]]
    finally:
        ref_eng.close()
    eng = ContinuousEngine(agent, slots=4, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        got = [f.result(timeout=600) for f in [eng.submit(q) for q in qs]]
        for r, g in zip(ref, got):
            assert g["answer"] == r["answer"], (g["answer"], r["answer"])
    finally:
        eng.close()


def test_host_owned_paging_never_pops_device_pages():
    """The round-4 allocator contract: admission pre-maps every page a row
    can touch and parked rows are frozen at length 1, so the in-program
    allocator must never pop — free_top stays at 1 (the tripwire the worker
    checks each segment) and the host free list returns to full size."""
    import time as _t

    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        futs = [eng.submit(f"q {i}?") for i in range(5)]
        [f.result(timeout=600) for f in futs]
        assert _wait_drained(eng) == 0
        deadline = _t.time() + 60
        free_top = None
        while _t.time() < deadline:
            try:
                free_top = int(eng._cache.free_top)
                break
            except RuntimeError:  # donated mid-poll; engine still settling
                _t.sleep(0.02)
        assert free_top == 1, f"device allocator popped pages (free_top={free_top})"
        assert len(eng._free_pages) == (
            eng.total_pages - 1 - len(eng._template_pages)
        )
    finally:
        eng.close()


def test_dense_int8_engine_matches_paged_int8_engine():
    """Continuous batching over the int8 dense slab (kv_backend="dense_int8"):
    quantize_kv's per-token scales are the same math in the slab and the
    page pool, so greedy answers are token-identical across the two int8
    backends — the SERVING.md matrix cell this pins."""
    agent = _agent(max_new=12)
    qs = [
        "where is the eiffel tower?",
        "name a large african animal",
        "how many legs has a spider",
        "what color is the sky above?",
    ]
    ref_eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged_int8",
                               page_size=8)
    try:
        ref = [f.result(timeout=600) for f in [ref_eng.submit(q) for q in qs]]
    finally:
        ref_eng.close()
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="dense_int8")
    try:
        got = [f.result(timeout=600) for f in [eng.submit(q) for q in qs]]
        for r, g in zip(ref, got):
            assert g["answer"] == r["answer"], (g["answer"], r["answer"])
        assert eng.stats()["kv_backend"] == "dense_int8"
        assert "total_pages" not in eng.stats()  # slab backend: no pool keys
    finally:
        eng.close()


def test_speculative_engine_paged_int8_matches_plain_engine():
    """Speculative continuous batching over the int8 page pools: greedy
    answers are token-identical to the plain paged_int8 engine (the target's
    int8 KV trajectory is draft-independent), and the factory routes a
    draft-carrying agent on paged_int8 to the spec engine."""
    from edgemesh.serve.continuous import (
        SpeculativeContinuousEngine,
        make_engine,
    )

    agent = _spec_agent()
    qs = [f"question number {i}: where is the eiffel tower?" for i in range(4)]
    plain = ContinuousEngine(agent, slots=4, chunk=4, kv_backend="paged_int8",
                             page_size=16)
    try:
        ref = [f.result(timeout=600) for f in [plain.submit(q) for q in qs]]
    finally:
        plain.close()
    spec = make_engine(agent, slots=4, chunk=6, kv_backend="paged_int8",
                       page_size=16)
    try:
        assert isinstance(spec, SpeculativeContinuousEngine)
        got = [f.result(timeout=600) for f in [spec.submit(q) for q in qs]]
        for r, g in zip(ref, got):
            assert g["answer"] == r["answer"], (g["answer"], r["answer"])
            assert g["generated"] == r["generated"]
        st = spec.stats()
        assert st["spec_rounds"] > 0 and st["spec_proposed"] > 0
        assert st["kv_backend"] == "paged_int8"
    finally:
        spec.close()


def test_per_request_budget_caps_generation():
    """submit(max_new=) caps one request below the engine budget; others
    keep the full budget (slot.remaining is host state, so this is free)."""
    agent = _agent(max_new=24)
    eng = ContinuousEngine(agent, slots=2, chunk=8, kv_backend="paged",
                           page_size=8)
    try:
        short = eng.submit("short one?", max_new=3)
        full = eng.submit("full one?")
        assert short.result(timeout=600)["generated"] <= 3
        assert full.result(timeout=600)["generated"] > 3
    finally:
        eng.close()
    import pytest as _pytest

    with _pytest.raises(ValueError, match="max_new"):
        _e = ContinuousEngine(agent, slots=1, chunk=4)
        try:
            _e.submit("q", max_new=0)
        finally:
            _e.close()


def test_sjf_admission_reorders_queue_fifo_does_not():
    """With one busy slot, SJF admits the cheapest waiting job first even
    when it arrived last; FIFO keeps arrival order. Start timestamps
    (t_start) expose the admission order directly."""
    agent = _agent(max_new=24)
    eng = ContinuousEngine(agent, slots=1, chunk=4, kv_backend="paged",
                           page_size=8, admission="sjf")
    try:
        hold = eng.submit("occupy the slot please?", max_new=24)
        deadline = time.time() + 300
        while eng.segments < 1 and time.time() < deadline:
            time.sleep(0.01)
        long2 = eng.submit("second long job?", max_new=24)
        short = eng.submit("short job?", max_new=2)
        hold.result(timeout=600)
        rs, rl = short.result(timeout=600), long2.result(timeout=600)
        assert rs["t_start"] < rl["t_start"], "SJF did not reorder"
        assert rs["generated"] <= 2
    finally:
        eng.close()

    eng2 = ContinuousEngine(agent, slots=1, chunk=4, kv_backend="paged",
                            page_size=8)  # default fifo
    try:
        hold = eng2.submit("occupy the slot please?", max_new=24)
        deadline = time.time() + 300
        while eng2.segments < 1 and time.time() < deadline:
            time.sleep(0.01)
        long2 = eng2.submit("second long job?", max_new=24)
        short = eng2.submit("short job?", max_new=2)
        hold.result(timeout=600)
        rl, rs = long2.result(timeout=600), short.result(timeout=600)
        assert rl["t_start"] < rs["t_start"], "FIFO order broken"
    finally:
        eng2.close()


def test_spec_engine_rejects_per_request_budget():
    from edgemesh.serve.continuous import SpeculativeContinuousEngine

    agent = _spec_agent()
    eng = SpeculativeContinuousEngine(agent, slots=2, chunk=6,
                                      kv_backend="paged", page_size=16)
    try:
        # Fails fast on the caller's thread, not asynchronously in _admit.
        with pytest.raises(ValueError, match="uniform budget"):
            eng.submit("any question?", max_new=4)
    finally:
        eng.close()
