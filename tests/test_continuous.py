"""Chunk-granular continuous batching (serve/continuous.py)."""

import time

import pytest

from edgemesh.agents.orchestrator import build_agent
from edgemesh.config import AgentSpec, ModelSpec, SamplingParams
from edgemesh.serve.continuous import ContinuousEngine


def _agent(max_new=24):
    return build_agent(
        AgentSpec(
            role="qa",
            model=ModelSpec(),
            sampling=SamplingParams(
                max_new_tokens=max_new, do_sample=False, repetition_penalty=1.0
            ),
        )
    )


def test_single_request_matches_direct_answer():
    agent = _agent()
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        got = eng.answer("where is the eiffel tower?")
        direct = agent.answer("where is the eiffel tower?")
        assert got["answer"] == direct["answer"]
        assert got["role"] == "qa"
    finally:
        eng.close()


def test_concurrent_requests_complete_and_share_segments():
    agent = _agent()
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        qs = [f"question number {i}?" for i in range(4)]
        futs = [eng.submit(q) for q in qs]
        results = [f.result(timeout=600) for f in futs]
        directs = [agent.answer(q) for q in qs]
        for r, d in zip(results, directs):
            assert r["answer"] == d["answer"]
        st = eng.stats()
        assert st["requests"] == 4
        assert st["max_concurrent"] >= 2  # they actually shared the loop
    finally:
        eng.close()


def test_late_arrival_joins_mid_flight():
    """A request submitted while another decodes is admitted at a segment
    boundary, not after the first finishes — the point of the engine."""
    agent = _agent(max_new=48)  # long enough to span several 8-token segments
    eng = ContinuousEngine(agent, slots=4, chunk=8)
    try:
        f1 = eng.submit("first question, a long answer please?")
        # Wait until the first request is actually decoding.
        deadline = time.time() + 300
        while eng.segments < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.segments >= 1
        f2 = eng.submit("second question arriving late?")
        r1, r2 = f1.result(timeout=600), f2.result(timeout=600)
        assert r1["answer"] is not None and r2["answer"] is not None
        assert eng.stats()["admitted_mid_flight"] >= 1
        # The late answer still matches its solo decode.
        assert r2["answer"] == agent.answer("second question arriving late?")["answer"]
    finally:
        eng.close()


def test_more_requests_than_slots_all_complete():
    agent = _agent(max_new=12)
    eng = ContinuousEngine(agent, slots=2, chunk=8)
    try:
        futs = [eng.submit(f"q {i}?") for i in range(5)]
        results = [f.result(timeout=600) for f in futs]
        assert len(results) == 5
        assert all(isinstance(r["answer"], str) for r in results)
    finally:
        eng.close()


def test_closed_engine_rejects():
    agent = _agent(max_new=4)
    eng = ContinuousEngine(agent, slots=2, chunk=4)
    eng.close()
    with pytest.raises(RuntimeError):
        eng.submit("too late")
