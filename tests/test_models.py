"""Model forward correctness: shapes, per-family dialects, and the load-bearing
invariant that incremental decode through the KV cache reproduces full-prompt
prefill logits (this is what the reference never tests — SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.config import SamplingParams
from edgemesh.models import init_kv_cache, init_params
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_decode, forward_prefill
from edgemesh.runtime import generate

FAMILIES = ["llama", "neox", "phi2", "mistral", "qwen2", "gemma", "phi3", "gemma2", "gpt2", "falcon"]



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

@pytest.mark.parametrize("family", FAMILIES)
def test_prefill_shapes(family):
    cfg = tiny_config(family)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch, seq = 2, 10
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    lengths = jnp.array([10, 7])
    cache = init_kv_cache(cfg, batch, 32)
    logits, cache = forward_prefill(cfg, params, tokens, lengths, cache)
    assert logits.shape == (batch, cfg.vocab_size)
    assert jnp.all(jnp.isfinite(logits))
    assert cache.lengths.tolist() == [10, 7]


@pytest.mark.parametrize("family", FAMILIES)
def test_incremental_decode_matches_prefill(family):
    """Prefill logits at position t must equal decode-step logits after feeding
    tokens 0..t-1 one at a time through the cache."""
    cfg = tiny_config(family)
    params = init_params(cfg, jax.random.PRNGKey(0))
    seq = 9
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, seq), 0, cfg.vocab_size)

    # Ground truth: full prefill over the first t tokens, for each t.
    full_cache = init_kv_cache(cfg, 1, 32)
    ref_logits, _ = forward_prefill(
        cfg, params, tokens, jnp.array([seq]), full_cache
    )

    # Incremental: prefill 1 token, then decode the rest.
    cache = init_kv_cache(cfg, 1, 32)
    logits, cache = forward_prefill(cfg, params, tokens[:, :1], jnp.array([1]), cache)
    for t in range(1, seq):
        logits, cache = forward_decode(cfg, params, tokens[:, t], cache)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )


def test_right_padding_invariance():
    """Rows padded to different amounts must produce identical last-token logits."""
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(3), (1, 6), 0, cfg.vocab_size)
    short = forward_prefill(
        cfg, params, toks, jnp.array([6]), init_kv_cache(cfg, 1, 32)
    )[0]
    padded = jnp.pad(toks, ((0, 0), (0, 4)))  # pad with zeros to length 10
    long = forward_prefill(
        cfg, params, padded, jnp.array([6]), init_kv_cache(cfg, 1, 32)
    )[0]
    np.testing.assert_allclose(np.asarray(short), np.asarray(long), rtol=1e-5, atol=1e-5)


def test_generate_greedy_deterministic_and_eos():
    cfg = tiny_config("llama")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 5), 0, cfg.vocab_size)
    lengths = jnp.array([5, 3])
    sampling = SamplingParams(max_new_tokens=8, do_sample=False, repetition_penalty=1.0)
    r1 = generate(cfg, params, tokens, lengths, sampling)
    r2 = generate(cfg, params, tokens, lengths, sampling)
    assert r1.tokens.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(r1.tokens), np.asarray(r2.tokens))
    assert int(jnp.sum(r1.num_generated)) == 16
    assert r1.tokens_per_sec > 0

    # With eos_id = the model's first greedy token, generation stops after 1.
    first = int(r1.tokens[0, 0])
    r3 = generate(cfg, params, tokens, lengths, sampling, eos_id=first)
    assert int(r3.num_generated[0]) == 1


def test_generate_sampled_reproducible_with_seed():
    cfg = tiny_config("neox")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 4), 0, cfg.vocab_size)
    lengths = jnp.array([4])
    sampling = SamplingParams(max_new_tokens=6, do_sample=True, seed=42)
    r1 = generate(cfg, params, tokens, lengths, sampling)
    r2 = generate(cfg, params, tokens, lengths, sampling)
    np.testing.assert_array_equal(np.asarray(r1.tokens), np.asarray(r2.tokens))


def test_sliding_window_attend_masks_old_positions():
    """attend with sliding_window w: slot j visible to query p iff
    p-w < j <= p. Pinned against an explicit mask computation."""
    import numpy as np

    from edgemesh.ops.attention import LayerKV, attend

    b, s, h, d, w = 1, 10, 2, 16, 4
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d), jnp.float32)
    positions = jnp.arange(s)[None, :]
    kv_valid = jnp.ones((b, s), bool)
    out = attend(q, LayerKV(k, v), positions, kv_valid, sliding_window=w)

    # Reference: full-window attend over the explicitly windowed slice.
    for p in (5, 9):
        lo = max(0, p - w + 1)
        ref = attend(
            q[:, p:p+1],
            LayerKV(k[:, lo:p+1], v[:, lo:p+1]),
            jnp.asarray([[p - lo]]),
            jnp.ones((b, p + 1 - lo), bool),
        )
        np.testing.assert_allclose(
            np.asarray(out[:, p]), np.asarray(ref[:, 0]), rtol=1e-5, atol=1e-5
        )


def test_mistral_family_generates():
    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime import generate

    cfg = tiny_config("mistral", vocab_size=64, sliding_window=6, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64, jnp.int32)
    r = generate(
        cfg, params, tokens, jnp.full((2,), 8, jnp.int32),
        SamplingParams(max_new_tokens=12, do_sample=False, repetition_penalty=1.0),
    )
    assert int(r.num_generated.sum()) == 24


def test_qwen3_qk_norm_paged_matches_dense():
    """QK-norm rides the shared qkv_proj, so the paged backend must be
    token-identical to dense for a qwen3-family config."""
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.runtime.generate import generate
    from edgemesh.runtime.paged_generate import generate_paged

    cfg = tiny_config("qwen3").replace(dtype="float32")
    assert cfg.qk_norm
    params = init_params(cfg, jax.random.PRNGKey(3))
    assert "q_norm" in params["layers"] and "k_norm" in params["layers"]
    tokens = jnp.array([[5, 9, 11, 42]], jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    sp = SamplingParams(max_new_tokens=5, do_sample=False, repetition_penalty=1.0)
    r_dense = generate(cfg, params, tokens, lengths, sp)
    r_paged = generate_paged(cfg, params, tokens, lengths, sp, page_size=8)
    np.testing.assert_array_equal(
        np.asarray(r_dense.tokens), np.asarray(r_paged.tokens)
    )
