"""Pipeline parallelism: stage-split forward must match the single-chip model
bit-for-bit (up to fp tolerance) — prefill, decode, and training logits."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models import init_kv_cache, init_params
from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import forward_decode, forward_prefill
from edgemesh.parallel.mesh import build_mesh
from edgemesh.parallel.pipeline import PipelineEngine
from edgemesh.training import forward_train



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config("llama", num_layers=4)  # 4 layers over pp=4 → 1 each
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(dp=1, pp=4, tp=2)
    engine = PipelineEngine(cfg, params, mesh, num_micro=2)
    return cfg, params, engine


def test_pipelined_prefill_matches_single(setup):
    cfg, params, engine = setup
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, cfg.vocab_size)
    lengths = jnp.array([8, 6, 8, 5])

    ref, ref_cache = forward_prefill(cfg, params, tokens, lengths, init_kv_cache(cfg, 4, 16))
    cache = engine.init_cache(4, 16)
    got, got_cache = engine.prefill(tokens, lengths, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
    # caches agree too (the layer split must not change what is stored)
    np.testing.assert_allclose(
        np.asarray(got_cache.k), np.asarray(ref_cache.k), rtol=2e-4, atol=2e-4
    )


def test_pipelined_decode_matches_single(setup):
    cfg, params, engine = setup
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
    lengths = jnp.array([6, 6])

    ref_cache = init_kv_cache(cfg, 2, 16)
    ref_logits, ref_cache = forward_prefill(cfg, params, tokens, lengths, ref_cache)
    cache = engine.init_cache(2, 16)
    logits, cache = engine.prefill(tokens, lengths, cache)

    nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)
    for _ in range(3):
        ref_logits, ref_cache = forward_decode(cfg, params, nxt, ref_cache)
        logits, cache = engine.decode(nxt, cache)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
        )
        nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)


def test_pipelined_generate_greedy(setup):
    cfg, params, engine = setup
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    lengths = jnp.array([5, 4])
    out = engine.generate_greedy(tokens, lengths, max_new=4)
    assert out.shape == (2, 4)
    # must equal the single-chip greedy decode
    from edgemesh.config import SamplingParams
    from edgemesh.runtime import generate

    ref = generate(cfg, params, tokens, lengths,
                   SamplingParams(max_new_tokens=4, do_sample=False, repetition_penalty=1.0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref.tokens))


def test_pipelined_train_forward_matches(setup):
    cfg, params, engine = setup
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 10), 0, cfg.vocab_size)
    lengths = jnp.array([10, 7])
    ref = forward_train(cfg, params, tokens, lengths)
    got = engine.forward_train(tokens, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_uneven_layer_split_rejected():
    cfg = tiny_config("llama", num_layers=3)
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(pp=4, tp=2)
    with pytest.raises(ValueError, match="divisible"):
        PipelineEngine(cfg, params, mesh)


def test_pipeline_int8_quantized(devices):
    """Quantized trees (int8 + int8 embedding) ride the pp layer split: the
    stacked kernel_q/scales leaves shard over pp like their bf16 kernels and
    greedy output matches the single-device quantized model."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.ops.int8 import quantize_embedding, quantize_params
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.pipeline import PipelineEngine
    from edgemesh.runtime import generate

    cfg = tiny_config("llama", num_layers=4, vocab_size=128, dtype="float32",
                      tie_embeddings=True)
    params = quantize_embedding(quantize_params(init_params(cfg, jax.random.PRNGKey(0))))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, cfg.vocab_size)
    lengths = jnp.array([5, 5])

    ref = generate(cfg, params, tokens, lengths,
                   SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0))
    mesh = build_mesh(pp=2)
    eng = PipelineEngine(cfg, params, mesh, num_micro=2, attention_impl="xla")
    got = eng.generate_greedy(tokens, lengths, max_new=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref.tokens))


def test_pipeline_gemma2_alternating_windows(devices):
    """Gemma-2 through the pipeline engine: each stage's pair scan keeps the
    global even-windowed/odd-full alternation, so greedy output matches the
    single-device path (fp32 — no quantization noise here)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from edgemesh.config import SamplingParams
    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.pipeline import PipelineEngine
    from edgemesh.runtime import generate

    cfg = tiny_config("gemma2", num_layers=4, vocab_size=128, max_seq_len=64,
                      dtype="float32").replace(sliding_window=6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 20), 0, 128, jnp.int32)
    lengths = jnp.asarray([20, 14], jnp.int32)

    ref = generate(cfg, params, tokens, lengths,
                   SamplingParams(max_new_tokens=6, do_sample=False, repetition_penalty=1.0))
    eng = PipelineEngine(cfg, params, build_mesh(pp=2), num_micro=2, attention_impl="xla")
    got = eng.generate_greedy(tokens, lengths, max_new=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref.tokens))


def test_pipeline_rejects_odd_layers_per_stage_for_alt_windows(devices):
    import jax
    import pytest

    from edgemesh.models.families import tiny_config
    from edgemesh.models.transformer import init_params
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.pipeline import PipelineEngine

    cfg = tiny_config("gemma2", num_layers=2, vocab_size=128,
                      dtype="float32").replace(sliding_window=6)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="even number of layers per stage"):
        PipelineEngine(cfg, params, build_mesh(pp=2), num_micro=2)
