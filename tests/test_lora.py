"""LoRA adapter finetuning (ops/lora.py): structure, forward parity,
training updates, merge semantics, and the config-driven train → restore →
merge round trip.

The reference never started finetuning (SURVEY.md §7: the xlsx roadmap's
"After Finetuning" rows are empty); LoRA is the edge-appropriate form —
Jetson-class memory cannot hold optimizer state for full weights, but
rank-8 adapters are kilobytes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edgemesh.models.families import tiny_config
from edgemesh.models.transformer import dense, init_params
from edgemesh.ops.lora import (
    attach_lora,
    init_lora_params,
    make_lora_optimizer,
    merge_lora,
    parse_targets,
)
from edgemesh.training import (
    causal_lm_loss,
    init_train_state,
    make_lora_train_step,
)



# Fast/slow tiers (pyproject markers): this whole file is multi-minute
# territory - deselect with `pytest -m "not slow"`.
pytestmark = pytest.mark.slow

@pytest.fixture(scope="module")
def base():
    cfg = tiny_config("llama", num_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _batch(cfg, b=2, s=8):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0, cfg.vocab_size)
    lengths = jnp.full((b,), s, jnp.int32)
    return tokens, lengths


def test_init_structure_and_sizes(base):
    cfg, params = base
    lora = init_lora_params(params, rank=4, alpha=8.0, targets="q,v")
    assert sorted(lora["layers"]) == ["q", "v"]
    L = params["layers"]["q"]["kernel"].shape[0]
    d_in, d_out = params["layers"]["q"]["kernel"].shape[-2:]
    assert lora["layers"]["q"]["lora_a"].shape == (L, d_in, 4)
    assert lora["layers"]["q"]["lora_b"].shape == (L, 4, d_out)
    assert lora["layers"]["q"]["lora_scale"].shape == (L,)
    np.testing.assert_allclose(np.asarray(lora["layers"]["q"]["lora_scale"]), 2.0)
    # B starts at zero -> adapted model == base model at init.
    assert not np.any(np.asarray(lora["layers"]["v"]["lora_b"]))


def test_unknown_target_rejected(base):
    cfg, params = base
    with pytest.raises(ValueError, match="not a dense layer leaf"):
        init_lora_params(params, rank=4, alpha=8.0, targets="q,bogus")
    assert parse_targets(" q , v ") == ("q", "v")


def test_attach_forward_matches_base_at_init(base):
    """lora_b = 0 => attach_lora changes nothing in the forward."""
    cfg, params = base
    lora = init_lora_params(params, rank=4, alpha=8.0)
    tokens, lengths = _batch(cfg)
    ref = causal_lm_loss(cfg, params, tokens, lengths)
    got = causal_lm_loss(cfg, attach_lora(params, lora), tokens, lengths)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)


def test_merge_matches_activation_side_application(base):
    """W + s·A@B applied to x must equal y_base + (x@A)@B·s (the dense()
    runtime form) — per sliced layer, with nonzero B."""
    cfg, params = base
    lora = init_lora_params(params, rank=4, alpha=8.0, key=jax.random.PRNGKey(3))
    # Give B real values so the test is not 0 == 0.
    lora["layers"]["q"]["lora_b"] = (
        jax.random.normal(jax.random.PRNGKey(4), lora["layers"]["q"]["lora_b"].shape) * 0.1
    ).astype(lora["layers"]["q"]["lora_b"].dtype)
    merged = merge_lora(params, lora)
    attached = attach_lora(params, lora)
    x = jax.random.normal(jax.random.PRNGKey(5), (3, cfg.hidden_size), jnp.float32)
    slice0 = lambda tree: jax.tree.map(lambda a: a[0], tree)
    y_merged = dense(slice0(merged["layers"]["q"]), x)
    y_applied = dense(slice0(attached["layers"]["q"]), x)
    np.testing.assert_allclose(
        np.asarray(y_applied), np.asarray(y_merged), rtol=2e-4, atol=2e-4
    )
    # merge_lora must not leave adapter leaves behind.
    assert "lora_a" not in merged["layers"]["q"]
    # non-target leaves are untouched (same objects).
    assert merged["layers"]["up"] is params["layers"]["up"]


def test_train_step_updates_adapters_only_and_learns(base):
    cfg, params = base
    lora = init_lora_params(params, rank=4, alpha=8.0)
    opt = make_lora_optimizer(lr=3e-2)
    state = init_train_state(cfg, lora, opt)
    step = make_lora_train_step(cfg, opt)
    tokens, lengths = _batch(cfg)
    losses = []
    for _ in range(5):
        state, loss = step(state, params, tokens, lengths)
        losses.append(float(loss))
    # Memorizing one tiny batch: loss must drop.
    assert losses[-1] < losses[0] - 0.05, losses
    # lora_scale is frozen by the multi_transform mask.
    np.testing.assert_allclose(
        np.asarray(state.params["layers"]["q"]["lora_scale"]), 2.0
    )
    # Adapters moved.
    assert np.any(np.asarray(state.params["layers"]["q"]["lora_b"]))
    # Merged model realizes the learned improvement end-to-end.
    merged = merge_lora(params, state.params)
    base_loss = float(causal_lm_loss(cfg, params, tokens, lengths))
    merged_loss = float(causal_lm_loss(cfg, merged, tokens, lengths))
    assert merged_loss < base_loss - 0.05, (merged_loss, base_loss)


def test_vocab_smaller_than_tokenizer_rejected():
    """A synthetic model vocab below the byte tokenizer's id range (EOS 257,
    PAD 258) silently NaN'd training via clamped OOB gathers before the
    _materialize guard; now it refuses with an actionable message."""
    from edgemesh.agents.orchestrator import _materialize
    from edgemesh.config import ModelSpec

    with pytest.raises(ValueError, match="vocab_size 256 < tokenizer"):
        _materialize(ModelSpec(vocab_size=256, num_layers=1, hidden_size=32), "qa")


def test_run_training_lora_and_inference_merge(tmp_path):
    """Config-driven round trip: `edgemesh train` with lora_rank > 0 writes
    adapter checkpoints; an inference agent with the same lora spec +
    train_checkpoint restores and merges them (orchestrator._materialize)."""
    from edgemesh.agents.orchestrator import _materialize
    from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec
    from edgemesh.training import run_training

    ckpt = str(tmp_path / "lora_ckpt")
    model = ModelSpec(
        family="llama", vocab_size=260, num_layers=2, hidden_size=64,
        num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=64,
        lora_rank=4, lora_alpha=8.0, lora_targets="q,v",
    )
    run_cfg = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=model)])
    run_cfg.train.steps = 3
    run_cfg.train.batch_size = 2
    run_cfg.train.seq_len = 32
    run_cfg.train.num_samples = 8
    run_cfg.train.checkpoint_dir = ckpt
    run_cfg.train.checkpoint_every = 3
    report = run_training(run_cfg)
    assert report["steps_run"] == 3 and report["lora_rank"] == 4
    assert report["final_loss"] is not None

    # Inference-side restore: same spec + train_checkpoint -> merged params.
    serve_model = ModelSpec(**{**model.__dict__, "train_checkpoint": ckpt})
    cfg, params, _tok = _materialize(serve_model, "qa")
    assert "lora_a" not in params["layers"]["q"]  # merged, not attached
    # The merged weights differ from the deterministic base init (the
    # adapters trained) — rebuild the base init to compare.
    base_cfg, base_params, _ = _materialize(
        ModelSpec(**{k: v for k, v in model.__dict__.items()
                     if k != "train_checkpoint"}), "qa")
    dq = np.asarray(params["layers"]["q"]["kernel"]) - np.asarray(
        base_params["layers"]["q"]["kernel"])
    assert np.any(dq != 0)
    # Non-target layers are bit-identical to the base init.
    du = np.asarray(params["layers"]["up"]["kernel"]) - np.asarray(
        base_params["layers"]["up"]["kernel"])
    assert not np.any(du != 0)


def test_lora_base_finetunes_a_trained_model(tmp_path):
    """The lora_base flow (round-4 VERDICT item): a FULL training run's
    checkpoint becomes the frozen base; adapters train on top of it; serving
    restores base + adapters and merges. Previously inexpressible —
    train_checkpoint could mean the base OR the adapters, never both."""
    from edgemesh.agents.orchestrator import _materialize
    from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec
    from edgemesh.training import run_training

    arch = dict(
        family="llama", vocab_size=260, num_layers=2, hidden_size=64,
        num_heads=4, num_kv_heads=2, intermediate_size=128, max_seq_len=64,
    )
    base_ckpt = str(tmp_path / "full_ckpt")
    adapter_ckpt = str(tmp_path / "adapter_ckpt")

    # 1. Full training run -> base checkpoint.
    run_cfg = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=ModelSpec(**arch))])
    run_cfg.train.steps = 3
    run_cfg.train.batch_size = 2
    run_cfg.train.seq_len = 32
    run_cfg.train.num_samples = 8
    run_cfg.train.checkpoint_dir = base_ckpt
    run_cfg.train.checkpoint_every = 3
    assert run_training(run_cfg)["steps_run"] == 3

    # 2. Adapter training ON TOP of the trained base.
    lora_model = ModelSpec(**arch, lora_rank=4, lora_alpha=8.0,
                           lora_targets="q,v", lora_base=base_ckpt)
    run_cfg2 = EdgeMeshConfig(agents=[AgentSpec(role="qa", model=lora_model)])
    run_cfg2.train.steps = 3
    run_cfg2.train.batch_size = 2
    run_cfg2.train.seq_len = 32
    run_cfg2.train.num_samples = 8
    run_cfg2.train.skip_samples = 8  # different split: a real adaptation
    run_cfg2.train.checkpoint_dir = adapter_ckpt
    run_cfg2.train.checkpoint_every = 3
    rep = run_training(run_cfg2)
    assert rep["steps_run"] == 3 and rep["lora_rank"] == 4

    # 3. Serving restore: base + adapters, merged.
    serve_model = ModelSpec(**{**lora_model.__dict__,
                               "train_checkpoint": adapter_ckpt})
    _, params, _ = _materialize(serve_model, "qa")
    _, trained_base, _ = _materialize(
        ModelSpec(**arch, lora_base=base_ckpt, lora_rank=4), "qa")
    _, raw_init, _ = _materialize(ModelSpec(**arch), "qa")
    import numpy as np

    # Non-target layers == the TRAINED base (not the raw init).
    np.testing.assert_array_equal(
        np.asarray(params["layers"]["up"]["kernel"]),
        np.asarray(trained_base["layers"]["up"]["kernel"]))
    assert np.any(np.asarray(trained_base["layers"]["up"]["kernel"])
                  != np.asarray(raw_init["layers"]["up"]["kernel"]))
    # Target layers == trained base + merged adapters (differ from both).
    assert np.any(np.asarray(params["layers"]["q"]["kernel"])
                  != np.asarray(trained_base["layers"]["q"]["kernel"]))

    # Ambiguity guard: two full checkpoints at once is refused.
    import pytest as _pytest
    with _pytest.raises(ValueError, match="ambiguous"):
        _materialize(ModelSpec(**arch, lora_base=base_ckpt,
                               train_checkpoint=base_ckpt), "qa")
