"""Distributed tracing fast tier (edgemesh/obs/trace.py + wiring): header
mint/parse round trips, skew-correction math, cross-process assembly from
synthetic multi-process logs, the `edgemesh obs trace` CLI, the compile
hook's counters, SpanTracker trace propagation + sampling, and the fleet
router's trace records over a fake transport — no model, no device."""

import json
import random

import pytest

from edgemesh.obs import Registry, SpanTracker
from edgemesh.obs.trace import (
    ROUTER_RECORD_EVENT,
    TRACE_HEADER,
    CompileEventHook,
    TraceContext,
    assemble_trace,
    clock_offset,
    critical_path,
    current_trace,
    load_trace,
    use_trace,
)
from edgemesh.utils.tracing import JsonlLogger

# ---------------------------------------------------------------------------
# Header mint / parse
# ---------------------------------------------------------------------------


def test_header_mint_parse_round_trip():
    rng = random.Random(11)
    ctx = TraceContext.mint(rng=rng)
    assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
    header = ctx.to_header()
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    assert TraceContext.parse(header) == ctx
    off = TraceContext.mint(sampled=False, rng=rng)
    assert off.to_header().endswith("-00")
    assert TraceContext.parse(off.to_header()) == off


def test_header_constant_is_shared_with_httputil():
    from edgemesh.serve import httputil

    assert httputil.TRACE_HEADER == TRACE_HEADER == "X-Edgemesh-Trace"


def test_parse_rejects_malformed_headers_quietly():
    good = TraceContext.mint(rng=random.Random(0))
    for bad in (
        None, "", "junk", "00-abc-def-01",
        good.to_header() + "-extra",
        "00-" + "g" * 32 + "-" + "1" * 16 + "-01",   # non-hex
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
        "00-" + good.trace_id + "-" + "0" * 16 + "-01",  # all-zero span id
    ):
        assert TraceContext.parse(bad) is None, bad


def test_child_keeps_trace_id_and_sampling_mints_new_span():
    rng = random.Random(3)
    root = TraceContext.mint(sampled=False, rng=rng)
    child = root.child(rng=rng)
    assert child.trace_id == root.trace_id
    assert child.span_id != root.span_id
    assert child.sampled is False


def test_ambient_context_var():
    assert current_trace() is None
    ctx = TraceContext.mint(rng=random.Random(5))
    with use_trace(ctx):
        assert current_trace() is ctx
        with use_trace(None):
            assert current_trace() is None
        assert current_trace() is ctx
    assert current_trace() is None


# ---------------------------------------------------------------------------
# Skew correction + assembly (synthetic multi-process logs)
# ---------------------------------------------------------------------------


def test_clock_offset_anchors_on_request_response_edges():
    # Router saw the attempt span [100.0, 101.0]; the replica's own clock
    # claims it worked [1050.1, 1050.9] — 950s ahead with 0.1s of wire
    # each way. The symmetric-network estimate recovers exactly -950.
    attempt = {"t0": 100.0, "t1": 101.0}
    assert clock_offset(attempt, 1050.1, 1050.9) == pytest.approx(-950.0)
    # Unfinished attempt (abandoned hedge): only the request edge anchors.
    assert clock_offset({"t0": 100.0, "t1": None}, 1050.1, 1050.9) == \
        pytest.approx(-950.1)


def _synthetic_records(tmp_path, skew_s=300.0):
    """Router + one failed attempt + winning attempt served by a replica
    whose clock runs ``skew_s`` ahead. Returns (trace_id, [log paths])."""
    rng = random.Random(42)
    root = TraceContext.mint(rng=rng)
    failed, winner = root.child(rng=rng), root.child(rng=rng)
    router_log, replica_log = tmp_path / "router.jsonl", tmp_path / "rep.jsonl"
    JsonlLogger(router_log).log(
        ROUTER_RECORD_EVENT,
        trace_id=root.trace_id, span_id=root.span_id, process="router",
        status=200, attempts=2, clock="wall", latency_s=1.0,
        spans=[
            {"name": "request", "span_id": root.span_id, "t0": 100.0, "t1": 101.0},
            {"name": "attempt", "span_id": failed.span_id, "replica": "r0",
             "hedge": False, "outcome": "connect", "status": None,
             "t0": 100.0, "t1": 100.1},
            {"name": "attempt", "span_id": winner.span_id, "replica": "r1",
             "hedge": False, "outcome": "ok", "status": 200,
             "t0": 100.2, "t1": 101.0},
        ],
    )
    # Engine record convention: perf_counter spans + ts_submit wall anchor.
    # Replica wall window: [100.3+skew, 100.9+skew] — inside the winning
    # attempt [100.2, 101.0] once the skew is corrected away.
    JsonlLogger(replica_log).log(
        "request_spans",
        rid=0, engine="continuous", status="ok",
        trace_id=root.trace_id, span_id="ab" * 8,
        parent_span_id=winner.span_id, ts_submit=100.3 + skew_s,
        generated=6, segments=1, latency_s=0.6,
        spans=[
            {"name": "queued", "t0": 7.0, "t1": 7.05},
            {"name": "prefill", "t0": 7.05, "t1": 7.25},
            {"name": "decode", "t0": 7.25, "t1": 7.6, "tokens": 6},
            {"name": "retire", "t0": 7.6, "t1": 7.6},
        ],
    )
    return root.trace_id, [router_log, replica_log]


def test_assembly_stitches_processes_and_corrects_skew(tmp_path):
    trace_id, logs = _synthetic_records(tmp_path, skew_s=300.0)
    doc = load_trace(trace_id, logs)
    assert doc["processes"] == 2
    tree = doc["tree"]
    assert tree["name"] == "request" and tree["process"] == "router"
    attempts = [c for c in tree["children"] if c["name"] == "attempt"]
    assert len(attempts) == 2
    # The failed attempt is a SIBLING of the winner, tagged with outcome.
    assert attempts[0]["outcome"] == "connect" and attempts[0]["replica"] == "r0"
    assert attempts[1]["outcome"] == "ok"
    server = attempts[1]["children"][0]
    assert server["name"] == "server"
    # Skew correction: the replica window lands inside the attempt span on
    # the router's clock, and the offset is the injected -300s (the wire
    # asymmetry is 0.1s front / 0.1s back, so the estimate is exact).
    assert server["skew_s"] == pytest.approx(-300.0, abs=1e-6)
    assert server["t0"] >= attempts[1]["t0"] - 1e-6
    assert server["t1"] <= attempts[1]["t1"] + 1e-6
    names = [s["name"] for s in server["children"]]
    assert names == ["queued", "prefill", "decode", "retire"]
    # Every corrected child edge is monotonic and inside the server window.
    for s in server["children"]:
        assert s["t1"] >= s["t0"] >= server["t0"] - 1e-6


def test_critical_path_sums_to_total_and_splits_stages(tmp_path):
    trace_id, logs = _synthetic_records(tmp_path)
    cp = load_trace(trace_id, logs)["critical_path"]
    assert cp["total_s"] == pytest.approx(1.0, abs=1e-6)
    assert cp["retry_wasted_s"] == pytest.approx(0.2, abs=1e-6)
    # wire = attempt (0.8) - server window (0.6)
    assert cp["wire_s"] == pytest.approx(0.2, abs=1e-6)
    assert cp["queue_s"] == pytest.approx(0.05, abs=1e-6)
    assert cp["prefill_s"] == pytest.approx(0.2, abs=1e-6)
    assert cp["decode_s"] == pytest.approx(0.35, abs=1e-6)
    parts = (cp["retry_wasted_s"] + cp["wire_s"] + cp["queue_s"]
             + cp["prefill_s"] + cp["decode_s"] + cp["other_s"])
    assert parts == pytest.approx(cp["total_s"], abs=1e-6)


def test_critical_path_collective_phase_and_byte_rollup():
    """tp serving: decode spans carry collective_bytes attrs (exact wire
    accounting) and backends that measure the phase emit "collective"
    spans — critical_path rolls both up, with collective_s a sub-phase OF
    decode (outside the sum-to-total)."""
    tree = {
        "name": "request", "t0": 0.0, "t1": 1.0,
        "children": [{
            "name": "server", "t0": 0.0, "t1": 1.0,
            "children": [
                {"name": "queued", "t0": 0.0, "t1": 0.1},
                {"name": "prefill", "t0": 0.1, "t1": 0.3,
                 "collective_bytes": 4096},
                {"name": "decode", "t0": 0.3, "t1": 0.9, "tokens": 6,
                 "collective_bytes": 1024},
                {"name": "decode", "t0": 0.9, "t1": 1.0, "tokens": 2,
                 "collective_bytes": 512},
                {"name": "collective", "t0": 0.4, "t1": 0.55},
            ],
        }],
    }
    cp = critical_path(tree)
    assert cp["collective_bytes"] == 4096 + 1024 + 512
    assert cp["collective_s"] == pytest.approx(0.15, abs=1e-6)
    # The sum-to-total contract is untouched by the sub-phase.
    parts = (cp["retry_wasted_s"] + cp["wire_s"] + cp["queue_s"]
             + cp["prefill_s"] + cp["decode_s"] + cp["other_s"])
    assert parts == pytest.approx(cp["total_s"], abs=1e-6)
    # Pre-collective trees keep zero defaults (forward compat both ways).
    assert critical_path(None)["collective_bytes"] == 0


def test_critical_path_prefers_won_attempt_over_late_ok_hedge_loser():
    # The primary answered the client at t=100.5 (won); the abandoned hedge
    # loser ALSO finished "ok" later. The split must describe the winner.
    tree = {
        "name": "request", "t0": 100.0, "t1": 100.6,
        "children": [
            {"name": "attempt", "outcome": "ok", "won": True,
             "t0": 100.0, "t1": 100.5, "children": []},
            {"name": "attempt", "outcome": "ok", "won": False, "hedge": True,
             "t0": 100.3, "t1": 101.4, "children": []},
        ],
    }
    cp = critical_path(tree)
    assert cp["retry_wasted_s"] == pytest.approx(0.0, abs=1e-6)
    assert cp["wire_s"] == pytest.approx(0.5, abs=1e-6)
    # Pre-marker records (no "won" key anywhere) fall back to last-ok.
    for att in tree["children"]:
        del att["won"]
    assert critical_path(tree)["retry_wasted_s"] == pytest.approx(0.3, abs=1e-6)


def test_assembly_without_router_record_synthesizes_root(tmp_path):
    trace_id, logs = _synthetic_records(tmp_path)
    doc = load_trace(trace_id, logs[1:])  # replica log only
    assert doc["processes"] == 1
    assert doc["tree"]["synthetic"] is True
    servers = [c for c in doc["tree"]["children"] if c["name"] == "server"]
    assert len(servers) == 1
    # Critical path still splits replica-side stages.
    cp = doc["critical_path"]
    assert cp["decode_s"] == pytest.approx(0.35, abs=1e-6)


def test_assemble_trace_ignores_other_trace_ids():
    doc = assemble_trace("feed" * 8, [{"event": "request_spans",
                                       "trace_id": "beef" * 8}])
    assert doc["processes"] == 0 and doc["tree"] is None
    assert critical_path(doc["tree"])["total_s"] is None


# ---------------------------------------------------------------------------
# `edgemesh obs trace` CLI
# ---------------------------------------------------------------------------


def test_obs_trace_cli_assembles_and_accepts_prefix(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    trace_id, logs = _synthetic_records(tmp_path)
    argv = ["trace", trace_id, "--logs"] + [str(p) for p in logs]
    assert obs_main(argv) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["trace_id"] == trace_id and doc["processes"] == 2
    assert doc["critical_path"]["total_s"] == pytest.approx(1.0, abs=1e-6)
    # Unique prefix works too.
    assert obs_main(["trace", trace_id[:8], "--logs",
                     str(logs[0]), str(logs[1])]) == 0
    assert json.loads(capsys.readouterr().out)["trace_id"] == trace_id


def test_obs_trace_cli_unknown_id_and_missing_log(tmp_path, capsys):
    from edgemesh.obs.cli import main as obs_main

    _, logs = _synthetic_records(tmp_path)
    assert obs_main(["trace", "dead" * 8, "--logs", str(logs[0])]) == 1
    assert "no records" in capsys.readouterr().err
    assert obs_main(["trace", "dead" * 8, "--logs",
                     str(tmp_path / "nope.jsonl")]) == 2
    assert "no such span log" in capsys.readouterr().err


def test_obs_summary_and_tail_on_empty_and_malformed_logs(tmp_path, capsys):
    """Satellite: an empty or all-malformed span log is an answer, not a
    crash — summary prints an explicit "requests": 0 report, exit 0."""
    from edgemesh.obs.cli import main as obs_main

    empty = tmp_path / "empty.jsonl"
    empty.touch()
    assert obs_main(["summary", str(empty)]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["requests"] == 0 and report["latency_s_p50"] is None
    assert obs_main(["tail", str(empty)]) == 0

    torn = tmp_path / "torn.jsonl"
    torn.write_text('not json at all\n{"event": "request_spans", "rid"\n')
    assert obs_main(["summary", str(torn)]) == 0
    out = capsys.readouterr()
    assert json.loads(out.out)["requests"] == 0
    assert "malformed" in out.err
    assert obs_main(["tail", str(torn)]) == 0
    assert obs_main(["prom", str(torn)]) == 0


# ---------------------------------------------------------------------------
# SpanTracker trace propagation + sampling
# ---------------------------------------------------------------------------


def _drive(tracker, rid, ctx=None):
    tr = tracker.submit(rid, ctx)
    tracker.admit_start(tr)
    tracker.admitted(tr, prompt_tokens=3)
    tracker.tokens(tr, 2)
    tracker.retire(tr)
    return tr


def test_span_tracker_joins_propagated_trace(tmp_path):
    tracker = SpanTracker(Registry(), tmp_path / "s.jsonl", engine="unit")
    ctx = TraceContext.mint(rng=random.Random(1))
    tr = _drive(tracker, 0, ctx)
    assert tr.trace_id == ctx.trace_id
    assert tr.parent_span_id == ctx.span_id
    assert tr.span_id not in (None, ctx.span_id)
    [rec] = JsonlLogger(tmp_path / "s.jsonl").read()
    assert rec["trace_id"] == ctx.trace_id
    assert rec["parent_span_id"] == ctx.span_id
    assert rec["span_id"] == tr.span_id
    # Wall anchor for assembly: ts_submit + spans[0].t0 is the submit edge.
    assert rec["ts_submit"] == pytest.approx(tr.ts_unix)
    assert rec["spans"][0]["t0"] == pytest.approx(tr.t_submit)


def test_span_tracker_mints_local_trace_when_none_propagated(tmp_path):
    tracker = SpanTracker(Registry(), tmp_path / "s.jsonl", engine="unit")
    tr = _drive(tracker, 0)
    assert tr.trace_id and tr.span_id and tr.parent_span_id is None
    [rec] = JsonlLogger(tmp_path / "s.jsonl").read()
    assert rec["trace_id"] == tr.trace_id and rec["parent_span_id"] is None


def test_sampled_out_requests_skip_span_io_but_count_in_metrics(tmp_path):
    reg = Registry()
    tracker = SpanTracker(reg, tmp_path / "s.jsonl", engine="unit")
    # Propagated sampled=False wins over the tracker's own rate.
    off = TraceContext.mint(sampled=False, rng=random.Random(2))
    _drive(tracker, 0, off)
    assert JsonlLogger(tmp_path / "s.jsonl").read() == []
    # Local sampling: rate 0 → no records, full metrics.
    t2 = SpanTracker(reg, tmp_path / "s2.jsonl", engine="unit2",
                     trace_sample=0.0)
    for rid in range(5):
        _drive(t2, rid)
    assert JsonlLogger(tmp_path / "s2.jsonl").read() == []
    s = reg.summary()
    assert s['edgemesh_requests_submitted_total{engine="unit"}'] == 1
    assert s['edgemesh_requests_submitted_total{engine="unit2"}'] == 5
    assert s['edgemesh_requests_completed_total{engine="unit2",status="ok"}'] == 5
    assert s['edgemesh_ttft_seconds{engine="unit2"}']["count"] == 5


# ---------------------------------------------------------------------------
# Compile hook
# ---------------------------------------------------------------------------


def test_compile_hook_counts_compiles_and_recompiles(tmp_path):
    reg = Registry()
    hook = CompileEventHook(registry=reg, span_log=tmp_path / "c.jsonl")
    hook.on_event("/jax/core/compile/jaxpr_trace_duration", 0.01)
    hook.on_event("/jax/core/compile/backend_compile_duration", 0.5)
    hook.on_event("/jax/core/compile/backend_compile_duration", 0.25)
    hook.on_event("/jax/core/unrelated_event", 9.0)  # not a compile: ignored
    s = reg.summary()
    assert s['edgemesh_jax_compiles_total{event="backend_compile_duration"}'] == 2
    assert s['edgemesh_jax_compiles_total{event="jaxpr_trace_duration"}'] == 1
    # Recompiles: backend compiles beyond the first in this process.
    assert s["edgemesh_jax_recompiles_total"] == 1
    assert s['edgemesh_jax_compile_seconds{event="backend_compile_duration"}'][
        "sum"] == pytest.approx(0.75)
    recs = JsonlLogger(tmp_path / "c.jsonl").read()
    assert [r["event"] for r in recs] == ["compile", "compile"]
    assert recs[0]["trace_id"] is None  # no ambient trace


def test_compile_hook_stamps_ambient_trace_and_joins_assembly(tmp_path):
    reg = Registry()
    hook = CompileEventHook(registry=reg, span_log=tmp_path / "c.jsonl")
    ctx = TraceContext.mint(rng=random.Random(9))
    with use_trace(ctx):
        hook.on_event("/jax/core/compile/backend_compile_duration", 0.125)
    [rec] = JsonlLogger(tmp_path / "c.jsonl").read()
    assert rec["trace_id"] == ctx.trace_id
    assert rec["parent_span_id"] == ctx.span_id
    # A compile record alone doesn't make a trace, but it attaches to one.
    router_rec = {
        "event": ROUTER_RECORD_EVENT, "trace_id": ctx.trace_id,
        "span_id": ctx.span_id, "clock": "wall", "status": 200,
        "attempts": 1,
        "spans": [{"name": "request", "span_id": ctx.span_id,
                   "t0": 1.0, "t1": 2.0}],
    }
    doc = assemble_trace(ctx.trace_id, [router_rec, rec])
    compiles = [c for c in doc["tree"]["children"] if c["name"] == "compile"]
    assert len(compiles) == 1
    assert compiles[0]["duration_s"] == pytest.approx(0.125)


def test_install_uninstall_compile_hook_dispatcher():
    from edgemesh.obs.trace import install_compile_hook, uninstall_compile_hook
    from edgemesh.obs.trace import _dispatch  # the process-wide fan-out

    reg = Registry()
    hook = install_compile_hook(registry=reg)
    try:
        _dispatch("/jax/core/compile/backend_compile_duration", 0.1)
        assert reg.summary()[
            'edgemesh_jax_compiles_total{event="backend_compile_duration"}'] == 1
    finally:
        uninstall_compile_hook(hook)
    _dispatch("/jax/core/compile/backend_compile_duration", 0.1)
    assert reg.summary()[
        'edgemesh_jax_compiles_total{event="backend_compile_duration"}'] == 1


# ---------------------------------------------------------------------------
# Router trace records over a fake transport
# ---------------------------------------------------------------------------


class FakeTransport:
    def __init__(self):
        self.calls = []
        self._routes = []

    def on(self, substr, handler):
        self._routes.append((substr, handler))
        return self

    def post_json(self, url, payload, timeout_s, headers=None):
        self.calls.append((url, dict(headers or {})))
        for substr, handler in self._routes:
            if substr in url:
                return handler(url, payload, headers or {})
        return 200, {"answer": "ok"}

    def get_json(self, url, timeout_s, headers=None):
        return 200, {}


def _router(tmp_path, transport, rids=("r0", "r1"), **kw):
    from edgemesh.fleet import FleetRouter, ReplicaRegistry

    reg = ReplicaRegistry()
    for rid in rids:
        reg.register(rid, f"http://{rid}")
    kw.setdefault("obs_registry", Registry())
    kw.setdefault("rng", random.Random(0))
    kw.setdefault("span_log", tmp_path / "router.jsonl")
    router = FleetRouter(reg, transport=transport, **kw)
    router._sleep = lambda s: None
    return router


def test_router_mints_context_propagates_header_and_logs_record(tmp_path):
    transport = FakeTransport()
    router = _router(tmp_path, transport)
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 200
    ctx = TraceContext.parse(headers[TRACE_HEADER])
    assert ctx is not None and ctx.sampled
    # The replica saw a CHILD span of the same trace.
    _, sent_headers = transport.calls[0]
    sent = TraceContext.parse(sent_headers[TRACE_HEADER])
    assert sent.trace_id == ctx.trace_id and sent.span_id != ctx.span_id
    [rec] = JsonlLogger(tmp_path / "router.jsonl").read()
    assert rec["event"] == ROUTER_RECORD_EVENT
    assert rec["trace_id"] == ctx.trace_id and rec["attempts"] == 1
    root, attempt = rec["spans"]
    assert root["name"] == "request" and attempt["name"] == "attempt"
    assert attempt["outcome"] == "ok" and attempt["status"] == 200
    assert attempt["won"] is True
    assert attempt["span_id"] == sent.span_id
    assert root["t0"] <= attempt["t0"] <= attempt["t1"] <= root["t1"]
    # /fleetz summaries + /debug/traces assembly from the in-memory ring.
    recent = router.recent_traces()
    assert recent[0]["trace_id"] == ctx.trace_id
    assert recent[0]["replicas"] in (["r0"], ["r1"])
    doc = router.get_trace(ctx.trace_id[:12])
    assert doc is not None and doc["critical_path"]["total_s"] is not None
    assert router.get_trace("ffff") is None


def test_router_retry_emits_sibling_attempt_spans(tmp_path):
    from edgemesh.fleet import TransportError

    transport = FakeTransport()

    def refuse(url, payload, headers):
        raise TransportError(f"{url}: refused")

    transport.on("r0", refuse)
    router = _router(tmp_path, transport)
    status, _, headers = router.handle_generate({"question": "q?"})
    assert status == 200
    [rec] = JsonlLogger(tmp_path / "router.jsonl").read()
    attempts = [s for s in rec["spans"] if s["name"] == "attempt"]
    # One request may take 1 attempt (picked r1 first) — force determinism:
    # with round-robin starting at r0 the first attempt fails. Either way
    # every failed attempt must appear as a closed sibling span.
    failed = [a for a in attempts if a["outcome"] == "connect"]
    ok = [a for a in attempts if a["outcome"] == "ok"]
    assert len(ok) == 1
    if failed:
        assert failed[0]["replica"] == "r0"
        assert failed[0]["t1"] is not None
        assert failed[0]["span_id"] != ok[0]["span_id"]
        assert rec["attempts"] == len(attempts)


def test_router_joins_client_supplied_trace(tmp_path):
    transport = FakeTransport()
    router = _router(tmp_path, transport)
    client_ctx = TraceContext.mint(rng=random.Random(4))
    status, _, headers = router.handle_generate(
        {"question": "q?"}, trace=client_ctx
    )
    assert status == 200
    assert TraceContext.parse(headers[TRACE_HEADER]) == client_ctx
    [rec] = JsonlLogger(tmp_path / "router.jsonl").read()
    assert rec["trace_id"] == client_ctx.trace_id


def test_router_get_trace_serves_newest_for_repeated_client_trace_id(tmp_path):
    # A client fanning out two requests under ONE supplied traceparent must
    # still be able to fetch /debug/traces/<that exact id>.
    transport = FakeTransport()
    router = _router(tmp_path, transport)
    client_ctx = TraceContext.mint(rng=random.Random(6))
    for _ in range(2):
        status, _, _ = router.handle_generate({"question": "q?"},
                                              trace=client_ctx)
        assert status == 200
    assert len(router.recent_traces()) == 2
    doc = router.get_trace(client_ctx.trace_id)
    assert doc is not None and doc["processes"] == 1
    # An ambiguous PREFIX (matching two distinct ids) still refuses.
    r2 = _router(tmp_path / "b", FakeTransport())
    a = TraceContext("aa" + "0" * 29 + "1", "1" * 16)
    b = TraceContext("aa" + "0" * 29 + "2", "2" * 16)
    r2.handle_generate({"question": "q?"}, trace=a)
    r2.handle_generate({"question": "q?"}, trace=b)
    assert r2.get_trace("aa") is None
    assert r2.get_trace(a.trace_id)["trace_id"] == a.trace_id


def test_router_trace_sampling_gates_io_not_metrics(tmp_path):
    transport = FakeTransport()
    obs = Registry()
    router = _router(tmp_path, transport, obs_registry=obs, trace_sample=0.0)
    for _ in range(4):
        status, _, headers = router.handle_generate({"question": "q?"})
        assert status == 200
        ctx = TraceContext.parse(headers[TRACE_HEADER])
        assert ctx is not None and ctx.sampled is False
    assert JsonlLogger(tmp_path / "router.jsonl").read() == []
    assert router.recent_traces() == []
    routed = sum(v for k, v in obs.summary().items()
                 if k.startswith("edgemesh_fleet_routed_total"))
    assert routed == 4
    # The replicas saw sampled=False and will skip THEIR span I/O too.
    for _, sent_headers in transport.calls:
        assert TraceContext.parse(sent_headers[TRACE_HEADER]).sampled is False


def test_debug_profile_endpoint_is_opt_in(tmp_path):
    """403 without profile_dir; with it, validation answers before any
    profiler work (the actual capture is exercised by the slow tier /
    manual ops — a capture burns real seconds)."""
    import urllib.error
    import urllib.request

    from edgemesh.serve.rest import serve_rest

    class FakeEnsemble:
        qa_agents = []
        refiner = None

    srv = serve_rest(FakeEnsemble(), host="127.0.0.1", port=0, block=False)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.server_address[1]}/debug/profile",
                timeout=10)
        assert e.value.code == 403
    finally:
        srv.shutdown()
    srv = serve_rest(FakeEnsemble(), host="127.0.0.1", port=0, block=False,
                     profile_dir=tmp_path)
    try:
        for q in ("seconds=999", "seconds=abc", "seconds=0"):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.server_address[1]}"
                    f"/debug/profile?{q}", timeout=10)
            assert e.value.code == 400, q
    finally:
        srv.shutdown()


def test_router_shed_paths_still_answer_with_trace_header(tmp_path):
    transport = FakeTransport()
    router = _router(tmp_path, transport, rids=())
    status, body, headers = router.handle_generate({"question": "q?"})
    assert status == 503 and "no available replica" in body["error"]
    assert TraceContext.parse(headers[TRACE_HEADER]) is not None
    [rec] = JsonlLogger(tmp_path / "router.jsonl").read()
    assert rec["status"] == 503 and rec["attempts"] == 0
