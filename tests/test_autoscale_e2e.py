"""Capacity-observatory end-to-end (slow tier): the closed control loop
over REAL replica subprocesses.

Three acceptance stories (ISSUE 14):

1. ``--admission auto`` under a rising open-loop load converges the
   router's ``max_inflight`` toward the knee an OFFLINE ``load_curve``
   sweep measures — zero operator tuning, with the whole story visible in
   ``/fleetz`` and ``obs summary``.
2. A replica spawned against a warm persistent compilation cache reaches
   first token by a pinned ratio faster than the cache-cold arm.
3. A propagated incident scales the fleet up: the router hands the
   incident to the autoscaler, which spawns a warm replica through the
   real SubprocessLauncher.

Multi-minute territory (every replica is a full `edgemesh serve` process
compiling a tiny model on its CPU slice) — nightly slow-e2e CI only.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPLICA_YAML = """
agents:
  - role: qa
    model: {family: llama, num_layers: 1, hidden_size: 32, num_heads: 4,
            num_kv_heads: 4, intermediate_size: 64}
    sampling: {max_new_tokens: 4, do_sample: false, repetition_penalty: 1.0}
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_replica(cfg_path: Path, port: int,
                   extra: tuple = ()) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "edgemesh.cli", "serve",
         "--config", str(cfg_path), "--port", str(port),
         "--continuous", "--batch", "2", *extra],
        env=env, cwd=Path(__file__).resolve().parent.parent,
    )


def _wait_ready(transport, ports, timeout_s=300.0):
    from edgemesh.fleet.transport import TransportError

    deadline = time.monotonic() + timeout_s
    pending = set(ports)
    while pending and time.monotonic() < deadline:
        for port in list(pending):
            try:
                status, _ = transport.get_json(
                    f"http://127.0.0.1:{port}/readyz", timeout_s=2.0)
            except TransportError:
                continue
            if status == 200:
                pending.discard(port)
        time.sleep(0.25)
    assert not pending, f"replicas on {sorted(pending)} never became ready"


def _stop(procs):
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def _first_token_wall(transport, cfg, port, extra, timeout_s=600.0):
    """Spawn one replica and return spawn→first-200-from-/generate."""
    from edgemesh.fleet.transport import TransportError

    t0 = time.monotonic()
    proc = _spawn_replica(cfg, port, extra)
    try:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            assert proc.poll() is None, \
                f"replica exited rc={proc.returncode} during boot"
            try:
                status, _ = transport.post_json(
                    f"http://127.0.0.1:{port}/generate",
                    {"question": "cold start probe?"}, timeout_s=60.0)
            except TransportError:
                time.sleep(0.2)
                continue
            if status == 200:
                return time.monotonic() - t0
            time.sleep(0.2)
        pytest.fail("replica never answered its first token")
    finally:
        _stop([proc])


def test_warm_start_beats_cold_by_the_pinned_ratio(tmp_path):
    """Acceptance (b): a compile-cache-hit spawn reaches first token at
    most 0.8x the cache-cold arm's wall. The cold arm POPULATES the cache
    the warm arm hits — same process image, same config, one variable."""
    from edgemesh.fleet import HttpTransport

    transport = HttpTransport()
    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    cache = tmp_path / "compile-cache"
    cache.mkdir()
    extra = ("--compile-cache-dir", str(cache))
    cold_s = _first_token_wall(transport, cfg, _free_port(), extra)
    entries = [p for p in cache.iterdir() if p.name.endswith("-cache")]
    if not entries:
        pytest.skip("this jax cannot persist its compilation cache on CPU")
    warm_s = _first_token_wall(transport, cfg, _free_port(), extra)
    ratio = warm_s / cold_s
    print(f"cold {cold_s:.1f}s -> warm {warm_s:.1f}s (ratio {ratio:.2f}, "
          f"{len(entries)} cache entries)")
    # The pinned ratio: warm start must beat cold by >= 20%. On this
    # 1-layer model compile dominates boot, so real runs land far lower;
    # 0.8 keeps the gate robust to CI noise.
    assert ratio <= 0.8, (
        f"warm start did not beat cold: {warm_s:.1f}s vs {cold_s:.1f}s")


def test_admission_auto_converges_to_the_measured_knee(tmp_path):
    """Acceptance (a): the knee tracker, fed only by the router's own
    per-window observations, lands max_inflight in the neighborhood of the
    knee an offline open-loop sweep measures — and the story is visible in
    /fleetz and `obs summary`."""
    from edgemesh.fleet import (
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.loadgen import (
        LengthMix,
        OpenLoopGenerator,
        PoissonProcess,
        TenantSpec,
        Workload,
        http_target,
        run_curve,
    )
    from edgemesh.obs import Registry

    transport = HttpTransport()
    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    ports = [_free_port() for _ in range(2)]
    procs = [_spawn_replica(cfg, p) for p in ports]
    front = prober = None
    try:
        _wait_ready(transport, ports)
        for p in ports:
            status, _ = transport.post_json(
                f"http://127.0.0.1:{p}/generate", {"question": "warmup?"},
                timeout_s=600.0)
            assert status == 200

        prompt_mix = LengthMix(median=60, sigma=0.0, lo=60, hi=60)

        def make_workload(rate, seed=5):
            return Workload([TenantSpec(
                name="load", arrival=PoissonProcess(max(0.2, rate), seed=11),
                prompt_mix=prompt_mix)], seed=seed)

        def boot_fleet(admission_auto):
            obs = Registry()
            registry = ReplicaRegistry(
                (f"replica-{i}", f"http://127.0.0.1:{p}")
                for i, p in enumerate(ports))
            router = FleetRouter(
                registry, balancer="least_outstanding", transport=transport,
                obs_registry=obs, max_attempts=1, attempt_timeout_s=120.0,
                default_deadline_s=120.0, max_inflight=32,
                admission_auto=admission_auto, admission_floor=2,
                admission_ceiling=64,
                span_log=(tmp_path / "router.jsonl") if admission_auto else None,
            )
            prober = HealthProber(registry, transport=transport,
                                  interval_s=1.0,
                                  on_incident=router.observe_incident,
                                  on_digest=router.note_digest).start()
            front = serve_fleet(router, host="127.0.0.1", port=0,
                                block=False)
            url = f"http://127.0.0.1:{front.server_address[1]}/generate"
            return router, prober, front, http_target(url, timeout_s=120.0)

        # ---- Offline sweep: the reference knee, measured open-loop.
        router, prober, front, target = boot_fleet(admission_auto=False)
        t_cal = time.perf_counter() + 2.5
        served = 0
        while time.perf_counter() < t_cal:
            s, _ = target({"question": "calibration?"}, {})
            served += 1 if s == 200 else 0
        capacity_rps = max(0.5, served / 2.5)
        slo_s = float(os.environ.get("EDGEMESH_SLO_TTFT_S", "2.0"))

        def make_run(rate):
            gen = OpenLoopGenerator(
                target, make_workload(rate).build_schedule(4.0),
                slo_latency_s=slo_s, duration_s=4.0)
            return gen.run()

        curve = run_curve(make_run,
                          [round(capacity_rps * f, 3) for f in (0.5, 1.5, 3.0)])
        offline_knee_rps = curve["knee_offered_rps"]
        prober.stop()
        front.shutdown()
        assert offline_knee_rps is not None

        # ---- Online: --admission auto under a RISING open-loop load.
        router, prober, front, target = boot_fleet(admission_auto=True)
        assert router.tuner is not None
        for phase_rate in (0.8 * capacity_rps, 2.0 * capacity_rps,
                           3.5 * capacity_rps):
            gen = OpenLoopGenerator(
                target, make_workload(phase_rate).build_schedule(6.0),
                slo_latency_s=slo_s, duration_s=6.0)
            gen.run()
        tuner = router.tuner.status()
        print("tuner:", json.dumps(tuner))
        # Zero operator tuning: the controller observed real windows and
        # holds a live knee estimate in the neighborhood of the offline
        # sweep's (generous tolerance — two 1-core replicas under a GIL
        # are a noisy instrument; the CLAIM is closed-loop consistency).
        assert tuner["windows"] >= 5
        knee = tuner["knee"]["knee_offered_rps"]
        assert knee is not None
        assert knee == pytest.approx(offline_knee_rps, rel=1.0)
        # The limit moved off its static guess and stayed inside the
        # configured band: the loop is CLOSED.
        assert 2 <= tuner["limit"] <= 64
        assert tuner["limit"] != 32 or tuner["windows"] < 3

        # ---- Visible everywhere: /fleetz carries tuner + capacity,
        # obs summary reports the knee row from the router span log.
        status, fleetz = transport.get_json(
            f"http://127.0.0.1:{front.server_address[1]}/fleetz",
            timeout_s=10.0)
        assert status == 200
        assert fleetz["admission"]["tuner"]["mode"] == "auto"
        assert fleetz["admission"]["tuner"]["limit"] == tuner["limit"]
        assert fleetz["capacity"]["fleet_est_req_s"] is not None
        assert fleetz["capacity"]["fleet_arrival_rps"] is not None
        out = subprocess.run(
            [sys.executable, "-m", "edgemesh.cli", "obs", "summary",
             str(tmp_path / "router.jsonl")],
            capture_output=True, text=True,
            cwd=Path(__file__).resolve().parent.parent)
        assert out.returncode == 0, out.stderr
        report = json.loads(out.stdout)
        assert report["knee"] is not None
        assert report["knee"]["limit"] == tuner["limit"] or \
            report["knee"]["action"] in ("increase", "decrease")
    finally:
        if prober is not None:
            prober.stop()
        if front is not None:
            front.shutdown()
        _stop(procs)


def test_incident_scales_the_fleet_up_with_a_warm_spawn(tmp_path):
    """Acceptance (c): a propagated incident reaches the autoscaler
    through the router and a REAL warm replica joins rotation, with the
    event visible in /fleetz and the cold-start metric stamped."""
    from edgemesh.fleet import (
        AutoScaler,
        FleetRouter,
        HealthProber,
        HttpTransport,
        ReplicaRegistry,
        serve_fleet,
    )
    from edgemesh.fleet.cli import SubprocessLauncher
    from edgemesh.obs import Registry

    transport = HttpTransport()
    cfg = tmp_path / "replica.yaml"
    cfg.write_text(REPLICA_YAML)
    cache = tmp_path / "compile-cache"
    cache.mkdir()
    port = _free_port()
    procs = [_spawn_replica(cfg, port,
                            ("--compile-cache-dir", str(cache)))]
    front = prober = scaler = None
    launcher = None
    try:
        _wait_ready(transport, [port])
        obs = Registry()
        registry = ReplicaRegistry([("replica-0", f"http://127.0.0.1:{port}")])
        router = FleetRouter(registry, transport=transport, obs_registry=obs,
                             max_attempts=2, attempt_timeout_s=120.0)
        args = argparse.Namespace(config=str(cfg),
                                  replica_extra="--continuous --batch 2",
                                  compile_cache_dir=str(cache))
        launcher = SubprocessLauncher(args, registry, transport,
                                      obs_registry=obs)
        scaler = AutoScaler(registry, launcher, router=router,
                            min_replicas=1, max_replicas=2,
                            # This test's fleet is idle: block the
                            # scale-DOWN path so it cannot reap the
                            # incident spawn mid-assertion.
                            down_after=10**6,
                            interval_s=0.5, obs_registry=obs)
        router.autoscaler = scaler
        prober = HealthProber(registry, transport=transport, interval_s=1.0,
                              on_incident=router.observe_incident,
                              on_digest=router.note_digest).start()
        scaler.start()
        front = serve_fleet(router, host="127.0.0.1", port=0, block=False)

        # The incident arrives exactly as the prober would deliver it.
        assert router.observe_incident(
            "replica-0", {"id": "inc-e2e-1", "kind": "slo_burst"}) is True
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            if len(registry.available()) >= 2:
                break
            time.sleep(0.5)
        assert len(registry.available()) >= 2, \
            "incident did not scale the fleet up"

        # The new replica actually serves through the frontend.
        req = urllib.request.Request(
            f"http://127.0.0.1:{front.server_address[1]}/generate",
            data=json.dumps({"question": "post-scale question?"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200

        # Visible: /fleetz autoscale event + metrics.
        status, fleetz = transport.get_json(
            f"http://127.0.0.1:{front.server_address[1]}/fleetz",
            timeout_s=10.0)
        assert status == 200
        events = fleetz["autoscale"]["recent_events"]
        assert any(e["action"] == "incident_up" and e["incident"] == "inc-e2e-1"
                   for e in events)
        summary = obs.summary()
        assert summary[
            'edgemesh_autoscale_events_total{action="incident_up"}'] == 1
        cold = [k for k in summary
                if k.startswith("edgemesh_cold_start_seconds")]
        assert cold, "cold-start telemetry missing"
        # The spawned replica's digest proves the shared cache engaged.
        deadline = time.monotonic() + 30.0
        cache_block = None
        while time.monotonic() < deadline:
            reps = {r.rid: r for r in registry.replicas()}
            scaled = next((r for rid, r in reps.items()
                           if rid.startswith("replica-scale")), None)
            if scaled is not None and isinstance(scaled.load, dict):
                cache_block = scaled.load.get("compile_cache")
                if cache_block:
                    break
            time.sleep(0.5)
        assert cache_block is not None and cache_block["enabled"] is True
    finally:
        if prober is not None:
            prober.stop()
        if scaler is not None:
            scaler.stop()
        if launcher is not None:
            launcher.stop_all()
        if front is not None:
            front.shutdown()
        _stop(procs)
