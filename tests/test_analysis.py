"""edgemesh.analysis: one known-bad fixture per lint rule (each rule
demonstrably fires), suppression/baseline mechanics, the abstract contract
pass, and the CLI exit-code contract. Fast tier — the contract pass is
eval_shape-only (no device programs compiled)."""

import json
import subprocess
import sys

from edgemesh.analysis.edgelint import RULES, lint_source
from edgemesh.analysis.findings import Baseline, Finding


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# EM101 jax-api-drift
# ---------------------------------------------------------------------------


def test_em101_fires_on_experimental_shard_map_import():
    # The exact import that broke all 7 seed ring-attention tests.
    findings = lint_source(
        "from jax.experimental.shard_map import shard_map\n",
        path="edgemesh/parallel/ring_attention.py",
    )
    assert rules_of(findings) == {"EM101"}
    assert "compat" in findings[0].message


def test_em101_fires_on_module_form_and_new_spelling():
    # (in_specs carries one spec for the lambda's one arg — the sharding
    # pass rightly flags a 0-vs-1 arity divergence as EM402 otherwise.)
    src = (
        "import jax\n"
        "import jax.experimental.maps\n"
        "f = jax.shard_map(lambda x: x, mesh=None, in_specs=(None,),\n"
        "                  out_specs=None)\n"
    )
    findings = lint_source(src, path="edgemesh/parallel/x.py")
    assert [f.rule for f in findings] == ["EM101", "EM101"]
    # Both the removed module AND the too-new direct spelling are drift.
    assert any("jax.experimental.maps" in f.message for f in findings)
    assert any("jax.shard_map" in f.message for f in findings)


def test_em101_fires_on_aliased_lax_pcast():
    src = "from jax import lax\ny = lax.pcast(1, 'sp', to='varying')\n"
    findings = lint_source(src, path="edgemesh/parallel/x.py")
    assert rules_of(findings) == {"EM101"}


def test_em101_allows_the_compat_shim_itself():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert lint_source(src, path="edgemesh/utils/compat.py") == []


# ---------------------------------------------------------------------------
# EM102 host-sync-in-jit
# ---------------------------------------------------------------------------

_EM102_SRC = """
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def f(x):
    s = x.sum().item()       # readback
    h = np.asarray(x)        # host materialization
    t = float(x[0])          # concretization error
    return s + t + h.sum()

def host_fn(x):
    return x.sum().item()    # fine: not traced
"""


def test_em102_fires_only_inside_traced_code():
    findings = lint_source(_EM102_SRC, path="edgemesh/x.py")
    assert [f.rule for f in findings] == ["EM102", "EM102", "EM102"]
    assert all(f.context == "f" for f in findings)


def test_em102_sees_through_lax_hofs():
    src = (
        "import jax\n"
        "from jax import lax\n"
        "def body(c, x):\n"
        "    return c + x.item(), None\n"
        "def run(xs):\n"
        "    return lax.scan(body, 0.0, xs)\n"
    )
    findings = lint_source(src, path="edgemesh/x.py")
    assert rules_of(findings) == {"EM102"}


# ---------------------------------------------------------------------------
# EM103 unsynced-timing
# ---------------------------------------------------------------------------

_EM103_BAD = """
import time
import jax.numpy as jnp

def bench(x):
    t0 = time.perf_counter()
    y = jnp.dot(x, x)        # dispatches async
    t1 = time.perf_counter() # window closes before the device finishes
    return t1 - t0, y
"""


def test_em103_fires_without_fence():
    findings = lint_source(_EM103_BAD, path="edgemesh/benchmarks.py")
    assert rules_of(findings) == {"EM103"}


def test_em103_quiet_with_method_fence():
    src = _EM103_BAD.replace(
        "t1 = time.perf_counter()",
        "y.block_until_ready()\n    t1 = time.perf_counter()",
    )
    assert lint_source(src, path="edgemesh/benchmarks.py") == []


def test_em103_nested_window_reported_once():
    # A defect inside a nested helper must be attributed to THAT def only,
    # not once per enclosing def.
    src = (
        "import time\n"
        "import jax.numpy as jnp\n"
        "def outer(x):\n"
        "    def bench(y):\n"
        "        t0 = time.perf_counter()\n"
        "        z = jnp.dot(y, y)\n"
        "        t1 = time.perf_counter()\n"
        "        return t1 - t0\n"
        "    return bench(x)\n"
    )
    findings = lint_source(src, path="edgemesh/x.py")
    assert [f.rule for f in findings] == ["EM103"]


def test_em103_quiet_with_function_fence():
    # device_sync(x) — edgemesh's own readback fence, function-call form.
    src = _EM103_BAD.replace(
        "t1 = time.perf_counter()",
        "device_sync(y)\n    t1 = time.perf_counter()",
    )
    assert lint_source(src, path="edgemesh/benchmarks.py") == []


# ---------------------------------------------------------------------------
# EM104 dead-jit-param
# ---------------------------------------------------------------------------

_EM104_SRC = """
import jax
from functools import partial

@partial(jax.jit, static_argnums=(2,))
def decode(tokens, cache, len_cap):
    return tokens + cache
"""


def test_em104_fires_on_dead_param():
    findings = lint_source(_EM104_SRC, path="edgemesh/runtime/generate.py")
    assert rules_of(findings) == {"EM104"}
    assert "len_cap" in findings[0].message


def test_em104_two_dead_params_on_one_def_both_reported():
    src = _EM104_SRC.replace("def decode(tokens, cache, len_cap):",
                             "def decode(tokens, cache, len_cap, other):")
    findings = lint_source(src, path="edgemesh/x.py")
    assert len(findings) == 2


def test_em104_underscore_prefix_is_exempt():
    src = _EM104_SRC.replace("len_cap", "_len_cap")
    assert lint_source(src, path="edgemesh/x.py") == []


def test_em104_ignores_unjitted_functions():
    src = "def f(a, unused):\n    return a\n"
    assert lint_source(src, path="edgemesh/x.py") == []


# ---------------------------------------------------------------------------
# EM105 jit-loop-unroll
# ---------------------------------------------------------------------------

_EM105_SRC = """
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    for i in range(64):
        x = jnp.sin(x)
    return x
"""


def test_em105_fires_on_large_unroll():
    findings = lint_source(_EM105_SRC, path="edgemesh/x.py")
    assert rules_of(findings) == {"EM105"}


def test_em105_allows_small_fixed_unroll():
    src = _EM105_SRC.replace("range(64)", "range(4)")
    assert lint_source(src, path="edgemesh/x.py") == []


# ---------------------------------------------------------------------------
# EM106 print-in-jit
# ---------------------------------------------------------------------------


def test_em106_fires_on_print_in_traced_code():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    print(f'x is {x}')\n"
        "    return x\n"
    )
    findings = lint_source(src, path="edgemesh/x.py")
    assert rules_of(findings) == {"EM106"}


def test_em106_quiet_outside_jit():
    src = "def f(x):\n    print(x)\n    return x\n"
    assert lint_source(src, path="edgemesh/x.py") == []


# ---------------------------------------------------------------------------
# EM107 raw-timing-in-serving
# ---------------------------------------------------------------------------

_EM107_SRC = (
    "import time\n"
    "def handle(req):\n"
    "    t0 = time.perf_counter()\n"
    "    return t0\n"
)


def test_em107_fires_in_serve_and_runtime_only():
    for path in ("edgemesh/serve/engine.py", "edgemesh/runtime/loop.py"):
        findings = lint_source(_EM107_SRC, path=path)
        assert rules_of(findings) == {"EM107"}, path
        assert "obs" in findings[0].message
    # Outside the serving stack, raw clocks are fine (benchmarks, eval, ...).
    assert lint_source(_EM107_SRC, path="edgemesh/ops/x.py") == []
    assert lint_source(_EM107_SRC, path="edgemesh/benchmarks.py") == []


def test_em107_sees_aliased_clocks_and_honors_disable():
    src = (
        "from time import monotonic\n"
        "def wait():\n"
        "    return monotonic()\n"
    )
    assert rules_of(lint_source(src, path="edgemesh/serve/x.py")) == {"EM107"}
    quiet = _EM107_SRC.replace(
        "    t0 = time.perf_counter()",
        "    t0 = time.perf_counter()  # edgelint: disable=EM107",
    )
    assert lint_source(quiet, path="edgemesh/serve/engine.py") == []


# ---------------------------------------------------------------------------
# EM110 serve-per-row-dispatch
# ---------------------------------------------------------------------------

_EM110_SRC = (
    "from edgemesh.runtime.paged_generate import forward_decode_paged\n"
    "def step(rows, cfg, params, cache):\n"
    "    outs = []\n"
    "    for tok in rows:\n"
    "        logits, cache = forward_decode_paged(cfg, params, tok, cache)\n"
    "        outs.append(logits)\n"
    "    return outs, cache\n"
)


def test_em110_fires_on_per_row_forward_loop_in_serve_only():
    findings = lint_source(_EM110_SRC, path="edgemesh/serve/continuous.py")
    assert rules_of(findings) == {"EM110"}
    assert findings[0].severity == "error"
    assert "ragged" in findings[0].message
    # Outside serve/ the rule is silent — runtime code may loop deliberately.
    assert lint_source(_EM110_SRC, path="edgemesh/runtime/stream.py") == []


def test_em110_quiet_outside_loops_and_inside_traced_code():
    once = (
        "from edgemesh.runtime.paged_generate import forward_ragged_paged\n"
        "def boundary(cfg, params, tokens, cu, cache):\n"
        "    return forward_ragged_paged(cfg, params, tokens, cu, cache, 16)\n"
    )
    assert lint_source(once, path="edgemesh/serve/continuous.py") == []
    # A loop INSIDE traced code unrolls — EM105's beat, not a host
    # dispatch-per-row problem.
    traced = (
        "import jax\n"
        "from edgemesh.runtime.paged_generate import forward_decode_paged\n"
        "@jax.jit\n"
        "def seg(cfg, params, toks, cache):\n"
        "    for t in toks:\n"
        "        _, cache = forward_decode_paged(cfg, params, t, cache)\n"
        "    return cache\n"
    )
    assert [
        f for f in lint_source(traced, path="edgemesh/serve/continuous.py")
        if f.rule == "EM110"
    ] == []


def test_em110_sees_local_jit_bindings_and_comprehensions():
    src = (
        "import jax\n"
        "from functools import partial\n"
        "from edgemesh.runtime.paged_generate import forward_prefill_paged\n"
        "_prefill_donated = partial(jax.jit, static_argnums=(0,),"
        " donate_argnums=(4,))(forward_prefill_paged)\n"
        "def admit_all(cfg, params, batch, caches):\n"
        "    return [_prefill_donated(cfg, params, t, l, c)"
        " for t, l, c in batch]\n"
    )
    findings = lint_source(src, path="edgemesh/serve/continuous.py")
    assert rules_of(findings) == {"EM110"}


def test_em110_disable_comment_suppresses():
    quiet = _EM110_SRC.replace(
        "        logits, cache = forward_decode_paged(cfg, params, tok, cache)",
        "        logits, cache = forward_decode_paged(cfg, params, tok, cache)"
        "  # edgelint: disable=EM110",
    )
    assert lint_source(quiet, path="edgemesh/serve/continuous.py") == []


def test_em110_shipped_serve_is_clean():
    # The rewired engine is the rule's reference fixture: the ragged
    # boundary replaced every per-row dispatch loop, so serve/ must lint
    # clean without suppressions.
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    serve = Path(__file__).resolve().parent.parent / "edgemesh" / "serve"
    assert [f for f in lint_paths([serve]) if f.rule == "EM110"] == []


# ---------------------------------------------------------------------------
# EM111 metric-naming
# ---------------------------------------------------------------------------

_EM111_SRC = (
    "def build(reg):\n"
    "    a = reg.counter('requests_total', 'no namespace')\n"
    "    b = reg.counter('edgemesh_requests', 'counter missing _total')\n"
    "    c = reg.gauge('edgemesh_pages_total', 'gauge with _total')\n"
    "    d = reg.histogram('edgemesh_ttft_total', 'histogram with _total')\n"
    "    e = reg.counter('edgemesh_ok_total', 'fine')\n"
    "    f = reg.gauge('edgemesh_pages', 'fine')\n"
    "    g = reg.histogram('edgemesh_ttft_seconds', 'fine')\n"
    "    return a, b, c, d, e, f, g\n"
)


def test_em111_fires_on_prefix_and_total_suffix_violations():
    findings = lint_source(_EM111_SRC, path="edgemesh/obs/device.py")
    assert [f.rule for f in findings] == ["EM111"] * 4
    assert all(f.severity == "warning" for f in findings)
    msgs = [f.message for f in findings]
    assert "namespace prefix" in msgs[0]
    assert "must end '_total'" in msgs[1]
    assert "must not end '_total'" in msgs[2]
    assert "must not end '_total'" in msgs[3]
    # Outside the shipped package (tests, docs snippets) the rule is
    # silent: throwaway fixture families are deliberate.
    assert lint_source(_EM111_SRC, path="tests/test_obs.py") == []


def test_em111_skips_dynamic_names_and_honors_disable():
    dynamic = (
        "def build(reg, name):\n"
        "    return reg.counter(name, 'dynamic: out of scope')\n"
    )
    assert lint_source(dynamic, path="edgemesh/obs/device.py") == []
    quiet = (
        "def build(reg):\n"
        "    return reg.counter('legacy_total', 'grandfathered')"
        "  # edgelint: disable=EM111\n"
    )
    assert lint_source(quiet, path="edgemesh/obs/device.py") == []


def test_em111_shipped_tree_is_clean():
    # Every metric the shipped package registers follows the convention —
    # the tree is the rule's reference fixture (docs/OBSERVABILITY.md
    # metric catalog).
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    assert [f for f in lint_paths([pkg]) if f.rule == "EM111"] == []


# ---------------------------------------------------------------------------
# EM112 unbounded-metric-label
# ---------------------------------------------------------------------------

_EM112_SRC = (
    "from edgemesh.obs.metrics import bounded_label\n"
    "def record(reg, payload, headers, tenant_param):\n"
    "    c = reg.counter('edgemesh_x_total', '', ('tenant',))\n"
    "    c.labels(tenant=payload.get('tenant')).inc()\n"        # raw call
    "    c.labels(session=headers['X-Session']).inc()\n"        # subscript
    "    t = payload.get('tenant')\n"
    "    c.labels(tenant=t).inc()\n"                            # tainted local
    "    lbl = bounded_label(payload.get('tenant'))\n"
    "    c.labels(tenant=lbl).inc()\n"                          # normalized local
    "    c.labels(tenant=bounded_label(t)).inc()\n"             # inline normalize
    "    c.labels(tenant='fixed').inc()\n"                      # constant
    "    c.labels(tenant=tenant_param).inc()\n"                 # param: trusted
    "    c.labels(engine=t).inc()\n"                            # non-identity label
)


def test_em112_flags_raw_request_labels_and_accepts_bounded():
    findings = [f for f in lint_source(_EM112_SRC,
                                       path="edgemesh/fleet/router.py")
                if f.rule == "EM112"]
    assert [f.line for f in findings] == [4, 5, 7]
    assert all(f.severity == "error" for f in findings)
    assert all("bounded_label" in f.message for f in findings)
    # Out of the shipped package: silent (test fixtures mint labels freely).
    assert [f for f in lint_source(_EM112_SRC, path="tests/test_obs.py")
            if f.rule == "EM112"] == []


def test_em112_honors_disable_and_reassignment_chain():
    quiet = (
        "def record(c, payload):\n"
        "    c.labels(tenant=payload.get('t')).inc()"
        "  # edgelint: disable=EM112\n"
    )
    assert [f for f in lint_source(quiet, path="edgemesh/obs/slo.py")
            if f.rule == "EM112"] == []
    # The LAST assignment before the call wins the taint judgment.
    relabeled = (
        "from edgemesh.obs.metrics import bounded_label\n"
        "def record(c, payload):\n"
        "    t = payload.get('tenant')\n"
        "    t = bounded_label(t)\n"
        "    c.labels(tenant=t).inc()\n"
    )
    assert [f for f in lint_source(relabeled, path="edgemesh/obs/slo.py")
            if f.rule == "EM112"] == []
    rebroken = (
        "from edgemesh.obs.metrics import bounded_label\n"
        "def record(c, payload):\n"
        "    t = bounded_label(payload.get('tenant'))\n"
        "    t = payload.get('tenant')\n"
        "    c.labels(tenant=t).inc()\n"
    )
    assert [f.rule for f in lint_source(rebroken, path="edgemesh/obs/slo.py")
            if f.rule == "EM112"] == ["EM112"]


def test_em112_shipped_tree_is_clean():
    # Every tenant/session label in the shipped package flows through
    # bounded_label — the tree is the rule's reference fixture.
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    assert [f for f in lint_paths([pkg]) if f.rule == "EM112"] == []


# ---------------------------------------------------------------------------
# EM113 span-schema-bypass
# ---------------------------------------------------------------------------

_EM113_SRC = (
    "import json\n"
    "def dump_spans(records, path):\n"
    "    with open(path, 'a') as f:\n"
    "        for r in records:\n"
    "            rec = {'event': 'request_spans', 'rid': r.rid,\n"
    "                   'spans': r.spans}\n"
    "            f.write(json.dumps(rec) + '\\n')\n"
)


def test_em113_fires_on_handrolled_span_jsonl_writer():
    findings = [f for f in lint_source(_EM113_SRC,
                                       path="edgemesh/serve/myobs.py")
                if f.rule == "EM113"]
    assert len(findings) == 1
    assert findings[0].severity == "error"
    assert "JsonlLogger" in findings[0].message
    # Outside the shipped package (tests, fixtures) the rule is silent.
    assert [f for f in lint_source(_EM113_SRC, path="tests/test_x.py")
            if f.rule == "EM113"] == []


def test_em113_sees_inline_dicts_event_constants_and_spans_key():
    # Inline dict with the event constant name (SPAN_RECORD_EVENT et al.).
    const = (
        "import json\n"
        "from edgemesh.obs.spans import SPAN_RECORD_EVENT\n"
        "def w(f, rid):\n"
        "    f.write(json.dumps({'event': SPAN_RECORD_EVENT, 'rid': rid}))\n"
    )
    assert [f.rule for f in lint_source(const, path="edgemesh/obs/extra.py")
            if f.rule == "EM113"] == ["EM113"]
    # A bare "spans" key counts even without the event field.
    spans_key = (
        "import json\n"
        "def w(f, tree):\n"
        "    f.write(json.dumps({'spans': tree}))\n"
    )
    assert [f.rule for f in lint_source(spans_key,
                                        path="edgemesh/fleet/extra.py")
            if f.rule == "EM113"] == ["EM113"]


def test_em113_quiet_on_opaque_payloads_and_non_span_events():
    # json.dumps of an opaque name: provenance invisible, out of scope.
    opaque = (
        "import json\n"
        "def send(f, payload):\n"
        "    f.write(json.dumps(payload))\n"
    )
    assert [f for f in lint_source(opaque, path="edgemesh/serve/rest2.py")
            if f.rule == "EM113"] == []
    # An event OUTSIDE the span vocabulary is someone else's log.
    other = (
        "import json\n"
        "def w(f):\n"
        "    f.write(json.dumps({'event': 'checkpoint_saved', 'step': 1}))\n"
    )
    assert [f for f in lint_source(other, path="edgemesh/serve/rest2.py")
            if f.rule == "EM113"] == []
    # Serializing without ANY file write in the function (an HTTP response
    # body, a debug repr) is not a bypass.
    no_write = (
        "import json\n"
        "def render(tree):\n"
        "    return json.dumps({'event': 'request_spans', 'spans': tree})\n"
    )
    assert [f for f in lint_source(no_write, path="edgemesh/serve/rest2.py")
            if f.rule == "EM113"] == []


def test_em113_allows_the_sanctioned_producers_and_disable():
    # The producers themselves are allowlisted by path.
    assert [f for f in lint_source(_EM113_SRC,
                                   path="edgemesh/utils/tracing.py")
            if f.rule == "EM113"] == []
    assert [f for f in lint_source(_EM113_SRC, path="edgemesh/obs/flight.py")
            if f.rule == "EM113"] == []
    quiet = _EM113_SRC.replace(
        "            f.write(json.dumps(rec) + '\\n')",
        "            f.write(json.dumps(rec) + '\\n')"
        "  # edgelint: disable=EM113",
    )
    assert [f for f in lint_source(quiet, path="edgemesh/serve/myobs.py")
            if f.rule == "EM113"] == []


def test_em113_shipped_tree_is_clean():
    # Every span-event write in the shipped package flows through
    # SpanTracker/FlightRecorder/JsonlLogger — the tree is the rule's
    # reference fixture (replay correctness depends on it).
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    assert [f for f in lint_paths([pkg]) if f.rule == "EM113"] == []


# ---------------------------------------------------------------------------
# EM114: ungated device sync in the serving stack
# ---------------------------------------------------------------------------


_EM114_SRC = (
    "import jax\n"
    "def drain(handles, out):\n"
    "    out.block_until_ready()\n"
    "    return jax.device_get(handles)\n"
)


def test_em114_fires_on_ungated_sync_in_serving_stack():
    for path in ("edgemesh/serve/batcher2.py", "edgemesh/runtime/gen2.py"):
        findings = [f for f in lint_source(_EM114_SRC, path=path)
                    if f.rule == "EM114"]
        # Both the method-style fence and the jax.device_get readback flag.
        assert len(findings) == 2, path
        assert all(f.severity == "error" for f in findings)
        assert "device_sync" in findings[0].message


def test_em114_resolves_import_aliases():
    aliased = (
        "from jax import device_get as fetch\n"
        "def drain(h):\n"
        "    return fetch(h)\n"
    )
    assert [f.rule for f in lint_source(aliased,
                                        path="edgemesh/serve/x.py")
            if f.rule == "EM114"] == ["EM114"]


def test_em114_quiet_outside_scope_and_for_device_sync():
    # Outside serve//runtime/ the fence is somebody's benchmark harness.
    assert [f for f in lint_source(_EM114_SRC, path="edgemesh/obs/probe.py")
            if f.rule == "EM114"] == []
    assert [f for f in lint_source(_EM114_SRC, path="tests/test_x.py")
            if f.rule == "EM114"] == []
    # The sanctioned fence: device_sync (tunnel-aware, sampled by the
    # ledger) stays legal everywhere.
    gated = (
        "from edgemesh.utils.compat import device_sync\n"
        "def measure(out):\n"
        "    device_sync(out)\n"
    )
    assert [f for f in lint_source(gated, path="edgemesh/serve/x.py")
            if f.rule == "EM114"] == []


def test_em114_inline_disable_suppresses():
    quiet = _EM114_SRC.replace(
        "    return jax.device_get(handles)",
        "    return jax.device_get(handles)  # edgelint: disable=EM114",
    ).replace(
        "    out.block_until_ready()",
        "    out.block_until_ready()  # edgelint: disable=EM114",
    )
    assert [f for f in lint_source(quiet, path="edgemesh/serve/x.py")
            if f.rule == "EM114"] == []


def test_em114_shipped_tree_is_clean():
    # Every host sync in serve//runtime/ is either the ledger's sampled
    # device_sync fence or an annotated already-complete readback — the
    # dispatch pipeline never stalls on an unannotated sync.
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    assert [f for f in lint_paths([pkg]) if f.rule == "EM114"] == []


# ---------------------------------------------------------------------------
# EM115: page-pool mutation outside the PoolLedger seam
# ---------------------------------------------------------------------------


_EM115_SRC = (
    "class Engine:\n"
    "    def steal(self):\n"
    "        return self._free_pages.pop()\n"
    "\n"
    "    def rebuild(self):\n"
    "        self._dfree = list(range(8))\n"
)


def test_em115_fires_on_unledgered_pool_mutation():
    for path in ("edgemesh/serve/engine2.py", "edgemesh/runtime/gen2.py"):
        findings = [f for f in lint_source(_EM115_SRC, path=path)
                    if f.rule == "EM115"]
        # Both the mutator call and the wholesale reassignment flag.
        assert len(findings) == 2, path
        assert all(f.severity == "error" for f in findings)
        assert "_pop_pages" in findings[0].message


def test_em115_seam_functions_are_exempt():
    # The seam itself (references .mem/.dmem), callers routing through
    # _pop_pages/_push_pages, and ledger construction all stay legal.
    seam = (
        "class Engine:\n"
        "    def _pop_pages(self, n):\n"
        "        taken = [self._free_pages.pop() for _ in range(n)]\n"
        "        self.mem.on_reserve(n)\n"
        "        return taken\n"
        "\n"
        "    def _retire(self, slot):\n"
        "        self._dfree.extend(slot.pages)\n"
        "        self.dmem.on_free(len(slot.pages))\n"
        "\n"
        "    def _admit(self, need):\n"
        "        return self._pop_pages(need)\n"
        "\n"
        "    def boot(self):\n"
        "        self._free_pages = list(range(1, 64))\n"
        "        self.mem = PoolLedger(total_pages=64)\n"
    )
    assert [f for f in lint_source(seam, path="edgemesh/serve/x.py")
            if f.rule == "EM115"] == []


def test_em115_quiet_outside_scope_and_for_other_lists():
    assert [f for f in lint_source(_EM115_SRC, path="edgemesh/obs/x.py")
            if f.rule == "EM115"] == []
    assert [f for f in lint_source(_EM115_SRC, path="tests/test_x.py")
            if f.rule == "EM115"] == []
    other = (
        "def drain(q):\n"
        "    q.pending.pop()\n"
        "    q.slots = []\n"
    )
    assert [f for f in lint_source(other, path="edgemesh/serve/x.py")
            if f.rule == "EM115"] == []


def test_em115_inline_disable_suppresses():
    quiet = _EM115_SRC.replace(
        "        return self._free_pages.pop()",
        "        return self._free_pages.pop()  # edgelint: disable=EM115",
    ).replace(
        "        self._dfree = list(range(8))",
        "        self._dfree = list(range(8))  # edgelint: disable=EM115",
    )
    assert [f for f in lint_source(quiet, path="edgemesh/serve/x.py")
            if f.rule == "EM115"] == []


def test_em115_shipped_tree_is_clean():
    # Every pool transition in serve//runtime/ reports to the PoolLedger —
    # the conservation invariant has no blind spots in the shipped engine.
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    assert [f for f in lint_paths([pkg]) if f.rule == "EM115"] == []


# ---------------------------------------------------------------------------
# Suppression + baseline mechanics
# ---------------------------------------------------------------------------


def test_inline_disable_suppresses_one_rule():
    src = _EM105_SRC.replace(
        "    for i in range(64):",
        "    for i in range(64):  # edgelint: disable=EM105",
    )
    assert lint_source(src, path="edgemesh/x.py") == []


def test_disable_on_def_line_covers_function_body():
    src = _EM105_SRC.replace(
        "def f(x):", "def f(x):  # edgelint: disable=EM105"
    )
    assert lint_source(src, path="edgemesh/x.py") == []


def test_baseline_filters_by_fingerprint_not_line_number():
    findings = lint_source(_EM104_SRC, path="edgemesh/x.py")
    baseline = Baseline.from_findings(findings)
    # Same finding shifted 5 lines down must stay baselined.
    shifted = lint_source("\n\n\n\n\n" + _EM104_SRC, path="edgemesh/x.py")
    assert shifted[0].line != findings[0].line
    assert baseline.filter(shifted) == []
    # A genuinely new finding still surfaces.
    fresh = Finding("EM104", "warning", "edgemesh/x.py", 1, "m", "g", "other src")
    assert baseline.filter([fresh]) == [fresh]


def test_baseline_roundtrip(tmp_path):
    findings = lint_source(_EM104_SRC, path="edgemesh/x.py")
    p = tmp_path / "baseline.json"
    Baseline.from_findings(findings).save(p)
    assert Baseline.load(p).filter(findings) == []


# ---------------------------------------------------------------------------
# Abstract contract pass
# ---------------------------------------------------------------------------


def test_contract_pass_is_green():
    from edgemesh.analysis.contracts import run_contracts

    findings = run_contracts()
    assert findings == [], [f.render() for f in findings]


def test_contract_pass_catches_cache_instability():
    # A decode step whose output cache grows by one slot per call: EM202.
    import jax
    import jax.numpy as jnp

    from edgemesh.analysis import contracts

    def bad_runner():
        def bad_decode(cache):
            return jnp.concatenate([cache, cache[:1]], axis=0)

        cache = jax.ShapeDtypeStruct((4, 8), jnp.float32)
        out = jax.eval_shape(bad_decode, cache)
        problems = []
        if contracts._avals(out) != contracts._avals(cache):
            problems.append(("EM202", "cache avals drifted"))
        return problems

    entry = [("bad.decode", "edgemesh/x.py", bad_runner)]
    old = contracts.ENTRY_POINTS
    contracts.ENTRY_POINTS = entry
    try:
        findings = contracts.run_contracts()
    finally:
        contracts.ENTRY_POINTS = old
    assert "EM202" in rules_of(findings)


def test_contract_pass_reports_trace_failures_as_em201():
    from edgemesh.analysis import contracts

    def broken_runner():
        raise TypeError("signature drifted")

    old = contracts.ENTRY_POINTS
    contracts.ENTRY_POINTS = [("broken.entry", "edgemesh/x.py", broken_runner)]
    try:
        findings = contracts.run_contracts()
    finally:
        contracts.ENTRY_POINTS = old
    em201 = [f for f in findings if f.rule == "EM201"]
    assert em201 and "signature drifted" in em201[0].message


def test_contract_pass_flags_unregistered_check_kernel():
    # Hide one registration: the registry-coverage check must flag the kernel.
    from edgemesh.analysis import contracts

    old = contracts.CHECK_CONTRACTS
    contracts.CHECK_CONTRACTS = [
        c for c in old if c["kernel"][1] != "int8_matmul_fused"
    ]
    try:
        findings = contracts._run_check_contracts()
    finally:
        contracts.CHECK_CONTRACTS = old
    assert any(
        f.rule == "EM204" and "int8_matmul_fused" in f.message for f in findings
    )


def test_contract_pass_flags_dead_contract_as_em205():
    # A checker that never fires on the bad inputs: EM205.
    from edgemesh.analysis import contracts

    old = contracts.CHECK_CONTRACTS
    dead = dict(old[-1])  # int8 entry
    dead = {**dead, "checker": "checked"}  # 'checked' exists but asserts nothing
    contracts.CHECK_CONTRACTS = [dead]
    try:
        findings = contracts._run_check_contracts()
    finally:
        contracts.CHECK_CONTRACTS = old
    assert any(f.rule == "EM205" for f in findings)


# ---------------------------------------------------------------------------
# Repo gate + CLI
# ---------------------------------------------------------------------------


def test_repo_lints_clean_against_baseline():
    """The merged tree itself must stay green: AST pass over edgemesh/ with
    the committed baseline applied (the cheap half of the CI gate; the
    contract half is test_contract_pass_is_green)."""
    from pathlib import Path

    from edgemesh.analysis.edgelint import lint_paths
    from edgemesh.analysis.findings import Baseline, default_baseline_path

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    fresh = Baseline.load(default_baseline_path()).filter(lint_paths([pkg]))
    assert fresh == [], [f.render() for f in fresh]


def test_cli_json_output_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    good = tmp_path / "good.py"
    good.write_text("def f(a):\n    return a\n")

    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--format", "json", "--no-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert [f["rule"] for f in report["findings"]] == ["EM104"]
    assert report["findings"][0]["fingerprint"]

    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(good),
         "--no-contracts", "--no-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_missing_path_is_usage_error_not_clean(tmp_path):
    # A typo'd path must not produce a permanently-green "clean"/exit 0 gate.
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis",
         str(tmp_path / "no_such_dir"), "--no-contracts"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_cli_write_baseline_grandfathers(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    bl = tmp_path / "bl.json"
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl), "--write-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "1 baselined" in proc.stdout


def test_cli_github_format_emits_workflow_annotations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--no-baseline", "--format", "github"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    line = proc.stdout.strip().splitlines()[0]
    assert line.startswith("::warning file=")
    assert ",line=" in line and "title=EM104" in line and "::parameter" in line


def test_cli_stale_baseline_entry_is_warned_not_silently_masking(tmp_path):
    # Grandfather a finding, then fix the code: the baseline entry is now
    # stale and must be REPORTED (it would mask a future finding at that
    # fingerprint), then removed by --prune-baseline.
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    bl = tmp_path / "bl.json"
    subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl), "--write-baseline"],
        capture_output=True, text=True, timeout=120, check=True,
    )
    bad.write_text(_EM104_SRC.replace("len_cap", "len_cap2"))
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl)],
        capture_output=True, text=True, timeout=120,
    )
    assert "stale baseline entry" in proc.stderr
    assert proc.returncode == 1  # the renamed finding is genuinely new
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl), "--prune-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "pruned 1 stale entry" in proc.stdout
    assert json.loads(bl.read_text())["findings"] == []


def test_cli_no_contracts_does_not_condemn_contract_baseline_entries(tmp_path):
    # --no-contracts skips the EM2xx pass: a baselined contract finding for
    # a linted file is ABSENT from the run, but that proves nothing — it
    # must not be reported stale (or pruned) by a lint-only invocation.
    target = tmp_path / "good.py"
    target.write_text("def f(a):\n    return a\n")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"findings": [{
        "fingerprint": "deadbeefdeadbeef", "rule": "EM204",
        "path": str(target), "context": "", "line_text": "x",
    }]}))
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(target),
         "--no-contracts", "--baseline", str(bl)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "stale baseline entry" not in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(target),
         "--no-contracts", "--baseline", str(bl), "--prune-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert "pruned 0 stale entries" in proc.stdout
    assert len(json.loads(bl.read_text())["findings"]) == 1


def test_cli_prune_with_no_baseline_is_a_usage_error(tmp_path):
    # --no-baseline empties the in-memory baseline; pruning against it
    # would rewrite the file to nothing. Must refuse, not destroy.
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    bl = tmp_path / "bl.json"
    subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl), "--write-baseline"],
        capture_output=True, text=True, timeout=120, check=True,
    )
    before = bl.read_text()
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl),
         "--no-baseline", "--prune-baseline"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "--prune-baseline" in proc.stderr
    assert bl.read_text() == before


def test_cli_stale_baseline_missing_file_detected(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(_EM104_SRC)
    bl = tmp_path / "bl.json"
    subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(bad),
         "--no-contracts", "--baseline", str(bl), "--write-baseline"],
        capture_output=True, text=True, timeout=120, check=True,
    )
    bad.unlink()
    other = tmp_path / "good.py"
    other.write_text("def f(a):\n    return a\n")
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(other),
         "--no-contracts", "--baseline", str(bl), "--format", "json"],
        capture_output=True, text=True, timeout=120,
    )
    assert "file no longer exists" in proc.stderr
    report = json.loads(proc.stdout)
    assert report["stale_baseline"][0]["reason"] == "file no longer exists"


def test_cli_whole_package_gate_is_green():
    """The tier-1 CI gate: `edgemesh lint` (AST + concurrency passes, no
    contracts so no jax import) over the whole shipped package exits 0 —
    any new rule regression or unbaselined finding fails the suite here."""
    from pathlib import Path

    pkg = Path(__file__).resolve().parent.parent / "edgemesh"
    proc = subprocess.run(
        [sys.executable, "-m", "edgemesh.analysis", str(pkg), "--no-contracts"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def _all_rule_tables():
    from edgemesh.analysis.concurrency import RULES as CONCURRENCY_RULES
    from edgemesh.analysis.contracts import CONTRACT_RULES
    from edgemesh.analysis.sharding import RULES as SHARDING_RULES
    from edgemesh.analysis.sharding import SHARDING_CONTRACT_RULES
    from edgemesh.analysis.wire import WIRE_CONTRACT_RULES, WIRE_RULES

    return (RULES, CONTRACT_RULES, CONCURRENCY_RULES, SHARDING_RULES,
            SHARDING_CONTRACT_RULES, WIRE_RULES, WIRE_CONTRACT_RULES)


def test_every_rule_has_metadata_and_unique_id():
    # One namespace across EVERY pass: a rule id claimed twice would make
    # baselines, --select filters, and disable comments ambiguous.
    seen: dict[str, str] = {}
    for table in _all_rule_tables():
        for rule, meta in table.items():
            assert rule not in seen, f"{rule} defined in two rule tables"
            seen[rule] = meta["name"]
            assert meta["severity"] in ("error", "warning"), rule
            assert meta["name"] and meta["summary"], rule


def test_every_rule_documented_in_analysis_md():
    # docs/ANALYSIS.md is the operator-facing contract: every shipped rule
    # id must have a table row there (catches doc drift for all future
    # rules, not just the latest pass), and the doc must not advertise
    # rules that no longer ship.
    import re
    from pathlib import Path

    doc = (Path(__file__).resolve().parent.parent / "docs" / "ANALYSIS.md"
           ).read_text()
    documented = set(re.findall(r"^\|\s*(EM\d{3})\s*\|", doc, re.MULTILINE))
    shipped = {rule for table in _all_rule_tables() for rule in table}
    missing = shipped - documented
    assert not missing, f"rules missing a docs/ANALYSIS.md row: {sorted(missing)}"
    phantom = documented - shipped
    assert not phantom, f"docs/ANALYSIS.md rows for unshipped rules: {sorted(phantom)}"


def test_em112_provenance_follows_source_order_not_walk_order():
    from edgemesh.analysis.edgelint import lint_source

    # Normalization AFTER a nested raw assignment: clean — the latest
    # SOURCE line wins, not ast.walk (breadth-first) order.
    normalized_last = (
        "from edgemesh.obs.metrics import bounded_label\n"
        "def record(c, payload, cond):\n"
        "    if cond:\n"
        "        t = payload.get('tenant')\n"
        "    t = bounded_label(t)\n"
        "    c.labels(tenant=t).inc()\n"
    )
    assert [f for f in lint_source(normalized_last, path="edgemesh/obs/slo.py")
            if f.rule == "EM112"] == []
    # The mirror: a nested RAW reassignment after normalization flags.
    raw_last = (
        "from edgemesh.obs.metrics import bounded_label\n"
        "def record(c, payload, cond):\n"
        "    t = bounded_label(payload.get('tenant'))\n"
        "    if cond:\n"
        "        t = payload.get('tenant')\n"
        "    c.labels(tenant=t).inc()\n"
    )
    assert [f.rule for f in lint_source(raw_last, path="edgemesh/obs/slo.py")
            if f.rule == "EM112"] == ["EM112"]
